"""Headline benchmark: 10k-validator ExtendedCommit-shaped signature batch.

Mirrors BASELINE.json's metric ("ed25519 sig-verifies/sec/chip; p50
Commit.VerifyCommit latency @10k vals") and the reference's bench harness
(``crypto/ed25519/bench_test.go:31-67``, which benches BatchVerify at fixed
sig counts): ed25519 signatures over ~120-byte vote-sign-bytes messages,
verified on the accelerator via the ZIP-215 kernel.

In ``commit`` mode two explicit comparison fields are emitted:
``vs_single_loop`` (speedup over a host single-verify loop) and
``vs_reference_batch_est`` (that number / 2 — curve25519-voi's CPU batch
mode runs ~2x its single path, so this estimates the speedup over the
reference's REAL baseline).  ``vs_baseline`` equals the reference-relative
estimate on every backend, so the driver's one JSON line can never be
misread as parity with the reference when it is only parity with our own
single-verify loop.

Robustness contract (the whole point of this file's structure): the parent
process NEVER imports jax.  The TPU attempt runs in a subprocess with a hard
timeout — on this image the axon TPU relay can wedge so that backend init
hangs forever — and on failure/timeout a CPU-backend subprocess runs
instead.  Exactly one JSON line is always printed, and the exit code is 0,
so the driver always records a result.

``BENCH_MODE`` selects what is measured (default "commit"):
- commit:    10k-validator ExtendedCommit-shaped batch (the headline)
- blocksync: K-block replay with cross-block commit batching vs
             one-commit-per-block (BASELINE configs[4],
             internal/blocksync/reactor.go:495 redesign)
- light:     1000-header sequential light sync on the batched verifier
             (BASELINE configs[3], light/client.go:609 redesign)
- merkle:    10k-leaf root+proofs + part-set proof build through the
             level-order dispatch vs the recursive hashlib reference
- light-serve: one validator serving a simulated skipping-client fleet
             through the light/serve.py tier — proofs/s + request p99
             with /status probed throughout, vs the per-proof re-hash
             baseline
- bls:       the r20 aggregate-commit fast path — BLS aggregate verify
             (two pairings, O(1) in N) vs the Ed25519 batched dense
             path over the 100/1k/10k-validator curve, plus wire sizes
- mesh:      the r19 true-SPMD path — weak-scaling over 1/2/4/8 devices
             (ONE sharded dispatch per bucket), blocksync window
             occupancy, a sharded-vs-single equal-work guard, the
             10k-validator commit p50 at full mesh width, and the
             fresh-process sharded-bundle first-dispatch gauge
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))


# --------------------------------------------------------------------------
# child: does the actual measurement on one backend, prints one JSON line
# --------------------------------------------------------------------------

def _mode_child_setup(tag: str, backend: str):
    """Shared scaffolding for the light/blocksync mode children: stderr
    note(), backend forcing, compile cache, and the same
    claims-TPU-but-got-CPU guard as the commit mode (a CPU box must fail
    the 'tpu' attempt so the parent re-runs it honestly labeled cpu)."""
    def note(msg):
        print(f"[bench:{tag}:{backend}] {msg}", file=sys.stderr, flush=True)

    from cometbft_tpu.jaxenv import enable_compile_cache, force_cpu_backend

    enable_compile_cache()
    if backend == "cpu":
        force_cpu_backend()
        # device kernel emulated on one CPU core is not a meaningful
        # fallback: measure the batching seam over host crypto instead
        return note, "cpu"
    import jax

    if jax.devices()[0].platform == "cpu":
        raise RuntimeError("requested accelerator but got CPU backend")
    return note, "jax"


def _timed_cold_warm(fn):
    t0 = time.perf_counter()
    fn()
    cold = time.perf_counter() - t0
    t0 = time.perf_counter()
    fn()
    return cold, time.perf_counter() - t0


def _child_light(backend: str, n_headers: int, n_vals: int) -> None:
    """1000-header sequential sync: batched device path vs per-header
    verification (BASELINE configs[3])."""
    note, kernel_backend = _mode_child_setup("light", backend)

    from cometbft_tpu.light import verify_adjacent, verify_sequential_batched
    from cometbft_tpu.testing import make_light_chain

    note(f"building {n_headers}-header chain @ {n_vals} validators")
    chain = make_light_chain(n_headers, n_vals=n_vals)
    now = chain[-1].header.time_ns + 60_000_000_000
    period = 3600 * 10**9

    note("batched sync (cold: includes compile)")
    cold, warm = _timed_cold_warm(lambda: verify_sequential_batched(
        "light-chain", chain[0], chain[1:], period, now,
        backend=kernel_backend))

    note("per-header baseline (host one-by-one)")
    t0 = time.perf_counter()
    prev = chain[0]
    for lb in chain[1:]:
        verify_adjacent("light-chain", prev, lb, period, now, backend="cpu")
        prev = lb
    per_header = time.perf_counter() - t0

    print(json.dumps({
        "metric": "light-client sequential sync, headers/sec "
                  f"({n_headers} headers @ {n_vals} vals, batched)",
        "value": round((n_headers - 1) / warm, 1),
        "unit": "headers/s",
        "vs_baseline": round(per_header / warm, 2),
        "batched_warm_s": round(warm, 3),
        "batched_cold_s": round(cold, 3),
        "per_header_s": round(per_header, 3),
        "backend": backend,
    }), flush=True)


def _child_blocksync(backend: str, n_blocks: int, n_vals: int) -> None:
    """K-block replay: the r13 cross-block ACCUMULATOR (deep
    verify-window dispatches, the shape `blocksync/reactor.py` stages
    during catch-up) vs the r06-r12 per-window baseline (32-block
    dispatches) vs one VerifyCommitLight per block (the reference's loop,
    BASELINE configs[4]).  ``BENCH_CHURN=k`` rotates one validator every
    k blocks, so batching is bounded by same-valset windows exactly like
    the reactor's valset-hash prefix check.  Reports batched vs
    unbatched sig-verifies/s and the mesh-occupancy of the accumulated
    dispatches; writes the JSON to ``BENCH_OUT`` (default
    ``docs/bench/r13-blocksync-mesh-cpu.json``)."""
    note, kernel_backend = _mode_child_setup("bs", backend)

    from cometbft_tpu.crypto import plan as deviceplan
    from cometbft_tpu.testing import make_light_chain
    from cometbft_tpu.types.validation import (VerifyCommitLight,
                                               verify_commits_light_batched)

    churn = int(os.environ.get("BENCH_CHURN", "0"))
    # the old reactor's fixed window vs the accumulator's default-deep one
    win_base = int(os.environ.get("BENCH_WINDOW", "32"))
    win_acc = int(os.environ.get("BENCH_ACC_WINDOW", "256"))
    note(f"building {n_blocks}-block chain @ {n_vals} validators"
         + (f", churn every {churn}" if churn else ""))
    chain = make_light_chain(n_blocks, n_vals=n_vals, rotate_every=churn)
    # group into same-valset runs (the reactor batches exactly such
    # prefixes); without churn this is one run covering the whole chain
    runs = []
    for lb in chain:
        vh = lb.validators.hash()
        if not runs or runs[-1][0] != vh:
            runs.append((vh, lb.validators, []))
        runs[-1][2].append((lb.commit.block_id, lb.height, lb.commit))

    def windowed(depth, occs=None):
        """One full verification pass at the given dispatch depth;
        records per-dispatch lane counts/occupancy in place so the
        TIMED pass supplies the occupancy figure (no extra replay of
        the whole workload just to re-count lanes)."""
        lanes = 0
        for _vh, vals_r, items_r in runs:
            for s in range(0, len(items_r), depth):
                lanes_w = verify_commits_light_batched(
                    "light-chain", vals_r, items_r[s:s + depth],
                    backend=kernel_backend)
                lanes += lanes_w
                if occs is not None:
                    occs.append(deviceplan.mesh_occupancy(lanes_w))
        return lanes

    reps = int(os.environ.get("BENCH_BS_REPS", "3"))

    def best_of(fn):
        # min over reps like the other modes: noise on a shared box must
        # not decide the accumulator-vs-window comparison
        t = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            t = min(t, time.perf_counter() - t0)
        return t

    note(f"accumulated verification (window {win_acc}) over {len(runs)} "
         f"same-valset run(s) (cold: includes compile; best of {reps})")
    occs: list = []
    n_lanes = 0

    def acc_pass():
        nonlocal n_lanes
        occs.clear()
        n_lanes = windowed(win_acc, occs)

    cold, _ = _timed_cold_warm(acc_pass)
    warm = best_of(acc_pass)

    note(f"per-window baseline (window {win_base}, the pre-r13 reactor)")
    warm_win = best_of(lambda: windowed(win_base))

    note("per-block baseline (the reference's loop shape, host crypto)")

    def per_block_pass():
        for lb in chain:
            VerifyCommitLight("light-chain", lb.validators,
                              lb.commit.block_id, lb.height, lb.commit,
                              backend="cpu")

    per_block = best_of(per_block_pass)

    # mesh occupancy of the accumulated dispatches: how full the padded
    # compiled shapes run, averaged over every window the pass dispatches
    occupancy = sum(occs) / len(occs) if occs else 0.0

    result = {
        "metric": "blocksync replay, blocks/sec "
                  f"({n_blocks} blocks @ {n_vals} vals, cross-block "
                  f"accumulator w={win_acc}"
                  + (f", churn@{churn}" if churn else "") + ")",
        "value": round(n_blocks / warm, 1),
        "unit": "blocks/s",
        "vs_baseline": round(per_block / warm, 2),
        "vs_window_baseline": round(warm_win / warm, 2),
        "batched_sigs_per_s": round(n_lanes / warm, 1),
        "window_sigs_per_s": round(n_lanes / warm_win, 1),
        "unbatched_sigs_per_s": round(n_lanes / per_block, 1),
        "mesh_occupancy": round(occupancy, 4),
        "verify_window": win_acc,
        "window_baseline": win_base,
        "batched_warm_s": round(warm, 3),
        "batched_cold_s": round(cold, 3),
        "window_warm_s": round(warm_win, 3),
        "per_block_s": round(per_block, 3),
        "lanes": n_lanes,
        "valset_windows": len(runs),
        "backend": backend,
    }
    out_path = os.environ.get(
        "BENCH_OUT", os.path.join(REPO, "docs", "bench",
                                  "r13-blocksync-mesh-cpu.json"))
    try:
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
        note(f"wrote {out_path}")
    except OSError as e:
        note(f"could not write {out_path}: {e}")
    print(json.dumps(result), flush=True)


def _child_verifycommit(backend: str, n_vals: int) -> None:
    """One VerifyCommitLight call at commit scale (BASELINE configs[2]:
    150-validator commit, CPU vs TPU backend through the seam)."""
    note, kernel_backend = _mode_child_setup("vc", backend)

    from cometbft_tpu.testing import make_light_chain
    from cometbft_tpu.types.validation import VerifyCommitLight

    note(f"building one commit @ {n_vals} validators")
    lb = make_light_chain(1, n_vals=n_vals)[0]

    note("seam verification (cold: includes compile)")
    cold, warm = _timed_cold_warm(lambda: VerifyCommitLight(
        "light-chain", lb.validators, lb.commit.block_id, lb.height,
        lb.commit, backend=kernel_backend))

    # Reference-faithful baseline: verifyCommitSingle's per-signature
    # loop (types/validation.go:303 — sign-bytes per lane + one verify
    # each), like the commit mode.  vs_baseline is that speedup / 2, the
    # curve25519-voi CPU-batch estimate — NOT a self-comparison (the r3
    # artifact divided two runs of the same RLC path, so its 0.9 was
    # noise around 1.0 by construction, not a deficit vs the reference).
    note("host baseline: reference-style single-verify loop")
    sigs = lb.commit.signatures
    # same early-exit semantics as the measured path (verifyCommitSingle
    # with countAllSignatures=false stops once tally > 2/3), and min over
    # 3 passes like _single_verify_us so one noisy pass can't inflate
    # the ratio
    needed = lb.validators.total_voting_power() * 2 // 3

    def single_loop():
        tally = 0
        for idx, cs in enumerate(sigs):
            if not cs.is_commit():
                continue
            val = lb.validators.get_by_index(idx)
            msg = lb.commit.vote_sign_bytes("light-chain", idx)
            if not val.pub_key.verify_signature(msg, cs.signature):
                raise RuntimeError("baseline verify failed")
            tally += val.voting_power
            if tally > needed:
                break

    single = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        single_loop()
        single = min(single, time.perf_counter() - t0)
    vs_single = single / warm

    print(json.dumps({
        "metric": f"VerifyCommitLight latency ({n_vals}-validator commit)",
        "value": round(warm * 1e3, 3),
        "unit": "ms",
        "vs_baseline": round(vs_single / 2.0, 2),
        "vs_single_loop": round(vs_single, 2),
        "vs_reference_batch_est": round(vs_single / 2.0, 2),
        "cold_s": round(cold, 3),
        "single_loop_s": round(single, 4),
        "backend": backend,
    }), flush=True)


def _child_stress(backend: str, n_vals: int, secp_pct: int) -> None:
    """BASELINE configs[5]: ExtendedCommit-scale batch with vote
    extensions and mixed secp256k1 keys.  Two signatures per validator
    (precommit + extension); ed25519 lanes ride the device, secp256k1
    lanes take the CPU route inside the same TpuBatchVerifier — the
    mixed-routing improvement over the reference's refusal to batch
    mixed key sets (types/validation.go:13-19)."""
    note, kernel_backend = _mode_child_setup("stress", backend)

    from cometbft_tpu.crypto.batch import create_batch_verifier
    from cometbft_tpu.crypto.keys import Ed25519PrivKey
    from cometbft_tpu.crypto.secp256k1 import Secp256k1PrivKey
    from cometbft_tpu.types.block_id import BlockID, PartSetHeader
    from cometbft_tpu.types.canonical import (
        canonical_vote_extension_sign_bytes, canonical_vote_sign_bytes)
    from cometbft_tpu.types.vote import PRECOMMIT_TYPE

    n_secp = n_vals * secp_pct // 100
    note(f"building {n_vals}-val extended commit ({n_secp} secp256k1)")
    bid = BlockID(b"\x11" * 32, PartSetHeader(1, b"\x22" * 32))
    items = []                      # (pub, msg, sig) x2 per validator
    for i in range(n_vals):
        if i < n_secp:
            priv = Secp256k1PrivKey.from_secret(b"stress%d" % i)
        else:
            priv = Ed25519PrivKey.from_secret(b"stress%d" % i)
        sb = canonical_vote_sign_bytes("stress", PRECOMMIT_TYPE, 5, 0,
                                       bid, 1_700_000_000_000_000_000 + i)
        eb = canonical_vote_extension_sign_bytes("stress", 5, 0,
                                                 b"ext%d" % i)
        items.append((priv.pub_key(), sb, priv.sign(sb)))
        items.append((priv.pub_key(), eb, priv.sign(eb)))

    def run_batch():
        bv = create_batch_verifier(kernel_backend)
        for pub, msg, sig in items:
            bv.add(pub, msg, sig)
        ok, _ = bv.verify()
        assert ok

    note("mixed batch verification (cold: includes compile)")
    cold, warm = _timed_cold_warm(run_batch)

    note("host baseline (single verifies, stride-sampled so the "
         "key-type mix matches the batch)")
    sample = items[::max(1, len(items) // 512)]
    t0 = time.perf_counter()
    for pub, msg, sig in sample:
        assert pub.verify_signature(msg, sig)
    host = (time.perf_counter() - t0) / len(sample) * len(items)

    print(json.dumps({
        "metric": f"mixed-key extended-commit verify ({n_vals} vals, "
                  f"{secp_pct}% secp256k1, 2 sigs/val)",
        "value": round(len(items) / warm, 1),
        "unit": "sigs/s",
        "vs_baseline": round(host / warm, 2),
        "p50_batch_latency_ms": round(warm * 1e3, 3),
        "cold_s": round(cold, 3),
        "backend": backend,
    }), flush=True)


def _child_merkle(backend: str, n_leaves: int, block_kb: int) -> None:
    """Merkle subsystem bench: 10k-leaf root+proofs build and a part-set
    proof build, production dispatch vs the recursive hashlib reference
    (the seed implementation).  On an accelerator backend the level
    kernel engages through the normal gate; on cpu the native/hashlib
    engines serve (the kernel measured slower than hashlib on host)."""
    import numpy as np

    def note(msg):
        print(f"[bench:merkle:{backend}] {msg}", file=sys.stderr, flush=True)

    if backend == "cpu":
        from cometbft_tpu.jaxenv import force_cpu_backend

        force_cpu_backend()
    else:
        from cometbft_tpu.jaxenv import enable_compile_cache

        enable_compile_cache()
        import jax

        if jax.devices()[0].platform == "cpu":
            raise RuntimeError("requested accelerator but got CPU backend")

    from cometbft_tpu.crypto import merkle
    from cometbft_tpu.types.part_set import PartSet

    rng = np.random.default_rng(2024)
    leaves = [rng.integers(0, 256, 32, dtype=np.uint8).tobytes()
              for _ in range(n_leaves)]

    def best(fn, reps=5):
        t = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            t = min(t, time.perf_counter() - t0)
        return t

    note(f"{n_leaves}-leaf root+proofs: production dispatch vs recursive")
    ref_root, _ = merkle.proofs_from_byte_slices_reference(leaves)
    root, _ = merkle.proofs_from_byte_slices(leaves)
    assert root == ref_root, "engine mismatch — dispatch is NOT bit-identical"
    t_batched = best(lambda: merkle.proofs_from_byte_slices(leaves))
    t_recursive = best(lambda: merkle.proofs_from_byte_slices_reference(
        leaves))

    note("root-only (app-hash shape)")
    t_root = best(lambda: merkle.hash_from_byte_slices_fast(leaves))
    t_root_ref = best(lambda: merkle.hash_from_byte_slices(leaves))

    note(f"part-set proof build ({block_kb} kB block, 1 kB parts)")
    data = rng.integers(0, 256, block_kb * 1024, dtype=np.uint8).tobytes()
    chunks = [data[i:i + 1024] for i in range(0, len(data), 1024)]
    t_ps = best(lambda: PartSet.from_data(data, part_size=1024))
    t_ps_ref = best(lambda: merkle.proofs_from_byte_slices_reference(chunks))

    print(json.dumps({
        "metric": f"merkle {n_leaves}-leaf root+proofs build "
                  "(level-order dispatch vs recursive hashlib)",
        "value": round(t_batched * 1e3, 3),
        "unit": "ms",
        "vs_baseline": round(t_recursive / t_batched, 2),
        "recursive_ms": round(t_recursive * 1e3, 3),
        "root_only_ms": round(t_root * 1e3, 3),
        "root_only_vs_recursive": round(t_root_ref / t_root, 2),
        "partset_build_ms": round(t_ps * 1e3, 3),
        "partset_vs_recursive": round(t_ps_ref / t_ps, 2),
        "n_leaves": n_leaves,
        "backend": backend,
    }), flush=True)


def _child_p50commit(backend: str, n_vals: int) -> None:
    """BASELINE's latency bar: p50 VerifyCommit @10k validators < 5 ms.
    Times the PRODUCTION dense dispatch (``crypto/batch.verify_dense``
    with the whole-valset cached-table route) end to end — host packing,
    coefficient draw, transfer, kernel, sync — and reports a
    pack/dispatch breakdown so the next latency fix targets the
    measured stage (VERDICT r4 next 4)."""
    note, kernel_backend = _mode_child_setup("p50", backend)

    import numpy as np

    from cometbft_tpu.crypto import batch as cb
    from cometbft_tpu.testing import dense_signature_batch

    note(f"building {n_vals}-validator commit-shaped batch")
    args, host_items = dense_signature_batch(n_vals, msg_len=120, seed=77,
                                             n_keys=min(n_vals, 256))
    pubs = np.asarray(args[0], np.uint8)
    sigs = np.concatenate([np.asarray(args[1], np.uint8),
                           np.asarray(args[2], np.uint8)], axis=1)
    msgs = np.stack([np.frombuffer(m, np.uint8).copy()
                     for _, m, _ in host_items])
    lens = np.full((n_vals,), msgs.shape[1], np.int64)
    # a REAL 10k valset has 10k distinct rows; the signing keys repeat
    # (sign cost), but the pubkey matrix identity drives the table cache
    scope = np.arange(n_vals, dtype=np.int64)

    def one_commit():
        out = cb.verify_dense(kernel_backend, pubs, sigs, msgs, lens,
                              valset_pubs=pubs, scope=scope)
        assert out is not None and out[0], "commit batch failed"

    note("cold call (compiles + builds the valset table)")
    cold, _ = _timed_cold_warm(one_commit)
    note(f"cold took {cold:.1f}s; timing warm commits")
    reps = int(os.environ.get("BENCH_REPS", "15"))
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        one_commit()
        times.append(time.perf_counter() - t0)
    p50 = float(np.percentile(times, 50))

    # breakdown (device path only — the native CPU route never packs
    # lane matrices): host packing (lane padding + SHA block assembly +
    # RLC coefficient draw) vs everything after dispatch, over the SAME
    # chunk sequence the measured commit actually runs (n_vals > the
    # lane cap dispatches several chunks, each paying its own pack)
    pack_ms = dispatch_ms = None
    if kernel_backend != "cpu":
        cap = cb._LANE_BUCKETS[-1]
        t0 = time.perf_counter()
        for _ in range(reps):
            for start in range(0, n_vals, cap):
                end = min(start + cap, n_vals)
                bb = cb._chunk_bucket(end - start, ())
                sl = slice(start, end)
                cb._padded_lane_args(pubs[sl], sigs[sl, :32],
                                     sigs[sl, 32:], msgs[sl], lens[sl], bb)
                cb._rlc_args(bb, end - start)
        pack_ms = round((time.perf_counter() - t0) / reps * 1e3, 3)
        dispatch_ms = round(p50 * 1e3 - pack_ms, 3)

    print(json.dumps({
        "metric": f"p50 VerifyCommit latency @{n_vals} validators "
                  f"(production dense dispatch)",
        "value": round(p50 * 1e3, 3),
        "unit": "ms",
        # BASELINE bar: < 5 ms p50; >1 means the bar is met
        "vs_baseline": round(5.0 / (p50 * 1e3), 3),
        "p50_ms": round(p50 * 1e3, 3),
        "p90_ms": round(float(np.percentile(times, 90)) * 1e3, 3),
        "pack_ms": pack_ms,
        "dispatch_ms": dispatch_ms,
        "cold_s": round(cold, 3),
        "n_validators": n_vals,
        "backend": backend,
    }), flush=True)


def _child_mesh(backend: str, out_path: str) -> None:
    """True-SPMD mesh bench (r19): every number measured from INSIDE the
    timed pass of the production dispatch, on ONE sharded program per
    bucket over an explicit device mesh.

    Sections of the artifact:
    - weak_scaling: the same per-device lane load (BENCH_MESH_LANES,
      default 256) over 1/2/4/8 devices (CPU host-device emulation
      locally, real chips when present) — per-bucket p50, occupancy,
      sigs/s.
    - window: the staged-window lane count the mesh-aware blocksync
      accumulator produces (plan.window_blocks) and its full-mesh
      occupancy (acceptance: >= 0.85).
    - equal_work_guard: the full-mesh lane count dispatched sharded vs
      single-device; the child EXITS NONZERO if sharded is slower than
      BENCH_MESH_TOL x single (default 1.25 on CPU emulation, 1.0 on a
      real accelerator).
    - commit10k: the BASELINE headline — p50 VerifyCommit @10k
      validators through the cached-valset route at full mesh width,
      recorded against the <5ms / >=20x-Go-batch targets.
    - first_dispatch: a sharded rlc bundle built here must load in a
      FRESH process and dispatch < 1s on the PR 5
      crypto_kernel_first_dispatch_seconds gauge.

    TPU projection methodology (for the committed CPU artifact): the
    emulated host devices SHARE the box's physical cores, so
    per-dispatch latency cannot drop with mesh width here — on CPU the
    weak-scaling curve validates that the sharded program adds no
    overhead (flat-ish p50 at D x the work = near-linear weak scaling),
    and the equal-work guard enforces the invariant that must hold on
    any backend.  The <5ms absolute bar is a per-chip-throughput
    number: project it from a real chip's single-device sigs/s times
    the mesh width (lanes are independent; the RLC fold crosses
    O(windows) points per verdict), then confirm on hardware with this
    same mode, which runs unchanged on a TPU host.
    """
    counts = sorted({int(x) for x in os.environ.get(
        "BENCH_MESH_COUNTS", "1,2,4,8").split(",") if int(x) > 0})
    if backend == "cpu":
        # BEFORE any jax import: the weak-scaling sweep needs emulated
        # host devices on a CPU-only box
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count"
                f"={max(counts)}").strip()
    note, _ = _mode_child_setup("mesh", backend)

    import dataclasses
    import tempfile

    import jax
    import numpy as np

    from cometbft_tpu.crypto import aotbundle
    from cometbft_tpu.crypto import batch as cb
    from cometbft_tpu.crypto import plan as deviceplan
    from cometbft_tpu.testing import dense_signature_batch

    ndev = len(jax.devices())
    counts = [c for c in counts if c <= ndev] or [1]
    per_dev = int(os.environ.get("BENCH_MESH_LANES", "256"))
    reps = int(os.environ.get("BENCH_MESH_REPS", "7"))
    max_d = max(counts)
    max_lanes = per_dev * max_d
    note(f"devices={ndev} counts={counts} per_device_lanes={per_dev}")

    note(f"building {max_lanes}-lane all-valid batch")
    args, items = dense_signature_batch(max_lanes, msg_len=120, seed=19,
                                        n_keys=256)
    pubs = np.asarray(args[0], np.uint8)
    rs8 = np.asarray(args[1], np.uint8)
    ss8 = np.asarray(args[2], np.uint8)
    msgs = np.stack([np.frombuffer(m, np.uint8).copy()
                     for _, m, _ in items])
    lens = np.full((max_lanes,), msgs.shape[1], np.int64)

    def set_mesh(d):
        deviceplan.configure(mesh_shape=(d,) if d > 1 else ())

    def run_lanes(n):
        out = cb.device_verify_ed25519(pubs[:n], rs8[:n], ss8[:n],
                                       msgs[:n], lens[:n])
        assert bool(out.all()), "all-valid batch rejected"

    def timed_pass(fn):
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            times.append(time.perf_counter() - t0)
        return (float(np.percentile(times, 50)),
                float(np.percentile(times, 90)))

    # ---- weak scaling: per-device load held constant over mesh width
    weak = []
    for d in counts:
        set_mesh(d)
        lanes = per_dev * d
        bb = deviceplan.chunk_bucket(
            lanes, tuple(range(d)) if d > 1 else ())
        note(f"[weak] D={d} lanes={lanes} bucket={bb}: cold dispatch")
        cold, _ = _timed_cold_warm(lambda: run_lanes(lanes))
        p50, p90 = timed_pass(lambda: run_lanes(lanes))
        weak.append({
            "devices": d, "lanes": lanes, "bucket": bb,
            "occupancy": round(deviceplan.mesh_occupancy(lanes, d), 4),
            "cold_s": round(cold, 3),
            "p50_ms": round(p50 * 1e3, 3),
            "p90_ms": round(p90 * 1e3, 3),
            "sigs_per_s": round(lanes / p50, 1),
        })
        note(f"[weak] D={d} p50={p50 * 1e3:.2f}ms "
             f"{lanes / p50:,.0f} sigs/s")
    for w in weak:
        w["scaling_vs_1dev"] = round(
            w["sigs_per_s"] / weak[0]["sigs_per_s"], 3)

    # ---- the blocksync staged-window workload at full mesh width
    set_mesh(max_d)
    bs_vals = int(os.environ.get("BENCH_MESH_WINDOW_VALS", "100"))
    bs_window = int(os.environ.get("BENCH_MESH_WINDOW", "32"))
    blocks = deviceplan.window_blocks(bs_window, bs_vals)
    win_lanes = blocks * bs_vals
    window = {
        "verify_window": bs_window, "n_vals": bs_vals,
        "staged_blocks": blocks, "lanes": win_lanes,
        "occupancy": round(
            deviceplan.mesh_occupancy(win_lanes, max_d), 4),
    }
    note(f"[window] {bs_window} cfg blocks x {bs_vals} vals -> "
         f"{blocks} staged blocks, occupancy {window['occupancy']}")

    # ---- equal-work guard: full-mesh lanes, sharded vs single-device
    tol = float(os.environ.get(
        "BENCH_MESH_TOL", "1.25" if backend == "cpu" else "1.0"))
    sharded_p50 = weak[-1]["p50_ms"]
    set_mesh(1)
    note(f"[guard] single-device equal work: {max_lanes} lanes")
    _timed_cold_warm(lambda: run_lanes(max_lanes))
    sp50, _ = timed_pass(lambda: run_lanes(max_lanes))
    guard = {
        "lanes": max_lanes,
        "sharded_p50_ms": sharded_p50,
        "single_p50_ms": round(sp50 * 1e3, 3),
        "tol": tol,
        "ratio": round(sharded_p50 / (sp50 * 1e3), 3),
        "ok": bool(sharded_p50 <= tol * sp50 * 1e3),
    }
    note(f"[guard] sharded/single = {guard['ratio']} (tol {tol})")

    # ---- BASELINE headline: 10k-validator commit p50, cached route
    n_vals = int(os.environ.get("BENCH_MESH_VALS", "10000"))
    commit = None
    if n_vals > 0:
        note(f"[commit] building {n_vals}-validator commit batch")
        cargs, citems = dense_signature_batch(n_vals, msg_len=120,
                                              seed=77, n_keys=256)
        cp = np.asarray(cargs[0], np.uint8)
        cr = np.asarray(cargs[1], np.uint8)
        cs = np.asarray(cargs[2], np.uint8)
        cm = np.stack([np.frombuffer(m, np.uint8).copy()
                       for _, m, _ in citems])
        cl = np.full((n_vals,), cm.shape[1], np.int64)
        scope = np.arange(n_vals, dtype=np.int64)

        def one_commit():
            out = cb.device_verify_ed25519_cached(cp, scope, cp, cr, cs,
                                                  cm, cl)
            assert bool(out.all()), "commit batch rejected"

        commit = {"n_validators": n_vals, "target_p50_ms": 5.0,
                  "target_vs_go_batch": 20.0}
        for tag, d in (("single", 1), ("sharded", max_d)):
            set_mesh(d)
            note(f"[commit] {tag} D={d}: cold (table + compiles)")
            cold, _ = _timed_cold_warm(one_commit)
            note(f"[commit] {tag} cold {cold:.1f}s; timing")
            p50, p90 = timed_pass(one_commit)
            commit[tag] = {
                "devices": d,
                "p50_ms": round(p50 * 1e3, 3),
                "p90_ms": round(p90 * 1e3, 3),
                "cold_s": round(cold, 3),
            }
            note(f"[commit] {tag} p50={p50 * 1e3:.2f}ms")
        commit["vs_target"] = round(
            5.0 / commit["sharded"]["p50_ms"], 4)
        commit["sharded_vs_single"] = round(
            commit["single"]["p50_ms"] / commit["sharded"]["p50_ms"], 3)

    # ---- PR 5 gauge: sharded bundle loads warm in a FRESH process
    first = None
    if max_d > 1 and int(os.environ.get("BENCH_MESH_GAUGE", "1")):
        set_mesh(max_d)
        # TWO sharded buckets: the rlc executable is the production
        # target but its serialized form can hit the known XLA CPU
        # deserialize quirk ("Symbols not found") in a fresh process —
        # in which case it reports degraded:deserialize (by design) and
        # the merkle bucket carries the warm-load proof instead
        gplan = dataclasses.replace(
            deviceplan.active(), warm_kinds=("rlc",), warm_tables=(),
            warm_merkle=(max_lanes,), warm_lanes=(max_lanes,),
            warm_blocks=(2,))
        with tempfile.TemporaryDirectory(prefix="bench-mesh-aot-") as td:
            bpath = os.path.join(td, "bundle.aot")
            t0 = time.perf_counter()
            binfo = aotbundle.build(plan=gplan, path=bpath)
            t_build = time.perf_counter() - t0
            note(f"[gauge] sharded bundle build {t_build:.1f}s "
                 f"-> {binfo['buckets']}")
            if "warm" in binfo["buckets"].values():
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--_mesh_gauge", bpath, str(max_d), str(max_lanes)],
                    env=dict(os.environ), timeout=300,
                    stdout=subprocess.PIPE, stderr=sys.stderr)
                parsed = None
                for line in reversed(
                        proc.stdout.decode(errors="replace").splitlines()):
                    if line.strip().startswith("{"):
                        parsed = json.loads(line)
                        break
                if parsed and parsed.get("seconds") is not None:
                    first = {
                        "key": parsed.get("key"),
                        "build_s": round(t_build, 2),
                        "fresh_process_first_dispatch_s":
                            round(parsed["seconds"], 4),
                        "warm": bool(parsed["seconds"] < 1.0),
                        "bucket_statuses": parsed.get("buckets"),
                    }
                    note(f"[gauge] fresh-process first dispatch "
                         f"{parsed['seconds'] * 1e3:.1f}ms via "
                         f"{parsed.get('key')}")
    set_mesh(1)

    top = weak[-1]
    doc = {
        "metric": ("sharded SPMD verify: full-mesh sigs/s, ONE dispatch "
                   f"over {max_d} devices (weak-scaling workload)"),
        "value": top["sigs_per_s"],
        "unit": "sigs/s",
        # the invariant every backend must hold: sharded >= single-device
        # throughput at equal work (>1 = sharding helps outright)
        "vs_baseline": round(
            guard["single_p50_ms"] / guard["sharded_p50_ms"], 3),
        "weak_scaling": weak,
        "window": window,
        "equal_work_guard": guard,
        "commit10k": commit,
        "first_dispatch": first,
        "devices_visible": ndev,
        "per_device_lanes": per_dev,
        "reps": reps,
        "projection": (
            "CPU host-device emulation shares the box's cores, so "
            "per-dispatch latency cannot drop with mesh width here; "
            "project chip throughput as single-device sigs/s x mesh "
            "width (lanes independent, RLC fold crosses O(windows) "
            "points), then confirm on hardware with this same mode."),
        "backend": backend,
    }
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps(doc), flush=True)
    if not guard["ok"]:
        note("EQUAL-WORK GUARD FAILED: sharded slower than single")
        sys.exit(3)


def _mesh_gauge_child(path: str, nd: int, lanes: int) -> None:
    """Fresh-process half of the mesh bench's first-dispatch proof."""
    import dataclasses

    from cometbft_tpu.jaxenv import enable_compile_cache, harden_cpu_pinned_env

    harden_cpu_pinned_env()
    enable_compile_cache()

    from cometbft_tpu.crypto import aotbundle
    from cometbft_tpu.crypto import plan as deviceplan
    from cometbft_tpu.libs import metrics

    plan = dataclasses.replace(
        deviceplan.DevicePlan(), warm_kinds=("rlc",), warm_tables=(),
        warm_merkle=(lanes,), warm_lanes=(lanes,), warm_blocks=(2,),
        mesh_shape=(nd,))
    info = aotbundle.load(path=path, plan=plan)
    # prefer the production rlc executable; fall back to the merkle
    # bucket when rlc hit the fresh-process deserialize quirk (its
    # status then reads degraded:deserialize — reported upstream)
    candidates = (
        (f"rlc:{lanes}x2@m{nd}", deviceplan.CompileBucket("rlc", lanes, 2)),
        (f"merkle_level:{lanes}@m{nd}",
         deviceplan.CompileBucket("merkle_level", lanes)),
    )
    hit, secs = None, None
    if info["status"] == "loaded":
        for key, bucket in candidates:
            if info["buckets"].get(key) != "warm":
                continue
            aotbundle.timed_call(key, *aotbundle.sample_args(bucket))
            g = metrics.gauge("crypto_kernel_first_dispatch_seconds", "")
            hit = key
            secs = g.value(kind=bucket.kind, lanes=str(lanes))
            break
    print(json.dumps({"loaded": info["status"] == "loaded", "key": hit,
                      "seconds": secs, "buckets": info.get("buckets")}),
          flush=True)


def _child_node(rate: float, duration_s: float, tx_size: int) -> None:
    """Single-node end-to-end throughput: one validator committing load
    txs through the FULL stack (RPC -> mempool -> consensus -> ABCI
    kvstore -> storage).  Reference baseline: ~700-723 tx/s single-node
    (docs/references/storage/README.md:193)."""
    import shutil
    import tempfile

    def note(msg):
        print(f"[bench:node] {msg}", file=sys.stderr, flush=True)

    base = tempfile.mkdtemp(prefix="bench-node-")
    home = os.path.join(base, "n0")
    try:
        from cometbft_tpu import loadtime
        from cometbft_tpu.config import test_consensus_config
        from cometbft_tpu.e2e.gen import HomeSpec, generate_homes
        from cometbft_tpu.rpc import HTTPClient

        rpc_port = int(os.environ.get("BENCH_NODE_RPC", "28657"))
        # unique per run so the readiness check can DETECT a stale node
        # from a previous run squatting on the port
        chain_id = f"bench-node-{os.getpid()}"

        def tweak(spec, cfg):
            cfg.base.signature_backend = "cpu"
            cfg.consensus = test_consensus_config()
            cfg.mempool.size = 20000

        generate_homes(base, [HomeSpec(name="n0", p2p_port=rpc_port - 1,
                                       rpc_port=rpc_port, power=10)],
                       chain_id, tweak=tweak)
        note("starting node process")
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        # `timeout` wrapper: even if this child is SIGKILLed (parent
        # attempt timeout), the node cannot outlive the run and squat on
        # the port for the next one
        ttl = int(duration_s) + 120
        with open(os.path.join(base, "node.log"), "ab") as lf:
            proc = subprocess.Popen(
                ["timeout", str(ttl), sys.executable, "-m",
                 "cometbft_tpu", "--home", home, "start"],
                stdout=lf, stderr=subprocess.STDOUT, env=env, cwd=REPO)
        try:
            import asyncio

            conns = int(os.environ.get("BENCH_NODE_CONNS", "8"))
            batch = int(os.environ.get("BENCH_NODE_BATCH", "4"))

            async def drive():
                cli = HTTPClient("127.0.0.1", rpc_port)
                for _ in range(120):           # wait for RPC
                    try:
                        st = await cli.call("status")
                        if st["node_info"]["network"] != chain_id:
                            # a STALE node from another run holds the
                            # port: driving it would record a bogus 0
                            raise RuntimeError(
                                f"port {rpc_port} is serving chain "
                                f"{st['node_info']['network']!r}, not "
                                f"the bench node")
                        break
                    except RuntimeError:
                        raise
                    except Exception:
                        await asyncio.sleep(0.25)
                else:
                    raise RuntimeError(
                        "bench node RPC never came up (see node.log)")
                note(f"driving {rate:.0f} tx/s for {duration_s:.0f}s "
                     f"({tx_size}B txs, {conns} connections, "
                     f"batch {batch})")
                gen = await loadtime.generate(cli, rate, duration_s,
                                              tx_size=tx_size,
                                              connections=conns,
                                              batch=batch)
                # let the backlog commit: a saturating drive leaves a
                # mempool tail, and counting only the mid-drive window
                # would understate committed throughput
                for _ in range(60):
                    un = await cli.call("num_unconfirmed_txs")
                    if int(un.get("n_txs", 0)) == 0:
                        break
                    await asyncio.sleep(0.5)
                await asyncio.sleep(1.0)
                rep = await loadtime.report(cli, run_id=gen["run_id"])
                return gen, rep

            gen, rep = asyncio.run(drive())
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        tput = rep.get("throughput_tx_s") or 0.0
        print(json.dumps({
            "metric": f"single-node end-to-end throughput "
                      f"({tx_size}B txs, builtin kvstore)",
            "value": tput,
            "unit": "tx/s",
            # reference storage study: ~700 tx/s single node
            "vs_baseline": round(tput / 700.0, 2),
            "sent": gen["sent"],
            "send_errors": gen["errors"],
            "committed": rep.get("txs", 0),
            "p50_latency_s": rep.get("p50_s"),
            "p99_latency_s": rep.get("p99_s"),
            "blocks": rep.get("blocks"),
            "load_connections": conns,
            "load_batch": batch,
            "backend": "cpu",
        }), flush=True)
    finally:
        shutil.rmtree(base, ignore_errors=True)


def _child_lightserve(n_clients: int, n_conns: int, n_txs: int,
                      proofs_per_req: int) -> None:
    """Light-serving tier under a simulated skipping-client fleet: one
    validator node serves ``n_clients`` logical light clients (each a
    coroutine doing the real bootstrap round trips — a batched
    ``light_blocks`` fetch, a ``light_proofs`` batch, and a
    ``light_verify`` trust-anchor check — multiplexed over ``n_conns``
    keep-alive connections), while a prober hits ``/status`` throughout.

    Reports proofs/s and request p50/p99, the /status latency under
    load (the admission gate + worker-thread discipline is what keeps it
    flat), the tier's cache hit tallies, and ``vs_baseline``: the
    server-side cost of the SAME proof workload through the per-proof
    re-hash baseline (one reference tree build per proof — the seed's
    ``_tx_proof_provider`` shape without a cache) over the tier's
    cached-tree batch path."""
    import asyncio

    def note(msg):
        print(f"[bench:light-serve] {msg}", file=sys.stderr, flush=True)

    from cometbft_tpu.jaxenv import force_cpu_backend

    force_cpu_backend()

    import numpy as np

    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.config import Config, test_consensus_config
    from cometbft_tpu.crypto import merkle
    from cometbft_tpu.node import Node
    from cometbft_tpu.rpc import HTTPClient
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.priv_validator import MockPV

    async def drive() -> dict:
        cfg = Config(consensus=test_consensus_config())
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        cfg.mempool.size = max(20000, n_txs * 2)
        cfg.base.signature_backend = "cpu"
        pv = MockPV.from_secret(b"bench-lightserve")
        doc = GenesisDoc(chain_id="bench-ls",
                         validators=[GenesisValidator(pv.get_pub_key(),
                                                      10)])
        node = await Node.create(doc, KVStoreApplication(),
                                 priv_validator=pv, config=cfg,
                                 name="bench-ls")
        await node.start()
        try:
            note(f"seeding a block with {n_txs} txs")
            for i in range(n_txs):
                await node.mempool.check_tx(b"bls%d=v" % i)
            deadline = time.monotonic() + 60
            tx_height, tx_count = None, 0
            while time.monotonic() < deadline:
                await asyncio.sleep(0.05)
                if node.mempool.size() == 0 and \
                        node.block_store.height() >= 2:
                    for h in range(1, node.block_store.height() + 1):
                        blk = node.block_store.load_block(h)
                        if blk is not None and len(blk.data.txs) > tx_count:
                            tx_height, tx_count = h, len(blk.data.txs)
                    break
            if tx_height is None:
                raise RuntimeError("seed txs never committed")
            # one more height so tx_height's commit is canonical
            target = node.block_store.height() + 1
            while node.block_store.height() < target and \
                    time.monotonic() < deadline:
                await asyncio.sleep(0.05)
            note(f"serving block: height {tx_height} with {tx_count} txs")

            host, port = node.rpc_addr
            tip = node.block_store.height()
            boot_heights = list(range(max(1, tip - 7), tip + 1))
            cli0 = HTTPClient(host, port)
            ent = await cli0.call("light_block", height=tx_height)
            hot_anchors = [{"height": tx_height,
                            "commit": ent["light_block"]["commit"]}]
            rng = np.random.default_rng(2026)
            idx_sets = [sorted(rng.choice(tx_count,
                                          size=min(proofs_per_req,
                                                   tx_count),
                                          replace=False).tolist())
                        for _ in range(64)]

            lat = {"light_blocks": [], "light_proofs": [],
                   "light_verify": []}
            served = {"proofs": 0}
            clients = [HTTPClient(host, port) for _ in range(n_conns)]

            async def one_client(i: int) -> None:
                cli = clients[i % n_conns]
                t0 = time.perf_counter()
                await cli.call("light_blocks", heights=boot_heights)
                lat["light_blocks"].append(time.perf_counter() - t0)
                t0 = time.perf_counter()
                pr = await cli.call("light_proofs", height=tx_height,
                                    kind="tx",
                                    indexes=idx_sets[i % len(idx_sets)])
                lat["light_proofs"].append(time.perf_counter() - t0)
                served["proofs"] += len(pr["proofs"])
                t0 = time.perf_counter()
                await cli.call("light_verify",
                               anchors=[hot_anchors[0]])
                lat["light_verify"].append(time.perf_counter() - t0)

            status_lat = []
            stop_probe = asyncio.Event()

            async def probe_status() -> None:
                pc = HTTPClient(host, port)
                while not stop_probe.is_set():
                    t0 = time.perf_counter()
                    await pc.call("status")
                    status_lat.append(time.perf_counter() - t0)
                    try:
                        await asyncio.wait_for(stop_probe.wait(), 0.05)
                    except asyncio.TimeoutError:
                        pass
                await pc.close()

            note(f"driving {n_clients} simulated skipping clients over "
                 f"{n_conns} connections (3 RPCs each)")
            prober = asyncio.create_task(probe_status())
            t_wall = time.perf_counter()
            await asyncio.gather(*(one_client(i)
                                   for i in range(n_clients)))
            t_wall = time.perf_counter() - t_wall
            stop_probe.set()
            await prober
            for c in clients:
                await c.close()

            st = await cli0.call("status")
            ls_stats = st.get("light_serve") or {}
            await cli0.close()

            # ---- server-side baseline: per-proof re-hash ----------------
            note("server-side baseline: per-proof re-hash vs cached tree")
            from cometbft_tpu.types.header import tx_hash as _txh

            blk = node.block_store.load_block(tx_height)
            leaves = [_txh(t) for t in blk.data.txs]
            idxs = idx_sets[0]
            tier = node.light_serve
            reps = 20

            t0 = time.perf_counter()
            for _ in range(reps):
                tier.proofs(tx_height, "tx", idxs)
            t_cached = (time.perf_counter() - t0) / reps

            t0 = time.perf_counter()
            for _ in range(3):
                for i in idxs:           # one full re-hash PER PROOF
                    _root, prs = merkle.proofs_from_byte_slices_reference(
                        leaves)
                    _ = prs[i]
            t_rehash = (time.perf_counter() - t0) / 3

            all_lat = sorted(lat["light_blocks"] + lat["light_proofs"]
                             + lat["light_verify"])
            nreq = len(all_lat)

            def pct(v, q):
                return float(np.percentile(v, q)) if v else 0.0

            return {
                "metric": f"light-serve proofs/s ({n_clients} simulated "
                          f"skipping clients, {tx_count}-tx block, "
                          f"{len(idxs)} proofs/req)",
                "value": round(served["proofs"] / t_wall, 1),
                "unit": "proofs/s",
                # per-proof re-hash baseline vs the cached-tree batch
                # path, same proof workload, measured server-side
                "vs_baseline": round(t_rehash / t_cached, 2),
                "requests_per_s": round(nreq / t_wall, 1),
                "p50_request_ms": round(pct(all_lat, 50) * 1e3, 2),
                "p99_request_ms": round(pct(all_lat, 99) * 1e3, 2),
                "p99_bootstrap_ms": round(
                    pct(lat["light_blocks"], 99) * 1e3, 2),
                "p99_proofs_ms": round(
                    pct(lat["light_proofs"], 99) * 1e3, 2),
                "p99_verify_ms": round(
                    pct(lat["light_verify"], 99) * 1e3, 2),
                "status_p99_ms": round(pct(status_lat, 99) * 1e3, 2),
                "status_max_ms": round(
                    max(status_lat) * 1e3 if status_lat else 0.0, 2),
                "status_samples": len(status_lat),
                "wall_s": round(t_wall, 3),
                "proofs_served": served["proofs"],
                "cached_batch_ms": round(t_cached * 1e3, 3),
                "rehash_batch_ms": round(t_rehash * 1e3, 3),
                "header_cache_hit_rate": round(
                    ls_stats.get("header_hits", 0)
                    / max(1, ls_stats.get("header_hits", 0)
                          + ls_stats.get("header_misses", 0)), 4),
                "verify_memo_hit_rate": round(
                    ls_stats.get("verify_hits", 0)
                    / max(1, ls_stats.get("verify_hits", 0)
                          + ls_stats.get("verify_misses", 0)), 4),
                "proof_cache_hits": ls_stats.get("proof_hits", 0),
                "clients": n_clients,
                "connections": n_conns,
                "txs_in_block": tx_count,
                "backend": "cpu",
            }
        finally:
            await node.stop()

    result = asyncio.run(drive())
    out_path = os.environ.get(
        "BENCH_OUT", os.path.join(REPO, "docs", "bench",
                                  "r14-light-serve-cpu.json"))
    try:
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
        note(f"wrote {out_path}")
    except OSError as e:
        note(f"could not write {out_path}: {e}")
    print(json.dumps(result), flush=True)


def _child_votegossip(backend: str, n_vals: int, dup_k: int,
                      n_slots: int) -> None:
    """Synthetic N-peer vote-gossip storm: every validator's precommit
    arrives ``dup_k`` times (re-gossip by k peers), across ``n_slots``
    height/round slots, each slot ending in a VerifyCommitLight over the
    assembled commit — the steady-state shape live consensus sees.

    Two passes over the identical stream:
    - per-vote baseline (today's default without a scheduler): each
      unique vote verifies one-at-a-time inside ``VoteSet.add_vote``;
      duplicates dedup in the vote set; the commit re-verifies every
      signature through the uncached dense batch.
    - scheduler path: all arrivals pre-verify concurrently through the
      coalescing ``VerificationScheduler`` (micro-batches through the
      routed BatchVerifier, in-flight dedup), then the same
      ``add_vote``/``VerifyCommitLight`` calls ride the verified-sig
      cache.

    Writes the JSON result to ``BENCH_OUT`` (default
    ``docs/bench/r07-vote-sched-cpu.json``) in addition to stdout."""
    note, kernel_backend = _mode_child_setup("votegossip", backend)

    import asyncio
    import random as _random

    from cometbft_tpu.crypto import scheduler as vsched
    from cometbft_tpu.crypto.keys import gen_priv_key
    from cometbft_tpu.types.block_id import BlockID
    from cometbft_tpu.types.part_set import PartSetHeader
    from cometbft_tpu.types.validation import VerifyCommitLight
    from cometbft_tpu.types.validator_set import Validator, ValidatorSet
    from cometbft_tpu.types.vote import PRECOMMIT_TYPE, Vote
    from cometbft_tpu.types.vote_set import VoteSet

    chain_id = "bench-votegossip"
    note(f"building {n_slots} slots x {n_vals} validators, "
         f"x{dup_k} gossip duplication")
    privs = [gen_priv_key() for _ in range(n_vals)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    by_addr = {p.pub_key().address(): p for p in privs}

    slots = []            # (events, commit, block_id) per slot
    rng = _random.Random(2026)
    for s in range(n_slots):
        height = s + 1
        bid = BlockID(bytes([s + 1]) * 32,
                      PartSetHeader(1, bytes([s + 2]) * 32))
        votes = []
        for i in range(n_vals):
            v = vals.get_by_index(i)
            vote = Vote(type=PRECOMMIT_TYPE, height=height, round=0,
                        block_id=bid, timestamp_ns=10_000 + i,
                        validator_address=v.address, validator_index=i)
            vote.signature = by_addr[v.address].sign(
                vote.sign_bytes(chain_id))
            votes.append(vote)
        vs = VoteSet(chain_id, height, 0, PRECOMMIT_TYPE, vals)
        for vote in votes:
            vs.add_vote(vote)
        commit = vs.make_commit()
        events = votes * dup_k
        rng.shuffle(events)
        slots.append((events, commit, bid, height))
    n_events = sum(len(ev) for ev, *_ in slots)

    def drive_stream() -> float:
        """One pass over every slot: add_vote per arrival + the final
        commit verification.  Identical call sequence in both passes —
        only the registered scheduler differs."""
        t0 = time.perf_counter()
        for events, commit, bid, height in slots:
            vs = VoteSet(chain_id, height, 0, PRECOMMIT_TYPE, vals)
            for vote in events:
                vs.add_vote(vote)
            VerifyCommitLight(chain_id, vals, bid, height, commit,
                              backend=kernel_backend)
        return time.perf_counter() - t0

    reps = int(os.environ.get("BENCH_VG_REPS", "5"))
    note(f"per-vote baseline pass (no scheduler), best of {reps}")
    assert vsched.get_scheduler() is None
    t_base = min(drive_stream() for _ in range(reps))

    async def sched_pass() -> tuple[float, dict]:
        sched = await vsched.acquire_scheduler(
            backend=kernel_backend, max_wait_ms=2.0, max_lanes=256)
        try:
            t0 = time.perf_counter()
            for events, commit, bid, height in slots:
                # concurrent arrival from k peers: every gossip copy is
                # submitted fire-and-forget like the reactor prefetch,
                # coalescing into micro-batches with in-flight dedup; one
                # barrier future stands in for the state queue
                loop = asyncio.get_running_loop()
                done = loop.create_future()
                remaining = len(events)

                def _arrived(_ok, _d=done):
                    nonlocal remaining
                    remaining -= 1
                    if remaining == 0 and not _d.done():
                        _d.set_result(None)

                for v in events:
                    sched.submit_nowait(
                        vals.get_by_index(v.validator_index).pub_key,
                        v.sign_bytes(chain_id), v.signature,
                        on_done=_arrived)
                await done
                vs = VoteSet(chain_id, height, 0, PRECOMMIT_TYPE, vals)
                for vote in events:
                    vs.add_vote(vote)       # cache hits
                VerifyCommitLight(chain_id, vals, bid, height, commit,
                                  backend=kernel_backend)
            dt = time.perf_counter() - t0
            return dt, sched.stats()
        finally:
            await vsched.release_scheduler()

    note(f"scheduler pass (coalescing + verified-sig cache), "
         f"best of {reps}")
    # best-of-N like the baseline (noise on a shared box must not decide
    # the comparison); each pass gets a FRESH scheduler + cache (stats
    # are per-instance), so every run re-verifies everything rather than
    # riding warm entries; the reported stats are the first pass's.
    t_sched, stats = asyncio.run(sched_pass())
    for _ in range(reps - 1):
        t2, _s2 = asyncio.run(sched_pass())
        t_sched = min(t_sched, t2)

    result = {
        "metric": f"vote-gossip verification storm, arrivals/sec "
                  f"({n_slots} slots x {n_vals} vals x{dup_k} dup, "
                  f"commit re-check included)",
        "value": round(n_events / t_sched, 1),
        "unit": "events/s",
        "vs_baseline": round(t_base / t_sched, 2),
        "baseline_events_per_s": round(n_events / t_base, 1),
        "baseline_s": round(t_base, 3),
        "scheduler_s": round(t_sched, 3),
        "cache_hit_rate": round(stats["cache_hit_rate"], 3),
        "dedup_inflight": stats["dedup_inflight"],
        "mean_batch_lanes": round(stats["mean_batch_lanes"], 1),
        "batches": stats["batches"],
        "n_events": n_events,
        "backend": backend,
    }
    out_path = os.environ.get(
        "BENCH_OUT", os.path.join(REPO, "docs", "bench",
                                  "r07-vote-sched-cpu.json"))
    try:
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
        note(f"wrote {out_path}")
    except OSError as e:
        note(f"could not write {out_path}: {e}")
    print(json.dumps(result), flush=True)


def _single_verify_us(host_items) -> float:
    """Single-verify baseline in us, min over 3 passes: a noisy shared
    box inflates one-shot timings, which would overstate vs_baseline (a
    faster batch number should come from the batch getting faster, not
    the baseline getting slower)."""
    from cometbft_tpu.crypto.keys import verify_ed25519_zip215

    sample = host_items[:min(256, len(host_items))]
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for pk, msg, sig in sample:
            assert verify_ed25519_zip215(pk, msg, sig)
        best = min(best, (time.perf_counter() - t0) / len(sample))
    return best * 1e6


def _child_scenarios(out_path: str) -> None:
    """``--mode scenarios``: sweep the scenario lab's curated suite
    (``cometbft_tpu.sim.scenario.curated_suite``) on the virtual clock,
    re-running the first scenario to enforce the replay contract, and
    write the full verdict JSON to ``out_path`` — the liveness analog
    of the perf guards: a regression that forks a net, loses recovery,
    or breaks replay determinism fails this run the same way a slow
    kernel fails a perf bar.

    The headline value is simulated-virtual-seconds per real second
    (how much adversarial time one CPU buys), but the pass/fail payload
    is the verdicts."""
    from cometbft_tpu.jaxenv import force_cpu_backend

    force_cpu_backend()
    from cometbft_tpu.sim.scenario import (chaos_signature_of,
                                           curated_suite, run_scenario)

    def note(msg):
        print(f"[bench:scenarios] {msg}", file=sys.stderr, flush=True)

    suite = curated_suite()
    only = os.environ.get("BENCH_SCENARIOS", "")
    if only:
        names = {n.strip() for n in only.split(",") if n.strip()}
        suite = [s for s in suite if s.name in names]
    verdicts = []
    failures_: list[str] = []
    total_virtual = 0.0
    t_all = time.perf_counter()
    replay_checked = False
    for scn in suite:
        note(f"running {scn.name} ({scn.n_nodes} nodes, "
             f"target h{scn.target_height})")
        t0 = time.perf_counter()
        if not replay_checked:
            v, sig1 = chaos_signature_of(scn)
            real_s = time.perf_counter() - t0
            # the replay double-run: its virtual seconds count toward
            # the headline total (the work really ran) but its real
            # time must not be billed to the scenario's own real_s
            v2, sig2 = chaos_signature_of(scn)
            if sig1 != sig2 or \
                    json.dumps(v, sort_keys=True) != \
                    json.dumps(v2, sort_keys=True):
                failures_.append(f"{scn.name}: replay diverged")
            total_virtual += v2["virtual_duration_s"]
            replay_checked = True
        else:
            v = run_scenario(scn)
            real_s = time.perf_counter() - t0
        v["real_s"] = round(real_s, 1)     # informational; excluded from
        # the replay compare above (which ran on the pristine dicts)
        verdicts.append(v)
        total_virtual += v["virtual_duration_s"]
        if not v["fork_free"]:
            failures_.append(f"{scn.name}: FORK")
        if not v["reached_target"]:
            failures_.append(
                f"{scn.name}: stuck at {v['common_height']}")
        note(f"  {scn.name}: h{v['common_height']} in "
             f"{v['virtual_duration_s']}s virtual / {real_s:.1f}s real, "
             f"fork_free={v['fork_free']}")
    real_total = time.perf_counter() - t_all
    doc = {"scenarios": verdicts, "failures": failures_,
           "real_total_s": round(real_total, 1),
           "virtual_total_s": round(total_virtual, 1)}
    if out_path:
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        note(f"verdicts -> {out_path}")
    print(json.dumps({
        "metric": f"scenario lab: adversarial virtual-seconds simulated "
                  f"per real second ({len(verdicts)} scenarios, "
                  f"fork-free + replay-identical required)",
        "value": round(total_virtual / max(real_total, 1e-9), 2),
        "unit": "virtual-s/s",
        "vs_baseline": 1.0 if not failures_ else 0.0,
        "scenarios_passed": len(verdicts) - len(
            {f.split(":")[0] for f in failures_}),
        "scenarios_total": len(verdicts),
        "failures": failures_,
        "virtual_total_s": round(total_virtual, 1),
        "real_total_s": round(real_total, 1),
        "backend": "cpu",
    }), flush=True)
    if failures_:
        raise SystemExit(1)


def _child_mempool(out_path: str) -> None:
    """``--mode mempool``: the r16 admission path under a signature-
    checking app — the mempool analog of the vote-gossip storm bench.

    Three measurements, one JSON:

    - **admission**: a seeded backlog of sig-carrying txs pushed through
      ``check_tx`` at high concurrency (sharded gates + per-shard
      CheckTx coalescer + VerificationScheduler micro-batching under
      the app).  Reports sustained admitted tx/s and p99 admission
      latency.
    - **recheck**: the same backlog rechecked two ways — the OLD serial
      loop (one awaited CheckTx per tx, direct single verification:
      exactly what ``update()`` did before r16) vs the batched pass
      (chunked concurrent CheckTx, signature checks coalesced into
      batch-verifier micro-batches).  The acceptance bar is >=2x.
    - **gossip bytes**: bytes-on-wire to re-gossip the whole pool to a
      peer set that ALREADY HOLDS every tx — full-body re-flood (old
      protocol) vs content-addressed announcements (32-byte hashes).
    """
    import asyncio

    from cometbft_tpu.jaxenv import force_cpu_backend

    force_cpu_backend()
    import msgpack

    from cometbft_tpu.abci.types import CheckTxResponse
    from cometbft_tpu.crypto import scheduler as vsched
    from cometbft_tpu.crypto.keys import Ed25519PrivKey
    from cometbft_tpu.mempool.clist_mempool import CListMempool
    from cometbft_tpu.mempool.reactor import MEMPOOL_CHANNEL, MempoolReactor

    def note(msg):
        print(f"[bench:mempool] {msg}", file=sys.stderr, flush=True)

    n_txs = int(os.environ.get("BENCH_MEMPOOL_TXS", "8192"))
    concurrency = int(os.environ.get("BENCH_MEMPOOL_CONC", "512"))
    shards = int(os.environ.get("BENCH_MEMPOOL_SHARDS", "4"))
    n_peers = int(os.environ.get("BENCH_MEMPOOL_PEERS", "8"))

    note(f"signing {n_txs} txs (32B pub + 64B sig + payload)")
    priv = Ed25519PrivKey.generate()
    pub = priv.pub_key()
    pub_b = pub.bytes()
    payloads = [b"mp%06d" % i + b"p" * 90 for i in range(n_txs)]
    txs = [pub_b + priv.sign(p) + p for p in payloads]

    class SigApp:
        """CheckTx = verify the embedded ed25519 signature.  With a
        VerificationScheduler running the verify coalesces into its
        micro-batches (what a production app using the repo's verify
        seam gets); without one it is a direct single verification —
        the pre-r16 serial-recheck cost model."""

        async def check_tx(self, tx: bytes, recheck: bool = False):
            p, sig, msg = tx[:32], tx[32:96], tx[96:]
            assert p == pub_b
            sched = vsched.get_scheduler()
            if sched is not None and sched.is_running:
                # the fire-and-forget submission path (what the
                # consensus reactor uses): no wait_for/shield per item
                fut = asyncio.get_running_loop().create_future()
                sched.submit_nowait(pub, msg, sig, on_done=fut.set_result)
                ok = await fut
            else:
                ok = pub.verify_signature(msg, sig)
            return CheckTxResponse(code=0 if ok else 1, gas_wanted=1)

    async def drive() -> dict:
        # cache_size=0: every tx is unique and the dedup cache must not
        # turn the second recheck pass into a no-op measurement
        sched = vsched.VerificationScheduler(
            backend="cpu", max_wait_ms=2.0, max_lanes=256, cache_size=0)
        await sched.start()
        vsched.set_scheduler(sched)
        app = SigApp()
        mp = CListMempool(app, max_txs=n_txs + 16, shards=shards,
                          cache_size=n_txs + 16, metrics_node="bench")

        # ---- admission: seeded backlog at bounded concurrency -------
        lat: list[float] = []
        sem = asyncio.Semaphore(concurrency)

        async def admit(tx: bytes) -> None:
            async with sem:
                t0 = time.perf_counter()
                await mp.check_tx(tx)
                lat.append(time.perf_counter() - t0)

        t0 = time.perf_counter()
        await asyncio.gather(*(admit(tx) for tx in txs))
        admit_s = time.perf_counter() - t0
        assert mp.size() == n_txs, mp.size()
        lat.sort()
        admit_p99_ms = lat[int(0.99 * (len(lat) - 1))] * 1e3
        note(f"admitted {n_txs} in {admit_s:.2f}s "
             f"({n_txs / admit_s:.0f} tx/s, p99 {admit_p99_ms:.1f} ms) "
             f"shards={mp.stats()['shards']}")

        # ---- recheck: batched pass vs the old serial loop -----------
        t0 = time.perf_counter()
        async with mp.lock():
            await mp.update(2, [], [])     # nothing committed: all
        batched_s = time.perf_counter() - t0   # survivors recheck
        assert mp.size() == n_txs
        await sched.stop()
        vsched.set_scheduler(None)         # serial baseline: direct
        t0 = time.perf_counter()           # verification per awaited tx
        for tx in txs:
            res = await app.check_tx(tx, recheck=True)
            assert res.is_ok
        serial_s = time.perf_counter() - t0
        speedup = serial_s / batched_s if batched_s > 0 else 0.0
        note(f"recheck: batched {batched_s:.2f}s vs serial "
             f"{serial_s:.2f}s -> {speedup:.2f}x")

        # ---- gossip bytes to an already-synced peer set -------------
        class CountingPeer:
            def __init__(self, pid):
                self.id = pid
                self.bytes = 0
                self.frames = 0

            def send(self, channel_id, msg):
                self.bytes += len(msg)
                self.frames += 1
                return True

        async def settle(reactor, peers):
            deadline = time.perf_counter() + 30
            while time.perf_counter() < deadline:
                await asyncio.sleep(0.05)
                if all(p.frames and p.bytes for p in peers):
                    # one idle gossip interval with no growth = settled
                    snap = [(p.frames, p.bytes) for p in peers]
                    await asyncio.sleep(0.1)
                    if snap == [(p.frames, p.bytes) for p in peers]:
                        return
            raise RuntimeError("gossip never settled")

        full_bytes = ann_bytes = 0
        for mode_name in ("full", "announce"):
            reactor = MempoolReactor(mp, gossip_sleep=0.01,
                                     gossip_mode=mode_name)
            peers = [CountingPeer(f"synced-{mode_name}-{i}")
                     for i in range(n_peers)]
            for p in peers:
                if mode_name == "announce":
                    # peer advertises the new protocol (hello)
                    reactor.receive(MEMPOOL_CHANNEL, p, msgpack.packb(
                        {"hi": 1}, use_bin_type=True))
                reactor.add_peer(p)
            await settle(reactor, peers)
            total = sum(p.bytes for p in peers)
            await reactor.stop()
            if mode_name == "full":
                full_bytes = total
            else:
                ann_bytes = total
        reduction = full_bytes / ann_bytes if ann_bytes else 0.0
        note(f"gossip to {n_peers} synced peers: full-body "
             f"{full_bytes / 1e6:.2f} MB vs announce "
             f"{ann_bytes / 1e6:.3f} MB ({reduction:.1f}x less wire)")

        total_checks = 3 * n_txs           # admit + 2 recheck passes
        total_s = admit_s + batched_s + serial_s
        return {
            "n_txs": n_txs,
            "concurrency": concurrency,
            "shards": shards,
            "admit_tx_s": round(n_txs / admit_s, 1),
            "admit_p99_ms": round(admit_p99_ms, 2),
            "recheck_batched_s": round(batched_s, 3),
            "recheck_serial_s": round(serial_s, 3),
            "recheck_batched_tx_s": round(n_txs / batched_s, 1),
            "recheck_speedup": round(speedup, 2),
            "gossip_peers": n_peers,
            "gossip_full_body_bytes": full_bytes,
            "gossip_announce_bytes": ann_bytes,
            "gossip_wire_reduction": round(reduction, 2),
            "sustained_checks_s": round(total_checks / total_s, 1),
        }

    loop = asyncio.new_event_loop()
    try:
        doc = loop.run_until_complete(drive())
    finally:
        loop.close()
    if out_path:
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        note(f"results -> {out_path}")
    value = doc["admit_tx_s"]
    print(json.dumps({
        "metric": "mempool admission+recheck throughput (sharded pool, "
                  "coalesced CheckTx, sig-verifying app)",
        "value": value,
        "unit": "tx/s",
        # the acceptance bar is the batched-recheck speedup over the
        # pre-r16 serial loop, normalized at the >=2x requirement
        "vs_baseline": round(doc["recheck_speedup"] / 2.0, 2),
        "backend": "cpu",
        **{k: doc[k] for k in (
            "admit_p99_ms", "recheck_speedup", "recheck_batched_tx_s",
            "gossip_wire_reduction", "sustained_checks_s")},
    }), flush=True)


def _child_statesync(out_path: str) -> None:
    """``--mode statesync``: the r18 snapshot fabric — three
    measurements, one JSON:

    - **serving**: chunks/s served through the reactor's byte-budgeted
      LRU + admission gate (cold pass loads from the app, warm passes
      hit RAM) and the warm cache hit ratio.
    - **bootstrap**: restore wall-clock over per-peer-bandwidth-limited
      serving peers, 1 peer vs 4 peers — multi-peer round-robin fetch
      must turn peer count into bandwidth (the ±-free speedup is the
      acceptance bar).
    - **fleet**: the 50-node scenario-lab program (40 concurrent
      bootstrappers, 4 seeds, gray failures + a byzantine seed serving
      corrupt chunks) run TWICE: verdicts must be byte-identical
      (replay contract), every bootstrapper must complete, the byzantine
      seed must be banned by all, and restore resets must be zero.
    """
    from cometbft_tpu.jaxenv import force_cpu_backend

    force_cpu_backend()

    import asyncio
    from types import SimpleNamespace

    from cometbft_tpu.abci import types as abci_t
    from cometbft_tpu.abci.client import LocalClient
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.sim.statesync_lab import (curated_statesync_scenario,
                                                run_statesync_scenario)
    from cometbft_tpu.statesync.reactor import StatesyncReactor
    from cometbft_tpu.statesync.syncer import Syncer

    def note(msg):
        print(f"[bench:statesync] {msg}", file=sys.stderr, flush=True)

    n_serves = int(os.environ.get("BENCH_SS_SERVES", "3000"))
    n_chunks = int(os.environ.get("BENCH_SS_CHUNKS", "64"))
    serve_delay = 0.005        # per-chunk service time per peer

    async def serving_leg() -> dict:
        app = KVStoreApplication()
        client = LocalClient(app)
        # ~1.5 MB of state -> ~24 chunks of 64 KiB
        await client.finalize_block(abci_t.FinalizeBlockRequest(
            txs=[b"bk%02d=" % i + b"v" * 32768 for i in range(48)],
            height=1, time_ns=0))
        await client.commit()
        snaps = await client.list_snapshots()
        snap = snaps[-1]
        reactor = StatesyncReactor(SimpleNamespace(snapshot=client),
                                   name="bench.ss")
        sink = SimpleNamespace(id="bench-peer",
                               send=lambda chan, msg: True)
        # cold pass (loads + fills the LRU), then the timed warm passes
        for i in range(snap.chunks):
            await reactor._serve_chunk(sink, {"h": snap.height,
                                              "f": snap.format, "i": i})
        t0 = time.perf_counter()
        for k in range(n_serves):
            i = k % snap.chunks
            await reactor._serve_chunk(sink, {"h": snap.height,
                                              "f": snap.format, "i": i})
        dt = time.perf_counter() - t0
        served = n_serves
        return {
            "snapshot_chunks": snap.chunks,
            "serves": served,
            "chunks_per_s": round(served / dt, 1),
            "warm_hit_ratio": round(served / (served + snap.chunks), 4),
            "cache_bytes": reactor._cache.bytes,
        }

    class _SerialPeerReactor:
        """Each peer is a serial worker: one chunk every serve_delay —
        aggregate throughput is proportional to peer count only if the
        fetcher spreads requests (same harness shape as
        tests/test_statesync.py)."""

        def __init__(self, box):
            self.box = box
            self.queues: dict[str, asyncio.Queue] = {}
            self.workers: list = []

        def request_chunk(self, peer, height, format_, index, h):
            if peer not in self.queues:
                self.queues[peer] = asyncio.Queue()
                self.workers.append(asyncio.get_event_loop().create_task(
                    self._serve(peer)))
            self.queues[peer].put_nowait((height, format_, index, h))

        async def _serve(self, peer):
            while True:
                height, format_, index, h = await self.queues[peer].get()
                await asyncio.sleep(serve_delay)
                self.box[0].add_chunk(peer, height, format_, index,
                                      b"DATA-%d" % index, h)

    async def bootstrap_leg(n_peers: int) -> float:
        class SnapConn:
            async def offer_snapshot(self, snapshot, app_hash):
                return abci_t.OFFER_SNAPSHOT_ACCEPT

            async def apply_snapshot_chunk(self, index, chunk, sender):
                return abci_t.APPLY_CHUNK_ACCEPT

        class QueryConn:
            async def info(self):
                return abci_t.InfoResponse(last_block_height=7,
                                           last_block_app_hash=b"\xab" *
                                           32)

        class Provider:
            async def app_hash(self, h):
                return b"\xab" * 32

            async def state(self, h):
                return "S"

            async def commit(self, h):
                return "C"

        conns = SimpleNamespace(snapshot=SnapConn(), query=QueryConn())
        box = [None]
        reactor = _SerialPeerReactor(box)
        syncer = Syncer(conns, Provider(), reactor=reactor,
                        in_memory_spool=True)
        box[0] = syncer
        snapshot = abci_t.Snapshot(height=7, format=1, chunks=n_chunks,
                                   hash=b"\xcd" * 32, metadata=b"")
        for k in range(n_peers):
            syncer.add_snapshot(f"peer{k}", snapshot)
        t0 = time.perf_counter()
        await syncer._restore(syncer._snapshots[(7, 1, b"\xcd" * 32)])
        dt = time.perf_counter() - t0
        for w in reactor.workers:
            w.cancel()
        syncer._pool.close()
        return dt

    async def drive() -> dict:
        serving = await serving_leg()
        note(f"serving: {serving['chunks_per_s']} chunks/s warm "
             f"({serving['snapshot_chunks']}-chunk snapshot)")
        t1 = await bootstrap_leg(1)
        t4 = await bootstrap_leg(4)
        note(f"bootstrap {n_chunks} chunks: 1 peer {t1:.2f}s, "
             f"4 peers {t4:.2f}s ({t1 / t4:.2f}x)")
        return {"serving": serving,
                "bootstrap": {
                    "n_chunks": n_chunks,
                    "serve_delay_s": serve_delay,
                    "single_peer_s": round(t1, 3),
                    "multi_peer_s": round(t4, 3),
                    "multi_peer_speedup": round(t1 / t4, 2)}}

    loop = asyncio.new_event_loop()
    try:
        doc = loop.run_until_complete(drive())
    finally:
        loop.close()

    failures_: list[str] = []
    scn = curated_statesync_scenario()
    note(f"fleet: {scn.n_bootstrappers} bootstrappers / "
         f"{scn.n_seeds} seeds / byzantine {scn.byzantine_seeds}")
    t0 = time.perf_counter()
    v1 = run_statesync_scenario(scn)
    fleet_real = time.perf_counter() - t0
    v2 = run_statesync_scenario(scn)
    if json.dumps(v1, sort_keys=True) != json.dumps(v2, sort_keys=True):
        failures_.append("fleet scenario: replay diverged")
    if v1["completed"] != scn.n_bootstrappers:
        failures_.append(f"fleet scenario: only {v1['completed']} of "
                         f"{scn.n_bootstrappers} completed")
    if v1["syncer_tallies"].get("restore_resets", 0) != 0:
        failures_.append("fleet scenario: corrupt chunk caused a "
                         "restore reset")
    if len(v1["byzantine_banned_by"]) < scn.n_bootstrappers:
        failures_.append("fleet scenario: byzantine seed not banned "
                         "by the whole fleet")
    v1["real_s"] = round(fleet_real, 1)
    doc["fleet"] = v1
    doc["failures"] = failures_
    dist = {k: x for k, x in v1["time_to_serving_height_s"].items()
            if k != "all"}
    replay_ok = "fleet scenario: replay diverged" not in failures_
    note(f"fleet: completed={v1['completed']} dist={dist} "
         f"replay_ok={replay_ok}")

    if out_path:
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        note(f"results -> {out_path}")
    print(json.dumps({
        "metric": "statesync fabric: chunks/s served warm through the "
                  "serving LRU (vs_baseline = 4-peer bootstrap speedup "
                  "over 1 peer; fleet scenario replay-identical, "
                  "reset-free, byzantine seed banned)",
        "value": doc["serving"]["chunks_per_s"],
        "unit": "chunks/s",
        "vs_baseline": 0.0 if failures_ else
        doc["bootstrap"]["multi_peer_speedup"],
        "multi_peer_speedup": doc["bootstrap"]["multi_peer_speedup"],
        "warm_hit_ratio": doc["serving"]["warm_hit_ratio"],
        "fleet_completed": v1["completed"],
        "fleet_time_to_serving_p50_s":
        v1["time_to_serving_height_s"]["p50"],
        "fleet_time_to_serving_max_s":
        v1["time_to_serving_height_s"]["max"],
        "failures": failures_,
        "backend": "cpu",
    }), flush=True)
    if failures_:
        raise SystemExit(1)


def _child_bls(out_path: str) -> None:
    """``--mode bls``: the aggregate-commit fast path — at each point of
    the 100/1k/10k-validator curve, a warm ``VerifyCommitLight`` over an
    aggregate BLS commit (bitmap decode + complement pubkey fold + two
    pairings, O(1) in N) against the same call over an Ed25519 dense
    commit (the production batched host path, O(N)), plus the wire size
    of both commits.  2% of the cohort is absent so the complement fold
    does real point arithmetic instead of returning the cached
    full-cohort sum.

    Headline ``value`` is the 10k-validator speedup; ``vs_baseline`` is
    that speedup / 10 (the acceptance bar is >= 10x, so > 1 means the
    bar is met).  The full curve goes to ``out_path``."""
    from cometbft_tpu.jaxenv import force_cpu_backend

    force_cpu_backend()

    def note(msg):
        print(f"[bench:bls] {msg}", file=sys.stderr, flush=True)

    from cometbft_tpu.crypto.bls12381 import aggregate_signatures
    from cometbft_tpu.crypto.keys import Ed25519PrivKey
    from cometbft_tpu.testing import bls_priv_from_secret
    from cometbft_tpu.types import codec
    from cometbft_tpu.types.block_id import BlockID
    from cometbft_tpu.types.canonical import canonical_vote_sign_bytes
    from cometbft_tpu.types.commit import (
        BLOCK_ID_FLAG_ABSENT, BLOCK_ID_FLAG_AGGREGATE,
        BLOCK_ID_FLAG_COMMIT, Commit, CommitSig, signer_bitmap)
    from cometbft_tpu.types.part_set import PartSetHeader
    from cometbft_tpu.types.validation import VerifyCommitLight
    from cometbft_tpu.types.validator_set import Validator, ValidatorSet
    from cometbft_tpu.types.vote import PRECOMMIT_TYPE

    chain_id = "bench-bls"
    height = 7
    bid = BlockID(b"\x01" * 32, PartSetHeader(1, b"\x02" * 32))
    reps = int(os.environ.get("BENCH_REPS", "5"))
    curve_ns = [int(x) for x in os.environ.get(
        "BENCH_BLS_CURVE", "100,1000,10000").split(",")]

    def warm_min(fn):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    curve = []
    for n in curve_ns:
        # ---- aggregate side: all-BLS valset, 2% absent
        note(f"n={n}: building BLS valset + aggregate commit")
        privs = [bls_priv_from_secret(b"bench-bls%d" % i) for i in range(n)]
        vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
        by_addr = {p.pub_key().address(): p for p in privs}
        absent = set(range(0, n, 50)) if n >= 100 else set()
        msg = canonical_vote_sign_bytes(chain_id, PRECOMMIT_TYPE, height,
                                        0, bid, 0)
        lanes, signers, sigs = [], [], []
        for i, v in enumerate(vals.validators):
            if i in absent:
                lanes.append(CommitSig(BLOCK_ID_FLAG_ABSENT))
                continue
            signers.append(i)
            sigs.append(by_addr[v.address].sign(msg))
            lanes.append(CommitSig(BLOCK_ID_FLAG_AGGREGATE, v.address,
                                   1_000_000 + i, b""))
        agg_commit = Commit(height, 0, bid, lanes,
                            aggregate_signatures(sigs, check=False),
                            signer_bitmap(signers, n))
        note(f"n={n}: cold aggregate verify (builds the cohort table)")
        t0 = time.perf_counter()
        VerifyCommitLight(chain_id, vals, bid, height, agg_commit)
        bls_cold = time.perf_counter() - t0
        bls_warm = warm_min(lambda: VerifyCommitLight(
            chain_id, vals, bid, height, agg_commit))

        # ---- dense side: all-Ed25519 valset, same shape/absentees
        note(f"n={n}: building Ed25519 valset + dense commit")
        eprivs = [Ed25519PrivKey.from_secret(b"bench-ed%d" % i)
                  for i in range(n)]
        evals = ValidatorSet([Validator(p.pub_key(), 10) for p in eprivs])
        eby_addr = {p.pub_key().address(): p for p in eprivs}
        elanes = []
        for i, v in enumerate(evals.validators):
            if i in absent:
                elanes.append(CommitSig(BLOCK_ID_FLAG_ABSENT))
                continue
            ts = 1_000_000 + i
            sb = canonical_vote_sign_bytes(chain_id, PRECOMMIT_TYPE,
                                           height, 0, bid, ts)
            elanes.append(CommitSig(BLOCK_ID_FLAG_COMMIT, v.address, ts,
                                    eby_addr[v.address].sign(sb)))
        ed_commit = Commit(height, 0, bid, elanes)
        note(f"n={n}: cold dense verify (builds the valset table)")
        t0 = time.perf_counter()
        VerifyCommitLight(chain_id, evals, bid, height, ed_commit,
                          backend="cpu")
        ed_cold = time.perf_counter() - t0
        ed_warm = warm_min(lambda: VerifyCommitLight(
            chain_id, evals, bid, height, ed_commit, backend="cpu"))

        bls_wire = len(codec.pack(agg_commit))
        ed_wire = len(codec.pack(ed_commit))
        point = {
            "n_vals": n,
            "signers": len(signers),
            "absent": len(absent),
            "bls_agg_verify_ms": round(bls_warm * 1e3, 3),
            "ed25519_batched_ms": round(ed_warm * 1e3, 3),
            "speedup": round(ed_warm / bls_warm, 2),
            "bls_wire_bytes": bls_wire,
            "ed25519_wire_bytes": ed_wire,
            "wire_reduction": round(ed_wire / bls_wire, 2),
            "bls_cold_s": round(bls_cold, 3),
            "ed25519_cold_s": round(ed_cold, 3),
        }
        note(f"n={n}: agg {point['bls_agg_verify_ms']}ms vs dense "
             f"{point['ed25519_batched_ms']}ms -> {point['speedup']}x, "
             f"wire {bls_wire}B vs {ed_wire}B")
        curve.append(point)

    head = curve[-1]
    doc = {"metric": "BLS aggregate-commit verify vs Ed25519 batched "
                     "dense path (warm VerifyCommitLight, CPU host "
                     "crypto)",
           "curve": curve, "backend": "cpu"}
    if out_path:
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        note(f"curve -> {out_path}")
    print(json.dumps({
        "metric": f"BLS aggregate-commit verify speedup vs Ed25519 "
                  f"batched path @{head['n_vals']} validators",
        "value": head["speedup"],
        "unit": "x",
        # acceptance bar: >= 10x at 10k validators; > 1 means met
        "vs_baseline": round(head["speedup"] / 10.0, 2),
        "bls_agg_verify_ms": head["bls_agg_verify_ms"],
        "ed25519_batched_ms": head["ed25519_batched_ms"],
        "wire_reduction": head["wire_reduction"],
        "curve": curve,
        "backend": "cpu",
    }), flush=True)


def _child_profile(out_path: str) -> None:
    """``--mode profile``: the hot-path profiling harness — run one
    scenario-lab scenario (default ``megamix-100``, the 100-node mixed-
    adversary fleet) under ``tracemalloc`` + ``cProfile`` and write a
    ranked top-allocators / top-callers report to ``out_path``.

    This is a *diagnostic* mode, not a guard: its job is to point at
    the dominant allocator and the dominant CPU sink so an optimisation
    PR can kill them and commit before/after reports side by side.
    Numbers here are NOT comparable to ``--mode scenarios`` wall times —
    tracemalloc alone multiplies allocation cost several-fold."""
    import cProfile
    import pstats
    import tracemalloc

    from cometbft_tpu.jaxenv import force_cpu_backend

    force_cpu_backend()
    from cometbft_tpu.sim.scenario import curated_suite, run_scenario

    def note(msg):
        print(f"[bench:profile] {msg}", file=sys.stderr, flush=True)

    want = os.environ.get("BENCH_PROFILE_SCENARIO", "megamix-100")
    cands = [s for s in curated_suite() if s.name == want]
    if not cands:
        raise SystemExit(f"unknown BENCH_PROFILE_SCENARIO {want!r}")
    scn = cands[0]
    top_n = int(os.environ.get("BENCH_PROFILE_TOP", "25"))

    def _rel(path: str) -> str:
        if path.startswith(REPO):
            return path[len(REPO):].lstrip(os.sep)
        # site-packages / stdlib frames: keep the last 3 components
        return os.sep.join(path.split(os.sep)[-3:])

    note(f"profiling {scn.name} ({scn.n_nodes} nodes, "
         f"target h{scn.target_height}) under tracemalloc+cProfile")
    tracemalloc.start(1)           # 1 frame: rank by allocation site
    prof = cProfile.Profile()
    t0 = time.perf_counter()
    prof.enable()
    verdict = run_scenario(scn)
    prof.disable()
    real_s = time.perf_counter() - t0
    snap = tracemalloc.take_snapshot()
    peak_b = tracemalloc.get_traced_memory()[1]
    tracemalloc.stop()

    snap = snap.filter_traces((
        tracemalloc.Filter(False, tracemalloc.__file__),
        tracemalloc.Filter(False, "<frozen importlib._bootstrap>"),
    ))
    allocs = []
    for stat in snap.statistics("lineno")[:top_n]:
        fr = stat.traceback[0]
        allocs.append({"site": f"{_rel(fr.filename)}:{fr.lineno}",
                       "size_kb": round(stat.size / 1024, 1),
                       "count": stat.count})

    st = pstats.Stats(prof)
    rows = []   # (file, line, func, ncalls, tottime, cumtime)
    for (fn, line, func), (_cc, nc, tt, ct, _cal) in st.stats.items():
        rows.append((fn, line, func, nc, tt, ct))

    def _fmt(r):
        fn, line, func, nc, tt, ct = r
        where = func if fn == "~" else f"{_rel(fn)}:{line}({func})"
        return {"func": where, "ncalls": nc,
                "tottime_s": round(tt, 3), "cumtime_s": round(ct, 3)}

    by_tot = [_fmt(r) for r in
              sorted(rows, key=lambda r: -r[4])[:top_n]]
    by_cum = [_fmt(r) for r in
              sorted(rows, key=lambda r: -r[5])[:top_n]]

    doc = {
        "metric": "hot-path profile: one scenario-lab run under "
                  "tracemalloc(1 frame) + cProfile (diagnostic; not "
                  "comparable to --mode scenarios timings)",
        "scenario": scn.name,
        "real_s": round(real_s, 1),
        "virtual_s": verdict["virtual_duration_s"],
        "reached_target": verdict["reached_target"],
        "fork_free": verdict["fork_free"],
        "peak_traced_mb": round(peak_b / 1e6, 1),
        "top_allocators": allocs,
        "top_functions_by_tottime": by_tot,
        "top_functions_by_cumtime": by_cum,
        "backend": "cpu",
    }
    if out_path:
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1)
        note(f"report -> {out_path}")
    top_alloc = allocs[0] if allocs else {}
    note(f"peak traced {doc['peak_traced_mb']} MB; top allocator "
         f"{top_alloc.get('site')} ({top_alloc.get('size_kb')} KB live, "
         f"{top_alloc.get('count')} blocks)")
    print(json.dumps({
        "metric": doc["metric"],
        "value": doc["peak_traced_mb"],
        "unit": "MB-peak",
        "vs_baseline": 1.0 if verdict["reached_target"] else 0.0,
        "scenario": scn.name,
        "real_s": doc["real_s"],
        "top_allocator": top_alloc.get("site"),
        "report": out_path,
        "backend": "cpu",
    }), flush=True)


def _child_main(backend: str, nsig: int) -> None:
    mode = os.environ.get("BENCH_MODE", "commit")
    if mode == "mempool":
        return _child_mempool(
            os.environ.get("BENCH_OUT",
                           os.path.join(REPO, "docs", "bench",
                                        "r16-mempool-cpu.json")))
    if mode == "scenarios":
        return _child_scenarios(
            os.environ.get("BENCH_OUT",
                           os.path.join(REPO, "docs", "bench",
                                        "r16-scenarios-cpu.json")))
    if mode == "statesync":
        return _child_statesync(
            os.environ.get("BENCH_OUT",
                           os.path.join(REPO, "docs", "bench",
                                        "r18-statesync-cpu.json")))
    if mode == "bls":
        return _child_bls(
            os.environ.get("BENCH_OUT",
                           os.path.join(REPO, "docs", "bench",
                                        "r20-bls-cpu.json")))
    if mode == "profile":
        return _child_profile(
            os.environ.get("BENCH_OUT",
                           os.path.join(REPO, "docs", "bench",
                                        "r21-profile-cpu.json")))
    if mode == "node":
        return _child_node(float(os.environ.get("BENCH_RATE", "2000")),
                           float(os.environ.get("BENCH_DURATION", "20")),
                           int(os.environ.get("BENCH_TX_SIZE", "256")))
    if mode == "light-serve":
        return _child_lightserve(
            int(os.environ.get("BENCH_LS_CLIENTS", "10000")),
            int(os.environ.get("BENCH_LS_CONNS", "32")),
            int(os.environ.get("BENCH_LS_TXS", "512")),
            int(os.environ.get("BENCH_LS_PROOFS", "8")))
    if mode == "light":
        return _child_light(backend,
                            int(os.environ.get("BENCH_HEADERS", "1000")),
                            int(os.environ.get("BENCH_VALS", "32")))
    if mode == "blocksync":
        return _child_blocksync(backend,
                                int(os.environ.get("BENCH_BLOCKS", "500")),
                                int(os.environ.get("BENCH_VALS", "32")))
    if mode == "verifycommit":
        return _child_verifycommit(backend,
                                   int(os.environ.get("BENCH_VALS", "150")))
    if mode == "stress":
        return _child_stress(backend,
                             int(os.environ.get("BENCH_VALS", "10000")),
                             int(os.environ.get("BENCH_SECP_PCT", "10")))
    if mode == "p50commit":
        return _child_p50commit(backend,
                                int(os.environ.get("BENCH_VALS", "10000")))
    if mode == "merkle":
        return _child_merkle(backend,
                             int(os.environ.get("BENCH_MERKLE_LEAVES",
                                                "10000")),
                             int(os.environ.get("BENCH_MERKLE_BLOCK_KB",
                                                "4096")))
    if mode == "vote-gossip":
        return _child_votegossip(backend,
                                 int(os.environ.get("BENCH_VALS", "256")),
                                 int(os.environ.get("BENCH_DUP_K", "3")),
                                 int(os.environ.get("BENCH_SLOTS", "4")))
    if mode == "mesh":
        return _child_mesh(backend, os.environ.get(
            "BENCH_OUT", os.path.join(REPO, "docs", "bench",
                                      f"r19-mesh-{backend}.json")))

    def note(msg):
        print(f"[bench:{backend}] {msg}", file=sys.stderr, flush=True)

    import numpy as np

    from cometbft_tpu.crypto.keys import verify_ed25519_zip215
    from cometbft_tpu.jaxenv import enable_compile_cache, force_cpu_backend
    from cometbft_tpu.testing import dense_signature_batch

    note("building signature batch")
    batch_args, host_items = dense_signature_batch(nsig, msg_len=120,
                                                   seed=2024)

    if backend == "cpu":
        # No accelerator: the device kernel emulated on one CPU core is
        # not what a CPU-only node runs.  Measure the production CPU
        # fallback (crypto/batch CpuBatchVerifier over host crypto)
        # against the single-verify loop instead.
        force_cpu_backend()
        from cometbft_tpu.crypto.batch import create_batch_verifier

        def run_batch():
            bv = create_batch_verifier("cpu")
            from cometbft_tpu.crypto.keys import Ed25519PubKey

            for pk, msg, sig in host_items:
                bv.add(Ed25519PubKey(pk), msg, sig)
            ok, _ = bv.verify()
            assert ok

        note("timing production CPU batch path")
        run_batch()
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            run_batch()
            times.append(time.perf_counter() - t0)
        p50 = float(np.percentile(times, 50))

        cpu_per_sig = _single_verify_us(host_items) / 1e6

        vs_single = (cpu_per_sig * nsig) / p50
        print(json.dumps({
            "metric": "ed25519 sig-verifies/sec/chip "
                      "(extended-commit-shaped batch)",
            "value": round(nsig / p50, 1),
            "unit": "sigs/s",
            # reference-relative: voi's CPU batch path is ~2x its single
            # verify, so the honest comparison halves the single-loop win
            "vs_baseline": round(vs_single / 2.0, 2),
            "vs_single_loop": round(vs_single, 2),
            "vs_reference_batch_est": round(vs_single / 2.0, 2),
            "p50_batch_latency_ms": round(p50 * 1e3, 3),
            "batch_size": nsig,
            "backend": "cpu",
            "device": "host (no accelerator; production CPU fallback path)",
            "cpu_single_verify_us": round(cpu_per_sig * 1e6, 1),
        }), flush=True)
        return

    import jax

    from cometbft_tpu.ops import ed25519, rlc

    enable_compile_cache()

    note("initializing backend")
    dev = jax.devices()[0]
    note(f"device = {dev}")
    if backend == "tpu" and dev.platform == "cpu":
        # jax silently fell back to CPU: fail so the parent runs the
        # properly-sized CPU attempt instead of mislabeling this one.
        raise RuntimeError("requested accelerator but got CPU backend")
    fn = jax.jit(ed25519.verify_padded)
    args = jax.device_put(batch_args, dev)
    note("compiling + first run (per-lane straus)")
    t0 = time.perf_counter()
    out = np.asarray(fn(*args))
    note(f"compile+run took {time.perf_counter() - t0:.1f}s")
    assert out.all(), "benchmark batch failed verification"

    reps = int(os.environ.get("BENCH_REPS", "10" if backend != "cpu" else "5"))
    profile_dir = os.environ.get("BENCH_PROFILE", "")
    if profile_dir:
        # tracing/profiling hook (SURVEY §5): captures an XLA/JAX trace of
        # the timed loop, viewable in TensorBoard/Perfetto
        note(f"capturing jax profiler trace to {profile_dir}")
        jax.profiler.start_trace(profile_dir)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn(*args)[0].block_until_ready()
        times.append(time.perf_counter() - t0)
    if profile_dir:
        jax.profiler.stop_trace()
    p50_straus = float(np.percentile(times, 50))

    # RLC batch kernel: the production fast path for batches >= the RLC
    # threshold (one all-or-nothing verdict; ~3x less group-op work)
    note("compiling + first run (rlc batch)")
    z = rlc.host_rlc_coeffs(nsig, np.ones(nsig, bool))
    rfn = jax.jit(rlc.verify_batch_rlc)
    rargs = jax.device_put(batch_args + (z,), dev)
    t0 = time.perf_counter()
    rok = bool(np.asarray(rfn(*rargs)))
    note(f"compile+run took {time.perf_counter() - t0:.1f}s")
    assert rok, "RLC rejected the benchmark batch"
    rtimes = []
    for _ in range(reps):
        t0 = time.perf_counter()
        rfn(*rargs).block_until_ready()
        rtimes.append(time.perf_counter() - t0)
    p50_rlc = float(np.percentile(rtimes, 50))

    # the production router dispatches RLC first at this batch size, so
    # the headline is the better of the two (they verify the same batch)
    p50 = min(p50_straus, p50_rlc)
    sigs_per_sec = nsig / p50

    # Host baseline: single-verify over a sample, extrapolated to nsig.
    cpu_per_sig = _single_verify_us(host_items) / 1e6
    vs_single = (cpu_per_sig * nsig) / p50

    print(json.dumps({
        "metric": "ed25519 sig-verifies/sec/chip "
                  "(extended-commit-shaped batch)",
        "value": round(sigs_per_sec, 1),
        "unit": "sigs/s",
        "vs_baseline": round(vs_single / 2.0, 2),
        "vs_single_loop": round(vs_single, 2),
        "vs_reference_batch_est": round(vs_single / 2.0, 2),
        "p50_batch_latency_ms": round(p50 * 1e3, 3),
        "straus_sigs_per_sec": round(nsig / p50_straus, 1),
        "rlc_sigs_per_sec": round(nsig / p50_rlc, 1),
        "rlc_vs_straus": round(p50_straus / p50_rlc, 2),
        "batch_size": nsig,
        "backend": backend,
        "device": str(dev),
        "cpu_single_verify_us": round(cpu_per_sig * 1e6, 1),
    }), flush=True)


# --------------------------------------------------------------------------
# parent: orchestrates attempts; never imports jax; always emits JSON
# --------------------------------------------------------------------------

def _run_attempt(backend: str, nsig: int, timeout_s: float) -> dict | None:
    env = dict(os.environ)
    if backend == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
    cmd = [sys.executable, os.path.abspath(__file__),
           "--_child", backend, str(nsig)]
    print(f"[bench] attempt backend={backend} nsig={nsig} "
          f"timeout={timeout_s:.0f}s", file=sys.stderr, flush=True)
    try:
        proc = subprocess.run(cmd, env=env, timeout=timeout_s,
                              stdout=subprocess.PIPE, stderr=sys.stderr)
    except subprocess.TimeoutExpired:
        print(f"[bench] backend={backend} TIMED OUT after {timeout_s:.0f}s",
              file=sys.stderr, flush=True)
        return None
    if proc.returncode != 0:
        print(f"[bench] backend={backend} exited rc={proc.returncode}",
              file=sys.stderr, flush=True)
        return None
    for line in reversed(proc.stdout.decode(errors="replace").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def main() -> None:
    nsig_tpu = int(os.environ.get("BENCH_NSIG", "10240"))
    # the headline shape is a 10k-validator EXTENDED commit (2 sigs/val,
    # chunked at the 4096-lane cap): production CPU batches are huge,
    # so a small default would UNDERstate the per-sig rate the node
    # actually sees (Pippenger's per-point cost falls with batch size)
    nsig_cpu = int(os.environ.get("BENCH_NSIG_CPU", "8192"))
    t_tpu = float(os.environ.get("BENCH_TPU_TIMEOUT", "480"))
    t_cpu = float(os.environ.get("BENCH_CPU_TIMEOUT", "900"))

    # BENCH_BACKEND forces a single attempt (chip_wake.sh uses it so a
    # file named r*-tpu.json really holds the tpu measurement).
    forced = os.environ.get("BENCH_BACKEND", "").strip().lower()
    platforms = os.environ.get("JAX_PLATFORMS", "")
    want_tpu = ("cpu" != platforms.strip().lower()) and forced != "cpu"
    if os.environ.get("BENCH_MODE") in ("node", "light-serve",
                                        "scenarios", "mempool",
                                        "statesync", "bls", "profile"):
        # these children hard-force CPU (full-stack measurements whose
        # bottleneck is the node, not a device leg): skip the
        # accelerator probe and the redundant tpu-labeled attempt
        want_tpu = False
        forced = "cpu"

    if want_tpu:
        # cheap pre-probe: when the accelerator relay is wedged, backend
        # init hangs forever, and the full attempt would burn its whole
        # timeout before the CPU fallback runs.  A throwaway process
        # answers the question.
        probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "60"))
        print(f"[bench] probing accelerator backend "
              f"({probe_timeout:.0f}s limit)", file=sys.stderr, flush=True)
        reason = ""
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(any(d.platform != 'cpu' "
                 "for d in jax.devices()))"],
                capture_output=True, timeout=probe_timeout, text=True)
            lines = probe.stdout.strip().splitlines()
            want_tpu = (probe.returncode == 0 and lines
                        and lines[-1] == "True")
            if not want_tpu:
                reason = (f"rc={probe.returncode}, "
                          f"stdout={lines[-1] if lines else ''!r}, "
                          f"stderr tail: {probe.stderr.strip()[-200:]!r}")
        except Exception as e:
            want_tpu = False
            reason = f"{type(e).__name__}: {e}"
        if not want_tpu:
            print(f"[bench] no live accelerator ({reason}); skipping "
                  f"the TPU attempt", file=sys.stderr, flush=True)

    attempts: list[tuple[str, int, float]] = []
    errors = []
    if want_tpu:
        attempts.append(("tpu", nsig_tpu, t_tpu))
    elif forced == "tpu":
        # forced-tpu with no accelerator available: record WHY nothing
        # ran rather than emitting "all backends failed: []"
        errors.append("tpu (forced, but no accelerator: probe failed "
                      "or JAX_PLATFORMS pins cpu)")
    if forced != "tpu":
        attempts.append(("cpu", nsig_cpu, t_cpu))

    # Run EVERY attempt and report the one the production dispatcher
    # would route to (crypto/batch probes both backends and picks by
    # measured throughput) — the first-success-wins policy would report
    # the accelerator even on workloads where the native CPU path is
    # faster, understating what a real node on this box achieves.
    results: list[dict] = []
    for backend, nsig, timeout_s in attempts:
        result = _run_attempt(backend, nsig, timeout_s)
        if result is not None:
            results.append(result)
        else:
            errors.append(backend)
    if results:
        # Compare on the measured value itself — each child computes
        # vs_baseline against its OWN in-process single-loop run, which
        # box contention can skew across attempts.  verifycommit is a
        # latency (lower wins); every other mode is a rate.
        if os.environ.get("BENCH_MODE") in ("verifycommit", "p50commit",
                                            "merkle"):
            best = min(results,
                       key=lambda r: r.get("value") or float("inf"))
        else:
            best = max(results, key=lambda r: r.get("value") or 0)
        others = [r for r in results if r is not best]
        if others:
            best["other_backends"] = {
                r.get("backend", "?"): {"value": r.get("value"),
                                        "vs_baseline": r.get("vs_baseline")}
                for r in others}
        print(json.dumps(best), flush=True)
        return

    # Every attempt failed: still emit a well-formed result line.
    mode = os.environ.get("BENCH_MODE", "commit")
    metric, unit = {
        "commit": ("ed25519 sig-verifies/sec/chip "
                   "(extended-commit-shaped batch)", "sigs/s"),
        "light": ("light-client sequential sync, headers/sec",
                  "headers/s"),
        "blocksync": ("blocksync replay, blocks/sec", "blocks/s"),
        "verifycommit": ("VerifyCommitLight latency", "ms"),
        "p50commit": ("p50 VerifyCommit latency @10k validators", "ms"),
        "merkle": ("merkle 10k-leaf root+proofs build", "ms"),
        "stress": ("mixed-key extended-commit verify", "sigs/s"),
        "node": ("single-node end-to-end throughput", "tx/s"),
        "vote-gossip": ("vote-gossip verification storm, arrivals/sec",
                        "events/s"),
        "light-serve": ("light-serve proofs/s under simulated "
                        "skipping clients", "proofs/s"),
        "scenarios": ("scenario lab: adversarial virtual-seconds "
                      "simulated per real second", "virtual-s/s"),
        "mempool": ("mempool admission+recheck throughput", "tx/s"),
        "statesync": ("statesync fabric: warm chunks/s served",
                      "chunks/s"),
        "mesh": ("sharded SPMD verify, full-mesh sigs/s", "sigs/s"),
        "bls": ("BLS aggregate-commit verify speedup vs Ed25519 "
                "batched path @10k validators", "x"),
        "profile": ("hot-path profile: scenario-lab run under "
                    "tracemalloc + cProfile", "MB-peak"),
    }.get(mode, (mode, "ops/s"))
    print(json.dumps({
        "metric": metric,
        "value": 0,
        "unit": unit,
        "vs_baseline": 0,
        "error": f"all backends failed: {errors}",
    }), flush=True)


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--_child":
        _child_main(sys.argv[2], int(sys.argv[3]))
    elif len(sys.argv) >= 5 and sys.argv[1] == "--_mesh_gauge":
        # fresh-process half of `--mode mesh`'s first-dispatch proof
        _mesh_gauge_child(sys.argv[2], int(sys.argv[3]), int(sys.argv[4]))
    else:
        # `--mode X` is sugar for BENCH_MODE=X (the env var wins if both
        # are set, matching every other BENCH_* knob)
        argv = sys.argv[1:]
        if "--mode" in argv:
            i = argv.index("--mode")
            if i + 1 >= len(argv):
                print("--mode requires a value", file=sys.stderr)
                sys.exit(2)
            os.environ.setdefault("BENCH_MODE", argv[i + 1])
        main()
