"""Headline benchmark: 10k-validator ExtendedCommit-shaped signature batch.

Mirrors BASELINE.json's metric ("ed25519 sig-verifies/sec/chip; p50
Commit.VerifyCommit latency @10k vals") and the reference's bench harness
(``crypto/ed25519/bench_test.go:31-67``, which benches BatchVerify at fixed
sig counts): 10240 ed25519 signatures over ~120-byte vote-sign-bytes
messages, verified on the accelerator via the ZIP-215 kernel.

``vs_baseline`` is the measured speedup over the host CPU single-verify
path (OpenSSL via the `cryptography` library on this machine's core — the
stand-in for the reference's Go curve25519-voi verifier; voi's batch mode
is ~2x the single path, so divide by ~2 for a conservative read).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def main():
    import jax

    from cometbft_tpu.crypto.keys import verify_ed25519_zip215
    from cometbft_tpu.ops import ed25519
    from cometbft_tpu.testing import dense_signature_batch

    nsig = int(os.environ.get("BENCH_NSIG", "10240"))
    batch_args, host_items = dense_signature_batch(nsig, msg_len=120, seed=2024)

    dev = jax.devices()[0]
    fn = jax.jit(ed25519.verify_padded)
    args = jax.device_put(batch_args, dev)
    out = np.asarray(fn(*args))          # compile + correctness
    assert out.all(), "benchmark batch failed verification"

    times = []
    for _ in range(10):
        t0 = time.perf_counter()
        fn(*args)[0].block_until_ready()
        times.append(time.perf_counter() - t0)
    p50 = float(np.percentile(times, 50))
    sigs_per_sec = nsig / p50

    # CPU baseline: host single-verify over a 512-sig sample, extrapolated
    sample = host_items[:512]
    t0 = time.perf_counter()
    for pk, msg, sig in sample:
        assert verify_ed25519_zip215(pk, msg, sig)
    cpu_per_sig = (time.perf_counter() - t0) / len(sample)
    vs_baseline = (cpu_per_sig * nsig) / p50

    print(json.dumps({
        "metric": "ed25519 sig-verifies/sec/chip (10k-validator extended-commit batch)",
        "value": round(sigs_per_sec, 1),
        "unit": "sigs/s",
        "vs_baseline": round(vs_baseline, 2),
        "p50_batch_latency_ms": round(p50 * 1e3, 3),
        "batch_size": nsig,
        "device": str(dev),
        "cpu_single_verify_us": round(cpu_per_sig * 1e6, 1),
    }))


if __name__ == "__main__":
    main()
