"""Headline benchmark: 10k-validator ExtendedCommit-shaped signature batch.

Mirrors BASELINE.json's metric ("ed25519 sig-verifies/sec/chip; p50
Commit.VerifyCommit latency @10k vals") and the reference's bench harness
(``crypto/ed25519/bench_test.go:31-67``, which benches BatchVerify at fixed
sig counts): ed25519 signatures over ~120-byte vote-sign-bytes messages,
verified on the accelerator via the ZIP-215 kernel.

``vs_baseline`` is the measured speedup over the host CPU single-verify
path (the stand-in for the reference's Go curve25519-voi verifier; voi's
batch mode is ~2x the single path, so divide by ~2 for a conservative read).

Robustness contract (the whole point of this file's structure): the parent
process NEVER imports jax.  The TPU attempt runs in a subprocess with a hard
timeout — on this image the axon TPU relay can wedge so that backend init
hangs forever — and on failure/timeout a CPU-backend subprocess runs
instead.  Exactly one JSON line is always printed, and the exit code is 0,
so the driver always records a result.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))


# --------------------------------------------------------------------------
# child: does the actual measurement on one backend, prints one JSON line
# --------------------------------------------------------------------------

def _child_main(backend: str, nsig: int) -> None:
    def note(msg):
        print(f"[bench:{backend}] {msg}", file=sys.stderr, flush=True)

    import jax

    from cometbft_tpu.jaxenv import enable_compile_cache, force_cpu_backend

    enable_compile_cache()
    if backend == "cpu":
        force_cpu_backend()

    import numpy as np

    from cometbft_tpu.crypto.keys import verify_ed25519_zip215
    from cometbft_tpu.ops import ed25519
    from cometbft_tpu.testing import dense_signature_batch

    note("building signature batch")
    batch_args, host_items = dense_signature_batch(nsig, msg_len=120,
                                                   seed=2024)

    note("initializing backend")
    dev = jax.devices()[0]
    note(f"device = {dev}")
    if backend == "tpu" and dev.platform == "cpu":
        # jax silently fell back to CPU: fail so the parent runs the
        # properly-sized CPU attempt instead of mislabeling this one.
        raise RuntimeError("requested accelerator but got CPU backend")
    fn = jax.jit(ed25519.verify_padded)
    args = jax.device_put(batch_args, dev)
    note("compiling + first run")
    t0 = time.perf_counter()
    out = np.asarray(fn(*args))
    note(f"compile+run took {time.perf_counter() - t0:.1f}s")
    assert out.all(), "benchmark batch failed verification"

    reps = int(os.environ.get("BENCH_REPS", "10" if backend != "cpu" else "5"))
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn(*args)[0].block_until_ready()
        times.append(time.perf_counter() - t0)
    p50 = float(np.percentile(times, 50))
    sigs_per_sec = nsig / p50

    # Host baseline: single-verify over a sample, extrapolated to nsig.
    sample = host_items[:min(256, len(host_items))]
    t0 = time.perf_counter()
    for pk, msg, sig in sample:
        assert verify_ed25519_zip215(pk, msg, sig)
    cpu_per_sig = (time.perf_counter() - t0) / len(sample)
    vs_baseline = (cpu_per_sig * nsig) / p50

    print(json.dumps({
        "metric": "ed25519 sig-verifies/sec/chip "
                  "(extended-commit-shaped batch)",
        "value": round(sigs_per_sec, 1),
        "unit": "sigs/s",
        "vs_baseline": round(vs_baseline, 2),
        "p50_batch_latency_ms": round(p50 * 1e3, 3),
        "batch_size": nsig,
        "backend": backend,
        "device": str(dev),
        "cpu_single_verify_us": round(cpu_per_sig * 1e6, 1),
    }), flush=True)


# --------------------------------------------------------------------------
# parent: orchestrates attempts; never imports jax; always emits JSON
# --------------------------------------------------------------------------

def _run_attempt(backend: str, nsig: int, timeout_s: float) -> dict | None:
    env = dict(os.environ)
    if backend == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
    cmd = [sys.executable, os.path.abspath(__file__),
           "--_child", backend, str(nsig)]
    print(f"[bench] attempt backend={backend} nsig={nsig} "
          f"timeout={timeout_s:.0f}s", file=sys.stderr, flush=True)
    try:
        proc = subprocess.run(cmd, env=env, timeout=timeout_s,
                              stdout=subprocess.PIPE, stderr=sys.stderr)
    except subprocess.TimeoutExpired:
        print(f"[bench] backend={backend} TIMED OUT after {timeout_s:.0f}s",
              file=sys.stderr, flush=True)
        return None
    if proc.returncode != 0:
        print(f"[bench] backend={backend} exited rc={proc.returncode}",
              file=sys.stderr, flush=True)
        return None
    for line in reversed(proc.stdout.decode(errors="replace").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def main() -> None:
    nsig_tpu = int(os.environ.get("BENCH_NSIG", "10240"))
    nsig_cpu = int(os.environ.get("BENCH_NSIG_CPU", "1024"))
    t_tpu = float(os.environ.get("BENCH_TPU_TIMEOUT", "480"))
    t_cpu = float(os.environ.get("BENCH_CPU_TIMEOUT", "900"))

    platforms = os.environ.get("JAX_PLATFORMS", "")
    want_tpu = ("cpu" != platforms.strip().lower())

    attempts: list[tuple[str, int, float]] = []
    if want_tpu:
        attempts.append(("tpu", nsig_tpu, t_tpu))
    attempts.append(("cpu", nsig_cpu, t_cpu))

    errors = []
    for backend, nsig, timeout_s in attempts:
        result = _run_attempt(backend, nsig, timeout_s)
        if result is not None:
            print(json.dumps(result), flush=True)
            return
        errors.append(backend)

    # Every attempt failed: still emit a well-formed result line.
    print(json.dumps({
        "metric": "ed25519 sig-verifies/sec/chip "
                  "(extended-commit-shaped batch)",
        "value": 0,
        "unit": "sigs/s",
        "vs_baseline": 0,
        "error": f"all backends failed: {errors}",
    }), flush=True)


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--_child":
        _child_main(sys.argv[2], int(sys.argv[3]))
    else:
        main()
