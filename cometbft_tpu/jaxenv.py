"""JAX backend environment control shared by tests, bench, and driver entry.

On this image a sitecustomize pre-registers the ``axon`` TPU backend whose
relay can wedge so that the first backend init (``jax.devices()``) hangs
forever.  Anything that is CPU-only by design (tests, the multichip dryrun,
the bench CPU fallback) must force the CPU backend *and* deregister the
axon/tpu factories before any backend init, or it can never be trusted to
terminate.  Keeping the defense here means one place to fix when a jax
upgrade moves the private factory registry.
"""

from __future__ import annotations

import os
import re

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def enable_compile_cache(cache_dir: str | None = None,
                         min_compile_secs: float = 2.0) -> None:
    """Point jax at the persistent on-disk XLA compile cache.

    The ed25519 kernel takes ~1 min to compile per batch-shape bucket on one
    CPU core; the cache makes every repeat process start in milliseconds.
    """
    import jax

    jax.config.update("jax_compilation_cache_dir",
                      cache_dir or os.path.join(_REPO, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      min_compile_secs)


def harden_cpu_pinned_env() -> None:
    """If the process is pinned to CPU (``JAX_PLATFORMS=cpu``), deregister
    the accelerator backend factories before first init: with the axon
    relay wedged, even CPU-pinned backend discovery can hang while the
    plugin registers.  No-op when an accelerator is wanted or a backend
    already initialized."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() != "cpu":
        return
    try:
        import jax
        from jax._src import xla_bridge as _xb

        if getattr(_xb, "_backends", None):
            return               # too late; whatever happened happened
        # the env var alone is not enough: the accelerator site hooks can
        # pin jax_platforms via config, which overrides the environment
        jax.config.update("jax_platforms", "cpu")
        _xb._backend_factories.pop("axon", None)
        _xb._backend_factories.pop("tpu", None)
    except Exception:
        pass


def force_cpu_backend(min_devices: int | None = None) -> None:
    """Force jax onto the CPU backend, optionally with >= min_devices
    virtual devices, before any backend init.

    Raises RuntimeError if a non-CPU backend was already initialized in this
    process — the config updates would silently not apply.
    """
    import jax
    from jax._src import xla_bridge as _xb

    platforms = sorted(getattr(_xb, "_backends", {}) or {})
    if platforms and platforms != ["cpu"]:
        raise RuntimeError(
            f"jax backend(s) {platforms} already initialized; "
            "force_cpu_backend() must run before any jax.devices()/jit")

    jax.config.update("jax_platforms", "cpu")
    try:
        _xb._backend_factories.pop("axon", None)
        _xb._backend_factories.pop("tpu", None)
    except AttributeError:  # private registry moved in a jax upgrade
        pass

    if min_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        m = re.search(r"xla_force_host_platform_device_count=(\d+)", flags)
        if m is None or int(m.group(1)) < min_devices:
            try:
                jax.config.update("jax_num_cpu_devices", min_devices)
            except Exception:
                os.environ["XLA_FLAGS"] = (
                    flags +
                    f" --xla_force_host_platform_device_count={min_devices}"
                ).strip()
