"""Random-linear-combination (RLC) batch verification on device.

The round-4 profile put 76% of device time in the per-lane Straus ladder
(~256 doublings + 128 table additions per signature); this kernel is the
structural answer (docs/explanation/tpu-kernel.md "what's next"): verify
the WHOLE batch with one cofactored random-linear-combination equation

    [8]( [Σᵢ zᵢsᵢ]B  -  Σᵢ [zᵢhᵢ](Aᵢ)  -  Σᵢ [zᵢ](Rᵢ) )  ==  identity

with independent 128-bit coefficients zᵢ — exactly what the native CPU
path (``native/ed25519.cpp``) and the reference's curve25519-voi batch
verifier do on host (``crypto/ed25519/ed25519.go:188-221``), redesigned
for the TPU's vector units:

- The doublings are paid ONCE for the whole batch (64 windows x 4),
  not once per lane: the MSB-first ladder walks 4-bit windows of all
  scalars simultaneously.
- Per window, each lane contributes one gathered table entry
  ([digit](-Aᵢ) from the cached per-validator tables, [digit](-Rᵢ)
  from per-batch tables), and the lane contributions collapse through a
  **binary tree of cached-coordinate additions** (``group.add_cc``):
  log2(B) levels of halving-width vector adds — total group-op work
  ~B per window instead of ~6B for the per-lane ladder, and every
  level is a dense vector op over the limb-major lane axis.
- The B term needs no tree: Σzᵢsᵢ mod L is a cheap mod-L sum and one
  scalar walks the constant [j]B niels table.
- zᵢ is 128 bits, so the R tree only runs for the lower 32 windows
  (a branch on the loop counter — compile-time-friendly ``lax.cond``).

Soundness: per-lane defects Dᵢ = sᵢB - hᵢAᵢ - Rᵢ of VALID signatures
are torsion (killed by the cofactor), so any zᵢ accept; a batch with a
non-torsion defect survives only if Σzᵢ·Dᵢ lands in torsion, which the
independent 128-bit zᵢ bound to probability ~2⁻¹²⁸.  Scalars need only
be correct mod L and < 2^256 (the cofactored-equation trick of
``ops/scalar.py``): [kL]P is torsion for every curve point P.  The RLC
verdict is all-or-nothing; on reject the dispatcher falls back to the
per-lane kernel (``ops/ed25519.py``) to localize failures, mirroring
the native CPU path's fallback contract.  Padding lanes carry zᵢ = 0
and contribute the identity to every sum.

Layout follows the promoted limb-major convention: byte matrices stay
batch-major at the interface, curve arithmetic runs over (20, B).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import fe, scalar, sha512
from .ed25519 import BASE_NIELS_T, _build_neg_a_table, _g
from .group import Cached, Niels

__all__ = ["verify_batch_rlc", "verify_batch_rlc_gather",
           "host_rlc_coeffs"]

_RADIX, _MASK = fe.RADIX, fe.MASK


def host_rlc_coeffs(n: int, active_mask=None, rng_bytes=None) -> np.ndarray:
    """(n, 10) int32 13-bit limbs of independent 128-bit coefficients.

    Inactive (padding) lanes get z = 0 so they drop out of every sum;
    active all-zero rows (probability 2⁻¹²⁸, but a z=0 lane would verify
    unchecked) are bumped to 1.  ``rng_bytes`` injects determinism for
    tests; production uses the OS CSPRNG — the coefficients must be
    unpredictable to an adversary who chose the signatures."""
    if rng_bytes is None:
        import secrets

        rng_bytes = secrets.token_bytes(16 * n)
    raw = np.frombuffer(rng_bytes, np.uint8).reshape(n, 16)
    limbs = np.zeros((n, scalar.Z_NLIMBS), np.int64)
    for i in range(scalar.Z_NLIMBS):
        bit0 = _RADIX * i
        acc = np.zeros((n,), np.int64)
        for j in range(bit0 // 8, min((bit0 + _RADIX + 7) // 8, 16)):
            shift = 8 * j - bit0
            b = raw[:, j].astype(np.int64)
            acc += (b << shift) if shift >= 0 else (b >> -shift)
        limbs[:, i] = acc & _MASK
    if active_mask is not None:
        limbs[~np.asarray(active_mask, bool)] = 0
        zero = (limbs.sum(axis=1) == 0) & np.asarray(active_mask, bool)
    else:
        zero = limbs.sum(axis=1) == 0
    limbs[zero, 0] = 1
    return limbs.astype(np.int32)


def _gather_all_windows(tab: Cached, digits) -> Cached:
    """Per-lane table (16, 20, B) + per-window digits (B, NW) ->
    cached entries (20, B*NW), LANE-MAJOR: column b*NW + w holds lane
    b's table row for window w.  Every window's gather happens at once,
    and the lane-major order makes the whole (window x lane) sheet one
    flat 2-D axis whose tree halving pairs lane b with lane b + B/2 for
    every window simultaneously."""
    nw = digits.shape[1]

    def one(c):
        ct = jnp.transpose(c, (2, 0, 1))         # (B, 16, 20)
        ent = jnp.take_along_axis(ct, digits[:, :, None], axis=1)
        return jnp.transpose(ent, (2, 0, 1)).reshape(c.shape[1], -1)

    return Cached(*[one(c) for c in tab]), nw


def _tree_reduce_lanes(ents: Cached, nw: int) -> Cached:
    """Binary tree of cached-coordinate additions over the lane-major
    (20, W*NW) sheet -> per-window sums (20, NW).

    All windows reduce simultaneously: the tree compiles ONCE for the
    whole verdict (log2(W) add_cc levels) instead of once per window
    body, and every level is a (20, (W/2)*NW)-wide vector op — the
    narrow tail of a per-window tree gets NW-fold occupancy here.
    Lanes pad to a power of two with identity entries (z = 0 padding
    lanes are already identity contributors, but arbitrary batch sizes
    appear in tests)."""
    w = ents.ypx.shape[1] // nw
    p2 = 1 << (w - 1).bit_length()
    if p2 != w:
        idc = _g.cache(_g.identity(((p2 - w) * nw,)))
        ents = Cached(*[jnp.concatenate([c, i_c], axis=1)
                        for c, i_c in zip(ents, idc)])
        w = p2
    while w > 1:
        h = (w // 2) * nw
        left = Cached(*[c[:, :h] for c in ents])
        right = Cached(*[c[:, h:] for c in ents])
        ents = _g.add_cc(left, right)
        w //= 2
    return ents                                   # (20, NW)


def _rlc_sums(neg_a_tab, ok_a, rb, sb, blocks, active, z10):
    """Per-window lane sums + the B-term scalar sum + the lane-ok
    verdict, for one (shard of a) batch.  Everything here is local to
    the lanes it sees — the sharded dispatch runs this per device and
    combines the outputs, the single-device path feeds them straight to
    :func:`_rlc_ladder`."""
    r_pt, ok_r = _g.decompress_zip215(jnp.transpose(rb))
    neg_r_tab = _build_neg_a_table(_g.neg_ext(r_pt))

    s20 = scalar.bytes32_to_limbs(sb)
    ok_s = scalar.lt_l(s20)
    h20 = scalar.reduce512(sha512.sha512_blocks(blocks, active))

    zh = scalar.mul_mod_l(h20, z10)              # (B, 20)
    zs_sum = scalar.sum_mod_l(scalar.mul_mod_l(s20, z10), axis=0)  # (20,)

    zh_dig = scalar.nibbles(zh)                  # (B, 64)
    z_dig = scalar.nibbles_k(z10, scalar.Z_NLIMBS, 32)   # (B, 32)

    # all 64 (resp. 32) per-window lane sums at once: one gather + one
    # shared tree — per-window sums (20, NW)
    sum_a = _tree_reduce_lanes(*_gather_all_windows(neg_a_tab, zh_dig))
    sum_r = _tree_reduce_lanes(*_gather_all_windows(neg_r_tab, z_dig))

    # ok bits only bind on ACTIVE lanes (z != 0): padding lanes repeat
    # lane 0's bytes on some callers but carry arbitrary garbage on
    # others, and a garbage padding lane must never veto the batch (its
    # z = 0 already removes it from every sum).  Active all-zero z rows
    # are bumped to 1 host-side, so z != 0 is exactly the active mask.
    active_lane = jnp.any(z10 != 0, axis=1)
    lanes_ok = jnp.all((ok_a & ok_r & ok_s) | ~active_lane)
    return sum_a, sum_r, zs_sum, lanes_ok


def _rlc_ladder(sum_a, sum_r, zs_sum):
    """The width-1 MSB-first ladder over precomputed per-window sums:
    64 x 4 doublings + one base-niels add + the A/R window sums, then
    the cofactored identity check."""
    sum_dig = scalar.nibbles(zs_sum)             # (64,)
    base_ents = jnp.take(jnp.asarray(BASE_NIELS_T), sum_dig,
                         axis=2)                 # (3, 20, 64)

    def window(i, acc):
        w = 63 - i
        acc = jax.lax.fori_loop(0, 4, lambda _, a: _g.dbl(a), acc)
        be = jax.lax.dynamic_slice_in_dim(base_ents, w, 1, axis=2)
        acc = _g.add_niels(acc, Niels(be[0], be[1], be[2]))
        sa = Cached(*[jax.lax.dynamic_slice_in_dim(c, w, 1, axis=1)
                      for c in sum_a])
        acc = _g.add_cached(acc, sa)

        def with_r(a):
            # w < 32 in this branch; the traced w>=32 index clamps
            # harmlessly (branch never executes there)
            sr = Cached(*[jax.lax.dynamic_slice_in_dim(c, w, 1, axis=1)
                          for c in sum_r])
            return _g.add_cached(a, sr)

        return jax.lax.cond(w < 32, with_r, lambda a: a, acc)

    acc = jax.lax.fori_loop(0, 64, window, _g.identity((1,)))
    return _g.is_identity(_g.mul_by_cofactor(acc))[0]


def _rlc_core(neg_a_tab, ok_a, rb, sb, blocks, active, z10):
    """Shared RLC ladder over per-lane [j](-A) cached tables."""
    sum_a, sum_r, zs_sum, lanes_ok = _rlc_sums(
        neg_a_tab, ok_a, rb, sb, blocks, active, z10)
    return lanes_ok & _rlc_ladder(sum_a, sum_r, zs_sum)


def verify_batch_rlc(pub, rb, sb, blocks, active, z10):
    """One-shot RLC verdict for a padded batch.

    pub/rb/sb (B, 32) int32 bytes; blocks/active as
    ``ed25519.verify_padded``; z10 (B, 10) int32 coefficient limbs
    (``host_rlc_coeffs`` — 0 on padding lanes).  Returns a scalar bool:
    True iff every active lane verifies (up to the 2⁻¹²⁸ RLC bound).
    """
    from .ed25519 import prepare_pubkey_tables

    neg_a_tab, ok_a = prepare_pubkey_tables(pub)
    return _rlc_core(neg_a_tab, ok_a, rb, sb, blocks, active, z10)


def verify_batch_rlc_gather(tab, ok_a, idx, rb, sb, blocks, active, z10):
    """RLC verdict through a CACHED whole-validator-set table
    (``ed25519.prepare_pubkey_tables`` output): the steady-state commit
    path — A decompression and table building amortize across commits,
    the doublings amortize across lanes, so per-commit device work is
    the gathers, two trees, and one width-1 ladder."""
    lane_tab = Cached(*[jnp.take(c, idx, axis=2) for c in tab])
    lane_ok = jnp.take(ok_a, idx, axis=0)
    return _rlc_core(lane_tab, lane_ok, rb, sb, blocks, active, z10)


def make_verify_batch_rlc_sharded(mesh, gather: bool = False):
    """RLC verdict sharded over the lane axis of ``mesh``.

    The tree reduce is group addition, not an elementwise sum, so the
    lane tree cannot simply ``psum``: instead each device runs
    :func:`_rlc_sums` on its own lane shard (decompression, hashing,
    gathers and the local reduction tree all stay collective-free), and
    only the per-device PARTIAL per-window sums — cached coordinates,
    (20, 96) per device — cross the interconnect, where a replicated
    tree of ``add_cc`` folds them before the single width-1 ladder.
    Cross-chip traffic is therefore O(windows) points per verdict,
    independent of batch size — the reduction the single-device gate at
    ``crypto/batch.py`` used to forbid.

    ``gather=True`` builds the cached-valset-table variant (table and ok
    mask replicated, per-lane args sharded).  Returns an UNJITTED
    callable with the same signature as the corresponding single-device
    entry; callers jit it once per mesh.
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    axis = mesh.axis_names[0]
    lane = P(axis)
    ndev = int(np.asarray(mesh.devices).size)

    def _local_sums(tab_or_pub, ok_or_none, *args):
        if gather:
            idx, rb, sb, blocks, active, z10 = args
            lane_tab = Cached(*[jnp.take(c, idx, axis=2)
                                for c in tab_or_pub])
            lane_ok = jnp.take(ok_or_none, idx, axis=0)
        else:
            from .ed25519 import prepare_pubkey_tables

            rb, sb, blocks, active, z10 = args
            lane_tab, lane_ok = prepare_pubkey_tables(tab_or_pub)
        sum_a, sum_r, zs, ok = _rlc_sums(lane_tab, lane_ok, rb, sb,
                                         blocks, active, z10)
        return (tuple(c[None] for c in sum_a),
                tuple(c[None] for c in sum_r), zs[None], ok[None])

    dev3 = P(axis, None, None)
    out_specs = ((dev3,) * len(Cached._fields),
                 (dev3,) * len(Cached._fields), P(axis, None),
                 P(axis))
    if gather:
        in_specs = ((P(),) * len(Cached._fields), P(),
                    lane, lane, lane, lane, lane, lane)
    else:
        in_specs = (lane, lane, lane, lane, lane, lane)
        # signature folds (pub, rb, ...) into (tab_or_pub, *args): drop
        # the unused ok slot by wrapping below
    smapped = shard_map(
        (lambda tab, ok, *a: _local_sums(tab, ok, *a)) if gather
        else (lambda pub, *a: _local_sums(pub, None, *a)),
        mesh=mesh, in_specs=in_specs, out_specs=out_specs)

    def _combine(sa_stk, sr_stk, zs_stk, ok_stk):
        sum_a = Cached(*[c[0] for c in sa_stk])
        sum_r = Cached(*[c[0] for c in sr_stk])
        for d in range(1, ndev):
            sum_a = _g.add_cc(sum_a, Cached(*[c[d] for c in sa_stk]))
            sum_r = _g.add_cc(sum_r, Cached(*[c[d] for c in sr_stk]))
        zs_sum = scalar.sum_mod_l(zs_stk, axis=0)
        return jnp.all(ok_stk) & _rlc_ladder(sum_a, sum_r, zs_sum)

    if gather:
        def fn(tab, ok_a, idx, rb, sb, blocks, active, z10):
            return _combine(*smapped(tuple(tab), ok_a, idx, rb, sb,
                                     blocks, active, z10))
    else:
        def fn(pub, rb, sb, blocks, active, z10):
            return _combine(*smapped(pub, rb, sb, blocks, active, z10))
    return fn
