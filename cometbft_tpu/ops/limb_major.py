"""Limb-major (20, B) variant of the Ed25519 verify kernel.

The production kernel (``ops/ed25519.py``) shapes field elements
``(B, 20)`` — limbs on the minor axis.  On TPU the minor axis maps to
the 128-wide vector lane dimension, so 20 limbs occupy 20 of 128 lanes
(~16% utilization) and ``fe.mul``'s Toeplitz intermediate is tiled
wastefully; the measured symptom is the large-batch HBM cliff
(docs/bench/r04-notes.md).  This module flips the layout: field
elements are ``(20, B)`` — the BATCH rides the vector lanes, limbs ride
the sublane axis — with the multiply as 20 statically-shifted
row-accumulations (no Toeplitz intermediate at all).  The CPU rehearsal
of ``scripts/kern_layout_probe.py`` measures the multiply alone at
~4.6x the batch-major form; this module exists so the next TPU window
can measure the WHOLE pipeline and, if the win holds, swap the dispatch
(`crypto/batch.py`) over.

Scope: fe + edwards layers only.  SHA-512 and the mod-L scalar pipeline
stay batch-major (together ~5% of device time) — their outputs feed the
ladder purely as (B,) gather indices, which are layout-agnostic.

Interface parity: :func:`verify_padded_lm` takes exactly the arguments
of ``ed25519.verify_padded`` and returns the same (B,) bool mask;
``tests/test_limb_major.py`` pins bit-identical accept/reject against
the production kernel over random batches and the ZIP-215 edge corpus.

Duplication note: the point formulas and exponentiation chains below
mirror ``ops/edwards.py`` / ``ops/fe.py`` verbatim modulo the broadcast
axis — deliberate for an EXPERIMENTAL twin that must not perturb the
production kernel while awaiting hardware numbers.  If the measured win
holds and this layout is promoted, the production ``edwards.py`` gets
parameterized over its field-ops module instead of keeping two copies.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np
import jax
import jax.numpy as jnp

from . import fe, scalar, sha512
from . import ed25519 as _prod

RADIX, MASK, NL, NC, FOLD = fe.RADIX, fe.MASK, fe.NLIMBS, fe.NCOLS, fe.FOLD


def _const(x_limbs) -> jnp.ndarray:
    """Canonical (20,) limb constant -> (20, 1) column for broadcast."""
    return jnp.asarray(np.asarray(x_limbs, np.int32).reshape(NL, 1))


ONE = _const(fe.ONE_LIMBS)
ZERO = _const(fe.ZERO_LIMBS)
D = _const(fe.D_LIMBS)
D2 = _const(fe.D2_LIMBS)
SQRT_M1 = _const(fe.SQRT_M1_LIMBS)
SUB_OFF = _const(fe.SUB_OFF)
P_COL = _const(fe.P_LIMBS)


# ------------------------------------------------------------------ fe

def _wrap_carry(x, passes: int):
    for _ in range(passes):
        lo = x & MASK
        hi = x >> RADIX
        wrapped = jnp.concatenate([hi[-1:] * FOLD, hi[:-1]], axis=0)
        x = lo + wrapped
    return x


def add(a, b):
    return _wrap_carry(a + b, 1)


def sub(a, b):
    return _wrap_carry(a + SUB_OFF - b, 2)


def neg(a):
    return sub(jnp.zeros_like(a), a)


def _reduce_columns(cols):
    """(39, B) product columns -> loose (20, B)."""
    lo = cols & MASK
    hi = cols >> RADIX
    limbs40 = jnp.concatenate([lo, jnp.zeros_like(lo[:1])],
                              axis=0).at[1:].add(hi)
    folded = limbs40[:NL] + FOLD * limbs40[NL:]
    return _wrap_carry(folded, 3)


def mul(a, b):
    """Shifted accumulation: 20 statically-placed partial products, no
    (…,20,39) intermediate (the batch-major kernel's HBM hazard)."""
    out = jnp.zeros((NC,) + jnp.broadcast_shapes(a.shape[1:], b.shape[1:]),
                    jnp.int32)
    for i in range(NL):
        out = out.at[i:i + NL].add(a[i:i + 1] * b)
    return _reduce_columns(out)


def square(a):
    return mul(a, a)


def select(mask, a, b):
    """mask (B,) bool -> limbs from a where true else b."""
    return jnp.where(mask[None, :], a, b)


def freeze(a):
    """Loose -> canonical in [0, p); mirrors fe.freeze on axis 0."""
    limbs = []
    c = jnp.zeros_like(a[0])
    for i in range(NL):
        t = a[i] + c
        limbs.append(t & MASK)
        c = t >> RADIX
    t = limbs[0] + c * FOLD
    limbs[0] = t & MASK
    c = t >> RADIX
    for i in range(1, NL):
        t = limbs[i] + c
        limbs[i] = t & MASK
        c = t >> RADIX
    limbs[0] = limbs[0] + c * FOLD
    q = limbs[19] >> 8
    limbs[19] = limbs[19] & 255
    c = q * 19
    for i in range(NL):
        t = limbs[i] + c
        limbs[i] = t & MASK
        c = t >> RADIX
    x = jnp.stack(limbs, axis=0)
    borrow = jnp.zeros_like(x[0])
    diff = []
    for i in range(NL):
        t = x[i] - jnp.int32(int(fe.P_LIMBS[i])) - borrow
        diff.append(t & MASK)
        borrow = (t >> RADIX) & 1
    d = jnp.stack(diff, axis=0)
    return select(borrow == 0, d, x)


def is_zero(a):
    return jnp.all(freeze(a) == 0, axis=0)


def eq(a, b):
    return is_zero(sub(a, b))


def from_bytes32_T(bt, mask_bit255: bool = True):
    """(32, B) little-endian bytes -> (20, B) limbs (raw 255-bit value)."""
    bt = bt.astype(jnp.int32)
    limbs = []
    for i in range(NL):
        bit0 = RADIX * i
        acc = jnp.zeros_like(bt[0])
        for j in range(bit0 // 8, min((bit0 + RADIX + 7) // 8, 32)):
            shift = 8 * j - bit0
            byte = bt[j]
            if mask_bit255 and j == 31:
                byte = byte & 127
            acc = acc + (byte << shift if shift >= 0 else byte >> -shift)
        limbs.append(acc & MASK)
    return jnp.stack(limbs, axis=0)


def _sq_n(a, n: int):
    if n <= 4:
        for _ in range(n):
            a = square(a)
        return a
    return jax.lax.fori_loop(0, n, lambda _, x: square(x), a)


def _pow_chain(z):
    """z^(2^250 - 1) (no z^11 second return: nothing here inverts)."""
    z2 = square(z)
    z9 = mul(z, _sq_n(z2, 2))
    z11 = mul(z2, z9)
    z_5_0 = mul(z9, square(z11))
    z_10_0 = mul(_sq_n(z_5_0, 5), z_5_0)
    z_20_0 = mul(_sq_n(z_10_0, 10), z_10_0)
    z_40_0 = mul(_sq_n(z_20_0, 20), z_20_0)
    z_50_0 = mul(_sq_n(z_40_0, 10), z_10_0)
    z_100_0 = mul(_sq_n(z_50_0, 50), z_50_0)
    z_200_0 = mul(_sq_n(z_100_0, 100), z_100_0)
    z_250_0 = mul(_sq_n(z_200_0, 50), z_50_0)
    return z_250_0


def pow22523(z):
    return mul(_sq_n(_pow_chain(z), 2), z)


def sqrt_ratio(u, v):
    v3 = mul(square(v), v)
    uv3 = mul(u, v3)
    uv7 = mul(uv3, square(square(v)))
    x = mul(uv3, pow22523(uv7))
    vxx = mul(v, square(x))
    ok_direct = eq(vxx, u)
    ok_flip = eq(vxx, neg(u))
    x = select(ok_direct, x, mul(x, SQRT_M1))
    return x, ok_direct | ok_flip


# ------------------------------------------------------------- edwards

class Ext(NamedTuple):
    x: jnp.ndarray
    y: jnp.ndarray
    z: jnp.ndarray
    t: jnp.ndarray


class Cached(NamedTuple):
    ypx: jnp.ndarray
    ymx: jnp.ndarray
    z2: jnp.ndarray
    t2d: jnp.ndarray


class Niels(NamedTuple):
    ypx: jnp.ndarray
    ymx: jnp.ndarray
    t2d: jnp.ndarray


def identity(n: int) -> Ext:
    zero = jnp.broadcast_to(ZERO, (NL, n))
    one = jnp.broadcast_to(ONE, (NL, n))
    return Ext(zero, one, one, zero)


def cache(p: Ext) -> Cached:
    return Cached(add(p.y, p.x), sub(p.y, p.x), add(p.z, p.z),
                  mul(p.t, D2))


def neg_ext(p: Ext) -> Ext:
    return Ext(neg(p.x), p.y, p.z, neg(p.t))


def dbl(p: Ext) -> Ext:
    a = square(p.x)
    b = square(p.y)
    c = add(square(p.z), square(p.z))
    h = add(a, b)
    e = sub(h, square(add(p.x, p.y)))
    g = sub(a, b)
    f = add(c, g)
    return Ext(mul(e, f), mul(g, h), mul(f, g), mul(e, h))


def add_cached(p: Ext, q: Cached) -> Ext:
    a = mul(sub(p.y, p.x), q.ymx)
    b = mul(add(p.y, p.x), q.ypx)
    c = mul(p.t, q.t2d)
    d = mul(p.z, q.z2)
    e = sub(b, a)
    f = sub(d, c)
    g = add(d, c)
    h = add(b, a)
    return Ext(mul(e, f), mul(g, h), mul(f, g), mul(e, h))


def add_niels(p: Ext, q: Niels) -> Ext:
    a = mul(sub(p.y, p.x), q.ymx)
    b = mul(add(p.y, p.x), q.ypx)
    c = mul(p.t, q.t2d)
    d = add(p.z, p.z)
    e = sub(b, a)
    f = sub(d, c)
    g = add(d, c)
    h = add(b, a)
    return Ext(mul(e, f), mul(g, h), mul(f, g), mul(e, h))


def decompress_zip215(enc_T):
    """(32, B) encoded bytes -> (Ext over (20, B), (B,) ok)."""
    sign = (enc_T[31].astype(jnp.int32) >> 7) & 1
    y = from_bytes32_T(enc_T, mask_bit255=True)
    yy = square(y)
    u = sub(yy, ONE)
    v = add(mul(yy, D), ONE)
    x, ok = sqrt_ratio(u, v)
    x = freeze(x)
    flip = (x[0] & 1) != sign
    x = select(flip, neg(x), x)
    one = jnp.broadcast_to(ONE, x.shape)
    return Ext(x, y, one, mul(x, y)), ok


def mul_by_cofactor(p: Ext) -> Ext:
    return dbl(dbl(dbl(p)))


def is_identity(p: Ext):
    return is_zero(p.x) & eq(p.y, p.z)


# -------------------------------------------------------------- kernel

# constant [j]B niels table, limb-major: (3, 20, 16)
BASE_NIELS_T = np.transpose(_prod.BASE_NIELS, (1, 2, 0)).copy()


def _build_neg_a_table(neg_a: Ext) -> Cached:
    """(16, 20, B)-stacked cached table of [j](-A), j = 0..15."""
    n = neg_a.x.shape[1]
    entries = [cache(identity(n)), cache(neg_a)]
    p2 = dbl(neg_a)
    entries.append(cache(p2))
    pj = p2
    for _ in range(3, 16):
        pj = add_cached(pj, entries[1])
        entries.append(cache(pj))
    return Cached(*[jnp.stack([e[i] for e in entries], axis=0)
                    for i in range(4)])


def _gather_niels(digit) -> Niels:
    """(B,) digit -> constant-table Niels entry over (20, B)."""
    tab = jnp.asarray(BASE_NIELS_T)              # (3, 20, 16)
    ent = jnp.take(tab, digit, axis=2)           # (3, 20, B)
    return Niels(ent[0], ent[1], ent[2])


def _gather_cached(tab: Cached, digit) -> Cached:
    """Per-lane table (16, 20, B) + (B,) digit -> (20, B) entry."""
    idx = digit[None, None, :]
    return Cached(*[jnp.take_along_axis(c, idx, axis=0)[0] for c in tab])


def verify_padded_lm(pub, rb, sb, blocks, active):
    """Drop-in limb-major twin of ``ed25519.verify_padded``: identical
    arguments (batch-major byte matrices) and identical (B,) verdict."""
    pub_T = jnp.transpose(pub)                   # (32, B)
    rb_T = jnp.transpose(rb)

    a_pt, ok_a = decompress_zip215(pub_T)
    neg_a_tab = _build_neg_a_table(neg_ext(a_pt))
    r_pt, ok_r = decompress_zip215(rb_T)

    # scalar + hash pipeline stays batch-major: outputs are (B,) digit
    # vectors consumed only as gather indices
    s_limbs = scalar.bytes32_to_limbs(sb)
    ok_s = scalar.lt_l(s_limbs)
    s_dig = scalar.nibbles(s_limbs)
    h_dig = scalar.nibbles(scalar.reduce512(
        sha512.sha512_blocks(blocks, active)))

    n = pub.shape[0]

    def window(i, acc):
        w = 63 - i
        acc = dbl(dbl(dbl(dbl(acc))))
        ds = jax.lax.dynamic_index_in_dim(s_dig, w, axis=s_dig.ndim - 1,
                                          keepdims=False)
        acc = add_niels(acc, _gather_niels(ds))
        dh = jax.lax.dynamic_index_in_dim(h_dig, w, axis=h_dig.ndim - 1,
                                          keepdims=False)
        acc = add_cached(acc, _gather_cached(neg_a_tab, dh))
        return acc

    acc = jax.lax.fori_loop(0, 64, window, identity(n))
    acc = add_cached(acc, cache(neg_ext(r_pt)))
    return ok_a & ok_r & ok_s & is_identity(mul_by_cofactor(acc))
