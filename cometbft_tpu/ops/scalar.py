"""Arithmetic mod L = 2^252 + 27742...493 (the Ed25519 group order), on device.

Used by the verify kernel for (a) the canonicity check ``S < L`` (ZIP-215
rejects non-canonical S, reference: curve25519-voi verify options) and
(b) reducing the 512-bit ``h = SHA-512(R||A||M)`` to a scalar.

A trick keeps this all-positive int32 (no signed-limb sc_reduce): the final
verification is *cofactored* (``[8](SB - hA - R) == 0``), so any h' ≡ h
(mod L) with h' < 2^256 verifies identically — [h'-h]A is killed by the
cofactor multiply even for mixed-order A.  We therefore reduce 512 → 256 bits
(not all the way below L): one (20-high-limb × 20)-matmul fold at the 2^260
boundary, three single-limb folds, then four folds at the 2^256 boundary.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from . import fe

L_INT = 2**252 + 27742317777372353535851937790883648493
RADIX, MASK, NL = fe.RADIX, fe.MASK, fe.NLIMBS

L_LIMBS = fe.limbs_from_int(L_INT)
# TAB[j] = limbs of 2^(13*(20+j)) mod L
TAB = np.stack([fe.limbs_from_int(pow(2, RADIX * (20 + j), L_INT))
                for j in range(NL)]).astype(np.int32)
# M260 = 2^260 mod L; R256 = 2^256 mod L
M260 = fe.limbs_from_int(pow(2, 260, L_INT))
R256 = fe.limbs_from_int(pow(2, 256, L_INT))


def _carry_exact(cols, nout: int):
    """Sequential exact carry; caller guarantees value < 2^(13*nout)."""
    limbs = []
    c = jnp.zeros_like(cols[..., 0])
    for i in range(cols.shape[-1]):
        t = cols[..., i] + c
        limbs.append(t & MASK)
        c = t >> RADIX
    while len(limbs) < nout:
        limbs.append(c & MASK)
        c = c >> RADIX
    return jnp.stack(limbs[:nout], axis=-1), c


def bytes32_to_limbs(b):
    """(…,32) bytes -> 20 canonical limbs of the full 256-bit value."""
    return fe.bytes_to_limbs(b, NL)


def bytes64_to_limbs40(b):
    """(…,64) bytes -> 40 canonical limbs (little-endian 512-bit value)."""
    return fe.bytes_to_limbs(b, 40)


def _fold256(x20):
    """One fold of bits >= 256 (limb 19 bits 9..12) via 2^256 ≡ R256."""
    v = x20[..., 19] >> 9
    lo = x20.at[..., 19].set(x20[..., 19] & 511)
    cols = lo + v[..., None] * jnp.asarray(R256)
    out, c = _carry_exact(cols, NL)
    return out


def reduce512(bytes64):
    """512-bit LE bytes -> canonical 20 limbs of some h' ≡ h (mod L), < 2^256."""
    x = bytes64_to_limbs40(bytes64)
    lo, hi = x[..., :NL], x[..., NL:]
    # matmul fold at 2^260: every high limb contributes via TAB
    cols = lo + jnp.einsum("...j,jk->...k", hi, jnp.asarray(TAB),
                           preferred_element_type=jnp.int32)
    x20, c = _carry_exact(cols, NL)          # value < 2^271 -> c < 2^11
    # single-limb folds at 2^260: carries shrink 2^11 -> 2^4 -> 1 -> 1 -> 0
    # (the 4th fold starts from value < 2^260 + 2^253, so lo < 2^253 when
    # c == 1 and the folded value < 2^254 — provably no 5th carry)
    for _ in range(4):
        cols = x20 + c[..., None] * jnp.asarray(M260)
        x20, c = _carry_exact(cols, NL)
    for _ in range(4):                        # endgame folds at 2^256
        x20 = _fold256(x20)
    return x20


def lt_l(x20):
    """(…,) bool: canonical-limb value < L (the S-canonicity check)."""
    lt = jnp.zeros(x20.shape[:-1], bool)
    for i in range(NL):
        li = jnp.int32(int(L_LIMBS[i]))
        lt = jnp.where(x20[..., i] < li, True,
                       jnp.where(x20[..., i] > li, False, lt))
    return lt


def nibbles_k(x, nlimbs: int, ndigits: int):
    """Canonical 13-bit limbs (…,nlimbs) -> (…,ndigits) radix-16 digits,
    LSB first (generalized digit extraction; the RLC coefficients are
    10-limb/32-digit, full scalars 20-limb/64-digit)."""
    digs = []
    for n in range(ndigits):
        bit0 = 4 * n
        j, s = divmod(bit0, RADIX)
        d = x[..., j] >> s
        if s > RADIX - 4 and j + 1 < nlimbs:
            d = d | (x[..., j + 1] << (RADIX - s))
        digs.append(d & 15)
    return jnp.stack(digs, axis=-1)


def nibbles(x20):
    """Canonical 20 limbs (< 2^256) -> (…,64) radix-16 digits, LSB first."""
    return nibbles_k(x20, NL, 64)


# ----- RLC batch-verification scalar arithmetic (ops/rlc.py) -----------
#
# The random-linear-combination kernel needs two more mod-L ops, both
# with the same "reduce to < 2^256, correct mod L" contract as reduce512
# (sufficient under the cofactored check — see module docstring):
# z·x products and batch sums.

Z_NLIMBS = 10                    # 130 bits: holds a 128-bit coefficient


def _fold_to_256(x20, c):
    """Shared endgame: fold an exact-carry residue (x20 < 2^260 in 20
    limbs, overflow carry c < 2^11) down to < 2^256 preserving mod L.
    The 4+4 fold counts inherit reduce512's bounds (its carry after the
    first fold is the larger: 2^11)."""
    for _ in range(4):
        cols = x20 + c[..., None] * jnp.asarray(M260)
        x20, c = _carry_exact(cols, NL)
    for _ in range(4):
        x20 = _fold256(x20)
    return x20


def mul_mod_l(x20, z10):
    """(…,20) canonical (< 2^256) x (…,10) canonical (< 2^130) ->
    (…,20) canonical, < 2^256 and ≡ x·z (mod L).

    Schoolbook columns: 29 columns, each ≤ 10·MASK² < 2^31 so the whole
    product stays int32; the < 2^386 result folds its 10 high limbs
    through TAB (2^(13·(20+j)) mod L) exactly like reduce512's matmul
    fold, then rides the shared endgame."""
    cols = jnp.zeros(jnp.broadcast_shapes(x20.shape[:-1], z10.shape[:-1])
                     + (NL + Z_NLIMBS - 1,), jnp.int32)
    for i in range(Z_NLIMBS):
        cols = cols.at[..., i:i + NL].add(z10[..., i:i + 1] * x20)
    x30, c = _carry_exact(cols, NL + Z_NLIMBS)
    lo, hi = x30[..., :NL], x30[..., NL:]
    cols2 = lo + jnp.einsum("...j,jk->...k", hi,
                            jnp.asarray(TAB[:Z_NLIMBS]),
                            preferred_element_type=jnp.int32)
    x20_, c = _carry_exact(cols2, NL)
    return _fold_to_256(x20_, c)


def sum_mod_l(x, axis: int = 0):
    """Sum canonical 20-limb values (< 2^256 each) over ``axis`` ->
    (…,20) canonical, < 2^256 and ≡ the sum (mod L).  Column sums must
    stay int32: requires at most 2^17 summands (the lane cap is 4096)."""
    assert x.shape[axis] <= (1 << 17)
    cols = jnp.sum(x, axis=axis)             # ≤ 2^17·MASK < 2^31 per col
    x21, c = _carry_exact(cols, NL + 1)      # value < 2^274 -> c == 0
    # fold limb 20 (≤ MASK) at the 2^260 boundary via M260
    cols2 = x21[..., :NL] + x21[..., NL:] * jnp.asarray(M260)
    x20, c = _carry_exact(cols2, NL)         # < 2^261 -> c ≤ 1 ≤ 2^11
    return _fold_to_256(x20, c)
