"""Arithmetic mod L = 2^252 + 27742...493 (the Ed25519 group order), on device.

Used by the verify kernel for (a) the canonicity check ``S < L`` (ZIP-215
rejects non-canonical S, reference: curve25519-voi verify options) and
(b) reducing the 512-bit ``h = SHA-512(R||A||M)`` to a scalar.

A trick keeps this all-positive int32 (no signed-limb sc_reduce): the final
verification is *cofactored* (``[8](SB - hA - R) == 0``), so any h' ≡ h
(mod L) with h' < 2^256 verifies identically — [h'-h]A is killed by the
cofactor multiply even for mixed-order A.  We therefore reduce 512 → 256 bits
(not all the way below L): one (20-high-limb × 20)-matmul fold at the 2^260
boundary, three single-limb folds, then four folds at the 2^256 boundary.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from . import fe

L_INT = 2**252 + 27742317777372353535851937790883648493
RADIX, MASK, NL = fe.RADIX, fe.MASK, fe.NLIMBS

L_LIMBS = fe.limbs_from_int(L_INT)
# TAB[j] = limbs of 2^(13*(20+j)) mod L
TAB = np.stack([fe.limbs_from_int(pow(2, RADIX * (20 + j), L_INT))
                for j in range(NL)]).astype(np.int32)
# M260 = 2^260 mod L; R256 = 2^256 mod L
M260 = fe.limbs_from_int(pow(2, 260, L_INT))
R256 = fe.limbs_from_int(pow(2, 256, L_INT))


def _carry_exact(cols, nout: int):
    """Sequential exact carry; caller guarantees value < 2^(13*nout)."""
    limbs = []
    c = jnp.zeros_like(cols[..., 0])
    for i in range(cols.shape[-1]):
        t = cols[..., i] + c
        limbs.append(t & MASK)
        c = t >> RADIX
    while len(limbs) < nout:
        limbs.append(c & MASK)
        c = c >> RADIX
    return jnp.stack(limbs[:nout], axis=-1), c


def bytes32_to_limbs(b):
    """(…,32) bytes -> 20 canonical limbs of the full 256-bit value."""
    return fe.bytes_to_limbs(b, NL)


def bytes64_to_limbs40(b):
    """(…,64) bytes -> 40 canonical limbs (little-endian 512-bit value)."""
    return fe.bytes_to_limbs(b, 40)


def _fold256(x20):
    """One fold of bits >= 256 (limb 19 bits 9..12) via 2^256 ≡ R256."""
    v = x20[..., 19] >> 9
    lo = x20.at[..., 19].set(x20[..., 19] & 511)
    cols = lo + v[..., None] * jnp.asarray(R256)
    out, c = _carry_exact(cols, NL)
    return out


def reduce512(bytes64):
    """512-bit LE bytes -> canonical 20 limbs of some h' ≡ h (mod L), < 2^256."""
    x = bytes64_to_limbs40(bytes64)
    lo, hi = x[..., :NL], x[..., NL:]
    # matmul fold at 2^260: every high limb contributes via TAB
    cols = lo + jnp.einsum("...j,jk->...k", hi, jnp.asarray(TAB),
                           preferred_element_type=jnp.int32)
    x20, c = _carry_exact(cols, NL)          # value < 2^271 -> c < 2^11
    # single-limb folds at 2^260: carries shrink 2^11 -> 2^4 -> 1 -> 1 -> 0
    # (the 4th fold starts from value < 2^260 + 2^253, so lo < 2^253 when
    # c == 1 and the folded value < 2^254 — provably no 5th carry)
    for _ in range(4):
        cols = x20 + c[..., None] * jnp.asarray(M260)
        x20, c = _carry_exact(cols, NL)
    for _ in range(4):                        # endgame folds at 2^256
        x20 = _fold256(x20)
    return x20


def lt_l(x20):
    """(…,) bool: canonical-limb value < L (the S-canonicity check)."""
    lt = jnp.zeros(x20.shape[:-1], bool)
    for i in range(NL):
        li = jnp.int32(int(L_LIMBS[i]))
        lt = jnp.where(x20[..., i] < li, True,
                       jnp.where(x20[..., i] > li, False, lt))
    return lt


def nibbles(x20):
    """Canonical 20 limbs (< 2^256) -> (…,64) radix-16 digits, LSB first."""
    digs = []
    for n in range(64):
        bit0 = 4 * n
        j, s = divmod(bit0, RADIX)
        d = x20[..., j] >> s
        if s > RADIX - 4 and j + 1 < NL:
            d = d | (x20[..., j + 1] << (RADIX - s))
        digs.append(d & 15)
    return jnp.stack(digs, axis=-1)
