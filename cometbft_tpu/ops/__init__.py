"""JAX/TPU compute kernels.

The compute hot path of the framework: GF(2^255-19) field arithmetic,
SHA-512, Edwards-curve point operations and the batched Ed25519 ZIP-215
verification kernel.  Everything here is pure-functional JAX over int32/uint32
arrays (no 64-bit integer multiplies — TPU vector units are 32-bit), shape
polymorphic over leading batch axes, and jit/vmap/shard_map-compatible.
"""
