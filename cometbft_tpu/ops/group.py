"""Twisted-Edwards (ed25519 curve) point formulas, written once and
parameterized over a field-arithmetic module.

Extended homogeneous coordinates (X:Y:Z:T), a = -1, the hwcd-2008
unified addition/doubling family — the same formulas curve25519-voi and
ref10 use (reference seam: the curve math behind
``crypto/ed25519/ed25519.go``), chosen because they are branch-free and
vectorize cleanly over the signature batch.

The formulas are pure compositions of field ops, so the data layout is
entirely the field module's business: :func:`make_group` instantiates
the whole group layer for either ``ops.fe`` (batch-major ``(B, 20)`` —
kept for the oracle-differential tests) or ``ops.fe_lm`` (limb-major
``(20, B)`` — the production kernel layout, see ``fe_lm``'s module doc
for the measured rationale).  A field module provides the arithmetic
(add/sub/neg/mul/square/select/freeze/is_zero/eq/sqrt_ratio) plus four
layout hooks: ``const`` (int -> broadcastable limb constant), ``bcast``
(constant x lane shape -> full array), ``sign_bit`` and ``limb0``
(byte/limb accessors), and ``from_bytes32``.

Representations (each component a limb array in the field layout):
- extended: ``(X, Y, Z, T)``  with x = X/Z, y = Y/Z, T = XY/Z
- cached:   ``(Y+X, Y-X, 2Z, 2dT)``   (general addition operand)
- niels:    ``(Y+X, Y-X, 2dXY)``      (affine table entry, Z = 1)
"""

from __future__ import annotations

from types import SimpleNamespace
from typing import NamedTuple

import jax.numpy as jnp


class Ext(NamedTuple):
    x: jnp.ndarray
    y: jnp.ndarray
    z: jnp.ndarray
    t: jnp.ndarray


class Cached(NamedTuple):
    ypx: jnp.ndarray
    ymx: jnp.ndarray
    z2: jnp.ndarray
    t2d: jnp.ndarray


class Niels(NamedTuple):
    ypx: jnp.ndarray
    ymx: jnp.ndarray
    t2d: jnp.ndarray


def make_group(f) -> SimpleNamespace:
    """Instantiate the point ops over field module ``f``."""
    P, D = f.P_INT, f.D_INT
    ONE_C = f.const(1)
    ZERO_C = f.const(0)
    D_C = f.const(D)
    D2_C = f.const(2 * D % P)
    INV2_C = f.const(pow(2, P - 2, P))
    INV2D_C = f.const(pow(2 * D % P, P - 2, P))

    def identity(lane_shape=()) -> Ext:
        zero = f.bcast(ZERO_C, lane_shape)
        one = f.bcast(ONE_C, lane_shape)
        return Ext(zero, one, one, zero)

    def cache(p: Ext) -> Cached:
        return Cached(f.add(p.y, p.x), f.sub(p.y, p.x), f.add(p.z, p.z),
                      f.mul(p.t, D2_C))

    def neg_ext(p: Ext) -> Ext:
        return Ext(f.neg(p.x), p.y, p.z, f.neg(p.t))

    def dbl(p: Ext) -> Ext:
        a = f.square(p.x)
        b = f.square(p.y)
        c = f.add(f.square(p.z), f.square(p.z))
        h = f.add(a, b)
        e = f.sub(h, f.square(f.add(p.x, p.y)))
        g = f.sub(a, b)
        ff = f.add(c, g)
        return Ext(f.mul(e, ff), f.mul(g, h), f.mul(ff, g), f.mul(e, h))

    def add_cached(p: Ext, q: Cached) -> Ext:
        a = f.mul(f.sub(p.y, p.x), q.ymx)
        b = f.mul(f.add(p.y, p.x), q.ypx)
        c = f.mul(p.t, q.t2d)
        d = f.mul(p.z, q.z2)
        e = f.sub(b, a)
        ff = f.sub(d, c)
        g = f.add(d, c)
        h = f.add(b, a)
        return Ext(f.mul(e, ff), f.mul(g, h), f.mul(ff, g), f.mul(e, h))

    def add_niels(p: Ext, q: Niels) -> Ext:
        a = f.mul(f.sub(p.y, p.x), q.ymx)
        b = f.mul(f.add(p.y, p.x), q.ypx)
        c = f.mul(p.t, q.t2d)
        d = f.add(p.z, p.z)
        e = f.sub(b, a)
        ff = f.sub(d, c)
        g = f.add(d, c)
        h = f.add(b, a)
        return Ext(f.mul(e, ff), f.mul(g, h), f.mul(ff, g), f.mul(e, h))

    def add_cc(p: Cached, q: Cached) -> Cached:
        """Cached x Cached -> Cached, for tree reductions (the RLC batch
        multiscalar, ``ops/rlc.py``): gathered table entries are already
        in cached form, and emitting cached form feeds the next tree
        level without a per-level ``cache()`` conversion.  Recovers the
        add_cached operands via the constant factors 1/2 and 1/(2d):
        T1*2dT2 = t2d_p*t2d_q/(2d), Z1*2Z2 = z2_p*z2_q/2."""
        a = f.mul(p.ymx, q.ymx)
        b = f.mul(p.ypx, q.ypx)
        c = f.mul(f.mul(p.t2d, q.t2d), INV2D_C)
        d = f.mul(f.mul(p.z2, q.z2), INV2_C)
        e = f.sub(b, a)
        ff = f.sub(d, c)
        g = f.add(d, c)
        h = f.add(b, a)
        x3 = f.mul(e, ff)
        y3 = f.mul(g, h)
        z3 = f.mul(ff, g)
        t3 = f.mul(e, h)
        return Cached(f.add(y3, x3), f.sub(y3, x3), f.add(z3, z3),
                      f.mul(t3, D2_C))

    def cached_to_ext(p: Cached) -> Ext:
        """Cached -> extended (X = (ypx-ymx)/2, Y = (ypx+ymx)/2,
        Z = z2/2, T = t2d/(2d)); used once at the end of a tree."""
        return Ext(f.mul(f.sub(p.ypx, p.ymx), INV2_C),
                   f.mul(f.add(p.ypx, p.ymx), INV2_C),
                   f.mul(p.z2, INV2_C),
                   f.mul(p.t2d, INV2D_C))

    def decompress_zip215(enc):
        """ZIP-215 (permissive) point decoding: non-canonical y >= p
        accepted, x = 0 with sign bit 1 accepted, small/mixed-order
        points fine; the only failure is a non-square x^2 candidate.
        Returns ``(Ext, ok)``; failed rows hold arbitrary but
        arithmetic-safe content (callers mask with ``ok``)."""
        sign = f.sign_bit(enc)
        y = f.from_bytes32(enc, True)
        yy = f.square(y)
        u = f.sub(yy, f.bcast(ONE_C, sign.shape))
        v = f.add(f.mul(yy, D_C), f.bcast(ONE_C, sign.shape))
        x, ok = f.sqrt_ratio(u, v)
        x = f.freeze(x)
        flip = (f.limb0(x) & 1) != sign
        x = f.select(flip, f.neg(x), x)
        return Ext(x, y, f.bcast(ONE_C, sign.shape), f.mul(x, y)), ok

    def mul_by_cofactor(p: Ext) -> Ext:
        import jax

        return jax.lax.fori_loop(0, 3, lambda _, q: dbl(q), p)

    def is_identity(p: Ext):
        """Projective identity check: X == 0 and Y == Z (mod p)."""
        return f.is_zero(p.x) & f.eq(p.y, p.z)

    return SimpleNamespace(
        f=f, identity=identity, cache=cache, neg_ext=neg_ext, dbl=dbl,
        add_cached=add_cached, add_niels=add_niels, add_cc=add_cc,
        cached_to_ext=cached_to_ext, decompress_zip215=decompress_zip215,
        mul_by_cofactor=mul_by_cofactor, is_identity=is_identity)
