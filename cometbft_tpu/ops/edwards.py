"""Batched twisted-Edwards (ed25519 curve) point operations on limb arrays.

Extended homogeneous coordinates (X:Y:Z:T), a = -1, with the standard
hwcd-2008 unified addition and doubling formulas — the same formula family
curve25519-voi/ref10 use (reference: the curve math behind
``crypto/ed25519/ed25519.go``), chosen here because they are branch-free and
vmap cleanly over the signature batch.

Point decompression implements **ZIP-215** (permissive) decoding: the
y-encoding may be non-canonical (y >= p), x = 0 with sign bit 1 is accepted,
and small/mixed-order points decode fine; the only failure is a non-square
x^2 candidate.  This matches CometBFT's vote-signature semantics exactly.

Representations (each component a (…,20) int32 limb array):
- extended: ``(X, Y, Z, T)``  with x = X/Z, y = Y/Z, T = XY/Z
- cached:   ``(Y+X, Y-X, 2Z, 2dT)``   (general addition operand)
- niels:    ``(Y+X, Y-X, 2dXY)``      (affine table entry, Z = 1)
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np
import jax.numpy as jnp

from . import fe


class Ext(NamedTuple):
    x: jnp.ndarray
    y: jnp.ndarray
    z: jnp.ndarray
    t: jnp.ndarray


class Cached(NamedTuple):
    ypx: jnp.ndarray
    ymx: jnp.ndarray
    z2: jnp.ndarray
    t2d: jnp.ndarray


class Niels(NamedTuple):
    ypx: jnp.ndarray
    ymx: jnp.ndarray
    t2d: jnp.ndarray


def identity(shape=()) -> Ext:
    zero = jnp.broadcast_to(jnp.asarray(fe.ZERO_LIMBS), shape + (fe.NLIMBS,))
    one = jnp.broadcast_to(jnp.asarray(fe.ONE_LIMBS), shape + (fe.NLIMBS,))
    return Ext(zero, one, one, zero)


def cache(p: Ext) -> Cached:
    return Cached(fe.add(p.y, p.x), fe.sub(p.y, p.x), fe.add(p.z, p.z),
                  fe.mul(p.t, jnp.asarray(fe.D2_LIMBS)))


def neg_ext(p: Ext) -> Ext:
    return Ext(fe.neg(p.x), p.y, p.z, fe.neg(p.t))


def dbl(p: Ext) -> Ext:
    a = fe.square(p.x)
    b = fe.square(p.y)
    c = fe.add(fe.square(p.z), fe.square(p.z))
    h = fe.add(a, b)
    e = fe.sub(h, fe.square(fe.add(p.x, p.y)))
    g = fe.sub(a, b)
    f = fe.add(c, g)
    return Ext(fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def add_cached(p: Ext, q: Cached) -> Ext:
    a = fe.mul(fe.sub(p.y, p.x), q.ymx)
    b = fe.mul(fe.add(p.y, p.x), q.ypx)
    c = fe.mul(p.t, q.t2d)
    d = fe.mul(p.z, q.z2)
    e = fe.sub(b, a)
    f = fe.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    return Ext(fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def add_niels(p: Ext, q: Niels) -> Ext:
    a = fe.mul(fe.sub(p.y, p.x), q.ymx)
    b = fe.mul(fe.add(p.y, p.x), q.ypx)
    c = fe.mul(p.t, q.t2d)
    d = fe.add(p.z, p.z)
    e = fe.sub(b, a)
    f = fe.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    return Ext(fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def decompress_zip215(enc_bytes):
    """ZIP-215 point decoding.  enc_bytes (…,32) int32 in [0,256).

    Returns ``(Ext, ok)``; for failed rows the point content is arbitrary but
    arithmetic-safe (callers mask with ``ok``).
    """
    sign = (enc_bytes[..., 31].astype(jnp.int32) >> 7) & 1
    y = fe.from_bytes32(enc_bytes, mask_bit255=True)   # value < 2^255, loose ok
    yy = fe.square(y)
    u = fe.sub(yy, jnp.asarray(fe.ONE_LIMBS))
    v = fe.add(fe.mul(yy, jnp.asarray(fe.D_LIMBS)), jnp.asarray(fe.ONE_LIMBS))
    x, ok = fe.sqrt_ratio(u, v)
    x = fe.freeze(x)
    flip = (x[..., 0] & 1) != sign
    x = fe.select(flip, fe.neg(x), x)
    return Ext(x, y, jnp.broadcast_to(jnp.asarray(fe.ONE_LIMBS), x.shape),
               fe.mul(x, y)), ok


def compress(p: Ext):
    """Canonical 32-byte encoding (…,32) int32; inverts Z (slow path/tests)."""
    zinv = fe.invert(p.z)
    x = fe.freeze(fe.mul(p.x, zinv))
    y = fe.to_bytes32(fe.mul(p.y, zinv))
    return y.at[..., 31].set(y[..., 31] | ((x[..., 0] & 1) << 7))


def mul_by_cofactor(p: Ext) -> Ext:
    return dbl(dbl(dbl(p)))


def is_identity(p: Ext):
    """(…,) bool: projective identity check X == 0 and Y == Z (mod p)."""
    return fe.is_zero(p.x) & fe.eq(p.y, p.z)
