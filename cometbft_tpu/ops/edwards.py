"""Batch-major instantiation of the Edwards point formulas.

The formulas themselves live once in ``ops/group.py`` (layout-generic);
this module binds them to the batch-major field layout (``ops/fe``,
elements ``(…, 20)``) and re-exports the classic names.  The PRODUCTION
verify kernel (``ops/ed25519.py``) uses the limb-major instantiation
instead — this one remains the differential-test surface (the
oracle-comparison tests in ``tests/test_ed25519_kernel.py`` drive point
ops in the oracle's natural batch-major shapes) plus the home of
``compress`` (a host/test-only op: the verify kernels never compress).
"""

from __future__ import annotations

import jax.numpy as jnp

from . import fe
from .group import Cached, Ext, Niels, make_group

_g = make_group(fe)

identity = _g.identity
cache = _g.cache
neg_ext = _g.neg_ext
dbl = _g.dbl
add_cached = _g.add_cached
add_niels = _g.add_niels
decompress_zip215 = _g.decompress_zip215
mul_by_cofactor = _g.mul_by_cofactor
is_identity = _g.is_identity

__all__ = ["Ext", "Cached", "Niels", "identity", "cache", "neg_ext", "dbl",
           "add_cached", "add_niels", "decompress_zip215", "compress",
           "mul_by_cofactor", "is_identity"]


def compress(p: Ext):
    """Canonical 32-byte encoding (…,32) int32; inverts Z (slow path/tests)."""
    zinv = fe.invert(p.z)
    x = fe.freeze(fe.mul(p.x, zinv))
    y = fe.to_bytes32(fe.mul(p.y, zinv))
    return y.at[..., 31].set(y[..., 31] | ((x[..., 0] & 1) << 7))
