"""The batched Ed25519 ZIP-215 verification kernel.

This is the framework's north-star op (reference seam:
``crypto/ed25519/ed25519.go:188-221`` BatchVerifier via curve25519-voi;
call sites ``types/validation.go:216``, ``light/verifier.go:56,71,124``,
``internal/blocksync/reactor.go:495``).  Per signature lane it checks, fully
on device:

    S < L,  A/R decode (ZIP-215 permissive),
    [8]([S]B - [h]A - R) == identity,   h = SHA-512(R || A || M) mod L

using one interleaved Straus ladder: 64 windows of 4 bits, 4 doublings per
window, one niels addition from a precomputed 16-entry [j]B table (constant,
gathered per lane) and one cached addition from a per-lane 16-entry [j](-A)
table.  Everything is branch-free int32/uint32 — one jit compile per
(batch, hash-blocks) bucket, embarrassingly parallel over lanes.

Layout: the public interface stays batch-major byte matrices
(``(B, 32)`` pubs/sig-halves, ``(B, NB, 32)`` hash blocks — what the
host packers emit and what the lane-axis sharding specs in
``parallel/mesh.py``/``crypto/batch.py`` shard on axis 0), but the curve
arithmetic inside runs **limb-major** ``(20, B)`` (``ops/fe_lm.py``):
the batch rides the TPU's 128-wide vector lane dimension instead of the
20-limb axis (~16% utilization the other way), and the field multiply is
a fusable shifted accumulation with no ``(B, 20, 39)`` Toeplitz
intermediate (the measured large-batch HBM cliff of round 4 —
docs/bench/r04-notes.md).  Measured on the full pipeline (CPU
rehearsal): 1.26-1.63x over batch-major, growing with batch size.  The
transposes at the boundary are free under jit relative to the ladder.
The SHA-512 and mod-L scalar pipelines stay batch-major — their outputs
feed the ladder purely as (B,) gather indices, which are
layout-agnostic.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import fe, fe_lm, scalar, sha512
from .group import Cached, Ext, Niels, make_group
from ..crypto import _ed25519_py as _ref

__all__ = ["verify_padded", "verify_padded_gather",
           "prepare_pubkey_tables", "BASE_NIELS", "BASE_NIELS_T"]

_g = make_group(fe_lm)


def _base_niels_table() -> np.ndarray:
    """(16, 3, 20) int32: niels form of [j]B for j in 0..15 (j=0 -> identity)."""
    p = _ref.P
    rows = []
    for j in range(16):
        if j == 0:
            x, y = 0, 1
        else:
            pt = _ref.pt_mul(j, _ref.BASE)
            zi = pow(pt[2], p - 2, p)
            x, y = pt[0] * zi % p, pt[1] * zi % p
        rows.append(np.stack([
            fe.limbs_from_int((y + x) % p),
            fe.limbs_from_int((y - x) % p),
            fe.limbs_from_int(2 * _ref.D * x % p * y % p),
        ]))
    return np.stack(rows).astype(np.int32)


BASE_NIELS = _base_niels_table()
# limb-major view for the kernel's constant-table gathers: (3, 20, 16)
BASE_NIELS_T = np.transpose(BASE_NIELS, (1, 2, 0)).copy()


def _build_neg_a_table(neg_a: Ext) -> Cached:
    """Per-lane cached table of [j](-A), j = 0..15: components (16, 20, B).

    The [3]..[15] chain runs under ``lax.scan`` (one addition compiled,
    13 executed): XLA compile time scales superlinearly with unrolled
    graph size, and the unrolled 13-step chain alone cost ~30 s of
    compile per bucket shape on the CPU backend."""
    n = neg_a.x.shape[1]
    c0 = _g.cache(_g.identity((n,)))
    c1 = _g.cache(neg_a)
    p2 = _g.dbl(neg_a)
    c2 = _g.cache(p2)

    def step(pj, _):
        nxt = _g.add_cached(pj, c1)
        return nxt, _g.cache(nxt)

    _, rest = jax.lax.scan(step, p2, None, length=13)   # caches of [3..15]
    head = [jnp.stack([a, b, c], axis=0)
            for a, b, c in zip(c0, c1, c2)]             # (3, 20, B) each
    return Cached(*[jnp.concatenate([h, r], axis=0)
                    for h, r in zip(head, rest)])


def _gather_niels(digit) -> Niels:
    """(B,) digit -> constant [j]B entry over (20, B)."""
    tab = jnp.asarray(BASE_NIELS_T)              # (3, 20, 16)
    ent = jnp.take(tab, digit, axis=2)           # (3, 20, B)
    return Niels(ent[0], ent[1], ent[2])


def _gather_cached(tab: Cached, digit) -> Cached:
    """Per-lane table (16, 20, B) + (B,) digit -> (20, B) entry."""
    idx = digit[None, None, :]
    return Cached(*[jnp.take_along_axis(c, idx, axis=0)[0] for c in tab])


def prepare_pubkey_tables(pub):
    """Per-validator precomputation, cacheable across commits: decompress
    A and build the 16-entry [j](-A) cached table for every lane.

    pub (N, 32) int32 -> (Cached table, components (16, 20, N); (N,) ok
    mask).  Validator sets are ~static across heights, so a node
    verifying consecutive commits re-uses these device arrays and the
    verify kernel skips decompression + table building entirely
    (TPU-side analogue of the reference's expanded-pubkey cache,
    ``crypto/ed25519/ed25519.go:42-67`` — but for whole validator sets).
    """
    a_pt, ok_a = _g.decompress_zip215(jnp.transpose(pub))
    return _build_neg_a_table(_g.neg_ext(a_pt)), ok_a


def _verify_core(neg_a_tab, ok_a, rb, sb, blocks, active, n: int):
    """Shared Straus ladder over precomputed per-lane [j](-A) tables.
    ``rb`` batch-major (B, 32); curve work limb-major over (20, B)."""
    r_pt, ok_r = _g.decompress_zip215(jnp.transpose(rb))

    # scalar + hash pipeline stays batch-major: outputs are (B,) digit
    # vectors consumed only as gather indices
    s_limbs = scalar.bytes32_to_limbs(sb)
    ok_s = scalar.lt_l(s_limbs)
    s_dig = scalar.nibbles(s_limbs)
    h_dig = scalar.nibbles(scalar.reduce512(
        sha512.sha512_blocks(blocks, active)))

    def window(i, acc):
        w = 63 - i
        # 4 doublings, rolled: compile one dbl body, run it 4x
        acc = jax.lax.fori_loop(0, 4, lambda _, a: _g.dbl(a), acc)
        ds = jax.lax.dynamic_index_in_dim(s_dig, w, axis=s_dig.ndim - 1,
                                          keepdims=False)
        acc = _g.add_niels(acc, _gather_niels(ds))
        dh = jax.lax.dynamic_index_in_dim(h_dig, w, axis=h_dig.ndim - 1,
                                          keepdims=False)
        acc = _g.add_cached(acc, _gather_cached(neg_a_tab, dh))
        return acc

    acc = jax.lax.fori_loop(0, 64, window, _g.identity((n,)))
    acc = _g.add_cached(acc, _g.cache(_g.neg_ext(r_pt)))
    return ok_a & ok_r & ok_s & _g.is_identity(_g.mul_by_cofactor(acc))


def verify_padded(pub, rb, sb, blocks, active):
    """Verify a padded batch of Ed25519 signatures on device.

    pub/rb/sb: (B, 32) int32 bytes (pubkey, sig[0:32], sig[32:64]);
    blocks: (B, NB, 32) uint32 prepadded SHA blocks of R||A||M
    (sha512.host_pad); active: (B,) int32 per-lane active block count.
    Returns (B,) bool.  Jit per (batch, NB) bucket.
    """
    neg_a_tab, ok_a = prepare_pubkey_tables(pub)
    return _verify_core(neg_a_tab, ok_a, rb, sb, blocks, active,
                        pub.shape[0])


def verify_padded_gather(tab, ok_a, idx, rb, sb, blocks, active):
    """Verify using a CACHED whole-validator-set table: ``tab``/``ok_a``
    are ``prepare_pubkey_tables`` output for all N validators; ``idx``
    (B,) int32 selects this batch's lanes (commit scope, padded to the
    lane bucket).  Skips per-call decompression and table building."""
    lane_tab = Cached(*[jnp.take(c, idx, axis=2) for c in tab])
    lane_ok = jnp.take(ok_a, idx, axis=0)
    return _verify_core(lane_tab, lane_ok, rb, sb, blocks, active,
                        idx.shape[0])
