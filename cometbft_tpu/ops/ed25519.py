"""The batched Ed25519 ZIP-215 verification kernel.

This is the framework's north-star op (reference seam:
``crypto/ed25519/ed25519.go:188-221`` BatchVerifier via curve25519-voi;
call sites ``types/validation.go:216``, ``light/verifier.go:56,71,124``,
``internal/blocksync/reactor.go:495``).  Per signature lane it checks, fully
on device:

    S < L,  A/R decode (ZIP-215 permissive),
    [8]([S]B - [h]A - R) == identity,   h = SHA-512(R || A || M) mod L

using one interleaved Straus ladder: 64 windows of 4 bits, 4 doublings per
window, one niels addition from a precomputed 16-entry [j]B table (constant,
gathered per lane) and one cached addition from a per-lane 16-entry [j](-A)
table.  Everything is branch-free int32/uint32 — one jit compile per
(batch, hash-blocks) bucket, embarrassingly parallel over lanes.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import fe, scalar, sha512
from .edwards import (Cached, Ext, Niels, add_cached, add_niels, cache,
                      dbl, decompress_zip215, identity, is_identity,
                      mul_by_cofactor, neg_ext)
from ..crypto import _ed25519_py as _ref

__all__ = ["verify_padded", "verify_padded_gather",
           "prepare_pubkey_tables", "BASE_NIELS"]


def _base_niels_table() -> np.ndarray:
    """(16, 3, 20) int32: niels form of [j]B for j in 0..15 (j=0 -> identity)."""
    p = _ref.P
    rows = []
    for j in range(16):
        if j == 0:
            x, y = 0, 1
        else:
            pt = _ref.pt_mul(j, _ref.BASE)
            zi = pow(pt[2], p - 2, p)
            x, y = pt[0] * zi % p, pt[1] * zi % p
        rows.append(np.stack([
            fe.limbs_from_int((y + x) % p),
            fe.limbs_from_int((y - x) % p),
            fe.limbs_from_int(2 * _ref.D * x % p * y % p),
        ]))
    return np.stack(rows).astype(np.int32)


BASE_NIELS = _base_niels_table()


def _build_neg_a_table(neg_a: Ext) -> Cached:
    """Per-lane cached table of [j](-A), j = 0..15, stacked on axis -2."""
    entries = [cache(identity(neg_a.x.shape[:-1])), cache(neg_a)]
    p2 = dbl(neg_a)
    entries.append(cache(p2))
    pj = p2
    for _ in range(3, 16):
        pj = add_cached(pj, entries[1])
        entries.append(cache(pj))
    return Cached(*[jnp.stack([e[i] for e in entries], axis=-2)
                    for i in range(4)])


def _gather_niels(table, digit) -> Niels:
    """Constant (16,3,20) table, (…,) digit -> per-lane Niels entry."""
    ent = jnp.take(table, digit, axis=0)
    return Niels(ent[..., 0, :], ent[..., 1, :], ent[..., 2, :])


def _gather_cached(tab: Cached, digit) -> Cached:
    idx = digit[..., None, None]
    return Cached(*[
        jnp.take_along_axis(c, idx, axis=-2)[..., 0, :] for c in tab])


def prepare_pubkey_tables(pub):
    """Per-validator precomputation, cacheable across commits: decompress
    A and build the 16-entry [j](-A) cached table for every lane.

    pub (N,32) int32 -> (Cached tables stacked on the lane axis, (N,)
    ok mask).  Validator sets are ~static across heights, so a node
    verifying consecutive commits re-uses these device arrays and the
    verify kernel skips decompression + table building entirely
    (TPU-side analogue of the reference's expanded-pubkey cache,
    ``crypto/ed25519/ed25519.go:42-67`` — but for whole validator sets).
    """
    a_pt, ok_a = decompress_zip215(pub)
    return _build_neg_a_table(neg_ext(a_pt)), ok_a


def _verify_core(neg_a_tab, ok_a, rb, sb, blocks, active, lane_shape):
    """Shared Straus ladder over precomputed per-lane [j](-A) tables."""
    r_pt, ok_r = decompress_zip215(rb)
    s_limbs = scalar.bytes32_to_limbs(sb)
    ok_s = scalar.lt_l(s_limbs)
    s_dig = scalar.nibbles(s_limbs)
    h_dig = scalar.nibbles(scalar.reduce512(sha512.sha512_blocks(blocks, active)))

    base_tab = jnp.asarray(BASE_NIELS)

    def window(i, acc):
        w = 63 - i
        acc = dbl(dbl(dbl(dbl(acc))))
        ds = jax.lax.dynamic_index_in_dim(s_dig, w, axis=s_dig.ndim - 1,
                                          keepdims=False)
        acc = add_niels(acc, _gather_niels(base_tab, ds))
        dh = jax.lax.dynamic_index_in_dim(h_dig, w, axis=h_dig.ndim - 1,
                                          keepdims=False)
        acc = add_cached(acc, _gather_cached(neg_a_tab, dh))
        return acc

    acc = jax.lax.fori_loop(0, 64, window, identity(lane_shape))
    acc = add_cached(acc, cache(neg_ext(r_pt)))
    return ok_a & ok_r & ok_s & is_identity(mul_by_cofactor(acc))


def verify_padded(pub, rb, sb, blocks, active):
    """Verify a padded batch of Ed25519 signatures on device.

    pub/rb/sb: (…,32) int32 bytes (pubkey, sig[0:32], sig[32:64]);
    blocks: (…,NB,32) uint32 prepadded SHA blocks of R||A||M (sha512.host_pad);
    active: (…,) int32 per-lane active block count.
    Returns (…,) bool.  Jit per (batch-shape, NB) bucket.
    """
    neg_a_tab, ok_a = prepare_pubkey_tables(pub)
    return _verify_core(neg_a_tab, ok_a, rb, sb, blocks, active,
                        pub.shape[:-1])


def verify_padded_gather(tab, ok_a, idx, rb, sb, blocks, active):
    """Verify using a CACHED whole-validator-set table: ``tab``/``ok_a``
    are ``prepare_pubkey_tables`` output for all N validators; ``idx``
    (B,) int32 selects this batch's lanes (commit scope, padded to the
    lane bucket).  Skips per-call decompression and table building."""
    lane_tab = Cached(*[jnp.take(c, idx, axis=0) for c in tab])
    lane_ok = jnp.take(ok_a, idx, axis=0)
    return _verify_core(lane_tab, lane_ok, rb, sb, blocks, active,
                        idx.shape)
