"""Batched BLS12-381 G1 masked point aggregation kernel.

The device half of the aggregate-commit fast path (``crypto/blsagg``):
given a valset's cached cohort table of affine G1 pubkeys and a per-commit
signer mask, fold the selected points into one sum — the aggregate public
key the host then feeds the two-pairing FastAggregateVerify.  One compile
per row bucket (``bls_agg:<rows>`` in ``crypto/plan.py``); the host path
(full-cohort-sum minus absentees, ``crypto/bls12381.aggregate_affine``)
remains the default and the fallback.

Field arithmetic: F_q (381 bits) as 32 little-endian limbs of 12 bits in
int32 — the widest radix whose schoolbook product coefficients
(32 x 4095^2 = 536M) and Montgomery-reduction accumulators (~1.07e9)
both stay under 2^31, so the whole pipeline is branch-free int32 like
the Ed25519 kernel.  Multiplication is Montgomery (R = 2^384) with an
unrolled 32-step REDC; point addition is the *complete* projective
formula for a = 0 short-Weierstrass curves (Renes-Costello-Batina 2015,
Algorithm 7, b3 = 3*4 = 12), so identity padding lanes, doublings and
cancellations all take the same straight-line code — no branches, no
incomplete-formula edge cases.  The sum runs as a log2(rows) tree
reduction over the batch axis.

The kernel returns the sum in *projective* canonical limbs: the single
modular inversion back to affine is one Python ``pow`` on the host —
cheaper than compiling a 381-bit inversion ladder for one point.
"""

from __future__ import annotations

import numpy as np

NLIMB = 32
LB = 12
MASK = (1 << LB) - 1

# curve constants (y^2 = x^3 + 4 over F_q)
P_INT = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB  # noqa: E501
_R = 1 << (NLIMB * LB)                       # Montgomery radix 2^384
_NPRIME = (-pow(P_INT, -1, 1 << LB)) % (1 << LB)


def limbs_from_int(v: int) -> np.ndarray:
    return np.array([(v >> (LB * i)) & MASK for i in range(NLIMB)],
                    np.int32)


def int_from_limbs(limbs) -> int:
    v = 0
    for i, x in enumerate(np.asarray(limbs).tolist()):
        v += int(x) << (LB * i)
    return v


P_LIMBS = limbs_from_int(P_INT)
_R2 = limbs_from_int(_R * _R % P_INT)        # to-Montgomery multiplier
_ONE = limbs_from_int(1)                     # from-Montgomery multiplier
_ONE_M = limbs_from_int(_R % P_INT)          # 1 in Montgomery form
_B3_M = limbs_from_int(12 * _R % P_INT)      # b3 = 3b = 12, Montgomery


def limbs_from_xy(xy: bytes) -> np.ndarray:
    """(2, 32) int32 limbs from a 96-byte canonical affine x||y point
    (the ``crypto/bls12381.pk_to_affine`` output)."""
    if len(xy) != 96:
        raise ValueError("affine point must be 96 bytes")
    x = int.from_bytes(xy[:48], "big")
    y = int.from_bytes(xy[48:], "big")
    return np.stack([limbs_from_int(x), limbs_from_int(y)])


def xy_from_projective(out) -> bytes | None:
    """Host-side return trip: projective (3, 32) canonical limbs ->
    96-byte affine x||y, or None for the point at infinity."""
    out = np.asarray(out)
    x, y, z = (int_from_limbs(out[i]) for i in range(3))
    if z == 0:
        return None
    zi = pow(z, P_INT - 2, P_INT)
    return ((x * zi % P_INT).to_bytes(48, "big")
            + (y * zi % P_INT).to_bytes(48, "big"))


# ------------------------------------------------------- field arithmetic
# Every helper takes/returns (..., 32) int32 limb arrays fully reduced
# (< p); intermediates are bounded as derived in the module docstring.


def _carry(x):
    import jax.numpy as jnp

    outs = []
    cr = jnp.zeros(x.shape[:-1], jnp.int32)
    for i in range(NLIMB):
        t = x[..., i] + cr
        outs.append(t & MASK)       # two's-complement AND: correct mod
        cr = t >> LB                # 2^12 residue + floor carry even for
    return jnp.stack(outs, axis=-1)  # the negative limbs _sub produces


def _cond_sub_p(x):
    """x - p when x >= p else x (x < 2p on entry), branch-free."""
    import jax.numpy as jnp

    outs = []
    br = jnp.zeros(x.shape[:-1], jnp.int32)
    for i in range(NLIMB):
        t = x[..., i] - int(P_LIMBS[i]) - br
        br = (t < 0).astype(jnp.int32)
        outs.append(t + (br << LB))
    d = jnp.stack(outs, axis=-1)
    return jnp.where((br == 0)[..., None], d, x)


def _add(a, b):
    return _cond_sub_p(_carry(a + b))


def _sub(a, b):
    return _cond_sub_p(_carry(a - b + P_LIMBS))


def _mul(a, b):
    """Montgomery product a*b*R^-1 mod p: schoolbook into a 64-limb
    accumulator, then 32 interleaved REDC steps, each folding the lowest
    live limb to zero and propagating its carry."""
    import jax.numpy as jnp

    c = jnp.zeros(jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
                  + (2 * NLIMB,), jnp.int32)
    for i in range(NLIMB):
        c = c.at[..., i:i + NLIMB].add(a[..., i:i + 1] * b)
    for i in range(NLIMB):
        # m depends only on c[i] mod 2^12 — mask BEFORE the multiply so
        # the product stays in int32
        m = ((c[..., i] & MASK) * _NPRIME) & MASK
        c = c.at[..., i:i + NLIMB].add(m[..., None] * P_LIMBS)
        c = c.at[..., i + 1].add(c[..., i] >> LB)
    return _cond_sub_p(_carry(c[..., NLIMB:]))


# ---------------------------------------------------------- curve group


def _padd(p1, p2):
    """Complete projective addition for a = 0 (RCB15 Algorithm 7,
    b3 = 12): handles identity, doubling and cancellation uniformly."""
    import jax.numpy as jnp

    b3 = jnp.asarray(_B3_M)
    x1, y1, z1 = p1
    x2, y2, z2 = p2
    t0 = _mul(x1, x2)
    t1 = _mul(y1, y2)
    t2 = _mul(z1, z2)
    t3 = _sub(_mul(_add(x1, y1), _add(x2, y2)), _add(t0, t1))
    t4 = _sub(_mul(_add(y1, z1), _add(y2, z2)), _add(t1, t2))
    xz = _sub(_mul(_add(x1, z1), _add(x2, z2)), _add(t0, t2))
    t0 = _add(_add(t0, t0), t0)           # 3 X1X2
    t2 = _mul(b3, t2)                     # b3 Z1Z2
    z3 = _add(t1, t2)
    t1 = _sub(t1, t2)
    yz = _mul(b3, xz)                     # b3 (X1Z2 + X2Z1)
    x3 = _sub(_mul(t3, t1), _mul(t4, yz))
    y3 = _add(_mul(yz, t0), _mul(t1, z3))
    z3 = _add(_mul(z3, t4), _mul(t0, t3))
    return x3, y3, z3


def aggregate_g1_masked(points, mask):
    """Masked G1 sum: ``points`` (R, 2, 32) int32 canonical affine limbs
    (see :func:`limbs_from_xy`), ``mask`` (R,) int32 — nonzero selects
    the row.  Returns the sum as (3, 32) projective canonical limbs
    (:func:`xy_from_projective` finishes on the host).  Pure jax; jit /
    AOT-compile per row bucket."""
    import jax.numpy as jnp

    r2 = jnp.asarray(_R2)
    one_m = jnp.asarray(_ONE_M)
    sel = (mask != 0)[:, None]
    # to Montgomery; deselected rows become the identity (0 : 1 : 0)
    x = jnp.where(sel, _mul(points[:, 0, :], r2), 0)
    y = jnp.where(sel, _mul(points[:, 1, :], r2), one_m)
    z = jnp.where(sel, one_m, 0)
    n = points.shape[0]
    pow2 = 1 << max(0, (n - 1).bit_length())
    if pow2 != n:                         # pad to a power of two with
        pad = pow2 - n                    # identity rows
        x = jnp.concatenate([x, jnp.zeros((pad, NLIMB), jnp.int32)])
        y = jnp.concatenate([y, jnp.tile(one_m, (pad, 1))])
        z = jnp.concatenate([z, jnp.zeros((pad, NLIMB), jnp.int32)])
        n = pow2
    while n > 1:
        h = n // 2
        x, y, z = _padd((x[:h], y[:h], z[:h]), (x[h:], y[h:], z[h:]))
        n = h
    one = jnp.asarray(_ONE)
    return jnp.stack([_mul(x[0], one), _mul(y[0], one), _mul(z[0], one)])
