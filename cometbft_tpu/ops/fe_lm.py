"""Limb-major GF(2^255 - 19) field arithmetic: elements are (20, B) int32.

The batch-major layout (``ops/fe.py``, elements ``(B, 20)``) puts the
20-limb axis on the TPU's 128-wide vector lane dimension — ~16% lane
utilization — and its einsum multiply materializes a ``(B, 20, 39)``
Toeplitz intermediate that falls out of VMEM past ~4k lanes (measured:
docs/bench/r04-notes.md).  This module flips the layout: the BATCH rides
the vector lanes, limbs ride the sublane axis, and the multiply is 20
statically-shifted row-accumulations with no Toeplitz intermediate.
Measured on the full verify pipeline (CPU rehearsal,
scripts/kern_layout_probe.py): 1.26x at 1024 lanes to 1.63x at 4096,
growing with batch size — which is why this is the production layout for
the point arithmetic (``ops/ed25519.py``) as of round 5.

Same representation as ``ops/fe.py`` (20 limbs of 13 bits, loose-form
bound LIMB_MAX, carries via parallel passes with the 2^260 ≡ 608 fold);
only the axis convention differs.  Byte-unpack utilities and the
scalar/SHA pipelines stay batch-major in their own modules — their
outputs feed the ladder purely as (B,) gather indices, which are
layout-agnostic.

Layout hooks consumed by ``ops/group.py`` (the layout-generic point
formulas): ``const``, ``bcast``, ``sign_bit``, ``limb0``,
``from_bytes32``.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import fe

RADIX, MASK, NL, NC, FOLD = fe.RADIX, fe.MASK, fe.NLIMBS, fe.NCOLS, fe.FOLD
P_INT, D_INT = fe.P_INT, fe.D_INT
LIMB_MAX = fe.LIMB_MAX


def const(x: int) -> jnp.ndarray:
    """Python int -> (20, 1) int32 limb column (broadcasts over lanes)."""
    return jnp.asarray(fe.limbs_from_int(x % P_INT).reshape(NL, 1))


def bcast(c, lane_shape) -> jnp.ndarray:
    """Broadcast a (20, 1) constant over a 1-D lane shape -> (20, n)."""
    (n,) = tuple(lane_shape)
    return jnp.broadcast_to(c, (NL, n))


def sign_bit(enc):
    """(32, B) encoded bytes -> (B,) Edwards sign bit."""
    return (enc[31].astype(jnp.int32) >> 7) & 1


def limb0(x):
    """Lowest limb, (B,) — parity source for frozen elements."""
    return x[0]


SUB_OFF = jnp.asarray(np.asarray(fe.SUB_OFF, np.int32).reshape(NL, 1))
SQRT_M1 = const(fe.SQRT_M1_INT)


def _wrap_carry(x, passes: int):
    """Parallel carry passes on (20, …) with the 2^260 ≡ 608 wraparound."""
    for _ in range(passes):
        lo = x & MASK
        hi = x >> RADIX
        wrapped = jnp.concatenate([hi[-1:] * FOLD, hi[:-1]], axis=0)
        x = lo + wrapped
    return x


def add(a, b):
    return _wrap_carry(a + b, 1)


def sub(a, b):
    return _wrap_carry(a + SUB_OFF - b, 2)


def neg(a):
    return sub(jnp.zeros_like(a), a)


def _reduce_columns(cols):
    """(39, B) int32 product columns -> loose (20, B)."""
    lo = cols & MASK
    hi = cols >> RADIX
    limbs40 = jnp.concatenate([lo, jnp.zeros_like(lo[:1])],
                              axis=0).at[1:].add(hi)
    folded = limbs40[:NL] + FOLD * limbs40[NL:]
    return _wrap_carry(folded, 3)


def mul(a, b):
    """Shifted accumulation: 20 statically-placed partial products into
    the 39 columns — a fully fusable elementwise graph, no (B, 20, 39)
    intermediate (the batch-major layout's HBM hazard)."""
    out = jnp.zeros((NC,) + jnp.broadcast_shapes(a.shape[1:], b.shape[1:]),
                    jnp.int32)
    for i in range(NL):
        out = out.at[i:i + NL].add(a[i:i + 1] * b)
    return _reduce_columns(out)


def square(a):
    return mul(a, a)


def mul_small(a, k: int):
    """Multiply by a small constant k < 2^15 (loose in, loose out)."""
    assert 0 < k < (1 << 15)
    return _wrap_carry(a * jnp.int32(k), 3)


def select(mask, a, b):
    """mask (B,) bool -> limbs from a where true else b."""
    return jnp.where(mask[None, :], a, b)


def freeze(a):
    """Loose -> canonical in [0, p); mirrors fe.freeze on axis 0."""
    limbs = []
    c = jnp.zeros_like(a[0])
    for i in range(NL):
        t = a[i] + c
        limbs.append(t & MASK)
        c = t >> RADIX
    t = limbs[0] + c * FOLD
    limbs[0] = t & MASK
    c = t >> RADIX
    for i in range(1, NL):
        t = limbs[i] + c
        limbs[i] = t & MASK
        c = t >> RADIX
    limbs[0] = limbs[0] + c * FOLD
    q = limbs[19] >> 8
    limbs[19] = limbs[19] & 255
    c = q * 19
    for i in range(NL):
        t = limbs[i] + c
        limbs[i] = t & MASK
        c = t >> RADIX
    x = jnp.stack(limbs, axis=0)
    borrow = jnp.zeros_like(x[0])
    diff = []
    for i in range(NL):
        t = x[i] - jnp.int32(int(fe.P_LIMBS[i])) - borrow
        diff.append(t & MASK)
        borrow = (t >> RADIX) & 1
    d = jnp.stack(diff, axis=0)
    return select(borrow == 0, d, x)


def is_zero(a):
    return jnp.all(freeze(a) == 0, axis=0)


def eq(a, b):
    return is_zero(sub(a, b))


def from_bytes32(bt, mask_bit255: bool = True):
    """(32, B) little-endian bytes -> (20, B) limbs of the raw 255-bit
    value (not reduced mod p; ZIP-215 decoding reduces lazily)."""
    bt = bt.astype(jnp.int32)
    limbs = []
    for i in range(NL):
        bit0 = RADIX * i
        acc = jnp.zeros_like(bt[0])
        for j in range(bit0 // 8, min((bit0 + RADIX + 7) // 8, 32)):
            shift = 8 * j - bit0
            byte = bt[j]
            if mask_bit255 and j == 31:
                byte = byte & 127
            acc = acc + (byte << shift if shift >= 0 else byte >> -shift)
        limbs.append(acc & MASK)
    return jnp.stack(limbs, axis=0)


def _sq_n(a, n: int):
    """Rolled squarings: compile one body regardless of n (see fe._sq_n)."""
    if n <= 1:
        return square(a) if n else a
    return jax.lax.fori_loop(0, n, lambda _, x: square(x), a)


def _pow_chain(z):
    """Shared ref10 ladder: returns (z^(2^250 - 1), z^11)."""
    z2 = square(z)
    z9 = mul(z, _sq_n(z2, 2))
    z11 = mul(z2, z9)
    z_5_0 = mul(z9, square(z11))
    z_10_0 = mul(_sq_n(z_5_0, 5), z_5_0)
    z_20_0 = mul(_sq_n(z_10_0, 10), z_10_0)
    z_40_0 = mul(_sq_n(z_20_0, 20), z_20_0)
    z_50_0 = mul(_sq_n(z_40_0, 10), z_10_0)
    z_100_0 = mul(_sq_n(z_50_0, 50), z_50_0)
    z_200_0 = mul(_sq_n(z_100_0, 100), z_100_0)
    z_250_0 = mul(_sq_n(z_200_0, 50), z_50_0)
    return z_250_0, z11


def pow22523(z):
    """z^((p-5)/8), ref10 addition chain."""
    z_250_0, _ = _pow_chain(z)
    return mul(_sq_n(z_250_0, 2), z)


def invert(z):
    """z^(p-2) = z^(2^255 - 21)."""
    z_250_0, z11 = _pow_chain(z)
    return mul(_sq_n(z_250_0, 5), z11)


def sqrt_ratio(u, v):
    """x with x^2 = u/v if it exists: (x, ok).  RFC 8032 decompression."""
    v3 = mul(square(v), v)
    uv3 = mul(u, v3)
    uv7 = mul(uv3, square(square(v)))
    x = mul(uv3, pow22523(uv7))
    vxx = mul(v, square(x))
    ok_direct = eq(vxx, u)
    ok_flip = eq(vxx, neg(u))
    x = select(ok_direct, x, mul(x, SQRT_M1))
    return x, ok_direct | ok_flip
