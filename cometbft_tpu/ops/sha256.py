"""Batched SHA-256 in JAX on uint32 words, for TPU.

Merkle hashing (block part sets, tx trees, validator-set/header/evidence
roots — ``crypto/merkle.py``) needs thousands of tiny SHA-256 calls per
block; this module computes a whole TREE LEVEL in one dispatch so the
per-call Python/hashlib overhead is paid once per level instead of once
per node.  Mirrors the ``ops/sha512.py`` design: branch-free compress,
host-side numpy padding into fixed 64-byte blocks, per-lane active-block
counts masking ragged tails so XLA sees static shapes.

SHA-256 is natively 32-bit, so unlike SHA-512 no (hi, lo) pair trick is
needed — every word is one uint32 lane and the TPU's vector units apply
directly.

Two kernels:

- :func:`sha256_blocks` — the generic prepadded-block digest (leaf
  hashing with variable-length items).
- :func:`merkle_inner_level` — the merkle hot path: one level of RFC-6962
  inner nodes ``SHA-256(0x01 || left || right)``.  The 65-byte message has
  a FIXED two-block padding, so the block assembly is branch-free device
  arithmetic on the parent digests and no per-lane masking is needed.

Round constants/IV are derived from first principles (frac of cube/square
roots of primes) at import and cross-checked against hashlib in tests.
"""

from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["sha256_blocks", "host_pad", "max_blocks_for_len",
           "merkle_inner_level", "words_to_bytes", "bytes_to_words"]


def _primes(n: int):
    ps, c = [], 2
    while len(ps) < n:
        if all(c % q for q in ps if q * q <= c):
            ps.append(c)
        c += 1
    return ps


def _icbrt(x: int) -> int:
    r = int(round(x ** (1 / 3)))
    while r * r * r > x:
        r -= 1
    while (r + 1) ** 3 <= x:
        r += 1
    return r


_M32 = (1 << 32) - 1
K = np.array([_icbrt(p << 96) & _M32 for p in _primes(64)], dtype=np.uint32)
IV = np.array([math.isqrt(p << 64) & _M32 for p in _primes(8)],
              dtype=np.uint32)


def _ror(x, n: int):
    return (x >> n) | (x << (32 - n))


def _big_sigma0(x):
    return _ror(x, 2) ^ _ror(x, 13) ^ _ror(x, 22)


def _big_sigma1(x):
    return _ror(x, 6) ^ _ror(x, 11) ^ _ror(x, 25)


def _sm_sigma0(x):
    return _ror(x, 7) ^ _ror(x, 18) ^ (x >> 3)


def _sm_sigma1(x):
    return _ror(x, 17) ^ _ror(x, 19) ^ (x >> 10)


def _compress(state, block):
    """One SHA-256 compression. state (…,8) u32, block (…,16) u32 BE words."""
    kc = jnp.asarray(K)

    def round_body(t, carry):
        av, w = carry
        a, b, c, d, e, f, g, h = [av[..., i] for i in range(8)]
        idx = t % 16
        wt = jax.lax.dynamic_index_in_dim(w, idx, axis=w.ndim - 1,
                                          keepdims=False)
        # schedule extension for t >= 16 (computed always, selected by mask)
        w2 = jax.lax.dynamic_index_in_dim(w, (t + 14) % 16, axis=w.ndim - 1,
                                          keepdims=False)
        w7 = jax.lax.dynamic_index_in_dim(w, (t + 9) % 16, axis=w.ndim - 1,
                                          keepdims=False)
        w15 = jax.lax.dynamic_index_in_dim(w, (t + 1) % 16, axis=w.ndim - 1,
                                           keepdims=False)
        ext = _sm_sigma1(w2) + w7 + _sm_sigma0(w15) + wt
        wt = jnp.where(t >= 16, ext, wt)
        w = jax.lax.dynamic_update_index_in_dim(w, wt, idx, axis=w.ndim - 1)

        kt = jax.lax.dynamic_index_in_dim(kc, t, axis=0, keepdims=False)
        t1 = h + _big_sigma1(e) + ((e & f) ^ (~e & g)) + kt + wt
        t2 = _big_sigma0(a) + ((a & b) ^ (a & c) ^ (b & c))
        av = jnp.stack([t1 + t2, a, b, c, d + t1, e, f, g], axis=-1)
        return (av, w)

    final, _ = jax.lax.fori_loop(0, 64, round_body, (state, block))
    return state + final


def sha256_blocks(blocks, nblocks_active):
    """Batched SHA-256 over prepadded blocks.

    blocks: (…, NB, 16) uint32 big-endian words (NB static);
    nblocks_active: (…,) int32 — per-lane number of real blocks (rest masked).
    Returns the digest as (…, 32) int32 bytes.
    """
    nb = blocks.shape[-2]
    state = jnp.broadcast_to(jnp.asarray(IV), blocks.shape[:-2] + (8,))
    for j in range(nb):
        new = _compress(state, blocks[..., j, :])
        mask = (j < nblocks_active)[..., None]
        state = jnp.where(mask, new, state)
    out = []
    for i in range(8):
        for sh in (24, 16, 8, 0):
            out.append(((state[..., i] >> sh) & 255).astype(jnp.int32))
    return jnp.stack(out, axis=-1)


def merkle_inner_level(left, right):
    """One merkle tree level: ``SHA-256(0x01 || left || right)`` per lane.

    left/right: (B, 8) uint32 big-endian digest words of the child nodes;
    returns the parent digests, (B, 8) uint32 — word form in and out so
    consecutive levels chain without byte repacking.

    The 65-byte message pads to exactly two blocks with constant padding
    (terminator at byte 65, bit length 520), so the whole level is two
    static compressions with the block words assembled by shifts from the
    child digests — no gather, no masking, no host round trip per node.
    """
    b0 = [jnp.uint32(0x01000000) | (left[:, 0] >> 8)]
    for i in range(1, 8):
        b0.append(((left[:, i - 1] & 0xFF) << 24) | (left[:, i] >> 8))
    b0.append(((left[:, 7] & 0xFF) << 24) | (right[:, 0] >> 8))
    for i in range(1, 8):
        b0.append(((right[:, i - 1] & 0xFF) << 24) | (right[:, i] >> 8))
    block0 = jnp.stack(b0, axis=-1)                       # (B, 16)

    lane = left[:, 0]
    zero = jnp.zeros_like(lane)
    b1 = [((right[:, 7] & 0xFF) << 24) | jnp.uint32(0x00800000)]
    b1 += [zero] * 14
    b1.append(jnp.full_like(lane, 65 * 8))                # bit length
    block1 = jnp.stack(b1, axis=-1)                       # (B, 16)

    state = jnp.broadcast_to(jnp.asarray(IV), left.shape[:1] + (8,))
    state = _compress(state, block0)
    return _compress(state, block1)


def max_blocks_for_len(msg_len: int) -> int:
    """Blocks needed for a message of msg_len bytes (incl. 9-byte padding)."""
    return (msg_len + 9 + 63) // 64


def host_pad(msgs: np.ndarray, lens: np.ndarray, nb: int):
    """Host-side SHA-256 padding into fixed (B, nb, 16) uint32 blocks.

    msgs: (B, L) uint8 (rows zero-filled past their length);
    lens: (B,) actual byte lengths;  nb: static block count >= per-row need.
    Returns (blocks (B, nb, 16) uint32, active (B,) int32).
    """
    msgs = np.asarray(msgs, dtype=np.uint8)
    lens = np.asarray(lens, dtype=np.int64)
    bsz, pad_len = msgs.shape[0], nb * 64
    assert int((lens + 9).max(initial=0)) <= pad_len, "bucket too small"
    buf = np.zeros((bsz, pad_len), np.uint8)
    buf[:, :msgs.shape[1]] = msgs
    # zero anything past each row's length, set 0x80 terminator
    col = np.arange(pad_len)
    buf[col[None, :] >= lens[:, None]] = 0
    buf[np.arange(bsz), lens] = 0x80
    # 64-bit big-endian bit length at the end of each row's final block
    active = ((lens + 9 + 63) // 64).astype(np.int64)
    bitlen = lens * 8
    for k in range(8):
        buf[np.arange(bsz), active * 64 - 1 - k] = (bitlen >> (8 * k)) & 255
    words = buf.reshape(bsz, nb, 16, 4)
    blocks = ((words[..., 0].astype(np.uint32) << 24)
              | (words[..., 1].astype(np.uint32) << 16)
              | (words[..., 2].astype(np.uint32) << 8)
              | words[..., 3].astype(np.uint32))
    return blocks, active.astype(np.int32)


def words_to_bytes(words: np.ndarray) -> np.ndarray:
    """(…, 8) uint32 big-endian digest words -> (…, 32) uint8 bytes."""
    w = np.ascontiguousarray(np.asarray(words, np.uint32))
    return w.astype(">u4").view(np.uint8).reshape(w.shape[:-1] + (32,))


def bytes_to_words(b: np.ndarray) -> np.ndarray:
    """(…, 32) uint8 digest bytes -> (…, 8) uint32 big-endian words."""
    a = np.ascontiguousarray(np.asarray(b, np.uint8))
    return a.view(">u4").astype(np.uint32).reshape(a.shape[:-1] + (8,))
