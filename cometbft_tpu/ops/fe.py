"""GF(2^255 - 19) field arithmetic on int32 limbs, for TPU.

Replaces the field arithmetic of curve25519-voi (the reference's Ed25519
backend, ``go.mod:50``) with a representation chosen for TPU vector units:
**20 limbs of 13 bits (radix 2^13) held in int32**.  With 13-bit limbs a
schoolbook product column is at most ``20 * (2^13)^2 < 2^31``, so the whole
multiplier runs in native int32 with no 64-bit widening — TPUs have no
native 64-bit integer multiply, which rules out the classical 25.5-bit-limb
(Go/C) layout.

Representation invariant ("loose" form): limbs are non-negative int32 with
``limb <= LIMB_MAX`` (9407).  All public ops accept and return loose form;
``freeze`` produces the canonical representative in ``[0, p)``.  Carrying is
done with *parallel* carry passes (every limb masked/shifted simultaneously,
overflow limb folded back through ``2^260 ≡ 608 (mod p)``) instead of a
sequential chain, so a carry costs ~3 vector ops rather than a 20-deep
dependency chain.

Shapes: field elements are int32 arrays ``(..., 20)``; all ops broadcast over
leading batch axes (the signature batch).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

RADIX = 13
MASK = (1 << RADIX) - 1          # 8191
NLIMBS = 20
NCOLS = 2 * NLIMBS - 1           # 39 product columns
# 2^260 = 2^(13*20) ≡ 2^5 * 19 = 608 (mod p)
FOLD = 608
LIMB_MAX = MASK + 1216           # loose-form bound; 20 * LIMB_MAX^2 < 2^31

P_INT = 2**255 - 19
D_INT = (-121665 * pow(121666, P_INT - 2, P_INT)) % P_INT
SQRT_M1_INT = pow(2, (P_INT - 1) // 4, P_INT)


def limbs_from_int(x: int) -> np.ndarray:
    """Python int -> canonical (20,) int32 limb array (host-side, constants)."""
    assert 0 <= x < 2**260
    return np.array([(x >> (RADIX * i)) & MASK for i in range(NLIMBS)],
                    dtype=np.int32)


def int_from_limbs(limbs) -> int:
    """(…,20) limbs -> Python int (host-side, tests)."""
    arr = np.asarray(limbs)
    return sum(int(arr[..., i]) << (RADIX * i) for i in range(NLIMBS))


P_LIMBS = limbs_from_int(P_INT)
D_LIMBS = limbs_from_int(D_INT)
D2_LIMBS = limbs_from_int(2 * D_INT % P_INT)
SQRT_M1_LIMBS = limbs_from_int(SQRT_M1_INT)
ONE_LIMBS = limbs_from_int(1)
ZERO_LIMBS = limbs_from_int(0)

# Subtraction offset: a multiple of p whose limb decomposition has every limb
# >= 2^14, so per-limb (a + SUB_OFF - b) never goes negative for loose a, b.
# We need  96p - 2^14 * sum(2^(13i))  to decompose into 13-bit limbs.
_U = (2**260 - 1) // MASK        # sum of 2^(13i), i in [0, 20)
_rem = 96 * P_INT - (1 << 14) * _U
assert 0 <= _rem < 2**260, "96p offset decomposition failed"
SUB_OFF = limbs_from_int(_rem) + np.int32(1 << 14)
assert int_from_limbs(SUB_OFF) == 96 * P_INT
assert SUB_OFF.min() >= 1 << 14 and SUB_OFF.max() <= MASK + (1 << 14)


def _wrap_carry(x, passes: int):
    """Parallel carry passes on (…,20) with 2^260 ≡ 608 wraparound."""
    for _ in range(passes):
        lo = x & MASK
        hi = x >> RADIX
        wrapped = jnp.concatenate(
            [hi[..., -1:] * FOLD, hi[..., :-1]], axis=-1)
        x = lo + wrapped
    return x


def add(a, b):
    """a + b (loose in, loose out)."""
    return _wrap_carry(a + b, 1)


def sub(a, b):
    """a - b (loose in, loose out); offsets by 96p to stay non-negative."""
    return _wrap_carry(a + jnp.asarray(SUB_OFF) - b, 2)


def neg(a):
    return sub(jnp.zeros_like(a), a)


def _reduce_columns(cols):
    """(…,39) int32 product columns -> loose (…,20)."""
    lo = cols & MASK
    hi = cols >> RADIX
    # one non-wrapping pass -> 40 limbs, each <= MASK + 2^18
    limbs40 = jnp.concatenate(
        [lo, jnp.zeros_like(lo[..., :1])], axis=-1
    ).at[..., 1:].add(hi)
    folded = limbs40[..., :NLIMBS] + FOLD * limbs40[..., NLIMBS:]
    return _wrap_carry(folded, 3)


# Toeplitz gather pattern: column k of the product takes b[k - i] from limb i.
_MUL_IDX = np.zeros((NLIMBS, NCOLS), np.int32)
_MUL_MSK = np.zeros((NLIMBS, NCOLS), np.int32)
for _i in range(NLIMBS):
    for _k in range(NCOLS):
        if 0 <= _k - _i < NLIMBS:
            _MUL_IDX[_i, _k] = _k - _i
            _MUL_MSK[_i, _k] = 1


def _mul_einsum(a, b):
    """One gather builds the (…,20,39) Toeplitz matrix of b, one int32
    contraction produces all 39 product columns — 3 XLA ops instead of
    an unrolled 400-MAC graph."""
    bmat = b[..., jnp.asarray(_MUL_IDX)] * jnp.asarray(_MUL_MSK)
    cols = jnp.einsum("...i,...ik->...k", a, bmat,
                      preferred_element_type=jnp.int32)
    return _reduce_columns(cols)


def _mul_shift(a, b):
    """Shifted accumulation: 20 statically-sliced partial products into
    the 39 columns, no (…,20,39) intermediate.  Candidate fix for the
    measured large-batch HBM cliff (TPU v5e: einsum throughput halves
    past ~4k lanes because the 32MB-per-mul Toeplitz intermediate falls
    out of VMEM — docs/bench/r04-notes.md); fully fusable elementwise
    graph instead."""
    out = jnp.zeros(a.shape[:-1] + (NCOLS,), jnp.int32)
    for i in range(NLIMBS):
        out = out.at[..., i:i + NLIMBS].add(a[..., i:i + 1] * b)
    return _reduce_columns(out)


# Selected at import: the einsum form is the measured default; the shift
# form is promotable once hardware numbers exist for it (the chip was
# wedged when it landed — see scripts/kern_layout_probe.py).
_MUL_IMPL = {"einsum": _mul_einsum, "shift": _mul_shift}


def mul(a, b):
    """Field multiply (loose in, loose out)."""
    return _mul_active(a, b)


import os as _os  # noqa: E402  (grouped with the selection it serves)

_mul_choice = _os.environ.get("COMETBFT_TPU_FE_MUL", "").strip().lower()
if _mul_choice and _mul_choice not in _MUL_IMPL:
    # a typo here would silently measure the WRONG kernel during a
    # scarce hardware window — fail loudly instead
    raise ValueError(
        f"COMETBFT_TPU_FE_MUL={_mul_choice!r}: expected one of "
        f"{sorted(_MUL_IMPL)}")
_mul_active = _MUL_IMPL.get(_mul_choice, _mul_einsum)


def square(a):
    return mul(a, a)


def mul_small(a, k: int):
    """Multiply by a small constant k (loose in, loose out).

    k < 2^15 keeps products < 9407 * 32767 < 2^31; three carry passes restore
    the loose bound from that magnitude (two are not enough above k ~ 40000).
    """
    assert 0 < k < (1 << 15)
    return _wrap_carry(a * jnp.int32(k), 3)


def select(mask, a, b):
    """Per-element select: mask (…,) bool -> limbs from a where true else b."""
    return jnp.where(mask[..., None], a, b)


# Layout hooks consumed by ops/group.py (the layout-generic point
# formulas); ops/fe_lm.py provides the limb-major counterparts.

def const(x: int) -> jnp.ndarray:
    """Python int -> (20,) int32 limb constant (broadcasts over lanes)."""
    return jnp.asarray(limbs_from_int(x % P_INT))


def bcast(c, lane_shape) -> jnp.ndarray:
    """Broadcast a (20,) constant over a lane shape -> lane_shape + (20,)."""
    return jnp.broadcast_to(c, tuple(lane_shape) + (NLIMBS,))


def sign_bit(enc):
    """(…, 32) encoded bytes -> (…,) Edwards sign bit."""
    return (enc[..., 31].astype(jnp.int32) >> 7) & 1


def limb0(x):
    """Lowest limb, (…,) — parity source for frozen elements."""
    return x[..., 0]


def freeze(a):
    """Loose -> canonical representative in [0, p). Sequential exact carry."""
    # exact carry chain; value < 20 * LIMB_MAX * 2^247 < 2^261
    limbs = []
    c = jnp.zeros_like(a[..., 0])
    for i in range(NLIMBS):
        t = a[..., i] + c
        limbs.append(t & MASK)
        c = t >> RADIX
    # overflow c (<= 1) folds via 2^260 ≡ 608.  The ripple can cascade through
    # every limb (e.g. value 2^260 - 1), and can even overflow limb 19 again —
    # in which case the remaining value is < 608 and a second fold cannot
    # cascade (608 + 607 < 2^13), so one full ripple + one add suffices.
    t = limbs[0] + c * FOLD
    limbs[0] = t & MASK
    c = t >> RADIX
    for i in range(1, NLIMBS):
        t = limbs[i] + c
        limbs[i] = t & MASK
        c = t >> RADIX
    limbs[0] = limbs[0] + c * FOLD
    # clear bits >= 255: q = value >> 255 (limb 19 bits 8..12), add 19q
    q = limbs[19] >> 8
    limbs[19] = limbs[19] & 255
    c = q * 19
    for i in range(NLIMBS):
        t = limbs[i] + c
        limbs[i] = t & MASK
        c = t >> RADIX
    # now value < p + 608: one conditional subtract of p
    x = jnp.stack(limbs, axis=-1)
    borrow = jnp.zeros_like(x[..., 0])
    diff = []
    for i in range(NLIMBS):
        t = x[..., i] - jnp.int32(int(P_LIMBS[i])) - borrow
        diff.append(t & MASK)
        borrow = (t >> RADIX) & 1   # t in (-2^13, 2^13): borrow is 0 or 1
    d = jnp.stack(diff, axis=-1)
    ge_p = borrow == 0
    return select(ge_p, d, x)


def is_zero(a):
    """(…,) bool: a ≡ 0 (mod p)."""
    return jnp.all(freeze(a) == 0, axis=-1)


def eq(a, b):
    return is_zero(sub(a, b))


def parity(a):
    """Canonical low bit (…,) int32 in {0,1}."""
    return freeze(a)[..., 0] & 1


def bytes_to_limbs(b, nlimbs: int, mask_top_bit: bool = False):
    """(…,nbytes) uint8/int32 little-endian bytes -> canonical 13-bit limbs.

    Shared unpack used for field elements (32 bytes -> 20 limbs), scalars
    (32 -> 20) and 512-bit hashes (64 -> 40).  ``mask_top_bit`` drops the
    highest bit of the last byte (the Edwards sign bit).
    """
    nbytes = b.shape[-1]
    b = b.astype(jnp.int32)
    limbs = []
    for i in range(nlimbs):
        bit0 = RADIX * i
        acc = jnp.zeros_like(b[..., 0])
        for j in range(bit0 // 8, min((bit0 + RADIX + 7) // 8, nbytes)):
            shift = 8 * j - bit0
            byte = b[..., j]
            if mask_top_bit and j == nbytes - 1:
                byte = byte & 127
            if shift >= 0:
                acc = acc + (byte << shift)
            else:
                acc = acc + (byte >> (-shift))
        limbs.append(acc & MASK)
    return jnp.stack(limbs, axis=-1)


def from_bytes32(b, mask_bit255: bool = True):
    """(…,32) LE bytes -> limbs of the raw 255-bit integer (not reduced mod
    p; the value is < 2^255 so loose-form bounds hold — ZIP-215 decoding
    reduces lazily via field ops)."""
    return bytes_to_limbs(b, NLIMBS, mask_top_bit=mask_bit255)


def to_bytes32(a):
    """Canonical little-endian encoding (…,32) int32 in [0,256). Freezes."""
    x = freeze(a)
    out = []
    for j in range(32):
        bit0 = 8 * j
        acc = jnp.zeros_like(x[..., 0])
        for i in range(bit0 // RADIX, min((bit0 + 7) // RADIX + 1, NLIMBS)):
            shift = bit0 - RADIX * i
            if shift >= 0:
                acc = acc | (x[..., i] >> shift)
            else:
                acc = acc | (x[..., i] << (-shift))
        out.append(acc & 255)
    return jnp.stack(out, axis=-1)


def _sq_n(a, n: int):
    """n successive squarings; rolled into fori_loop to keep graphs small
    (compile time scales superlinearly with unrolled op count)."""
    if n <= 1:
        return square(a) if n else a
    return jax.lax.fori_loop(0, n, lambda _, x: square(x), a)


def _pow_chain(z):
    """Shared ref10 ladder: returns (z^(2^250 - 1), z^11)."""
    z2 = square(z)                     # 2
    z9 = mul(z, _sq_n(z2, 2))          # 9
    z11 = mul(z2, z9)                  # 11
    z_5_0 = mul(z9, square(z11))       # 2^5 - 2^0
    z_10_0 = mul(_sq_n(z_5_0, 5), z_5_0)
    z_20_0 = mul(_sq_n(z_10_0, 10), z_10_0)
    z_40_0 = mul(_sq_n(z_20_0, 20), z_20_0)
    z_50_0 = mul(_sq_n(z_40_0, 10), z_10_0)
    z_100_0 = mul(_sq_n(z_50_0, 50), z_50_0)
    z_200_0 = mul(_sq_n(z_100_0, 100), z_100_0)
    z_250_0 = mul(_sq_n(z_200_0, 50), z_50_0)
    return z_250_0, z11


def pow22523(z):
    """z^((p-5)/8) = z^(2^252 - 3), ref10 addition chain."""
    z_250_0, _ = _pow_chain(z)
    return mul(_sq_n(z_250_0, 2), z)


def invert(z):
    """z^(p-2) = z^(2^255 - 21)."""
    z_250_0, z11 = _pow_chain(z)
    return mul(_sq_n(z_250_0, 5), z11)


def sqrt_ratio(u, v):
    """x with x^2 = u/v, if it exists (RFC 8032 decompression core).

    Returns ``(x, ok)``: ok is False where u/v is a non-square.  The returned
    x is an arbitrary root (caller fixes parity).
    """
    v3 = mul(square(v), v)
    uv3 = mul(u, v3)
    uv7 = mul(uv3, square(square(v)))
    x = mul(uv3, pow22523(uv7))
    vxx = mul(v, square(x))
    ok_direct = eq(vxx, u)
    ok_flip = eq(vxx, neg(u))
    x_flip = mul(x, jnp.asarray(SQRT_M1_LIMBS))
    x = select(ok_direct, x, x_flip)
    return x, ok_direct | ok_flip
