"""Batched SHA-512 in JAX on uint32 pairs, for TPU.

Ed25519 verification needs ``h = SHA-512(R || A || M)`` per signature; this
module computes it on-device for the whole batch (reference hot path:
``crypto/ed25519/ed25519.go`` via curve25519-voi, which hashes on CPU —
here the hash rides the same TPU batch as the curve math).

TPUs have no 64-bit integer units, so a u64 is a pair of uint32 lanes
``(hi, lo)``; adds carry via an unsigned compare, rotates recombine across the
pair.  Messages are padded host-side (cheap numpy) into fixed 128-byte blocks;
on device every lane runs the same static number of block compressions with a
per-lane active-block count masking the tail — XLA sees static shapes, the
batch stays dense.

Round constants/IV are derived from first principles (frac of cube/square
roots of primes) at import and cross-checked against hashlib in tests.
"""

from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["sha512_blocks", "host_pad", "max_blocks_for_len"]


def _primes(n: int):
    ps, c = [], 2
    while len(ps) < n:
        if all(c % q for q in ps if q * q <= c):
            ps.append(c)
        c += 1
    return ps


def _icbrt(x: int) -> int:
    r = int(round(x ** (1 / 3)))
    while r * r * r > x:
        r -= 1
    while (r + 1) ** 3 <= x:
        r += 1
    return r


_M64 = (1 << 64) - 1
_K64 = [_icbrt(p << 192) & _M64 for p in _primes(80)]
_IV64 = [math.isqrt(p << 128) & _M64 for p in _primes(8)]

K = np.array([[k >> 32, k & 0xFFFFFFFF] for k in _K64], dtype=np.uint32)
IV = np.array([[v >> 32, v & 0xFFFFFFFF] for v in _IV64], dtype=np.uint32)


def _add64(a, b):
    lo = a[1] + b[1]
    carry = (lo < b[1]).astype(jnp.uint32)
    return (a[0] + b[0] + carry, lo)


def _add64n(*xs):
    acc = xs[0]
    for x in xs[1:]:
        acc = _add64(acc, x)
    return acc


def _ror64(x, n: int):
    hi, lo = x
    if n == 32:
        return (lo, hi)
    if n < 32:
        return ((hi >> n) | (lo << (32 - n)), (lo >> n) | (hi << (32 - n)))
    m = n - 32
    return ((lo >> m) | (hi << (32 - m)), (hi >> m) | (lo << (32 - m)))


def _shr64(x, n: int):
    hi, lo = x
    if n < 32:
        return (hi >> n, (lo >> n) | (hi << (32 - n)))
    return (jnp.zeros_like(hi), hi >> (n - 32))


def _xor64(*xs):
    hi, lo = xs[0]
    for x in xs[1:]:
        hi, lo = hi ^ x[0], lo ^ x[1]
    return (hi, lo)


def _big_sigma0(x):
    return _xor64(_ror64(x, 28), _ror64(x, 34), _ror64(x, 39))


def _big_sigma1(x):
    return _xor64(_ror64(x, 14), _ror64(x, 18), _ror64(x, 41))


def _sm_sigma0(x):
    return _xor64(_ror64(x, 1), _ror64(x, 8), _shr64(x, 7))


def _sm_sigma1(x):
    return _xor64(_ror64(x, 19), _ror64(x, 61), _shr64(x, 6))


def _ch(e, f, g):
    return ((e[0] & f[0]) ^ (~e[0] & g[0]), (e[1] & f[1]) ^ (~e[1] & g[1]))


def _maj(a, b, c):
    return ((a[0] & b[0]) ^ (a[0] & c[0]) ^ (b[0] & c[0]),
            (a[1] & b[1]) ^ (a[1] & c[1]) ^ (b[1] & c[1]))


def _compress(state, block):
    """One SHA-512 compression. state (…,8,2) u32, block (…,32) u32 BE words."""
    w = block.reshape(block.shape[:-1] + (16, 2))
    kc = jnp.asarray(K)

    def round_body(t, carry):
        av, w = carry
        a, b, c, d, e, f, g, h = [(av[..., i, 0], av[..., i, 1])
                                  for i in range(8)]
        idx = t % 16
        wt_arr = jax.lax.dynamic_index_in_dim(w, idx, axis=w.ndim - 2,
                                              keepdims=False)
        wt = (wt_arr[..., 0], wt_arr[..., 1])
        # schedule extension for t >= 16 (computed always, selected by mask)
        w2 = jax.lax.dynamic_index_in_dim(w, (t + 14) % 16, axis=w.ndim - 2,
                                          keepdims=False)
        w7 = jax.lax.dynamic_index_in_dim(w, (t + 9) % 16, axis=w.ndim - 2,
                                          keepdims=False)
        w15 = jax.lax.dynamic_index_in_dim(w, (t + 1) % 16, axis=w.ndim - 2,
                                           keepdims=False)
        ext = _add64n(_sm_sigma1((w2[..., 0], w2[..., 1])),
                      (w7[..., 0], w7[..., 1]),
                      _sm_sigma0((w15[..., 0], w15[..., 1])),
                      wt)
        use_ext = (t >= 16).astype(jnp.uint32)
        wt = (wt[0] * (1 - use_ext) + ext[0] * use_ext,
              wt[1] * (1 - use_ext) + ext[1] * use_ext)
        w = jax.lax.dynamic_update_index_in_dim(
            w, jnp.stack(wt, axis=-1), idx, axis=w.ndim - 2)

        kt_arr = jax.lax.dynamic_index_in_dim(kc, t, axis=0, keepdims=False)
        kt = (jnp.broadcast_to(kt_arr[0], wt[0].shape),
              jnp.broadcast_to(kt_arr[1], wt[1].shape))
        t1 = _add64n(h, _big_sigma1(e), _ch(e, f, g), kt, wt)
        t2 = _add64(_big_sigma0(a), _maj(a, b, c))
        new = [_add64(t1, t2), a, b, c, _add64(d, t1), e, f, g]
        av = jnp.stack([jnp.stack(p, axis=-1) for p in new], axis=-2)
        return (av, w)

    final, _ = jax.lax.fori_loop(0, 80, round_body, (state, w))
    # feed-forward add
    hi = state[..., 0] + final[..., 0]
    lo = state[..., 1] + final[..., 1]
    carry = (lo < state[..., 1]).astype(jnp.uint32)
    return jnp.stack([hi + carry, lo], axis=-1)


def sha512_blocks(blocks, nblocks_active):
    """Batched SHA-512 over prepadded blocks.

    blocks: (…, NB, 32) uint32 big-endian words (NB static);
    nblocks_active: (…,) int32 — per-lane number of real blocks (rest masked).
    Returns the digest as (…, 64) int32 bytes.
    """
    nb = blocks.shape[-2]
    state = jnp.broadcast_to(jnp.asarray(IV), blocks.shape[:-2] + (8, 2))
    for j in range(nb):
        new = _compress(state, blocks[..., j, :])
        mask = (j < nblocks_active)[..., None, None]
        state = jnp.where(mask, new, state)
    # big-endian byte unpack: per u64, hi word then lo word
    out = []
    for i in range(8):
        for word in (state[..., i, 0], state[..., i, 1]):
            for sh in (24, 16, 8, 0):
                out.append(((word >> sh) & 255).astype(jnp.int32))
    return jnp.stack(out, axis=-1)


def max_blocks_for_len(msg_len: int) -> int:
    """Blocks needed for a message of msg_len bytes (incl. 17-byte padding)."""
    return (msg_len + 17 + 127) // 128


def host_pad(msgs: np.ndarray, lens: np.ndarray, nb: int):
    """Host-side SHA-512 padding into fixed (B, nb, 32) uint32 blocks.

    msgs: (B, L) uint8 (rows zero-filled past their length);
    lens: (B,) actual byte lengths;  nb: static block count >= per-row need.
    Returns (blocks (B, nb, 32) uint32, active (B,) int32).
    """
    msgs = np.asarray(msgs, dtype=np.uint8)
    lens = np.asarray(lens, dtype=np.int64)
    bsz, pad_len = msgs.shape[0], nb * 128
    assert int((lens + 17).max(initial=0)) <= pad_len, "bucket too small"
    buf = np.zeros((bsz, pad_len), np.uint8)
    buf[:, :msgs.shape[1]] = msgs
    # zero anything past each row's length, set 0x80 terminator
    col = np.arange(pad_len)
    buf[col[None, :] >= lens[:, None]] = 0
    buf[np.arange(bsz), lens] = 0x80
    # 128-bit big-endian bit length at the end of each row's final block
    active = ((lens + 17 + 127) // 128).astype(np.int64)
    bitlen = lens * 8
    for k in range(8):
        buf[np.arange(bsz), active * 128 - 1 - k] = (bitlen >> (8 * k)) & 255
    words = buf.reshape(bsz, nb, 32, 4)
    blocks = ((words[..., 0].astype(np.uint32) << 24)
              | (words[..., 1].astype(np.uint32) << 16)
              | (words[..., 2].astype(np.uint32) << 8)
              | words[..., 3].astype(np.uint32))
    return blocks, active.astype(np.int32)
