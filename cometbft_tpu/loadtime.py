"""Load generation + latency reporting (reference: ``test/loadtime/`` —
a tx generator that embeds send timestamps, and a report tool that
recovers per-tx latency from committed chain data).

Load txs are kvstore-compatible ``k=v`` pairs::

    load:<run-id>:<seq>=<send_time_ns_hex>:<padding>

The report scans committed blocks over RPC and, for every load tx,
computes ``block_time - send_time`` (the reference's
``loadtime/report`` does exactly this from the tx payload timestamp
and the block header time), then prints distribution statistics.
"""

from __future__ import annotations

import asyncio
import time

PREFIX = b"load:"


def make_load_tx(run_id: str, seq: int, size: int = 256,
                 now_ns: int | None = None) -> bytes:
    t = time.time_ns() if now_ns is None else now_ns
    key = b"%s%s:%d" % (PREFIX, run_id.encode(), seq)
    body = key + b"=" + format(t, "x").encode() + b":"
    pad = max(0, size - len(body))
    return body + b"x" * pad


def parse_load_tx(tx: bytes) -> tuple[str, int, int] | None:
    """-> (run_id, seq, send_time_ns) or None for non-load txs."""
    if not tx.startswith(PREFIX) or b"=" not in tx:
        return None
    key, val = tx.split(b"=", 1)
    try:
        run_id, seq = key[len(PREFIX):].rsplit(b":", 1)
        t_hex = val.split(b":", 1)[0]
        return run_id.decode(), int(seq), int(t_hex, 16)
    except (ValueError, UnicodeDecodeError):
        return None


async def generate(client, rate: float, duration_s: float,
                   tx_size: int = 256, run_id: str | None = None,
                   broadcast: str = "broadcast_tx_async",
                   connections: int = 1, batch: int = 1) -> dict:
    """Drive ``rate`` tx/s at a node for ``duration_s`` through the RPC
    client (loadtime's generator loop, minus the UUID machinery).

    ``connections`` runs that many concurrent sender loops splitting the
    rate (loadtime's `-c` flag): one serial HTTP round-trip per tx caps
    a single loop at ~600 tx/s, which under-drives a saturation
    measurement.  ``batch`` > 1 sends that many txs per JSON-RPC batch
    request (one HTTP round-trip), for saturation drives where even the
    fan-out can't keep up."""
    run_id = run_id or format(int(time.time()) & 0xFFFFFF, "x")
    counters = {"sent": 0, "errors": 0}
    seq = iter(range(1 << 62))
    n = max(1, int(connections))
    # one keep-alive connection per worker (HTTPClient serializes its own
    # connection); worker 0 reuses the caller's client
    clients = [client]
    owned: list = []                # only close clients WE created
    if n > 1 and hasattr(client, "host") and hasattr(client, "port"):
        owned = [client.clone()
                 for _ in range(n - 1)]
        clients += owned
    else:
        clients *= n

    b = max(1, int(batch))

    async def worker(cli, worker_rate: float):
        interval = b / worker_rate
        t_end = time.monotonic() + duration_s
        next_at = time.monotonic()
        while time.monotonic() < t_end:
            txs = [make_load_tx(run_id, next(seq), tx_size)
                   for _ in range(b)]
            try:
                if b == 1:
                    await cli.call(broadcast, tx=txs[0].hex())
                    counters["sent"] += 1
                else:
                    outs = await cli.call_batch(
                        [(broadcast, {"tx": t.hex()}) for t in txs])
                    bad = sum(1 for o in outs if isinstance(o, Exception))
                    counters["sent"] += len(txs) - bad
                    counters["errors"] += bad
            except Exception:
                counters["errors"] += b
            next_at += interval
            delay = next_at - time.monotonic()
            if delay > 0:
                await asyncio.sleep(delay)

    try:
        await asyncio.gather(*(worker(c, rate / n) for c in clients[:n]))
    finally:
        for c in owned:
            try:
                await c.close()
            except Exception:
                pass
    return {"run_id": run_id, "sent": counters["sent"],
            "errors": counters["errors"], "rate": rate,
            "duration_s": duration_s, "connections": n}


async def report(client, run_id: str | None = None,
                 min_height: int = 1) -> dict:
    """Scan the chain via RPC and compute the latency distribution of
    committed load txs (loadtime/report's ``Report`` statistics)."""
    st = await client.call("status")
    tip = st["sync_info"]["latest_block_height"]
    tx_send: list[tuple[int, int]] = []      # (height, send_ts_ns)
    first_h = last_h = None
    block_time: dict[int, int] = {}
    for h in range(max(1, min_height), tip + 1):
        blk = await client.call("block", height=h)
        hdr = blk["block"]["hdr"]
        block_time[h] = hdr["ts"]
        for tx_hex in blk["block"]["data"]["txs"]:
            tx = bytes.fromhex(tx_hex["~b"]) if isinstance(tx_hex, dict) \
                else bytes.fromhex(tx_hex)
            parsed = parse_load_tx(tx)
            if parsed is None:
                continue
            rid, _seq, t_send = parsed
            if run_id is not None and rid != run_id:
                continue
            tx_send.append((h, t_send))
            first_h = h if first_h is None else first_h
            last_h = h
    if not tx_send:
        return {"txs": 0}
    # Latency target: when PBTS is off, block h's own header time is the
    # MEDIAN PRECOMMIT TIME OF HEIGHT h-1 (BFT time, sm/validation.py
    # median_time) — about one interval before h was even proposed, so
    # "header.ts - send" goes negative for promptly-included txs (the
    # reference's loadtime/report subtracts its own block time too, but
    # it measures PBTS chains where that IS the proposal time).  The next
    # block's timestamp is height h's commit-time proxy under both time
    # schemes, so latency = ts(h+1) - send; the tip block falls back to
    # its own ts (txs there are a tail fraction once the run drains).
    latencies_ns = [
        (block_time.get(h + 1, block_time[h]) - t_send)
        for h, t_send in tx_send]
    lat_s = sorted(x / 1e9 for x in latencies_ns)

    def pct(p):
        return lat_s[min(len(lat_s) - 1, int(p * len(lat_s)))]

    # Throughput window: first SEND to last COMMIT (the commit-time
    # proxy of the last tx-bearing block).  A block-timestamp span
    # (ts(last_h) - ts(first_h)) would measure burst rate, not sustained
    # throughput — when a starved node commits the whole run in two
    # giant blocks, that span is one block interval and the "throughput"
    # inflates ~50x.  Sends and header times come from different clocks
    # (sender wall clock vs BFT median time): cross-host clock skew adds
    # directly to the mixed window and can even zero it, so the window
    # of record is the MAX of the mixed span and two same-clock spans
    # (send-clock span; header-time span anchored one block before the
    # first tx block) — skew can only shrink a max, not inflate the
    # number — and all three spans ship in the artifact for
    # cross-machine comparison (ADVICE r4).
    send_min_ns = min(t for _, t in tx_send)
    send_max_ns = max(t for _, t in tx_send)
    end_ns = block_time.get(last_h + 1, block_time[last_h])
    mixed_s = (end_ns - send_min_ns) / 1e9
    send_span_s = (send_max_ns - send_min_ns) / 1e9
    header_start_ns = block_time.get(first_h - 1, block_time[first_h])
    header_span_s = (end_ns - header_start_ns) / 1e9
    window_s = max(mixed_s, send_span_s, header_span_s)
    return {
        "txs": len(lat_s),
        "blocks": (last_h - first_h + 1) if first_h else 0,
        "min_s": round(lat_s[0], 4),
        "p50_s": round(pct(0.50), 4),
        "p90_s": round(pct(0.90), 4),
        "p99_s": round(pct(0.99), 4),
        "max_s": round(lat_s[-1], 4),
        "avg_s": round(sum(lat_s) / len(lat_s), 4),
        "throughput_tx_s": round(len(lat_s) / window_s, 2)
        if window_s > 0 else None,
        "window_s": round(window_s, 3),
        "window_mixed_s": round(mixed_s, 3),
        "window_send_clock_s": round(send_span_s, 3),
        "window_header_clock_s": round(header_span_s, 3),
    }
