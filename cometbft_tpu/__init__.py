"""tpu-bft: a TPU-native BFT state-machine-replication framework.

A from-scratch re-design of CometBFT's capabilities (Tendermint consensus,
ABCI 2.0, mempool, block sync, state sync, light client, evidence, p2p, RPC,
WAL-backed crash recovery) built idiomatically on JAX/XLA.  The defining
feature is a TPU execution backend for the signature-verification hot path:
a ``jax.vmap``'d Ed25519 (SHA-512 + Curve25519) batch-verify kernel behind
the ``crypto.BatchVerifier`` seam (reference: ``crypto/crypto.go:44-52``,
``crypto/batch/batch.go``), used by ``VerifyCommit``/``VerifyCommitLight``
(``types/validation.go``), the light-client verifier (``light/verifier.go``)
and cross-block-batched blocksync replay (``internal/blocksync/reactor.go:495``).

Layout (bottom-up, mirroring SURVEY.md §1's layer map):

- ``ops``        JAX/TPU kernels: fe25519 limb arithmetic, SHA-512, Edwards
                 point ops, the Ed25519 ZIP-215 batch-verify kernel.
- ``parallel``   device meshes and sharded (multi-chip) batch verification.
- ``crypto``     key/signature interfaces, batch-verifier dispatch, merkle.
- ``libs``       service lifecycle, logging, pubsub, events, metrics, bits.
- ``types``      Block/Header/Vote/Commit/ValidatorSet/... + commit verification.
- ``storage``    KV abstraction, block store, state store.
- ``abci``       ABCI 2.0 application interface, clients/servers, kvstore app.
- ``proxy``      multiplexed app connections (consensus/mempool/query/snapshot).
- ``mempool``    CList mempool + cache.
- ``consensus``  Tendermint state machine, WAL, replay/handshake.
- ``blocksync``  fast sync with cross-block signature batching.
- ``statesync``  snapshot sync.
- ``light``      light client (sequential + skipping verification, detector).
- ``evidence``   evidence pool and verification.
- ``p2p``        transport, secret connection, multiplexed channels, switch, pex.
- ``privval``    file/remote private validators with double-sign protection.
- ``rpc``        JSON-RPC/WebSocket server and client.
- ``node``       full-node assembly.
- ``cmd``        CLI.
"""

__version__ = "0.1.0"
