"""Light-block providers (reference: ``light/provider/provider.go``; the
http provider is ``light/provider/http``).

``LocalNodeProvider`` serves light blocks straight from a node's block and
state stores (the in-process analogue of the reference's RPC provider —
the RPC-backed provider plugs in the same interface once the RPC client
exists)."""

from __future__ import annotations

from abc import ABC, abstractmethod

from .types import LightBlock, LightClientError


class ProviderError(LightClientError):
    pass


class ErrLightBlockNotFound(ProviderError):
    pass


class Provider(ABC):
    @abstractmethod
    async def light_block(self, height: int) -> LightBlock:
        """Light block at height (0 = latest).  Raises
        ErrLightBlockNotFound."""

    async def report_evidence(self, evidence) -> None:
        """Deliver attack evidence to the peer behind this provider
        (reference: ``light/provider/provider.go`` ReportEvidence — the
        detector sends each side's incriminating evidence to the honest
        party).  Default: no submission channel, drop."""

    def id(self) -> str:
        return type(self).__name__


class LocalNodeProvider(Provider):
    def __init__(self, block_store, state_store, name: str = "local",
                 evidence_pool=None):
        self.block_store = block_store
        self.state_store = state_store
        self.name = name
        self.evidence_pool = evidence_pool
        self.received_evidence: list = []

    def id(self) -> str:
        return self.name

    async def report_evidence(self, evidence) -> None:
        """Record (and, when a pool is wired, submit) reported attack
        evidence — the in-process stand-in for the RPC provider's
        /broadcast_evidence round-trip."""
        self.received_evidence.append(evidence)
        if self.evidence_pool is not None:
            try:
                self.evidence_pool.add_evidence(evidence)
            except Exception:
                pass                  # submission is best-effort

    async def light_block(self, height: int) -> LightBlock:
        if height == 0:
            height = self.block_store.height()
        block = self.block_store.load_block(height)
        commit = self.block_store.load_block_commit(height)
        if commit is None:
            seen = self.block_store.load_seen_commit()
            if seen is not None and seen.height == height:
                commit = seen
        vals = self.state_store.load_validators(height)
        if block is None or commit is None or vals is None:
            raise ErrLightBlockNotFound(
                f"{self.name}: no light block at height {height}")
        return LightBlock(header=block.header, commit=commit,
                          validators=vals)
