"""Attack detection: compare the primary's newly verified header against
every witness (reference: ``light/detector.go:28,121``).

A witness that serves a DIFFERENT validly-signed header at the same height
means either the primary or the witness is attacking: the divergence is
surfaced as DivergenceError carrying LightClientAttackEvidence for both
sides (the reference sends evidence to the respective honest parties)."""

from __future__ import annotations

from ..types.evidence import LightClientAttackEvidence
from .provider import ErrLightBlockNotFound
from .types import LightBlock, LightClientError


class DivergenceError(LightClientError):
    def __init__(self, witness_id: str, primary_block: LightBlock,
                 witness_block: LightBlock, evidence):
        self.witness_id = witness_id
        self.primary_block = primary_block
        self.witness_block = witness_block
        self.evidence = evidence
        super().__init__(
            f"witness {witness_id} diverges at height "
            f"{primary_block.height}: primary "
            f"{primary_block.header.hash().hex()[:12]} vs witness "
            f"{witness_block.header.hash().hex()[:12]}")


async def detect_divergence(client, lb: LightBlock, now_ns: int) -> None:
    """detector.go:28 detectDivergence: every witness must agree on the
    header hash at lb.height.

    A witness reply is only treated as a conflict if it is itself a
    validly signed light block (detector.go compareNewHeaderWithWitness
    verifies before examining) — otherwise one broken witness could DoS
    the client with fabricated headers; such witnesses are dropped."""
    from ..types.validation import CommitVerificationError, VerifyCommitLight

    bad_witnesses = []
    try:
        for witness in client.witnesses:
            try:
                wlb = await witness.light_block(lb.height)
            except ErrLightBlockNotFound:
                continue             # witness lags; reference retries later
            if wlb.header.hash() == lb.header.hash():
                continue
            err = wlb.validate_basic(client.chain_id)
            if err is None:
                try:
                    VerifyCommitLight(client.chain_id, wlb.validators,
                                      wlb.commit.block_id, wlb.height,
                                      wlb.commit, backend=client.backend)
                except CommitVerificationError as e:
                    err = str(e)
            if err is not None:
                # not a real signed fork, just a broken/lying witness
                bad_witnesses.append(witness)
                continue
            # validly signed conflicting header: an actual attack on one
            # side (detector.go:121 handleConflictingHeaders)
            trusted = client.store.latest()
            common_height = trusted.height if trusted is not None \
                else lb.height
            ev = LightClientAttackEvidence(
                conflicting_header_hash=wlb.header.hash(),
                conflicting_height=wlb.height,
                common_height=min(common_height, wlb.height),
                total_voting_power=wlb.validators.total_voting_power(),
                timestamp_ns=wlb.header.time_ns,
                conflicting_block=wlb)
            raise DivergenceError(witness.id(), lb, wlb, ev)
    finally:
        for w in bad_witnesses:
            client.witnesses.remove(w)
