"""Attack detection: compare the primary's newly verified header against
every witness (reference: ``light/detector.go:28`` detectDivergence,
``:121`` handleConflictingHeaders, ``:285``
examineConflictingHeaderAgainstTrace).

All witnesses are queried CONCURRENTLY (the reference fans out a
goroutine per witness, ``light/client.go:1046-1067``; here one asyncio
gather).  A witness that serves a different validly-signed header at the
same height means either the primary or the witness is attacking: the
detector walks the primary's verification trace against the witness to
find the true common (fork) height, builds LightClientAttackEvidence
against BOTH sides, submits each to the respective honest party (the
witness gets the evidence incriminating the primary, the primary gets
the evidence incriminating the witness), and raises DivergenceError.

Witness hygiene: replies that fail basic validation or signature
verification mark the witness bad and drop it (a broken witness must not
DoS the client with fabricated headers); a witness that persistently
answers ErrLightBlockNotFound (lagging) is dropped after
``MAX_WITNESS_LAG_STRIKES`` consecutive misses — the reference tracks
and replaces such witnesses rather than retrying them forever."""

from __future__ import annotations

import asyncio

from ..types.evidence import LightClientAttackEvidence
from .provider import ErrLightBlockNotFound
from .types import LightBlock, LightClientError

# consecutive not-found replies before a lagging witness is dropped
MAX_WITNESS_LAG_STRIKES = 3


class DivergenceError(LightClientError):
    def __init__(self, witness_id: str, primary_block: LightBlock,
                 witness_block: LightBlock, evidence,
                 evidence_against_witness=None, common_height: int = 0):
        self.witness_id = witness_id
        self.primary_block = primary_block
        self.witness_block = witness_block
        # evidence incriminating the primary (named ``evidence`` for the
        # original one-sided API); its twin incriminates the witness
        self.evidence = evidence
        self.evidence_against_primary = evidence
        self.evidence_against_witness = evidence_against_witness
        self.common_height = common_height
        super().__init__(
            f"witness {witness_id} diverges at height "
            f"{primary_block.height} (common height {common_height}): "
            f"primary {primary_block.header.hash().hex()[:12]} vs witness "
            f"{witness_block.header.hash().hex()[:12]}")


def _verify_witness_block(client, wlb: LightBlock) -> str | None:
    """Basic + signature verification of a witness-served block: the
    detector must never build evidence from (or be DoS'd by) an
    unsigned fabrication (detector.go compareNewHeaderWithWitness)."""
    from ..types.validation import CommitVerificationError, VerifyCommitLight

    err = wlb.validate_basic(client.chain_id)
    if err is not None:
        return err
    try:
        VerifyCommitLight(client.chain_id, wlb.validators,
                          wlb.commit.block_id, wlb.height, wlb.commit,
                          backend=client.backend, use_cache=False)
    except CommitVerificationError as e:
        return str(e)
    return None


async def _examine_against_trace(client, witness, trace: list[LightBlock]):
    """Walk the primary's verification trace against the witness to
    locate the fork (detector.go:285 examineConflictingHeaderAgainstTrace):
    returns ``(common, primary_divergent, witness_divergent)`` where
    ``common`` is the LAST trace block the witness agrees with and the
    divergent pair sit at the first trace height where hashes split.
    The witness's divergent block must itself verify — otherwise the
    witness is lying rather than forked, and LightClientError names it."""
    w0 = await witness.light_block(trace[0].height)
    if w0.header.hash() != trace[0].header.hash():
        raise LightClientError(
            f"witness {witness.id()} disagrees with the trace root at "
            f"height {trace[0].height}: no common header exists")
    common = trace[0]
    for tb in trace[1:]:
        wb = await witness.light_block(tb.height)
        if wb.header.hash() != tb.header.hash():
            err = _verify_witness_block(client, wb)
            if err is not None:
                raise LightClientError(
                    f"witness {witness.id()} served an invalid divergent "
                    f"block at height {tb.height}: {err}")
            return common, tb, wb
        common = tb
    raise LightClientError(
        f"witness {witness.id()} agrees with the whole trace; "
        f"no divergence to examine")


def _attack_evidence(block: LightBlock, common: LightBlock
                     ) -> LightClientAttackEvidence:
    return LightClientAttackEvidence(
        conflicting_header_hash=block.header.hash(),
        conflicting_height=block.height,
        common_height=common.height,
        total_voting_power=block.validators.total_voting_power(),
        timestamp_ns=block.header.time_ns,
        conflicting_block=block)


def _lag_strikes(client) -> dict:
    if not hasattr(client, "_witness_lag_strikes"):
        client._witness_lag_strikes = {}
    return client._witness_lag_strikes


async def detect_divergence(client, lb: LightBlock, now_ns: int,
                            trace: list[LightBlock] | None = None) -> None:
    """detector.go:28 detectDivergence: every witness must agree on the
    header hash at lb.height; on a validly-signed conflict, examine the
    trace, build two-sided evidence, dispatch it, and raise."""
    if not client.witnesses:
        return
    if not trace:
        latest = client.store.latest()
        trace = [latest, lb] if latest is not None and \
            latest.height < lb.height else [lb]
    witnesses = list(client.witnesses)
    replies = await asyncio.gather(
        *(w.light_block(lb.height) for w in witnesses),
        return_exceptions=True)

    strikes = _lag_strikes(client)
    bad_witnesses = []
    conflicts = []                    # (witness, wlb), verified-signed
    for witness, res in zip(witnesses, replies):
        if isinstance(res, asyncio.CancelledError):
            # gather(return_exceptions=True) swallows cancellation into
            # the result list: a cancelled cross-check is the CALLER
            # shutting down, not a broken witness — re-raise so the
            # cancellation propagates instead of striking the witness
            raise res
        if isinstance(res, ErrLightBlockNotFound):
            # lagging witness: tolerated a few times, then dropped — a
            # witness that can never serve the height gives no attack
            # coverage and would otherwise be retried forever
            n = strikes.get(witness.id(), 0) + 1
            strikes[witness.id()] = n
            if n >= MAX_WITNESS_LAG_STRIKES:
                bad_witnesses.append(witness)
            continue
        if isinstance(res, BaseException):
            bad_witnesses.append(witness)
            continue
        strikes.pop(witness.id(), None)
        if res.header.hash() == lb.header.hash():
            continue
        if _verify_witness_block(client, res) is not None:
            # not a real signed fork, just a broken/lying witness
            bad_witnesses.append(witness)
            continue
        conflicts.append((witness, res))

    try:
        if not conflicts:
            return
        # a real fork on at least one side: walk the trace against EVERY
        # conflicting witness until one yields a verified two-sided
        # divergence (detector.go:121 examines each conflict).  A trace
        # walk that fails — the witness served an invalid or missing
        # intermediate block — marks THAT witness bad and moves on: one
        # broken witness must not mask a real attack another conflicting
        # witness can still prove.
        last_err: Exception | None = None
        witness = wlb = None
        common = primary_div = witness_div = None
        for cand, cand_wlb in conflicts:
            try:
                common, primary_div, witness_div = \
                    await _examine_against_trace(client, cand, trace)
            except (LightClientError, ErrLightBlockNotFound) as e:
                bad_witnesses.append(cand)
                last_err = e
                continue
            witness, wlb = cand, cand_wlb
            break
        if witness is None:
            # every conflicting witness failed the walk: surface the
            # last failure (callers treat it as witness misbehavior)
            raise last_err if isinstance(last_err, LightClientError) \
                else LightClientError(
                    f"all conflicting witnesses failed the trace walk: "
                    f"{last_err}")
        ev_against_primary = _attack_evidence(primary_div, common)
        ev_against_witness = _attack_evidence(witness_div, common)
        # evidence goes to whichever side is honest: the witness
        # receives the case against the primary and vice versa
        # (detector.go handleConflictingHeaders evidence dispatch)
        for target, ev in ((witness, ev_against_primary),
                           (client.primary, ev_against_witness)):
            try:
                await target.report_evidence(ev)
            except Exception:
                pass                  # best-effort, like the reference
        raise DivergenceError(witness.id(), primary_div, witness_div,
                              ev_against_primary, ev_against_witness,
                              common.height)
    finally:
        for w in bad_witnesses:
            if w in client.witnesses:
                client.witnesses.remove(w)
            strikes.pop(w.id(), None)
