"""Light-client RPC proxy: an RPC endpoint whose answers are VERIFIED
against the trusted header chain before being returned (reference:
``light/proxy/proxy.go`` + the ``cometbft light`` daemon).

A wallet pointing at this proxy gets full-node convenience with
light-client trust: headers/commits/validators come from the light
client's verification pipeline, and blocks fetched from the primary are
only returned when their hash matches the verified header."""

from __future__ import annotations

from ..rpc.core import RPCError
from ..rpc.json import jsonable
from .client import Client
from .types import LightClientError


class LightProxy:
    """The 'node' the RPC server wraps: routes resolve against a light
    client instead of local stores."""

    def __init__(self, client: Client, primary_rpc):
        self.client = client
        self.primary_rpc = primary_rpc     # HTTPClient to the full node
        self.event_bus = None
        self.name = "light-proxy"


async def _lb(env, height) -> "tuple":
    proxy: LightProxy = env.node
    try:
        if height in (None, 0, "0", ""):
            lb = await proxy.client.update()
            if lb is None:
                lb = proxy.client.latest_trusted()
        else:
            lb = await proxy.client.verify_light_block_at_height(
                int(height))
    except LightClientError as e:
        raise RPCError(-32603, f"light verification failed: {e}")
    if lb is None:
        raise RPCError(-32603, "no trusted block available")
    return lb


async def status(env) -> dict:
    proxy: LightProxy = env.node
    latest = proxy.client.latest_trusted()
    return {
        "node_info": {"moniker": proxy.name,
                      "network": proxy.client.chain_id},
        "sync_info": {
            "latest_block_height": latest.height if latest else 0,
            "latest_block_hash":
                latest.header.hash().hex() if latest else "",
            "trusted": True,
        },
    }


async def header(env, height=None) -> dict:
    lb = await _lb(env, height)
    return {"header": jsonable(lb.header), "verified": True}


async def commit(env, height=None) -> dict:
    lb = await _lb(env, height)
    return {"header": jsonable(lb.header), "commit": jsonable(lb.commit),
            "canonical": True, "verified": True}


async def validators(env, height=None, page=1, per_page=30) -> dict:
    """Same shape + pagination as the full-node route (a light client can
    point at a light proxy)."""
    from ..rpc.core import paginate_validators

    lb = await _lb(env, height)
    out = paginate_validators(lb.validators, lb.height, page, per_page)
    out["verified"] = True
    return out


async def block(env, height=None) -> dict:
    """Fetch the full block from the primary, admit it only if its hash
    matches the VERIFIED header (proxy.go block verification)."""
    proxy: LightProxy = env.node
    lb = await _lb(env, height)
    res = await proxy.primary_rpc.call("block", height=lb.height)
    from ..rpc.json import from_jsonable
    from ..types import codec
    from ..types.block_id import BlockID
    from ..types.part_set import PartSet

    blk = from_jsonable(res["block"])
    if blk.hash() != lb.header.hash():
        raise RPCError(-32603,
                       "primary served a block that does not match the "
                       "verified header (possible attack)")
    # NEVER echo the primary's block_id: recompute it from the verified
    # block so a forged id can't ride a valid body (light/rpc/client.go
    # Block checks BlockID.Hash too)
    parts = PartSet.from_data(codec.pack(blk))
    bid = BlockID(blk.hash(), parts.header())
    return {"block_id": jsonable(bid), "block": res["block"],
            "verified": True}


async def health(env) -> dict:
    return {}


PROXY_ROUTES = {
    "health": health,
    "status": status,
    "header": header,
    "commit": commit,
    "validators": validators,
    "block": block,
}


async def run_light_proxy(client: Client, primary_rpc,
                          host: str = "127.0.0.1", port: int = 0):
    """Start the verified-RPC proxy; returns (server, (host, port))."""
    from ..rpc.server import RPCServer

    server = RPCServer(LightProxy(client, primary_rpc),
                       routes=PROXY_ROUTES)
    addr = await server.listen(host, port)
    return server, addr
