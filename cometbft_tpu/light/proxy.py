"""Light-client RPC proxy: an RPC endpoint whose answers are VERIFIED
against the trusted header chain before being returned (reference:
``light/proxy/proxy.go`` + the ``cometbft light`` daemon).

A wallet pointing at this proxy gets full-node convenience with
light-client trust: headers/commits/validators come from the light
client's verification pipeline, and blocks fetched from the primary are
only returned when their hash matches the verified header."""

from __future__ import annotations

from ..rpc.core import RPCError
from ..rpc.json import jsonable
from .client import Client
from .types import LightClientError


class LightProxy:
    """The 'node' the RPC server wraps: routes resolve against a light
    client instead of local stores."""

    def __init__(self, client: Client, primary_rpc):
        self.client = client
        self.primary_rpc = primary_rpc     # HTTPClient to the full node
        self.event_bus = None
        self.name = "light-proxy"


CODE_NOT_YET_AVAILABLE = -32001      # retryable: chain hasn't caught up


async def _lb(env, height) -> "tuple":
    from .provider import ErrLightBlockNotFound

    proxy: LightProxy = env.node
    try:
        if height in (None, 0, "0", ""):
            lb = await proxy.client.update()
            if lb is None:
                lb = proxy.client.latest_trusted()
        else:
            lb = await proxy.client.verify_light_block_at_height(
                int(height))
    except ErrLightBlockNotFound as e:
        # benign: the primary simply doesn't have that height yet
        raise RPCError(CODE_NOT_YET_AVAILABLE, str(e))
    except LightClientError as e:
        raise RPCError(-32603, f"light verification failed: {e}")
    if lb is None:
        raise RPCError(-32603, "no trusted block available")
    return lb


async def status(env) -> dict:
    proxy: LightProxy = env.node
    latest = proxy.client.latest_trusted()
    return {
        "node_info": {"moniker": proxy.name,
                      "network": proxy.client.chain_id},
        "sync_info": {
            "latest_block_height": latest.height if latest else 0,
            "latest_block_hash":
                latest.header.hash().hex() if latest else "",
            "trusted": True,
        },
    }


async def header(env, height=None) -> dict:
    lb = await _lb(env, height)
    return {"header": jsonable(lb.header), "verified": True}


async def commit(env, height=None) -> dict:
    lb = await _lb(env, height)
    return {"header": jsonable(lb.header), "commit": jsonable(lb.commit),
            "canonical": True, "verified": True}


async def validators(env, height=None, page=1, per_page=30) -> dict:
    """Same shape + pagination as the full-node route (a light client can
    point at a light proxy)."""
    from ..rpc.core import paginate_validators

    lb = await _lb(env, height)
    out = paginate_validators(lb.validators, lb.height, page, per_page)
    out["verified"] = True
    return out


async def block(env, height=None) -> dict:
    """Fetch the full block from the primary, admit it only if its hash
    matches the VERIFIED header (proxy.go block verification)."""
    proxy: LightProxy = env.node
    lb = await _lb(env, height)
    res = await proxy.primary_rpc.call("block", height=lb.height)
    from ..rpc.json import from_jsonable
    from ..types import codec
    from ..types.block_id import BlockID
    from ..types.part_set import PartSet

    blk = from_jsonable(res["block"])
    if blk.hash() != lb.header.hash():
        raise RPCError(-32603,
                       "primary served a block that does not match the "
                       "verified header (possible attack)")
    # NEVER echo the primary's block_id: recompute it from the verified
    # block so a forged id can't ride a valid body (light/rpc/client.go
    # Block checks BlockID.Hash too)
    parts = PartSet.from_data(codec.pack(blk))
    bid = BlockID(blk.hash(), parts.header())
    return {"block_id": jsonable(bid), "block": res["block"],
            "verified": True}


async def abci_query(env, path="", data=None, height=0) -> dict:
    """Verified state query: fetch value + merkle proof from the primary,
    check the proof chain against the app hash in the VERIFIED header at
    height+1 (light/rpc/client.go ABCIQueryWithOptions with prove=true —
    the wallet-grade query flow)."""
    from ..crypto.merkle import ProofOp, ProofOpError, ProofOperators

    proxy: LightProxy = env.node
    raw = bytes.fromhex(data) if isinstance(data, str) else (data or b"")
    res = await proxy.primary_rpc.call("abci_query", path=path,
                                       data=raw.hex(), height=int(height),
                                       prove=True)
    r = res["response"]
    if r["code"] != 0 or not r["value"]:
        raise RPCError(-32603,
                       f"query failed or empty (cannot verify): {r['log']}")
    if not r["proof_ops"]:
        raise RPCError(-32603, "primary returned no proof")
    q_height = r["height"]
    # app hash AFTER q_height lives in header q_height+1, which may not be
    # committed for another block interval: retry briefly
    import asyncio as _aio

    lb = None
    for _ in range(25):
        try:
            lb = await _lb(env, q_height + 1)
            break
        except RPCError as e:
            if e.code != CODE_NOT_YET_AVAILABLE:
                raise            # a verification FAILURE is an attack signal
            await _aio.sleep(0.2)
    if lb is None:
        raise RPCError(-32603,
                       f"header {q_height + 1} not yet available to "
                       "verify the query against")
    try:
        ops = ProofOperators.decode(
            [ProofOp(op["type"], bytes.fromhex(op["key"]),
                     bytes.fromhex(op["data"]))
             for op in r["proof_ops"]])
        ops.verify(lb.header.app_hash, [raw], bytes.fromhex(r["value"]))
    except ProofOpError as e:
        raise RPCError(-32603, f"proof verification FAILED: {e}")
    return {"response": r, "verified": True}


async def health(env) -> dict:
    return {}


PROXY_ROUTES = {
    "health": health,
    "status": status,
    "header": header,
    "commit": commit,
    "validators": validators,
    "block": block,
    "abci_query": abci_query,
}


async def run_light_proxy(client: Client, primary_rpc,
                          host: str = "127.0.0.1", port: int = 0):
    """Start the verified-RPC proxy; returns (server, (host, port))."""
    from ..rpc.server import RPCServer

    server = RPCServer(LightProxy(client, primary_rpc),
                       routes=PROXY_ROUTES)
    addr = await server.listen(host, port)
    return server, addr
