"""Light-client SERVING tier: one full node answering a fleet of
skipping-verification light clients at CDN-ish volume ("Practical Light
Clients for Committee-Based Blockchains", PAPERS.md).

``light/`` was purely a consumer — client, verifier, providers.  This
module is the producer side, built on three enabling pieces the repo
already had:

- the per-level merkle node cache (``crypto/merkle.TreeCache``, the PR 3
  level-order engine): each block's tx/validator tree is built ONCE and
  every proof request afterwards — any subset of indexes, any number of
  clients — is pure index arithmetic, zero re-hashing;
- an LRU of signed headers + canonical commits + validator sets keyed by
  trust-period windows: bootstrap traffic clusters inside the trusting
  period (a skipping client jumps from an in-period anchor to the tip),
  so entries whose header leaves the window stop earning their memory
  and are evicted on sight — repeated ``light_block(height)`` requests
  inside the window hit memory (pre-serialized, even the JSON projection
  is amortized), not the blockstore;
- batched server-side commit verification for client-supplied trust
  anchors through ``verify_commits_light_batched(use_cache=True)``: the
  PR 4 verified-signature dedup cache makes the second client's
  re-verification of a hot anchor nearly free, and a whole-commit
  verdict memo makes the identical anchor a single dict hit (positive
  verdicts only — a bad commit re-verifies every time).

Concurrency: every method is synchronous and thread-safe (one lock
around the caches, per-key build dedup for tree construction) — the RPC
routes run them in worker threads so a 10k-client storm never stalls the
event loop, and the PR 9 admission gate sheds the overflow with 503 +
Retry-After while ``/status`` keeps answering.
"""

from __future__ import annotations

import functools
import hashlib
import json
import threading
import time

from ..crypto import merkle
from ..libs import metrics
from ..types.header import tx_hash as _tx_hash
from ..types.validation import (ErrBatchItemInvalid, ErrInvalidSignature,
                                verify_commits_light_batched)

NS = 1_000_000_000

PROOF_KINDS = ("tx", "validator")


class LightServeError(Exception):
    """Serving-tier failure surfaced to the RPC layer; ``code`` follows
    JSON-RPC (-32602 invalid request, -32603 internal/not-found)."""

    code = -32603


class LightServeRequestError(LightServeError):
    code = -32602


@functools.cache
def _ls_metrics():
    """Registered once (libs.metrics dedups by name)."""
    return (
        metrics.counter(
            "lightserve_proofs_served_total",
            "merkle inclusion proofs served by the light-serving tier, "
            "by leaf kind"),
        metrics.counter(
            "lightserve_light_blocks_served_total",
            "light blocks (header+commit+valset) served"),
        metrics.counter(
            "lightserve_cache_hits_total",
            "light-serve cache hits, by cache (header/proof/verify)"),
        metrics.counter(
            "lightserve_cache_misses_total",
            "light-serve cache misses, by cache"),
        metrics.counter(
            "lightserve_cache_evictions_total",
            "light-serve cache evictions, by cache and reason "
            "(lru/trust_period)"),
        metrics.counter(
            "lightserve_anchors_verified_total",
            "client-supplied trust anchors verified, by verdict "
            "(ok/bad/cached)"),
        metrics.histogram(
            "lightserve_request_seconds",
            "serving-tier request latency, by route (the p99 surface)"),
        metrics.gauge(
            "lightserve_header_cache_entries",
            "entries in the header/commit/valset LRU"),
        metrics.gauge(
            "lightserve_proof_cache_entries",
            "per-block proof trees retained ((height, kind) entries)"),
    )


class _LRU:
    """Minimal insertion-ordered LRU (dict ordering) with an optional
    byte budget — a 10k-validator light-block entry runs megabytes of
    commit+valset JSON, so counting entries alone would let the header
    cache eat gigabytes on a large chain.  NOT thread-safe — the tier
    serializes access under its own lock."""

    __slots__ = ("max_size", "max_bytes", "d", "sizes", "bytes")

    def __init__(self, max_size: int, max_bytes: int = 0):
        self.max_size = max(0, int(max_size))
        self.max_bytes = max(0, int(max_bytes))
        self.d: dict = {}
        self.sizes: dict = {}
        self.bytes = 0

    def __len__(self) -> int:
        return len(self.d)

    def get(self, key):
        v = self.d.get(key)
        if v is not None:                      # move-to-end refresh
            del self.d[key]
            self.d[key] = v
        return v

    def pop(self, key) -> None:
        if key in self.d:
            del self.d[key]
            self.bytes -= self.sizes.pop(key, 0)

    def put(self, key, value, nbytes: int = 0) -> int:
        """Insert; returns how many entries were LRU-evicted (count cap
        or byte budget)."""
        if self.max_size == 0:
            return 0
        self.pop(key)
        self.d[key] = value
        self.sizes[key] = nbytes
        self.bytes += nbytes
        n = 0
        while len(self.d) > self.max_size or \
                (self.max_bytes and self.bytes > self.max_bytes
                 and len(self.d) > 1):
            oldest = next(iter(self.d))
            del self.d[oldest]
            self.bytes -= self.sizes.pop(oldest, 0)
            n += 1
        return n


class LightServeTier:
    """The node-side serving tier; constructed by ``Node.create`` and
    read by the ``light_*`` RPC routes (``rpc/core.py``)."""

    def __init__(self, block_store, state_store, chain_id: str, *,
                 backend: str | None = None,
                 header_cache_size: int = 4096,
                 header_cache_bytes: int = 256 * 1024 * 1024,
                 proof_cache_blocks: int = 64,
                 verify_cache_size: int = 4096,
                 trust_period_ns: int = 168 * 3600 * NS,
                 max_batch: int = 128,
                 max_proofs: int = 4096,
                 now_ns=time.time_ns,
                 name: str = "node"):
        self.block_store = block_store
        self.state_store = state_store
        self.chain_id = chain_id
        self.backend = backend
        self.trust_period_ns = int(trust_period_ns)
        self.max_batch = max(1, int(max_batch))
        self.max_proofs = max(1, int(max_proofs))
        self.now_ns = now_ns
        self.name = name
        # RLock: tally/evict helpers take the lock themselves and are
        # also called from sections that already hold it
        self._lock = threading.RLock()
        self._headers = _LRU(header_cache_size,   # height -> entry dict
                             header_cache_bytes)
        self._trees = _LRU(proof_cache_blocks)    # (height, kind) -> TreeCache
        self._verify_memo = _LRU(verify_cache_size)  # (h, sha256) -> True
        self._valsets = _LRU(64)                  # height -> ValidatorSet
        self._valset_json = _LRU(16)              # valset hash -> jsonable
        self._building: dict = {}                 # build-latch key -> Event
        m = _ls_metrics()
        self._m_proofs = {k: m[0].bind(kind=k) for k in PROOF_KINDS}
        self._m_blocks = m[1].bind()
        self._m_hit = {c: m[2].bind(cache=c)
                       for c in ("header", "proof", "verify")}
        self._m_miss = {c: m[3].bind(cache=c)
                        for c in ("header", "proof", "verify")}
        self._m_evict = m[4]
        self._m_anchor = {v: m[5].bind(verdict=v)
                          for v in ("ok", "bad", "cached")}
        self._m_lat = {r: m[6].bind(route=r)
                       for r in ("light_block", "light_blocks",
                                 "light_proofs", "light_verify")}
        self._g_headers = m[7].bind()
        self._g_trees = m[8].bind()
        # per-instance tallies for stats()/bench (the Prometheus registry
        # is process-global and outlives instances)
        self._t = {"blocks_served": 0, "proofs_served": 0,
                   "header_hits": 0, "header_misses": 0,
                   "proof_hits": 0, "proof_misses": 0,
                   "verify_hits": 0, "verify_misses": 0,
                   "evictions_lru": 0, "evictions_trust_period": 0,
                   "anchors_ok": 0, "anchors_bad": 0}

    # ----------------------------------------------------------- internals

    def _jsonable(self, obj):
        from ..rpc.json import jsonable   # lazy: rpc imports are heavy

        return jsonable(obj)

    def _expired(self, time_ns: int) -> bool:
        return time_ns + self.trust_period_ns <= self.now_ns()

    def _tally(self, name: str, n: int = 1) -> None:
        """Per-instance counter bump under the lock — the tier is hit
        from many worker threads, and an unlocked += loses updates."""
        with self._lock:
            self._t[name] += n

    def _evict(self, cache: str, reason: str, n: int = 1) -> None:
        if n:
            self._m_evict.inc(n, cache=cache, reason=reason)
            self._tally(f"evictions_{reason}", n)

    def _resolve_height(self, height) -> int:
        bs = self.block_store
        if height in (None, 0, "0", ""):
            h = bs.height()
            if h == 0:
                raise LightServeError("empty block store")
            return h
        try:
            h = int(height)
        except (TypeError, ValueError):
            raise LightServeRequestError(f"bad height {height!r}") from None
        if h < bs.base() or h > bs.height():
            raise LightServeError(
                f"height {h} is not available (base {bs.base()}, "
                f"height {bs.height()})")
        return h

    def _valset(self, height: int):
        with self._lock:
            vals = self._valsets.get(height)
        if vals is not None:
            return vals
        vals = self.state_store.load_validators(height)
        if vals is None:
            raise LightServeError(f"no validator set at height {height}")
        with self._lock:
            self._valsets.put(height, vals)
        return vals

    # --------------------------------------------------------- light blocks

    def _load_entry(self, h: int) -> dict:
        """Blockstore path: build + serialize one light-block entry."""
        block = self.block_store.load_block(h)
        commit = self.block_store.load_block_commit(h)
        canonical = True
        if commit is None:
            seen = self.block_store.load_seen_commit()
            if seen is not None and seen.height == h:
                commit, canonical = seen, False
        vals = self.state_store.load_validators(h)
        if block is None or commit is None or vals is None:
            raise LightServeError(f"no light block at height {h}")
        vh = vals.hash()
        with self._lock:
            self._valsets.put(h, vals)
            vals_json = self._valset_json.get(vh)
        if vals_json is None:
            # ONE serialized valset dict shared by every same-valset
            # height (valsets rotate slowly; at 10k validators the JSON
            # runs ~1 MB, so per-height copies would dominate the cache)
            vals_json = self._jsonable(vals)
            with self._lock:
                self._valset_json.put(vh, vals_json)
        return {
            "height": h,
            "canonical": canonical,
            "time_ns": block.header.time_ns,
            # rough retained-size estimate for the byte budget: commit
            # sigs dominate (~200 B of JSON each); aggregate lanes carry
            # no per-lane signature (~70 B addr+ts) and the one shared
            # aggregate+bitmap is ~300 B; the shared valset dict is
            # accounted once in its own small LRU
            "bytes": 2048
            + sum(70 if cs.is_aggregate() else 200
                  for cs in commit.signatures)
            + (300 if commit.agg_signature else 0),
            "light_block": {
                "header": self._jsonable(block.header),
                "commit": self._jsonable(commit),
                "validators": vals_json,
                "total_voting_power": vals.total_voting_power(),
            },
        }

    def _cached_entry(self, h: int) -> dict | None:
        """Header-LRU consult under the lock, applying the freshness
        rules (trust-period window, seen-commit superseded by a
        canonical commit).  Counts the hit; misses are counted by the
        builder."""
        tip = self.block_store.height()
        with self._lock:
            ent = self._headers.get(h)
            if ent is not None and self._expired(ent["time_ns"]):
                # trust-period window: a header that can no longer anchor
                # a skipping client stops earning its slot
                self._headers.pop(h)
                self._evict("header", "trust_period")
                self._g_headers.set(len(self._headers))
                ent = None
            if ent is not None and not ent["canonical"] and h < tip:
                # the seen-commit answer got superseded by a canonical
                # commit (next block landed): refresh from the store
                self._headers.pop(h)
                ent = None
            if ent is not None:
                self._m_hit["header"].inc()
                self._tally("header_hits")
        return ent

    def _light_block_entry(self, height) -> dict:
        h = self._resolve_height(height)
        key = ("hdr", h)
        while True:
            ent = self._cached_entry(h)
            if ent is not None:
                self._m_blocks.inc()
                self._tally("blocks_served")
                return ent
            with self._lock:
                ev = self._building.get(key)
                if ev is None:
                    self._building[key] = threading.Event()
                    break                      # we are the builder
            # a concurrent storm on a cold height (the fresh tip, a hot
            # bootstrap anchor) must build + serialize the entry ONCE —
            # followers wait for the builder, then re-read the cache
            ev.wait(timeout=30.0)
        try:
            self._m_miss["header"].inc()
            self._tally("header_misses")
            ent = self._load_entry(h)
            if not self._expired(ent["time_ns"]):
                with self._lock:
                    self._evict("header", "lru",
                                self._headers.put(h, ent, ent["bytes"]))
                    self._g_headers.set(len(self._headers))
            self._m_blocks.inc()
            self._tally("blocks_served")
            return ent
        finally:
            with self._lock:
                ev = self._building.pop(key, None)
            if ev is not None:
                ev.set()

    def light_block(self, height=None) -> dict:
        """One signed header + commit + validator set, cache-served."""
        t0 = time.perf_counter()
        try:
            ent = self._light_block_entry(height)
            return {"height": ent["height"], "canonical": ent["canonical"],
                    "light_block": ent["light_block"]}
        finally:
            self._m_lat["light_block"].observe(time.perf_counter() - t0)

    def light_blocks(self, heights) -> dict:
        """Batched bootstrap: many light blocks in ONE request.  Missing
        heights come back as per-item errors — a fleet bootstrap must not
        fail wholesale because one height was pruned."""
        t0 = time.perf_counter()
        try:
            hs = _as_int_list(heights, "heights")
            if not hs:
                raise LightServeRequestError("heights must be non-empty")
            if len(hs) > self.max_batch:
                raise LightServeRequestError(
                    f"{len(hs)} heights > lightserve.max_batch "
                    f"({self.max_batch})")
            out = []
            for h in hs:
                try:
                    ent = self._light_block_entry(h)
                    out.append({"height": ent["height"],
                                "canonical": ent["canonical"],
                                "light_block": ent["light_block"]})
                except LightServeError as e:
                    out.append({"height": h, "error": str(e)})
            return {"light_blocks": out,
                    "base": self.block_store.base(),
                    "latest": self.block_store.height()}
        finally:
            self._m_lat["light_blocks"].observe(time.perf_counter() - t0)

    # --------------------------------------------------------------- proofs

    def _leaves(self, h: int, kind: str) -> list[bytes]:
        if kind == "tx":
            block = self.block_store.load_block(h)
            if block is None:
                raise LightServeError(f"no block at height {h}")
            return [_tx_hash(t) for t in block.data.txs]
        if kind == "validator":
            return [v.simple_encode() for v in self._valset(h).validators]
        raise LightServeRequestError(
            f"unknown proof kind {kind!r} (expected one of {PROOF_KINDS})")

    def _tree(self, h: int, kind: str) -> merkle.TreeCache:
        """(height, kind) tree through the LRU, built at most once even
        under a concurrent storm (per-key build dedup: followers wait for
        the builder rather than burning a duplicate build)."""
        key = (h, kind)
        while True:
            with self._lock:
                tree = self._trees.get(key)
                if tree is not None:
                    self._m_hit["proof"].inc()
                    self._tally("proof_hits")
                    return tree
                ev = self._building.get(key)
                if ev is None:
                    self._building[key] = threading.Event()
                    break                      # we are the builder
            ev.wait(timeout=30.0)
            with self._lock:
                tree = self._trees.get(key)
            if tree is not None:
                self._m_hit["proof"].inc()
                self._tally("proof_hits")
                return tree
            # builder failed (missing block, ...): fall through and try
            # to build it ourselves — the same error will surface here
        try:
            self._m_miss["proof"].inc()
            self._tally("proof_misses")
            tree = merkle.TreeCache.build(self._leaves(h, kind))
            with self._lock:
                self._evict("proof", "lru", self._trees.put(key, tree))
                self._g_trees.set(len(self._trees))
            return tree
        finally:
            with self._lock:
                ev = self._building.pop(key, None)
            if ev is not None:
                ev.set()

    def proofs(self, height=None, kind: str = "tx", indexes=None) -> dict:
        """Batched inclusion proofs for one block: the per-level node
        cache is built once, every requested index is gathered out of it.
        ``indexes=None`` serves every leaf (bounded by max_proofs)."""
        t0 = time.perf_counter()
        try:
            if kind not in PROOF_KINDS:
                raise LightServeRequestError(
                    f"unknown proof kind {kind!r} "
                    f"(expected one of {PROOF_KINDS})")
            h = self._resolve_height(height)
            tree = self._tree(h, kind)
            total = tree.total
            if indexes is None:
                if total > self.max_proofs:
                    raise LightServeRequestError(
                        f"{total} leaves > lightserve.max_proofs "
                        f"({self.max_proofs}); pass explicit indexes")
                idxs = list(range(total))
            else:
                idxs = _as_int_list(indexes, "indexes")
                if len(idxs) > self.max_proofs:
                    raise LightServeRequestError(
                        f"{len(idxs)} indexes > lightserve.max_proofs "
                        f"({self.max_proofs})")
                bad = [i for i in idxs if not 0 <= i < total]
                if bad:
                    raise LightServeRequestError(
                        f"leaf index {bad[0]} out of range "
                        f"(total {total})")
            proofs = tree.proofs(idxs)
            self._m_proofs[kind].inc(len(proofs))
            self._tally("proofs_served", len(proofs))
            return {
                "height": h,
                "kind": kind,
                "total": total,
                "root": tree.root.hex(),
                "proofs": [{"total": p.total, "index": p.index,
                            "leaf_hash": p.leaf_hash.hex(),
                            "aunts": [a.hex() for a in p.aunts]}
                           for p in proofs],
            }
        finally:
            self._m_lat["light_proofs"].observe(time.perf_counter() - t0)

    # ----------------------------------------------------- anchor verification

    @staticmethod
    def _anchor_key(height: int, commit_json) -> tuple:
        """Whole-commit verdict memo key: height + a digest of the RAW
        JSON form — a hot anchor hits before it is even deserialized."""
        raw = json.dumps(commit_json, sort_keys=True,
                         separators=(",", ":")).encode()
        return (height, hashlib.sha256(raw).digest())

    def verify_commits(self, anchors) -> dict:
        """Batched server-side verification of client-supplied trust
        anchors: each anchor is ``{"height": h, "commit": <jsonable>}``.
        The server attests per anchor that the commit is a valid > 2/3
        commit OF ITS OWN CHAIN's block at that height.  Same-valset runs
        verify in single batched dispatches
        (``verify_commits_light_batched`` with the PR 4 dedup cache), and
        identical hot anchors hit a whole-commit verdict memo (positive
        verdicts only — a bad commit re-verifies every time)."""
        t0 = time.perf_counter()
        try:
            return self._verify_commits(anchors)
        finally:
            self._m_lat["light_verify"].observe(time.perf_counter() - t0)

    def _verify_commits(self, anchors) -> dict:
        from ..rpc.json import from_jsonable

        if not isinstance(anchors, list) or not anchors:
            raise LightServeRequestError("anchors must be a non-empty list")
        if len(anchors) > self.max_batch:
            raise LightServeRequestError(
                f"{len(anchors)} anchors > lightserve.max_batch "
                f"({self.max_batch})")
        results: list[dict | None] = [None] * len(anchors)
        pending: list[tuple[int, int, object]] = []   # (slot, height, commit)
        keys: dict[int, tuple] = {}
        for slot, a in enumerate(anchors):
            if not isinstance(a, dict) or "height" not in a \
                    or "commit" not in a:
                raise LightServeRequestError(
                    f"anchor #{slot} must be {{height, commit}}")
            try:
                h = self._resolve_height(a["height"])
            except LightServeError as e:
                results[slot] = {"height": a.get("height"), "ok": False,
                                 "error": str(e)}
                self._m_anchor["bad"].inc()
                self._tally("anchors_bad")
                continue
            key = self._anchor_key(h, a["commit"])
            with self._lock:
                hit = self._verify_memo.get(key) is not None
            if hit:
                self._m_hit["verify"].inc()
                self._tally("verify_hits")
                self._m_anchor["cached"].inc()
                self._tally("anchors_ok")
                results[slot] = {"height": h, "ok": True, "cached": True}
                continue
            self._m_miss["verify"].inc()
            self._tally("verify_misses")
            try:
                commit = from_jsonable(a["commit"])
            except Exception as e:
                results[slot] = {"height": h, "ok": False,
                                 "error": f"undecodable commit: {e}"}
                self._m_anchor["bad"].inc()
                self._tally("anchors_bad")
                continue
            err = self._check_anchor_shape(h, commit)
            if err is not None:
                results[slot] = {"height": h, "ok": False, "error": err}
                self._m_anchor["bad"].inc()
                self._tally("anchors_bad")
                continue
            keys[slot] = key
            pending.append((slot, h, commit))
        # group by validator set and verify each group in batched
        # dispatches, demuxing per-item failures
        groups: dict[bytes, list] = {}
        for slot, h, commit in pending:
            try:
                vals = self._valset(h)
            except LightServeError as e:
                results[slot] = {"height": h, "ok": False, "error": str(e)}
                self._m_anchor["bad"].inc()
                self._tally("anchors_bad")
                continue
            vh = vals.hash()
            if vh not in groups:
                groups[vh] = ([], vals)
            groups[vh][0].append((slot, h, commit))
        for _vh, (members, vals) in groups.items():
            self._verify_group(vals, members, results, keys)
        n_ok = sum(1 for r in results if r and r.get("ok"))
        return {"results": results, "ok": n_ok,
                "failed": len(results) - n_ok}

    def _check_anchor_shape(self, h: int, commit) -> str | None:
        """Pre-verification shape checks: the commit must BE a commit
        (the codec decodes any registered type — a Vote-shaped payload
        must fail here, not as an AttributeError mid-batch) and claim
        exactly our chain's block at that height."""
        from ..types.commit import Commit

        if not isinstance(commit, Commit):
            return f"anchor commit is a {type(commit).__name__}, " \
                   "not a Commit"
        err = commit.validate_basic()
        if err:
            return f"invalid commit: {err}"
        if getattr(commit, "height", None) != h:
            return (f"commit height {getattr(commit, 'height', None)} "
                    f"!= anchor height {h}")
        meta = self.block_store.load_block_meta(h)
        if meta is None:
            return f"no block meta at height {h}"
        if commit.block_id.hash != meta.block_id.hash:
            return "commit signs a different block than this chain's"
        return None

    def _verify_group(self, vals, members: list, results: list,
                      keys: dict) -> None:
        """One same-valset run through the batched verifier; on a bad
        item, record its verdict and re-batch the remainder (the demux
        contract: an ErrInvalidSignature cause proves every EARLIER item;
        any other cause proves nothing about them)."""
        todo = list(members)
        while todo:
            items = [(c.block_id, h, c) for _s, h, c in todo]
            try:
                verify_commits_light_batched(
                    self.chain_id, vals, items, backend=self.backend,
                    use_cache=True)
            except ErrBatchItemInvalid as e:
                bad_slot, bad_h, _c = todo[e.item]
                results[bad_slot] = {"height": bad_h, "ok": False,
                                     "error": str(e.cause)}
                self._m_anchor["bad"].inc()
                self._tally("anchors_bad")
                if isinstance(e.cause, ErrInvalidSignature):
                    # every earlier item's lanes are proven valid
                    for s, h, _c2 in todo[:e.item]:
                        self._record_ok(s, h, results, keys)
                    todo = todo[e.item + 1:]
                else:
                    # pre-dispatch failure: earlier items unproven
                    todo = todo[:e.item] + todo[e.item + 1:]
                continue
            for s, h, _c in todo:
                self._record_ok(s, h, results, keys)
            return

    def _record_ok(self, slot: int, h: int, results: list,
                   keys: dict) -> None:
        results[slot] = {"height": h, "ok": True, "cached": False}
        self._m_anchor["ok"].inc()
        self._tally("anchors_ok")
        key = keys.get(slot)
        if key is not None:
            with self._lock:
                self._verify_memo.put(key, True)

    # -------------------------------------------------------------- surface

    def stats(self) -> dict:
        """Operator surface (/status light_serve block, bench, tests)."""
        with self._lock:
            out = dict(self._t)
            out["header_cache_entries"] = len(self._headers)
            out["header_cache_bytes"] = self._headers.bytes
            out["proof_cache_entries"] = len(self._trees)
            out["verify_memo_entries"] = len(self._verify_memo)
        return out


def _as_int_list(v, what: str) -> list[int]:
    """Accept a JSON list of ints, a comma-separated string (URI-style
    GET can't carry arrays), or a bare int."""
    if isinstance(v, int):
        v = [v]
    if isinstance(v, str):
        v = [p for p in v.split(",") if p.strip()]
    if not isinstance(v, list):
        raise LightServeRequestError(f"{what} must be a list")
    try:
        return [int(x) for x in v]
    except (TypeError, ValueError):
        raise LightServeRequestError(
            f"{what} must contain only integers") from None
