from .client import SEQUENTIAL, SKIPPING, Client, TrustOptions
from .detector import DivergenceError
from .provider import (ErrLightBlockNotFound, LocalNodeProvider, Provider,
                       ProviderError)
from .store import TrustedStore
from .types import (ErrInvalidHeader, ErrNewValSetCantBeTrusted, LightBlock,
                    LightClientError)
from .verifier import (verify, verify_adjacent, verify_non_adjacent,
                       verify_sequential_batched)

__all__ = [
    "Client", "TrustOptions", "SEQUENTIAL", "SKIPPING", "TrustedStore",
    "Provider", "LocalNodeProvider", "ProviderError",
    "ErrLightBlockNotFound", "LightBlock", "LightClientError",
    "ErrInvalidHeader", "ErrNewValSetCantBeTrusted", "DivergenceError",
    "verify", "verify_adjacent", "verify_non_adjacent",
    "verify_sequential_batched",
]
