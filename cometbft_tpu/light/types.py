"""Light-client types (reference: ``types/light.go`` LightBlock /
SignedHeader)."""

from __future__ import annotations

from dataclasses import dataclass

from ..types.commit import Commit
from ..types.header import Header
from ..types.validator_set import ValidatorSet


class LightClientError(Exception):
    pass


class ErrNewValSetCantBeTrusted(LightClientError):
    """< trust-level of the trusted set signed the new header: bisect
    (light/verifier.go ErrNewValSetCantBeTrusted)."""


class ErrInvalidHeader(LightClientError):
    pass


@dataclass
class LightBlock:
    """SignedHeader (header + commit) + the validator set that signed it
    (types/light.go:12)."""

    header: Header
    commit: Commit
    validators: ValidatorSet

    @property
    def height(self) -> int:
        return self.header.height

    def validate_basic(self, chain_id: str) -> str | None:
        if self.header is None or self.commit is None:
            return "missing header or commit"
        if self.validators is None:
            return "missing validator set"
        if self.header.chain_id != chain_id:
            return f"header from another chain {self.header.chain_id!r}"
        err = self.commit.validate_basic()
        if err:
            return err
        if self.header.validators_hash != self.validators.hash():
            return "validators don't match header validators_hash"
        if self.commit.height != self.header.height:
            return "commit height != header height"
        if self.commit.block_id.hash != self.header.hash():
            return "commit signs a different header"
        return None
