"""Trusted light-block store (reference: ``light/store/db``)."""

from __future__ import annotations

from ..storage.db import KVStore, MemDB, height_key
from ..types import codec
from .types import LightBlock

K_LB = b"lb/"
K_SIZE = b"lbsz"


class TrustedStore:
    def __init__(self, db: KVStore | None = None):
        self.db = db or MemDB()

    def save(self, lb: LightBlock) -> None:
        self.db.set(height_key(K_LB, lb.height), codec.pack(
            {"h": lb.header, "c": lb.commit, "v": lb.validators}))

    @staticmethod
    def _decode(raw: bytes) -> LightBlock:
        d = codec.unpack(raw)       # values come back as typed objects
        return LightBlock(header=d["h"], commit=d["c"], validators=d["v"])

    def get(self, height: int) -> LightBlock | None:
        raw = self.db.get(height_key(K_LB, height))
        return self._decode(raw) if raw is not None else None

    def latest(self) -> LightBlock | None:
        best = None
        for _, raw in self.db.iterate(K_LB, K_LB + b"\xff" * 12):
            best = raw
        return self._decode(best) if best is not None else None

    def first(self) -> LightBlock | None:
        for _, raw in self.db.iterate(K_LB, K_LB + b"\xff" * 12):
            return self._decode(raw)
        return None

    def prune(self, keep: int) -> None:
        keys = [k for k, _ in self.db.iterate(K_LB, K_LB + b"\xff" * 12)]
        for k in keys[:-keep] if keep else keys:
            self.db.delete(k)
