"""Light client (reference: ``light/client.go:133`` Client).

Tracks a trusted header chain from a trust anchor (height + hash inside
the trusting period), fetching light blocks from a primary provider and
cross-checking against witnesses (detector).  Verification is *skipping*
with bisection by default (``light/client.go:702`` verifySkipping): jump
straight to the target and only fill in intermediate headers when the
trusted validator set has rotated too far (ErrNewValSetCantBeTrusted).
Sequential mode uses the batched verifier — runs of same-valset headers
become single device dispatches (BASELINE configs[3])."""

from __future__ import annotations

import time
from fractions import Fraction

from .detector import DivergenceError, detect_divergence
from .provider import Provider
from .store import TrustedStore
from .types import (ErrNewValSetCantBeTrusted, LightBlock, LightClientError)
from .verifier import (DEFAULT_TRUST_LEVEL, MAX_CLOCK_DRIFT_NS, verify,
                       verify_adjacent, verify_non_adjacent,
                       verify_sequential_batched)

SEQUENTIAL = "sequential"
SKIPPING = "skipping"


class TrustOptions:
    """Trust anchor (light.TrustOptions, light/client.go:60)."""

    def __init__(self, period_ns: int, height: int, header_hash: bytes):
        self.period_ns = period_ns
        self.height = height
        self.header_hash = header_hash


class Client:
    def __init__(self, chain_id: str, trust_options: TrustOptions,
                 primary: Provider, witnesses: list[Provider] | None = None,
                 store: TrustedStore | None = None,
                 mode: str = SKIPPING,
                 trust_level: Fraction = DEFAULT_TRUST_LEVEL,
                 max_clock_drift_ns: int = MAX_CLOCK_DRIFT_NS,
                 backend: str | None = None,
                 pruning_size: int = 1000,
                 now_ns=time.time_ns):
        self.chain_id = chain_id
        self.trust = trust_options
        self.primary = primary
        self.witnesses = list(witnesses or [])
        self.store = store or TrustedStore()
        self.mode = mode
        self.trust_level = trust_level
        self.max_clock_drift_ns = max_clock_drift_ns
        self.backend = backend
        # light/client.go:26 defaultPruningSize: the store keeps at most
        # this many light blocks (0 = unbounded)
        if pruning_size < 0:
            raise ValueError("pruning_size must be >= 0")
        self.pruning_size = pruning_size
        self.now_ns = now_ns

    def _save(self, lb) -> None:
        self.store.save(lb)
        if self.pruning_size:
            self.store.prune(self.pruning_size)

    # ------------------------------------------------------------ anchor

    async def initialize(self) -> LightBlock:
        """Fetch + pin the trust anchor (light/client.go initializeWithTrustOptions)."""
        lb = await self.primary.light_block(self.trust.height)
        if lb.header.hash() != self.trust.header_hash:
            raise LightClientError(
                "primary's header at trust height does not match the "
                "trusted hash")
        err = lb.validate_basic(self.chain_id)
        if err:
            raise LightClientError(f"invalid trust anchor: {err}")
        self._save(lb)
        return lb

    def latest_trusted(self) -> LightBlock | None:
        return self.store.latest()

    # ------------------------------------------------------------ verify

    async def verify_light_block_at_height(self, height: int,
                                           now_ns: int | None = None
                                           ) -> LightBlock:
        """light/client.go:470 VerifyLightBlockAtHeight."""
        now_ns = now_ns if now_ns is not None else self.now_ns()
        got = self.store.get(height)
        if got is not None:
            return got
        trusted = self.store.latest()
        if trusted is None:
            trusted = await self.initialize()
        if height <= trusted.height:
            return await self._verify_backwards_or_fetch(height, trusted,
                                                         now_ns)
        target = await self.primary.light_block(height)
        verified = await self._verify_light_block(trusted, target, now_ns)
        # cross-check BEFORE anything is persisted: a divergent target must
        # never enter the trusted store (it would short-circuit future
        # calls via the cache above and skew the detector's common height).
        # The verification trace (trusted root + every newly verified
        # block, ascending) lets the detector walk to the true fork height.
        await self._cross_check(target, now_ns,
                                trace=[trusted] + sorted(
                                    verified, key=lambda b: b.height))
        for lb in verified:
            self.store.save(lb)
        if self.pruning_size:        # one pass after the batch, not per save
            self.store.prune(self.pruning_size)
        return target

    async def update(self, now_ns: int | None = None) -> LightBlock | None:
        """Verify the primary's latest header (light/client.go:432)."""
        now_ns = now_ns if now_ns is not None else self.now_ns()
        latest = await self.primary.light_block(0)
        trusted = self.store.latest()
        if trusted is not None and latest.height <= trusted.height:
            return trusted
        return await self.verify_light_block_at_height(latest.height,
                                                       now_ns)

    async def _verify_light_block(self, trusted: LightBlock,
                                  target: LightBlock,
                                  now_ns: int) -> list[LightBlock]:
        """Returns the newly verified blocks WITHOUT persisting them — the
        caller saves only after the witness cross-check passes."""
        if self.mode == SEQUENTIAL:
            return await self._verify_sequential(trusted, target, now_ns)
        return await self._verify_skipping(trusted, target, now_ns)

    async def _verify_sequential(self, trusted: LightBlock,
                                 target: LightBlock,
                                 now_ns: int) -> list[LightBlock]:
        """Fetch every intermediate header, prove them in batched device
        dispatches (client.go:609 verifySequential, TPU-redesigned)."""
        chain = []
        for h in range(trusted.height + 1, target.height):
            chain.append(await self.primary.light_block(h))
        chain.append(target)
        verify_sequential_batched(self.chain_id, trusted, chain,
                                  self.trust.period_ns, now_ns,
                                  self.max_clock_drift_ns, self.backend)
        return chain

    async def _verify_skipping(self, trusted: LightBlock,
                               target: LightBlock,
                               now_ns: int) -> list[LightBlock]:
        """client.go:702 verifySkipping: try the jump; on
        ErrNewValSetCantBeTrusted bisect down until it verifies, then
        continue up from the new pivot."""
        verified = []
        pivots = [target]
        cur = trusted
        while pivots:
            candidate = pivots[-1]
            try:
                verify_non_adjacent(self.chain_id, cur, candidate,
                                    self.trust.period_ns, now_ns,
                                    self.trust_level,
                                    self.max_clock_drift_ns, self.backend)
            except ErrNewValSetCantBeTrusted:
                mid = (cur.height + candidate.height) // 2
                if mid in (cur.height, candidate.height):
                    raise LightClientError(
                        "bisection exhausted: adjacent header unverifiable")
                pivots.append(await self.primary.light_block(mid))
                continue
            verified.append(candidate)
            cur = candidate
            pivots.pop()
        return verified

    async def _verify_backwards_or_fetch(self, height: int,
                                         trusted: LightBlock,
                                         now_ns: int) -> LightBlock:
        """Historic header below the trusted head: fetch and hash-link
        backwards (client.go backwards)."""
        lb = await self.primary.light_block(height)
        err = lb.validate_basic(self.chain_id)
        if err:
            raise LightClientError(f"invalid historic header: {err}")
        # walk back from the closest trusted block above
        cur = trusted
        while cur.height > height + 1:
            prev = await self.primary.light_block(cur.height - 1)
            if cur.header.last_block_id.hash != prev.header.hash():
                raise LightClientError(
                    f"hash chain break at height {prev.height}")
            cur = prev
        if cur.header.last_block_id.hash != lb.header.hash():
            raise LightClientError(
                f"historic header {height} not linked to trusted chain")
        # no prune here: a backwards-verified HISTORIC block is the oldest
        # key by construction — pruning would delete it immediately and
        # the cache would never help repeat historic queries
        self.store.save(lb)
        return lb

    # ---------------------------------------------------------- detector

    async def _cross_check(self, lb: LightBlock, now_ns: int,
                           trace: list[LightBlock] | None = None) -> None:
        if self.witnesses:
            await detect_divergence(self, lb, now_ns, trace=trace)
