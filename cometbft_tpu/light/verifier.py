"""Light-client header verification (reference: ``light/verifier.go``).

- ``verify_adjacent``   (:91): consecutive heights; the new header's
  validator hash must equal the trusted header's next_validators_hash,
  then its own validator set must have signed with > 2/3.
- ``verify_non_adjacent`` (:30): any height gap; the TRUSTED set must have
  signed with >= trust-level (default 1/3) — else
  ErrNewValSetCantBeTrusted triggers bisection — and the new set with
  > 2/3.
- ``verify``            (:133): dispatcher.
- ``verify_sequential_batched``: the TPU redesign of sequential sync —
  runs of headers sharing one validator set are proven in a single device
  batch instead of one VerifyCommitLight dispatch per header
  (BASELINE configs[3]: 1000-header sync)."""

from __future__ import annotations

from fractions import Fraction

from ..types.validation import (CommitVerificationError,
                                ErrNotEnoughVotingPower,
                                VerifyCommitLight, VerifyCommitLightTrusting,
                                verify_commits_light_batched)
from .types import (ErrInvalidHeader, ErrNewValSetCantBeTrusted, LightBlock,
                    LightClientError)

DEFAULT_TRUST_LEVEL = Fraction(1, 3)
MAX_CLOCK_DRIFT_NS = 10 * 1_000_000_000


def _verify_new_header_and_vals(chain_id: str, trusted: LightBlock,
                                untrusted: LightBlock, now_ns: int,
                                max_clock_drift_ns: int) -> None:
    """light/verifier.go:177 verifyNewHeaderAndVals."""
    err = untrusted.validate_basic(chain_id)
    if err:
        raise ErrInvalidHeader(err)
    if untrusted.height <= trusted.height:
        raise ErrInvalidHeader(
            f"expected height > {trusted.height}, got {untrusted.height}")
    if untrusted.header.time_ns <= trusted.header.time_ns:
        raise ErrInvalidHeader("header time not after trusted header")
    if untrusted.header.time_ns >= now_ns + max_clock_drift_ns:
        raise ErrInvalidHeader("header time from the future")


def _check_trusted_period(trusted: LightBlock, trusting_period_ns: int,
                          now_ns: int) -> None:
    if trusted.header.time_ns + trusting_period_ns <= now_ns:
        raise LightClientError(
            f"trusted header {trusted.height} expired "
            "(outside trusting period)")


def verify_adjacent(chain_id: str, trusted: LightBlock,
                    untrusted: LightBlock, trusting_period_ns: int,
                    now_ns: int,
                    max_clock_drift_ns: int = MAX_CLOCK_DRIFT_NS,
                    backend: str | None = None) -> None:
    """light/verifier.go:91 VerifyAdjacent."""
    if untrusted.height != trusted.height + 1:
        raise ErrInvalidHeader("headers must be adjacent in height")
    _check_trusted_period(trusted, trusting_period_ns, now_ns)
    _verify_new_header_and_vals(chain_id, trusted, untrusted, now_ns,
                                max_clock_drift_ns)
    if untrusted.header.validators_hash != \
            trusted.header.next_validators_hash:
        raise ErrInvalidHeader(
            "header validators_hash != trusted next_validators_hash")
    VerifyCommitLight(chain_id, untrusted.validators,
                      untrusted.commit.block_id, untrusted.height,
                      untrusted.commit, backend=backend, use_cache=False)


def verify_non_adjacent(chain_id: str, trusted: LightBlock,
                        untrusted: LightBlock, trusting_period_ns: int,
                        now_ns: int,
                        trust_level: Fraction = DEFAULT_TRUST_LEVEL,
                        max_clock_drift_ns: int = MAX_CLOCK_DRIFT_NS,
                        backend: str | None = None) -> None:
    """light/verifier.go:30 VerifyNonAdjacent."""
    if untrusted.height == trusted.height + 1:
        return verify_adjacent(chain_id, trusted, untrusted,
                               trusting_period_ns, now_ns,
                               max_clock_drift_ns, backend)
    _check_trusted_period(trusted, trusting_period_ns, now_ns)
    _verify_new_header_and_vals(chain_id, trusted, untrusted, now_ns,
                                max_clock_drift_ns)
    # the OLD (trusted) validator set must still vouch with >= trust level
    # (hot path: light/verifier.go:56)
    try:
        VerifyCommitLightTrusting(chain_id, trusted.validators,
                                  untrusted.commit, trust_level,
                                  backend=backend)
    except ErrNotEnoughVotingPower as e:
        raise ErrNewValSetCantBeTrusted(str(e)) from e
    # and the NEW set must have signed its own header with > 2/3 (:71)
    VerifyCommitLight(chain_id, untrusted.validators,
                      untrusted.commit.block_id, untrusted.height,
                      untrusted.commit, backend=backend, use_cache=False)


def verify(chain_id: str, trusted: LightBlock, untrusted: LightBlock,
           trusting_period_ns: int, now_ns: int,
           trust_level: Fraction = DEFAULT_TRUST_LEVEL,
           max_clock_drift_ns: int = MAX_CLOCK_DRIFT_NS,
           backend: str | None = None) -> None:
    """light/verifier.go:133 Verify dispatcher."""
    if untrusted.height != trusted.height + 1:
        verify_non_adjacent(chain_id, trusted, untrusted,
                            trusting_period_ns, now_ns, trust_level,
                            max_clock_drift_ns, backend)
    else:
        verify_adjacent(chain_id, trusted, untrusted, trusting_period_ns,
                        now_ns, max_clock_drift_ns, backend)


def verify_sequential_batched(chain_id: str, trusted: LightBlock,
                              chain: list[LightBlock],
                              trusting_period_ns: int, now_ns: int,
                              max_clock_drift_ns: int = MAX_CLOCK_DRIFT_NS,
                              backend: str | None = None,
                              max_batch: int = 256) -> None:
    """Sequentially verify a contiguous header chain, batching commit
    signatures of same-validator-set runs into single device dispatches.

    Semantically identical to calling ``verify_adjacent`` per header (the
    reference's verifySequential, light/client.go:609) — the cheap
    structural checks still run per header in order; only the signature
    work is fused.  A 1000-header sync at 150 validators becomes ~4 device
    batches instead of 1000."""
    _check_trusted_period(trusted, trusting_period_ns, now_ns)
    prev = trusted
    i = 0
    while i < len(chain):
        # collect a same-valset run starting at i
        run = []
        vals_hash = chain[i].header.validators_hash
        j = i
        while j < len(chain) and len(run) < max_batch and \
                chain[j].header.validators_hash == vals_hash:
            lb = chain[j]
            if lb.height != prev.height + 1:
                raise ErrInvalidHeader(
                    f"chain gap at height {lb.height} "
                    f"(prev {prev.height})")
            _verify_new_header_and_vals(chain_id, prev, lb, now_ns,
                                        max_clock_drift_ns)
            if lb.header.validators_hash != \
                    prev.header.next_validators_hash:
                raise ErrInvalidHeader(
                    f"header {lb.height} validators_hash != "
                    "prev next_validators_hash")
            run.append(lb)
            prev = lb
            j += 1
        # one device batch proves the whole run (shared validator set)
        verify_commits_light_batched(
            chain_id, run[0].validators,
            [(lb.commit.block_id, lb.height, lb.commit) for lb in run],
            backend=backend)
        i = j
