"""RPC-backed light-block provider: fetch headers/commits/validators from
a full node's JSON-RPC endpoint (reference: ``light/provider/http`` — the
provider real light clients use in production)."""

from __future__ import annotations

from ..crypto.keys import pub_key_from_type_bytes
from ..libs import log as _tmlog
from ..rpc.client import HTTPClient
from ..rpc.core import RPCError
from ..rpc.json import from_jsonable, jsonable
from ..types.validator_set import Validator, ValidatorSet
from .provider import ErrLightBlockNotFound, Provider
from .types import LightBlock


class RPCProvider(Provider):
    def __init__(self, host: str, port: int, name: str | None = None,
                 *, tls: bool = False):
        """``tls=True`` reaches an HTTPS-configured node (self-signed
        accepted: the light client's trust comes from header hashes and
        the trusted anchor, not from the TLS channel)."""
        self.client = HTTPClient(host, port, tls=tls, tls_verify=False)
        self.name = name or f"rpc:{host}:{port}"

    def id(self) -> str:
        return self.name

    async def report_evidence(self, evidence) -> None:
        """Deliver attack evidence to the node behind this provider via a
        ``/broadcast_evidence`` round-trip (light/provider/http
        ReportEvidence) — the detector sends each side's incriminating
        evidence to the honest party, and the base-class no-op silently
        dropped it for RPC-backed witnesses.  Submission is best-effort:
        a dead or rejecting node logs a warning (the divergence itself
        still raises at the caller), it must not mask the fork."""
        try:
            await self.client.call("broadcast_evidence",
                                   evidence=jsonable(evidence))
        except Exception as e:
            _tmlog.logger("light").warn(
                "evidence report failed; the peer never received it",
                provider=self.name, err=str(e))

    async def light_block(self, height: int) -> LightBlock:
        try:
            cm = await self.client.call("commit", height=height or None)
            if cm.get("header") is None or cm.get("commit") is None:
                raise ErrLightBlockNotFound(
                    f"{self.name}: no commit at {height}")
            header = from_jsonable(cm["header"])
            commit = from_jsonable(cm["commit"])
            vals = await self._validators(commit.height)
        except RPCError as e:
            raise ErrLightBlockNotFound(f"{self.name}: {e}") from e
        except OSError as e:
            raise ErrLightBlockNotFound(
                f"{self.name}: unreachable: {e}") from e
        return LightBlock(header=header, commit=commit, validators=vals)

    async def _validators(self, height: int) -> ValidatorSet:
        vals: list[Validator] = []
        page = 1
        while True:
            res = await self.client.call("validators", height=height,
                                         page=page, per_page=100)
            for v in res["validators"]:
                vals.append(Validator(
                    pub_key_from_type_bytes(v["pub_key_type"],
                                            bytes.fromhex(v["pub_key"])),
                    v["voting_power"], v["proposer_priority"]))
            if len(vals) >= res["total"] or not res["validators"]:
                return ValidatorSet(vals)
            page += 1
