"""RPC-backed light-block provider: fetch headers/commits/validators from
a full node's JSON-RPC endpoint (reference: ``light/provider/http`` — the
provider real light clients use in production).

Two robustness properties on top of the plain client:

- transient failures (connection drops, timeouts, a 503 from the
  serving node's admission gate) retry with bounded exponential backoff
  instead of failing the caller's whole bisection on one flaky fetch —
  a shed request is exactly the one the server ASKED us to retry;
- when the node runs the light-serving tier, one ``light_block`` RPC
  answers with header + commit + validator set in a single round trip;
  nodes without the route (pre-lightserve) degrade to the classic
  ``commit`` + paged ``validators`` fetch path automatically.
"""

from __future__ import annotations

import asyncio

from ..crypto.keys import pub_key_from_type_bytes
from ..libs import log as _tmlog
from ..rpc.client import HTTPClient
from ..rpc.core import RPCError
from ..rpc.json import from_jsonable, jsonable
from ..types.validator_set import Validator, ValidatorSet
from .provider import ErrLightBlockNotFound, Provider
from .types import LightBlock


def _transient(e: Exception) -> bool:
    """Worth retrying?  Network-layer failures and the serving node's
    overload shed (HTTP 503 / JSON-RPC -32000 "overloaded") are
    transient; a definitive RPC answer (no such height, bad params) is
    not."""
    if isinstance(e, (ConnectionError, asyncio.TimeoutError, OSError)):
        return True
    if isinstance(e, RPCError) and e.code == -32000:
        return True
    return False


class RPCProvider(Provider):
    def __init__(self, host: str, port: int, name: str | None = None,
                 *, tls: bool = False, retries: int = 2,
                 backoff_s: float = 0.25):
        """``tls=True`` reaches an HTTPS-configured node (self-signed
        accepted: the light client's trust comes from header hashes and
        the trusted anchor, not from the TLS channel).  ``retries`` bounds
        how many times one call is re-attempted on a transient failure
        (0 disables), each wait doubling from ``backoff_s``."""
        self.client = HTTPClient(host, port, tls=tls, tls_verify=False)
        self.name = name or f"rpc:{host}:{port}"
        self.retries = max(0, int(retries))
        self.backoff_s = max(0.0, float(backoff_s))
        # None = unknown, probed on first light_block; False once the
        # node answered "method not found" (pre-lightserve node)
        self._has_light_block: bool | None = None

    def id(self) -> str:
        return self.name

    async def _call(self, method: str, **params):
        """One RPC with bounded-backoff retry on transient failures."""
        delay = self.backoff_s
        for attempt in range(self.retries + 1):
            try:
                return await self.client.call(method, **params)
            except Exception as e:
                if attempt >= self.retries or not _transient(e):
                    raise
                _tmlog.logger("light").warn(
                    "transient provider error; retrying",
                    provider=self.name, method=method,
                    attempt=attempt + 1, err=str(e))
                if delay > 0:
                    await asyncio.sleep(delay)
                delay *= 2

    async def report_evidence(self, evidence) -> None:
        """Deliver attack evidence to the node behind this provider via a
        ``/broadcast_evidence`` round-trip (light/provider/http
        ReportEvidence) — the detector sends each side's incriminating
        evidence to the honest party, and the base-class no-op silently
        dropped it for RPC-backed witnesses.  Submission is best-effort:
        a dead or rejecting node logs a warning (the divergence itself
        still raises at the caller), it must not mask the fork."""
        try:
            await self._call("broadcast_evidence",
                             evidence=jsonable(evidence))
        except Exception as e:
            _tmlog.logger("light").warn(
                "evidence report failed; the peer never received it",
                provider=self.name, err=str(e))

    async def light_block(self, height: int) -> LightBlock:
        if self._has_light_block is not False:
            try:
                return await self._light_block_served(height)
            except RPCError as e:
                if e.code == -32601:
                    # route absent or tier disabled: remember and fall
                    # back to the classic three-fetch path
                    self._has_light_block = False
                else:
                    raise ErrLightBlockNotFound(f"{self.name}: {e}") from e
            except OSError as e:
                raise ErrLightBlockNotFound(
                    f"{self.name}: unreachable: {e}") from e
        return await self._light_block_classic(height)

    async def _light_block_served(self, height: int) -> LightBlock:
        """Single-round-trip fetch through the serving tier."""
        res = await self._call("light_block", height=height or None)
        self._has_light_block = True
        lb = res.get("light_block") or {}
        header = from_jsonable(lb.get("header"))
        commit = from_jsonable(lb.get("commit"))
        vals = from_jsonable(lb.get("validators"))
        if header is None or commit is None or vals is None:
            raise ErrLightBlockNotFound(
                f"{self.name}: malformed light block at {height}")
        return LightBlock(header=header, commit=commit, validators=vals)

    async def _light_block_classic(self, height: int) -> LightBlock:
        try:
            cm = await self._call("commit", height=height or None)
            if cm.get("header") is None or cm.get("commit") is None:
                raise ErrLightBlockNotFound(
                    f"{self.name}: no commit at {height}")
            header = from_jsonable(cm["header"])
            commit = from_jsonable(cm["commit"])
            vals = await self._validators(commit.height)
        except RPCError as e:
            raise ErrLightBlockNotFound(f"{self.name}: {e}") from e
        except OSError as e:
            raise ErrLightBlockNotFound(
                f"{self.name}: unreachable: {e}") from e
        return LightBlock(header=header, commit=commit, validators=vals)

    async def _validators(self, height: int) -> ValidatorSet:
        vals: list[Validator] = []
        page = 1
        while True:
            res = await self._call("validators", height=height,
                                   page=page, per_page=100)
            for v in res["validators"]:
                vals.append(Validator(
                    pub_key_from_type_bytes(v["pub_key_type"],
                                            bytes.fromhex(v["pub_key"])),
                    v["voting_power"], v["proposer_priority"]))
            if len(vals) >= res["total"] or not res["validators"]:
                return ValidatorSet(vals)
            page += 1
