"""Statesync syncer: bootstrap a fresh node from an application snapshot
instead of replaying the chain (reference: ``statesync/syncer.go:53,144,
240,321,357`` + ``chunks.go`` + ``snapshots.go``).

Flow (syncer.go SyncAny):
1. discover snapshots from peers;
2. verify the snapshot height against the light client (trusted app
   hash from header h+1) and OfferSnapshot to the local app;
3. fetch chunks from the peers advertising the snapshot, ApplySnapshotChunk;
4. ABCI Info must land on (height, app_hash);
5. bootstrap the state store from the light-client state and record the
   trusted commit so consensus/blocksync can continue from h."""

from __future__ import annotations

import asyncio
import functools

from ..libs import aio, clock

from ..abci import types as abci
from ..libs import log as tmlog
from .stateprovider import StateProvider


@functools.cache
def _ss_metrics():
    from types import SimpleNamespace

    from ..libs import metrics as m

    return SimpleNamespace(
        senders_banned=m.counter(
            "statesync_senders_banned_total",
            "snapshot senders the app rejected (REJECT_SENDER offers or "
            "ApplySnapshotChunk reject_senders) — a stalled sync with "
            "this climbing means the snapshot sources are bad, not "
            "the network"),
        formats_rejected=m.counter(
            "statesync_formats_rejected_total",
            "snapshot offers rejected with REJECT_FORMAT (final per "
            "format for the whole sync)"))

CHUNK_TIMEOUT = 10.0
# Outstanding chunk requests per serving peer (the reference runs 4
# concurrent chunk fetchers, statesync/syncer.go chunkFetchers): enough
# to keep every peer's pipe full, bounded so one node is never flooded
# and restore throughput scales with the number of serving peers.
MAX_INFLIGHT_PER_PEER = 4
DISCOVERY_TIME = 0.5


class StatesyncError(Exception):
    pass


class _RejectFormat(StatesyncError):
    """App returned OFFER_SNAPSHOT_REJECT_FORMAT (syncer.go:38)."""


class _RejectSender(StatesyncError):
    """App returned OFFER_SNAPSHOT_REJECT_SENDER (syncer.go:40)."""


class _PendingSnapshot:
    def __init__(self, snapshot):
        self.snapshot = snapshot
        self.peers: list[str] = []


class _ChunkStore:
    """Received-chunk spool (reference: ``statesync/chunks.go`` — chunks
    land in a temp dir, NOT in memory): a snapshot can be many GB, and
    out-of-order chunks would otherwise pile up in RAM while the strictly
    sequential applier waits for the next index.  Dict-shaped so the
    syncer reads naturally; senders stay in a small in-memory map."""

    def __init__(self):
        import threading

        self._dir: str | None = None     # created on first write
        self._senders: dict[int, str] = {}
        self._closed = False             # late async writes must not
        #   resurrect the spool dir after close()
        # guards the closed/dir transitions against writer threads
        # (spool writes run in asyncio.to_thread)
        self._mu = threading.Lock()

    def _path(self, idx: int) -> str:
        import os

        return os.path.join(self._dir, f"{idx}.chunk")

    def __contains__(self, idx: int) -> bool:
        return idx in self._senders

    def __setitem__(self, idx: int, value) -> None:
        import os
        import tempfile

        data, sender = value
        with self._mu:
            if self._closed:
                return
            if self._dir is None:
                self._dir = tempfile.mkdtemp(prefix="statesync-chunks-")
            # unique tmp per WRITE: duplicate deliveries of the same
            # chunk spool concurrently, and sharing one tmp path would
            # interleave their bytes into a torn file
            self._tmp_seq = getattr(self, "_tmp_seq", 0) + 1
            tmp = self._path(idx) + f".{self._tmp_seq}.tmp"
        # the chunk file carries its own sender (len-prefixed header), so
        # a reader always sees an ATOMIC (sender, data) pair even while a
        # duplicate delivery from another peer is mid-replace
        sb = sender.encode()
        with open(tmp, "wb") as f:
            f.write(bytes([len(sb)]) + sb + data)
        with self._mu:
            if self._closed:             # closed while writing: discard
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                return
            os.replace(tmp, self._path(idx))
            self._senders[idx] = sender

    def __getitem__(self, idx: int):
        with open(self._path(idx), "rb") as f:
            raw = f.read()
        n = raw[0]
        return raw[1 + n:], raw[1:1 + n].decode()

    def pop(self, idx: int, default=None):
        import os

        with self._mu:
            if idx not in self._senders:
                return default
            sender = self._senders.pop(idx)
            if self._dir is not None:
                try:
                    os.remove(self._path(idx))
                except OSError:
                    pass
        return sender

    def pop_if_sender(self, idx: int, sender: str) -> bool:
        """Atomically remove chunk ``idx`` ONLY if it still came from
        ``sender`` — the banned-mid-write guard must not delete a fresh
        replacement a good peer just spooled over it."""
        import os

        with self._mu:
            if self._senders.get(idx) != sender:
                return False
            self._senders.pop(idx)
            if self._dir is not None:
                try:
                    os.remove(self._path(idx))
                except OSError:
                    pass
        return True

    def indices_from(self, sender: str) -> list[int]:
        return [i for i, s in self._senders.items() if s == sender]

    def clear(self) -> None:
        for idx in list(self._senders):
            self.pop(idx)

    def close(self) -> None:
        import shutil

        with self._mu:
            self._closed = True
            d, self._dir = self._dir, None
            self._senders.clear()
        if d is not None:
            shutil.rmtree(d, ignore_errors=True)


class Syncer:
    def __init__(self, app_conns, state_provider: StateProvider,
                 reactor=None, name: str = "syncer"):
        self.app_conns = app_conns
        self.provider = state_provider
        self.reactor = reactor
        self.name = name
        self.log = tmlog.logger("statesync", node=name)
        self._snapshots: dict[tuple, _PendingSnapshot] = {}
        self._chunks = _ChunkStore()     # idx -> (data, sender), on disk
        self._banned: set[str] = set()   # app-rejected senders
        self._m = _ss_metrics()
        self._chunk_event = asyncio.Event()
        self._current = None
        # the event loop holds only weak refs to tasks; spool writes must
        # stay strongly referenced until done or they can be GC'd mid-write
        self._spool_tasks: set[asyncio.Task] = set()

    # ------------------------------------------------ reactor callbacks

    def add_snapshot(self, peer_id: str, snapshot) -> None:
        key = (snapshot.height, snapshot.format, snapshot.hash)
        if peer_id in self._banned:
            return      # snapshots.go RejectPeer: bans outlive rounds
        pending = self._snapshots.setdefault(key,
                                             _PendingSnapshot(snapshot))
        if peer_id not in pending.peers:
            pending.peers.append(peer_id)

    def add_chunk(self, peer_id: str, height: int, format_: int,
                  index: int, chunk: bytes, snapshot_hash: bytes = b""
                  ) -> None:
        cur = self._current
        if cur is None or cur.snapshot.height != height or \
                cur.snapshot.format != format_ or \
                snapshot_hash != cur.snapshot.hash:
            return      # stale response from another snapshot: drop
        # the index comes off the WIRE and becomes a spool filename:
        # anything but an in-range int is malicious or corrupt
        if not isinstance(index, int) or isinstance(index, bool) or \
                not 0 <= index < cur.snapshot.chunks:
            self.log.warn("dropping chunk with invalid index",
                          peer=peer_id[:8], index=repr(index)[:40])
            return
        if peer_id in self._banned:
            return      # late delivery from a sender the app rejected
        if not isinstance(chunk, (bytes, bytearray)):
            return
        # spool write off the event loop: a multi-GB snapshot's chunks
        # must not stall consensus/p2p on disk IO.  The store ref is
        # captured so a write landing after a snapshot switch goes to the
        # (closed, write-refusing) OLD store, never the new one.
        store = self._chunks

        async def _spool():
            try:
                await asyncio.to_thread(
                    store.__setitem__, index, (bytes(chunk), peer_id))
            except OSError as e:
                # a full disk must surface as a DISK problem, not decay
                # into a misleading fetch timeout
                self.log.error("chunk spool write failed", index=index,
                               err=repr(e))
                return
            if self._chunks is not store:
                return                   # snapshot switched mid-write
            if peer_id in self._banned:
                # banned while the write was in flight: the purge already
                # ran, so the late insert must not resurrect poison (but
                # only OUR chunk — never a good peer's fresh replacement)
                store.pop_if_sender(index, peer_id)
                return
            self._chunk_event.set()

        aio.spawn(_spool(), self._spool_tasks)

    def remove_peer(self, peer_id: str) -> None:
        for pending in self._snapshots.values():
            if peer_id in pending.peers:
                pending.peers.remove(peer_id)

    def _note_sender_banned(self, peer_id: str) -> None:
        """One app-rejected sender: count it (a stalled sync must be
        diagnosable from /metrics) and feed the p2p peer-quality scorer
        so the node drops/bans the peer node-wide, not just for this
        sync."""
        self._banned.add(peer_id)
        self._m.senders_banned.inc(node=self.name)
        sw = getattr(self.reactor, "switch", None) \
            if self.reactor is not None else None
        if sw is not None and hasattr(sw, "report_peer"):
            try:
                sw.report_peer(peer_id, "bad_snapshot_chunk",
                               detail="app rejected snapshot sender",
                               disconnect=True)
            except Exception:
                pass

    # ------------------------------------------------------------- sync

    async def sync(self, discovery_time: float = DISCOVERY_TIME,
                   rounds: int = 5):
        """syncer.go SyncAny: returns (state, commit) for the restored
        height.  Raises StatesyncError when no snapshot can be restored.

        Discovery repeats per round with a FRESH offer pool: peers prune
        old snapshots as the chain advances, so offers must be recent
        relative to the fetch or the chunks will be gone by the time they
        are requested (the reference's retryHook re-requests snapshots
        for the same reason)."""
        rejected_formats: set[int] = set()   # REJECT_FORMAT is final
        try:
            return await self._sync_rounds(discovery_time, rounds,
                                           rejected_formats)
        finally:
            # success closed it already (idempotent); this covers the
            # all-rounds-exhausted raise, whose spool would otherwise
            # leak GBs in the temp dir for the process lifetime
            self._chunks.close()

    async def _sync_rounds(self, discovery_time: float, rounds: int,
                           rejected_formats: set):
        for round_ in range(rounds):
            self._snapshots.clear()
            if self.reactor is not None:
                self.reactor.broadcast_snapshot_request()
            await clock.sleep(discovery_time)
            tried: set = set()
            while True:
                best = self._best_snapshot(tried, rejected_formats)
                if best is None:
                    break                    # pool exhausted: re-discover
                tried.add((best.snapshot.height, best.snapshot.format,
                           best.snapshot.hash))
                try:
                    return await self._restore(best)
                except _RejectFormat:
                    # syncer.go:208 — skip every snapshot of this format
                    rejected_formats.add(best.snapshot.format)
                    self._m.formats_rejected.inc(node=self.name)
                    self.log.warn("snapshot format rejected",
                                  format=best.snapshot.format)
                except _RejectSender:
                    # syncer.go:212 — distrust every peer advertising it
                    banned = list(best.peers)
                    for p in banned:
                        self._note_sender_banned(p)
                        self.remove_peer(p)
                    self.log.warn("snapshot senders rejected",
                                  peers=len(banned))
                except StatesyncError as e:
                    self.log.warn("snapshot restore failed; trying next",
                                  height=best.snapshot.height, err=str(e))
        raise StatesyncError(f"no viable snapshots after {rounds} rounds")

    def _best_snapshot(self, tried: set,
                       rejected_formats: set | None = None
                       ) -> _PendingSnapshot | None:
        candidates = [p for k, p in self._snapshots.items()
                      if k not in tried and p.peers
                      and p.snapshot.format not in (rejected_formats or ())]
        if not candidates:
            return None
        return max(candidates, key=lambda p: p.snapshot.height)

    async def _restore(self, pending: _PendingSnapshot):
        snapshot = pending.snapshot
        h = snapshot.height
        self.log.info("restoring snapshot", height=h,
                      chunks=snapshot.chunks)

        # trusted app hash from the light client (syncer.go verifyApp prep)
        try:
            trusted_app_hash = await self.provider.app_hash(h)
        except Exception as e:
            raise StatesyncError(f"cannot verify snapshot height: {e}")

        resp = await self.app_conns.snapshot.offer_snapshot(
            snapshot, trusted_app_hash)
        if resp == abci.OFFER_SNAPSHOT_REJECT_FORMAT:
            raise _RejectFormat(f"format {snapshot.format}")
        if resp == abci.OFFER_SNAPSHOT_REJECT_SENDER:
            raise _RejectSender("providers rejected")
        if resp != abci.OFFER_SNAPSHOT_ACCEPT:
            raise StatesyncError(f"app rejected snapshot ({resp})")

        self._current = pending
        self._chunks.close()
        self._chunks = _ChunkStore()
        # NOTE: self._banned persists across snapshots — a sender the
        # app rejected once stays distrusted for the whole sync
        try:
            await self._fetch_and_apply(pending)
        finally:
            self._current = None

        # the app must now report the snapshot height + trusted hash
        # (syncer.go verifyApp)
        info = await self.app_conns.query.info()
        if info.last_block_height != h:
            raise StatesyncError(
                f"app restored to height {info.last_block_height}, "
                f"expected {h}")
        if info.last_block_app_hash != trusted_app_hash:
            raise StatesyncError("app hash mismatch after restore")

        try:
            state = await self.provider.state(h)
            commit = await self.provider.commit(h)
        except Exception as e:
            # e.g. the chain hasn't reached h+2 yet so the light client
            # cannot assemble the post-h state: a retryable condition,
            # not a fatal one
            raise StatesyncError(f"cannot build state at {h}: {e}")
        self._chunks.close()          # spool dir gone; lazily recreated
        self.log.info("snapshot restored", height=h)
        return state, commit

    MAX_CHUNK_RETRIES = 3

    async def _fetch_and_apply(self, pending) -> None:
        snapshot = pending.snapshot
        applied: set[int] = set()
        requested: dict[int, tuple[float, str]] = {}  # chunk -> (t, peer)
        retries: dict[int, int] = {}
        next_peer = 0
        last_progress = clock.monotonic()
        while len(applied) < snapshot.chunks:
            # request chunks that were never requested or whose request
            # timed out — NOT everything missing on every wakeup, which
            # would re-transfer in-flight chunks O(n^2).  Each peer holds
            # at most MAX_INFLIGHT_PER_PEER outstanding requests, so
            # restore bandwidth scales with serving peers instead of
            # flooding one.
            now = clock.monotonic()
            inflight: dict[str, int] = {}
            for i, (t, peer) in requested.items():
                # an assignment consumes its peer's budget until the
                # chunk arrives OR the chunk is re-requested elsewhere
                # (which overwrites requested[i]) — aging it out earlier
                # would let a slow-but-alive peer accumulate 2x the cap
                if i not in self._chunks and i not in applied:
                    inflight[peer] = inflight.get(peer, 0) + 1
            for i in range(snapshot.chunks):
                if i in self._chunks or i in applied:
                    continue
                prev = requested.get(i)
                if prev is not None and now - prev[0] < CHUNK_TIMEOUT / 2:
                    continue
                if not pending.peers:
                    raise StatesyncError("no peers serving the snapshot")
                # next peer with spare in-flight budget (round-robin)
                peer = None
                for _ in range(len(pending.peers)):
                    cand = pending.peers[next_peer % len(pending.peers)]
                    next_peer += 1
                    if inflight.get(cand, 0) < MAX_INFLIGHT_PER_PEER:
                        peer = cand
                        break
                if peer is None:
                    break           # every peer's pipe is full this round
                inflight[peer] = inflight.get(peer, 0) + 1
                requested[i] = (now, peer)
                if self.reactor is not None:
                    self.reactor.request_chunk(peer, snapshot.height,
                                               snapshot.format, i,
                                               snapshot.hash)
            # wake on new chunks OR periodically: an in-flight async
            # spool whose sender was banned mid-write leaves a stuck
            # `requested` entry that only the age-out re-request path
            # clears, so the loop must re-evaluate before the full
            # timeout.  The timeout itself is PROGRESS-based (any chunk
            # arrival or apply resets it).
            try:
                await clock.wait_for(self._chunk_event.wait(),
                                       CHUNK_TIMEOUT / 4)
                self._chunk_event.clear()
                last_progress = clock.monotonic()
            except asyncio.TimeoutError:
                if clock.monotonic() - last_progress > CHUNK_TIMEOUT:
                    raise StatesyncError("timed out fetching chunks")

            # apply in STRICT index order (the ABCI restore contract —
            # reference chunks.Next() blocks for the next sequential
            # index); later chunks wait in self._chunks until their turn
            while len(applied) in self._chunks:
                i = len(applied)
                data, sender = self._chunks[i]
                resp = await self.app_conns.snapshot.apply_snapshot_chunk(
                    i, data, sender)
                if isinstance(resp, int):   # bare-status app shorthand
                    resp = abci.ApplySnapshotChunkResponse(result=resp)

                # syncer.go:438 — the app can name bad senders and ask
                # for specific chunks again regardless of the result
                for bad in resp.reject_senders:
                    self._note_sender_banned(bad)
                    if bad in pending.peers:
                        pending.peers.remove(bad)
                    # chunks.DiscardSender: everything unapplied from the
                    # rejected sender is poisoned
                    for j in self._chunks.indices_from(bad):
                        self._chunks.pop(j)
                        requested.pop(j, None)
                    self.log.warn("banned snapshot sender", peer=bad)

                full_reset = resp.result == abci.APPLY_CHUNK_RETRY
                for j in resp.refetch_chunks:
                    if j < len(applied):
                        # an already-applied chunk cannot be re-applied
                        # mid-stream (the restore is strictly sequential):
                        # discard all progress, like RETRY
                        full_reset = True
                    self._chunks.pop(j, None)
                    requested.pop(j, None)

                bump_retry = full_reset or i in resp.refetch_chunks
                if bump_retry:
                    retries[i] = retries.get(i, 0) + 1
                    if retries[i] > self.MAX_CHUNK_RETRIES:
                        raise StatesyncError(
                            f"chunk {i} refused {retries[i]} times")
                if full_reset:
                    # the app discarded its accumulated restore progress
                    # (e.g. whole-snapshot hash mismatch): refetch all
                    applied.clear()
                    self._chunks.clear()
                    requested.clear()
                    break
                if resp.result == abci.APPLY_CHUNK_ACCEPT:
                    if i in resp.refetch_chunks:
                        break   # app wants this very chunk again: not
                                # applied; the outer loop re-requests it
                    applied.add(i)
                    self._chunks.pop(i)   # applied: free its spool file
                else:
                    raise StatesyncError(
                        f"app aborted on chunk {i} ({resp.result})")
