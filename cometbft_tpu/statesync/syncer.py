"""Statesync syncer: bootstrap a fresh node from an application snapshot
instead of replaying the chain (reference: ``statesync/syncer.go:53,144,
240,321,357`` + ``chunks.go`` + ``snapshots.go``).

Flow (syncer.go SyncAny):
1. discover snapshots from peers;
2. verify the snapshot height against the light client (trusted app
   hash from header h+1) and OfferSnapshot to the local app;
3. negotiate the chunk manifest (per-chunk sha256 bound to the snapshot
   hash, see ``manifest.py``) from the peers whose offers agree on it;
4. fetch chunks from the peers advertising the snapshot — every chunk
   verified against the manifest BEFORE it is spooled, a mismatch bans
   the sender and re-requests only that chunk from another holder —
   then ApplySnapshotChunk in strict index order;
5. ABCI Info must land on (height, app_hash);
6. bootstrap the state store from the light-client state and record the
   trusted commit so consensus/blocksync can continue from h.

The spool is content-addressed (``_BlobPool``): chunk bytes are stored
under their sha256, so duplicate deliveries, identical chunks across
heights/formats (app state barely changes block-to-block) and snapshot
retry rounds all hit the same blob.  Released blobs are RETAINED up to
a byte budget, which is what makes a failed restore resumable — the
next attempt adopts every blob the manifest says it already has."""

from __future__ import annotations

import asyncio
import errno
import functools
import hashlib
import os
import shutil
import tempfile
import threading

from ..libs import aio, clock, failures

from ..abci import types as abci
from ..libs import log as tmlog
from .manifest import manifest_root, valid_hash_list
from .stateprovider import StateProvider


@functools.cache
def _ss_metrics():
    from types import SimpleNamespace

    from ..libs import metrics as m

    return SimpleNamespace(
        senders_banned=m.counter(
            "statesync_senders_banned_total",
            "snapshot senders the app rejected (REJECT_SENDER offers or "
            "ApplySnapshotChunk reject_senders) — a stalled sync with "
            "this climbing means the snapshot sources are bad, not "
            "the network"),
        formats_rejected=m.counter(
            "statesync_formats_rejected_total",
            "snapshot offers rejected with REJECT_FORMAT (final per "
            "format for the whole sync)"),
        chunks_verified=m.counter(
            "statesync_chunks_verified_total",
            "fetched chunks that passed the manifest hash check before "
            "spooling"),
        hash_mismatches=m.counter(
            "statesync_chunk_hash_mismatches_total",
            "fetched chunks whose sha256 did not match the manifest — "
            "each one is a corrupt or malicious sender caught BEFORE "
            "the app saw the bytes"),
        chunks_dedup=m.counter(
            "statesync_chunks_dedup_total",
            "spool writes satisfied by an existing content-addressed "
            "blob (duplicate delivery, cross-snapshot identical chunk, "
            "or retry-round resume)"),
        chunks_resumed=m.counter(
            "statesync_chunks_resumed_total",
            "chunks adopted from the retained blob pool at restore "
            "start instead of being re-fetched (resumable multi-peer "
            "fetch)"),
        restore_resets=m.counter(
            "statesync_restore_resets_total",
            "full restore resets (APPLY_CHUNK_RETRY / refetch of an "
            "applied chunk) — with manifest verification active this "
            "should stay at zero"),
        spool_fatal=m.counter(
            "statesync_spool_fatal_io_total",
            "chunk-spool writes that died on a fatal IO error (ENOSPC/"
            "EIO/...): the sync fails with the disk as the cause "
            "instead of decaying into a fetch timeout"))


CHUNK_TIMEOUT = 10.0
# Outstanding chunk requests per serving peer (the reference runs 4
# concurrent chunk fetchers, statesync/syncer.go chunkFetchers): enough
# to keep every peer's pipe full, bounded so one node is never flooded
# and restore throughput scales with the number of serving peers.
MAX_INFLIGHT_PER_PEER = 4
DISCOVERY_TIME = 0.5
DISCOVERY_ROUNDS = 5
# Byte budget for retained (released-but-kept) spool blobs — the
# resumability / cross-snapshot dedup window.
SPOOL_RETAIN_BYTES = 64 * 1024 * 1024

# Mirrors the consensus fsyncgate discipline (consensus/state.py): these
# errnos mean the STORAGE is gone, not that this one write was unlucky.
_FATAL_IO_ERRNOS = frozenset({errno.EIO, errno.ENOSPC, errno.EROFS,
                              errno.EDQUOT, errno.ENXIO})


def _is_fatal_io_error(e: OSError) -> bool:
    return getattr(e, "errno", None) in _FATAL_IO_ERRNOS


class StatesyncError(Exception):
    pass


class StatesyncFatalError(StatesyncError):
    """Unretryable failure (fatal spool IO): retrying another snapshot
    would hit the same dead disk, so this aborts the whole sync with
    the real cause instead of burning the remaining rounds."""


class _RejectFormat(StatesyncError):
    """App returned OFFER_SNAPSHOT_REJECT_FORMAT (syncer.go:38)."""


class _RejectSender(StatesyncError):
    """App returned OFFER_SNAPSHOT_REJECT_SENDER (syncer.go:40)."""


class _PendingSnapshot:
    def __init__(self, snapshot):
        self.snapshot = snapshot
        self.peers: list[str] = []
        # peer -> advertised manifest root (absent for legacy peers)
        self.manifest_roots: dict[str, bytes] = {}


class _BlobPool:
    """Content-addressed blob storage under the spool dir (or in memory
    for the deterministic sim, which must not touch disk or threads).

    Blobs are refcounted by the chunk stores indexing into the pool;
    a blob whose last reference is released moves to a byte-budgeted
    retained tier instead of being deleted, so identical chunks across
    snapshot attempts / heights / formats never transfer twice."""

    def __init__(self, in_memory: bool = False, retain_bytes: int = 0):
        self.in_memory = bool(in_memory)
        self.retain_bytes = max(0, int(retain_bytes))
        self._dir: str | None = None     # created on first disk write
        self._mem: dict[bytes, bytes] = {}
        self._refs: dict[bytes, int] = {}
        self._sizes: dict[bytes, int] = {}
        self._retained: dict[bytes, int] = {}    # hash -> size, LRU order
        self._retained_bytes = 0
        self._closed = False
        self.dedup_hits = 0
        # guards every map transition against writer threads (disk
        # spool writes run in asyncio.to_thread)
        self._mu = threading.Lock()
        self._tmp_seq = 0

    def _path(self, h: bytes) -> str:
        return os.path.join(self._dir, h.hex() + ".blob")

    def put(self, h: bytes, data: bytes) -> bool:
        """Store ``data`` under its hash and take one reference.
        Returns False when the pool is closed (late async write)."""
        with self._mu:
            if self._closed:
                return False
            if h in self._refs:
                self._refs[h] += 1
                self.dedup_hits += 1
                return True
            if h in self._retained:
                self._retained_bytes -= self._retained.pop(h)
                self._refs[h] = 1
                self.dedup_hits += 1
                return True
            if self.in_memory:
                self._mem[h] = bytes(data)
                self._refs[h] = 1
                self._sizes[h] = len(data)
                return True
            if self._dir is None:
                self._dir = tempfile.mkdtemp(prefix="statesync-chunks-")
            # unique tmp per WRITE: concurrent duplicate deliveries of
            # the same content spool concurrently, and sharing one tmp
            # path would interleave their bytes into a torn file
            self._tmp_seq += 1
            tmp = self._path(h) + f".{self._tmp_seq}.tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        with self._mu:
            if self._closed:             # closed while writing: discard
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                return False
            os.replace(tmp, self._path(h))
            self._refs[h] = self._refs.get(h, 0) + 1
            self._sizes[h] = len(data)
        return True

    def acquire(self, h: bytes) -> bool:
        """Take a reference on an EXISTING blob (resume/adopt path)."""
        with self._mu:
            if self._closed:
                return False
            if h in self._refs:
                self._refs[h] += 1
                return True
            if h in self._retained:
                self._retained_bytes -= self._retained.pop(h)
                self._refs[h] = 1
                return True
            return False

    def get(self, h: bytes) -> bytes:
        if self.in_memory:
            return self._mem[h]
        with open(self._path(h), "rb") as f:
            return f.read()

    def release(self, h: bytes) -> None:
        """Drop one reference; the last drop retires the blob into the
        byte-budgeted retained tier (or deletes it at budget 0)."""
        delete: list[bytes] = []
        with self._mu:
            n = self._refs.get(h)
            if n is None:
                return
            if n > 1:
                self._refs[h] = n - 1
                return
            del self._refs[h]
            size = self._sizes.get(h, 0)
            if self.retain_bytes > 0:
                self._retained[h] = size
                self._retained_bytes += size
                while self._retained_bytes > self.retain_bytes \
                        and len(self._retained) > 1:
                    old, osize = next(iter(self._retained.items()))
                    del self._retained[old]
                    self._retained_bytes -= osize
                    delete.append(old)
            else:
                delete.append(h)
        for d in delete:
            self._delete(d)

    def _delete(self, h: bytes) -> None:
        self._sizes.pop(h, None)
        if self.in_memory:
            self._mem.pop(h, None)
        elif self._dir is not None:
            try:
                os.remove(self._path(h))
            except OSError:
                pass

    def close(self) -> None:
        with self._mu:
            self._closed = True
            d, self._dir = self._dir, None
            self._mem.clear()
            self._refs.clear()
            self._sizes.clear()
            self._retained.clear()
            self._retained_bytes = 0
        if d is not None:
            shutil.rmtree(d, ignore_errors=True)


class _ChunkStore:
    """Received-chunk spool (reference: ``statesync/chunks.go`` — chunks
    land on disk, NOT in memory): a snapshot can be many GB, and
    out-of-order chunks would otherwise pile up in RAM while the strictly
    sequential applier waits for the next index.  Dict-shaped so the
    syncer reads naturally; one store indexes ONE snapshot attempt, and
    the bytes live in a (possibly shared, attempt-outliving)
    :class:`_BlobPool` keyed by content hash."""

    def __init__(self, pool: "_BlobPool | None" = None,
                 in_memory: bool = False, retain_bytes: int = 0):
        self._pool = pool if pool is not None else \
            _BlobPool(in_memory=in_memory, retain_bytes=retain_bytes)
        self._owns_pool = pool is None
        self._senders: dict[int, str] = {}
        self._hashes: dict[int, bytes] = {}
        self._closed = False             # late async writes must not
        #   resurrect the spool after close()
        self._mu = threading.Lock()

    @property
    def _dir(self):
        return self._pool._dir

    def __contains__(self, idx: int) -> bool:
        return idx in self._senders

    def __setitem__(self, idx: int, value) -> None:
        data, sender = value
        data = bytes(data)
        h = hashlib.sha256(data).digest()
        with self._mu:
            if self._closed:
                return
        if not self._pool.put(h, data):
            return
        old = None
        with self._mu:
            if self._closed:             # closed while writing: discard
                self._pool.release(h)
                return
            old = self._hashes.get(idx)
            self._hashes[idx] = h
            self._senders[idx] = sender
        if old is not None and old != h:
            self._pool.release(old)
        elif old == h:                   # duplicate delivery, same bytes
            self._pool.release(old)

    def __getitem__(self, idx: int):
        return self._pool.get(self._hashes[idx]), self._senders[idx]

    def adopt(self, idx: int, h: bytes, sender: str = "") -> bool:
        """Index an already-pooled blob as chunk ``idx`` (the manifest
        told us its hash) — the resumable-fetch fast path."""
        with self._mu:
            if self._closed or idx in self._hashes:
                return False
        if not self._pool.acquire(h):
            return False
        with self._mu:
            if self._closed or idx in self._hashes:
                self._pool.release(h)
                return False
            self._hashes[idx] = h
            self._senders[idx] = sender
        return True

    def _release_locked(self, idx: int) -> bytes | None:
        self._senders.pop(idx, None)
        return self._hashes.pop(idx, None)

    def pop(self, idx: int, default=None):
        with self._mu:
            if idx not in self._senders:
                return default
            sender = self._senders[idx]
            h = self._release_locked(idx)
        if h is not None:
            self._pool.release(h)
        return sender

    def pop_if_sender(self, idx: int, sender: str) -> bool:
        """Atomically remove chunk ``idx`` ONLY if it still came from
        ``sender`` — the banned-mid-write guard must not delete a fresh
        replacement a good peer just spooled over it."""
        with self._mu:
            if self._senders.get(idx) != sender:
                return False
            h = self._release_locked(idx)
        if h is not None:
            self._pool.release(h)
        return True

    def indices_from(self, sender: str) -> list[int]:
        return [i for i, s in self._senders.items() if s == sender]

    def clear(self) -> None:
        for idx in list(self._senders):
            self.pop(idx)

    def close(self) -> None:
        with self._mu:
            self._closed = True
            self._senders.clear()
            hashes = list(self._hashes.values())
            self._hashes.clear()
        for h in hashes:
            self._pool.release(h)
        if self._owns_pool:
            self._pool.close()


class Syncer:
    MAX_CHUNK_RETRIES = 3

    def __init__(self, app_conns, state_provider: StateProvider,
                 reactor=None, name: str = "syncer", *,
                 chunk_timeout: float = CHUNK_TIMEOUT,
                 max_inflight_per_peer: int = MAX_INFLIGHT_PER_PEER,
                 discovery_time: float = DISCOVERY_TIME,
                 discovery_rounds: int = DISCOVERY_ROUNDS,
                 chunk_retries: int = MAX_CHUNK_RETRIES,
                 spool_retain_bytes: int = SPOOL_RETAIN_BYTES,
                 in_memory_spool: bool = False):
        self.app_conns = app_conns
        self.provider = state_provider
        self.reactor = reactor
        self.name = name
        self.log = tmlog.logger("statesync", node=name)
        self.chunk_timeout = float(chunk_timeout)
        self.max_inflight_per_peer = int(max_inflight_per_peer)
        self.discovery_time = float(discovery_time)
        self.discovery_rounds = int(discovery_rounds)
        self.chunk_retries = int(chunk_retries)
        self._snapshots: dict[tuple, _PendingSnapshot] = {}
        self._pool = _BlobPool(in_memory=in_memory_spool,
                               retain_bytes=spool_retain_bytes)
        self._sync_spool = bool(in_memory_spool)   # write inline (sim)
        self._chunks = _ChunkStore(pool=self._pool)
        self._banned: set[str] = set()   # rejected / corrupting senders
        self._m = _ss_metrics()
        # plain-int mirrors of the statesync_* counters: the sim lab
        # reads per-NODE tallies, which process-wide metrics can't give
        self.tallies: dict[str, int] = {
            "chunks_verified": 0, "chunk_hash_mismatches": 0,
            "chunks_dedup": 0, "chunks_resumed": 0,
            "restore_resets": 0, "senders_banned": 0,
            "slow_strikes": 0}
        self._chunk_event = asyncio.Event()
        self._current: _PendingSnapshot | None = None
        self._manifest: list[bytes] | None = None   # per-chunk sha256
        self._manifest_box: list[bytes] | None = None
        self._manifest_event = asyncio.Event()
        self._expect_root: bytes | None = None
        self._fatal: StatesyncFatalError | None = None
        self._refetch: set[int] = set()  # verification-failed indices
        # per-peer slow strikes (request age-outs): slow peers are
        # deprioritized and reported at low weight — NOT banned, which
        # is reserved for provably bad bytes
        self._timeouts: dict[str, int] = {}
        # the event loop holds only weak refs to tasks; spool writes must
        # stay strongly referenced until done or they can be GC'd mid-write
        self._spool_tasks: set[asyncio.Task] = set()

    # ------------------------------------------------ reactor callbacks

    def add_snapshot(self, peer_id: str, snapshot,
                     manifest_root: bytes | None = None) -> None:
        key = (snapshot.height, snapshot.format, snapshot.hash)
        if peer_id in self._banned:
            return      # snapshots.go RejectPeer: bans outlive rounds
        pending = self._snapshots.setdefault(key,
                                             _PendingSnapshot(snapshot))
        if peer_id not in pending.peers:
            pending.peers.append(peer_id)
        if isinstance(manifest_root, (bytes, bytearray)) and manifest_root:
            pending.manifest_roots[peer_id] = bytes(manifest_root)

    def add_manifest(self, peer_id: str, height: int, format_: int,
                     snapshot_hash: bytes, hashes) -> None:
        """A ``mres`` hash list: verified against the offer-advertised
        root before it becomes THE manifest for the current restore."""
        cur = self._current
        if cur is None or self._expect_root is None or \
                cur.snapshot.height != height or \
                cur.snapshot.format != format_ or \
                snapshot_hash != cur.snapshot.hash:
            return      # stale / unsolicited manifest: drop
        if not valid_hash_list(cur.snapshot.hash, hashes,
                               cur.snapshot.chunks, self._expect_root):
            self.log.warn("manifest failed verification",
                          peer=peer_id[:8], height=height)
            self._note_sender_banned(peer_id,
                                     detail="manifest/root mismatch")
            self.remove_peer(peer_id)
            self._manifest_box = None
            self._manifest_event.set()   # wake negotiation: next holder
            return
        self._manifest_box = [bytes(x) for x in hashes]
        self._manifest_event.set()

    def add_chunk(self, peer_id: str, height: int, format_: int,
                  index: int, chunk: bytes, snapshot_hash: bytes = b""
                  ) -> None:
        cur = self._current
        if cur is None or cur.snapshot.height != height or \
                cur.snapshot.format != format_ or \
                snapshot_hash != cur.snapshot.hash:
            return      # stale response from another snapshot: drop
        # the index comes off the WIRE and becomes a spool filename:
        # anything but an in-range int is malicious or corrupt
        if not isinstance(index, int) or isinstance(index, bool) or \
                not 0 <= index < cur.snapshot.chunks:
            self.log.warn("dropping chunk with invalid index",
                          peer=peer_id[:8], index=repr(index)[:40])
            return
        if peer_id in self._banned:
            return      # late delivery from a sender the app rejected
        if not isinstance(chunk, (bytes, bytearray)):
            return
        if self._manifest is not None:
            # content check BEFORE the spool (the whole point of the
            # manifest): bad bytes ban the sender and re-request THIS
            # chunk from another holder — the restore never resets
            if hashlib.sha256(bytes(chunk)).digest() != \
                    self._manifest[index]:
                self._m.hash_mismatches.inc(node=self.name)
                self.tallies["chunk_hash_mismatches"] += 1
                self.log.warn("chunk hash mismatch; banning sender",
                              peer=peer_id[:8], index=index)
                self._note_sender_banned(
                    peer_id, detail=f"chunk {index} hash mismatch")
                self.remove_peer(peer_id)
                self._refetch.add(index)
                self._chunk_event.set()
                return
            self._m.chunks_verified.inc(node=self.name)
            self.tallies["chunks_verified"] += 1
        if self._sync_spool:
            # deterministic-sim mode: the pool is in memory, so the
            # write is cheap and MUST stay on the loop (executor
            # completion order is real-time nondeterminism)
            try:
                self._spool_write(self._chunks, index, bytes(chunk),
                                  peer_id)
            except OSError as e:
                self._spool_failed(index, e)
                return
            self._chunk_event.set()
            return
        # spool write off the event loop: a multi-GB snapshot's chunks
        # must not stall consensus/p2p on disk IO.  The store ref is
        # captured so a write landing after a snapshot switch goes to the
        # (closed, write-refusing) OLD store, never the new one.
        store = self._chunks

        async def _spool():
            try:
                await asyncio.to_thread(
                    self._spool_write, store, index, bytes(chunk), peer_id)
            except OSError as e:
                self._spool_failed(index, e)
                return
            if self._chunks is not store:
                return                   # snapshot switched mid-write
            if peer_id in self._banned:
                # banned while the write was in flight: the purge already
                # ran, so the late insert must not resurrect poison (but
                # only OUR chunk — never a good peer's fresh replacement).
                # Flag the index for immediate re-request instead of
                # letting its stale `requested` entry age out.
                if store.pop_if_sender(index, peer_id):
                    self._refetch.add(index)
                self._chunk_event.set()
                return
            self._chunk_event.set()

        aio.spawn(_spool(), self._spool_tasks)

    def _spool_write(self, store: _ChunkStore, index: int, data: bytes,
                     peer_id: str) -> None:
        fired = failures.fire("statesync.spool.enospc", node=self.name)
        if fired is not None:
            raise OSError(errno.ENOSPC,
                          "injected: no space left on device")
        before = self._pool.dedup_hits
        store[index] = (data, peer_id)
        gained = self._pool.dedup_hits - before
        if gained:
            self._m.chunks_dedup.inc(gained, node=self.name)
            self.tallies["chunks_dedup"] += gained

    def _spool_failed(self, index: int, e: OSError) -> None:
        """Satellite of the fsyncgate discipline: a full/dead disk must
        surface as a DISK problem that fails the sync, not decay into a
        misleading fetch timeout."""
        if _is_fatal_io_error(e):
            self._m.spool_fatal.inc(node=self.name)
            self._fatal = StatesyncFatalError(
                f"chunk spool hit fatal IO error "
                f"({errno.errorcode.get(e.errno, e.errno)}): {e}")
            self.log.error("fatal chunk-spool IO error; failing sync",
                           index=index, err=repr(e))
            self._chunk_event.set()      # wake the fetch loop NOW
            return
        self.log.error("chunk spool write failed", index=index,
                       err=repr(e))

    def remove_peer(self, peer_id: str) -> None:
        for pending in self._snapshots.values():
            if peer_id in pending.peers:
                pending.peers.remove(peer_id)
        cur = self._current
        if cur is not None and peer_id in cur.peers:
            cur.peers.remove(peer_id)

    def _note_sender_banned(self, peer_id: str,
                            detail: str = "app rejected snapshot sender"
                            ) -> None:
        """One bad sender: count it (a stalled sync must be diagnosable
        from /metrics) and feed the p2p peer-quality scorer so the node
        drops/bans the peer node-wide, not just for this sync."""
        self._banned.add(peer_id)
        self._m.senders_banned.inc(node=self.name)
        self.tallies["senders_banned"] += 1
        sw = getattr(self.reactor, "switch", None) \
            if self.reactor is not None else None
        if sw is not None and hasattr(sw, "report_peer"):
            try:
                sw.report_peer(peer_id, "bad_snapshot_chunk",
                               detail=detail, disconnect=True)
            except Exception:
                pass

    def _note_slow_peer(self, peer_id: str) -> None:
        """A request age-out: slow, not (provably) evil.  Deprioritized
        in the fetch rotation and reported at low weight so persistent
        molasses eventually costs the peer its slot — but one hiccup
        never bans a peer the way bad bytes do."""
        self._timeouts[peer_id] = self._timeouts.get(peer_id, 0) + 1
        self.tallies["slow_strikes"] += 1
        sw = getattr(self.reactor, "switch", None) \
            if self.reactor is not None else None
        if sw is not None and hasattr(sw, "report_peer"):
            try:
                sw.report_peer(peer_id, "snapshot_timeout",
                               detail="chunk request aged out")
            except Exception:
                pass

    # ------------------------------------------------------------- sync

    async def sync(self, discovery_time: float | None = None,
                   rounds: int | None = None):
        """syncer.go SyncAny: returns (state, commit) for the restored
        height.  Raises StatesyncError when no snapshot can be restored.

        Discovery repeats per round with a FRESH offer pool: peers prune
        old snapshots as the chain advances, so offers must be recent
        relative to the fetch or the chunks will be gone by the time they
        are requested (the reference's retryHook re-requests snapshots
        for the same reason)."""
        if discovery_time is None:
            discovery_time = self.discovery_time
        if rounds is None:
            rounds = self.discovery_rounds
        rejected_formats: set[int] = set()   # REJECT_FORMAT is final
        try:
            return await self._sync_rounds(discovery_time, rounds,
                                           rejected_formats)
        finally:
            # success closed it already (idempotent); this covers the
            # all-rounds-exhausted raise, whose spool would otherwise
            # leak GBs in the temp dir for the process lifetime
            self._chunks.close()
            self._pool.close()

    async def _sync_rounds(self, discovery_time: float, rounds: int,
                           rejected_formats: set):
        for round_ in range(rounds):
            self._snapshots.clear()
            if self.reactor is not None:
                self.reactor.broadcast_snapshot_request()
            await clock.sleep(discovery_time)
            tried: set = set()
            while True:
                best = self._best_snapshot(tried, rejected_formats)
                if best is None:
                    break                    # pool exhausted: re-discover
                tried.add((best.snapshot.height, best.snapshot.format,
                           best.snapshot.hash))
                try:
                    return await self._restore(best)
                except StatesyncFatalError:
                    raise                    # dead disk: no more rounds
                except _RejectFormat:
                    # syncer.go:208 — skip every snapshot of this format
                    rejected_formats.add(best.snapshot.format)
                    self._m.formats_rejected.inc(node=self.name)
                    self.log.warn("snapshot format rejected",
                                  format=best.snapshot.format)
                except _RejectSender:
                    # syncer.go:212 — distrust every peer advertising it
                    banned = list(best.peers)
                    for p in banned:
                        self._note_sender_banned(p)
                        self.remove_peer(p)
                    self.log.warn("snapshot senders rejected",
                                  peers=len(banned))
                except StatesyncError as e:
                    self.log.warn("snapshot restore failed; trying next",
                                  height=best.snapshot.height, err=str(e))
        raise StatesyncError(f"no viable snapshots after {rounds} rounds")

    def _best_snapshot(self, tried: set,
                       rejected_formats: set | None = None
                       ) -> _PendingSnapshot | None:
        candidates = [p for k, p in self._snapshots.items()
                      if k not in tried and p.peers
                      and p.snapshot.format not in (rejected_formats or ())]
        if not candidates:
            return None
        return max(candidates, key=lambda p: p.snapshot.height)

    async def _restore(self, pending: _PendingSnapshot):
        snapshot = pending.snapshot
        h = snapshot.height
        self.log.info("restoring snapshot", height=h,
                      chunks=snapshot.chunks)

        # trusted app hash from the light client (syncer.go verifyApp prep)
        try:
            trusted_app_hash = await self.provider.app_hash(h)
        except Exception as e:
            raise StatesyncError(f"cannot verify snapshot height: {e}")

        resp = await self.app_conns.snapshot.offer_snapshot(
            snapshot, trusted_app_hash)
        if resp == abci.OFFER_SNAPSHOT_REJECT_FORMAT:
            raise _RejectFormat(f"format {snapshot.format}")
        if resp == abci.OFFER_SNAPSHOT_REJECT_SENDER:
            raise _RejectSender("providers rejected")
        if resp != abci.OFFER_SNAPSHOT_ACCEPT:
            raise StatesyncError(f"app rejected snapshot ({resp})")

        self._current = pending
        # a FRESH index per attempt (late async writes land in the old,
        # closed store) over the SHARED blob pool — so chunks fetched by
        # a failed attempt are adopted below instead of re-transferred
        self._chunks.close()
        self._chunks = _ChunkStore(pool=self._pool)
        # NOTE: self._banned persists across snapshots — a sender the
        # app rejected once stays distrusted for the whole sync
        try:
            self._manifest = await self._obtain_manifest(pending)
            if self._manifest is not None:
                resumed = 0
                for i, ch in enumerate(self._manifest):
                    if self._chunks.adopt(i, ch):
                        resumed += 1
                if resumed:
                    self._m.chunks_resumed.inc(resumed, node=self.name)
                    self.tallies["chunks_resumed"] += resumed
                    self.log.info("resumed chunks from spool",
                                  resumed=resumed, total=snapshot.chunks)
                    self._chunk_event.set()
            await self._fetch_and_apply(pending)
        finally:
            self._current = None
            self._manifest = None

        # the app must now report the snapshot height + trusted hash
        # (syncer.go verifyApp)
        info = await self.app_conns.query.info()
        if info.last_block_height != h:
            raise StatesyncError(
                f"app restored to height {info.last_block_height}, "
                f"expected {h}")
        if info.last_block_app_hash != trusted_app_hash:
            raise StatesyncError("app hash mismatch after restore")

        try:
            state = await self.provider.state(h)
            commit = await self.provider.commit(h)
        except Exception as e:
            # e.g. the chain hasn't reached h+2 yet so the light client
            # cannot assemble the post-h state: a retryable condition,
            # not a fatal one
            raise StatesyncError(f"cannot build state at {h}: {e}")
        self._chunks.close()          # spool freed; lazily recreated
        self.log.info("snapshot restored", height=h)
        return state, commit

    async def _obtain_manifest(self, pending: _PendingSnapshot
                               ) -> list[bytes] | None:
        """Negotiate the chunk manifest for this snapshot.  The root is
        taken from the LARGEST agreeing set of offering peers
        (deterministic tie-break on the digest); the hash list is then
        fetched from those peers and verified against the root.  Peers
        that advertised no root (legacy protocol) contribute nothing
        here but still serve chunks — which ARE verified when a
        manifest exists.  Returns None only when nobody advertised a
        root at all (pure-legacy restore, unverified as before)."""
        snapshot = pending.snapshot
        roots: dict[bytes, list[str]] = {}
        for p, r in pending.manifest_roots.items():
            if p in self._banned or p not in pending.peers:
                continue
            roots.setdefault(r, []).append(p)
        if not roots or self.reactor is None:
            return None
        root, holders = max(roots.items(),
                            key=lambda kv: (len(kv[1]), kv[0]))
        self._expect_root = root
        try:
            for peer in list(holders):
                if peer in self._banned or peer not in pending.peers:
                    continue
                self._manifest_box = None
                self._manifest_event.clear()
                if not self.reactor.request_manifest(
                        peer, snapshot.height, snapshot.format,
                        snapshot.hash):
                    continue
                try:
                    await clock.wait_for(self._manifest_event.wait(),
                                         self.chunk_timeout)
                except asyncio.TimeoutError:
                    self._note_slow_peer(peer)
                    continue
                if self._manifest_box is not None:
                    return self._manifest_box
                # verification failed inside add_manifest (peer banned
                # there): fall through to the next holder
            raise StatesyncError("no advertised manifest could be "
                                 "fetched and verified")
        finally:
            self._expect_root = None
            self._manifest_box = None

    async def _fetch_and_apply(self, pending) -> None:
        snapshot = pending.snapshot
        applied: set[int] = set()
        requested: dict[int, tuple[float, str]] = {}  # chunk -> (t, peer)
        retries: dict[int, int] = {}
        next_peer = 0
        timeout = self.chunk_timeout
        last_progress = clock.monotonic()
        while len(applied) < snapshot.chunks:
            if self._fatal is not None:
                raise self._fatal
            # a verification failure freed its request slot: re-request
            # immediately from another holder instead of waiting for
            # the age-out
            if self._refetch:
                for i in list(self._refetch):
                    requested.pop(i, None)
                self._refetch.clear()
            # request chunks that were never requested or whose request
            # timed out — NOT everything missing on every wakeup, which
            # would re-transfer in-flight chunks O(n^2).  Each peer holds
            # at most max_inflight_per_peer outstanding requests, so
            # restore bandwidth scales with serving peers instead of
            # flooding one.
            now = clock.monotonic()
            inflight: dict[str, int] = {}
            for i, (t, peer) in requested.items():
                # an assignment consumes its peer's budget until the
                # chunk arrives OR the chunk is re-requested elsewhere
                # (which overwrites requested[i]) — aging it out earlier
                # would let a slow-but-alive peer accumulate 2x the cap
                if i not in self._chunks and i not in applied:
                    inflight[peer] = inflight.get(peer, 0) + 1
            # slow peers drift to the back of the rotation (stable sort:
            # with no strikes this IS the plain round-robin order)
            peers = sorted(pending.peers,
                           key=lambda p: self._timeouts.get(p, 0))
            for i in range(snapshot.chunks):
                if i in self._chunks or i in applied:
                    continue
                prev = requested.get(i)
                if prev is not None and now - prev[0] < timeout / 2:
                    continue
                if prev is not None:
                    # the previous holder sat on it: strike it as slow
                    self._note_slow_peer(prev[1])
                if not pending.peers:
                    raise StatesyncError("no peers serving the snapshot")
                # next peer with spare in-flight budget (round-robin)
                peer = None
                for _ in range(len(peers)):
                    cand = peers[next_peer % len(peers)]
                    next_peer += 1
                    if inflight.get(cand, 0) < self.max_inflight_per_peer:
                        peer = cand
                        break
                if peer is None:
                    break           # every peer's pipe is full this round
                inflight[peer] = inflight.get(peer, 0) + 1
                requested[i] = (now, peer)
                if self.reactor is not None:
                    self.reactor.request_chunk(peer, snapshot.height,
                                               snapshot.format, i,
                                               snapshot.hash)
            # wake on new chunks OR periodically: an in-flight async
            # spool whose sender was banned mid-write leaves a stuck
            # `requested` entry that only the age-out re-request path
            # clears, so the loop must re-evaluate before the full
            # timeout.  The timeout itself is PROGRESS-based (any chunk
            # arrival or apply resets it).
            try:
                await clock.wait_for(self._chunk_event.wait(),
                                     timeout / 4)
                self._chunk_event.clear()
                last_progress = clock.monotonic()
            except asyncio.TimeoutError:
                if clock.monotonic() - last_progress > timeout:
                    raise StatesyncError("timed out fetching chunks")
            if self._fatal is not None:
                raise self._fatal

            # apply in STRICT index order (the ABCI restore contract —
            # reference chunks.Next() blocks for the next sequential
            # index); later chunks wait in self._chunks until their turn
            while len(applied) in self._chunks:
                i = len(applied)
                data, sender = self._chunks[i]
                resp = await self.app_conns.snapshot.apply_snapshot_chunk(
                    i, data, sender)
                if isinstance(resp, int):   # bare-status app shorthand
                    resp = abci.ApplySnapshotChunkResponse(result=resp)

                # syncer.go:438 — the app can name bad senders and ask
                # for specific chunks again regardless of the result
                for bad in resp.reject_senders:
                    self._note_sender_banned(bad)
                    if bad in pending.peers:
                        pending.peers.remove(bad)
                    # chunks.DiscardSender: everything unapplied from the
                    # rejected sender is poisoned — spooled chunks AND
                    # in-flight requests (freeing the slot re-requests
                    # from an honest peer on the next loop pass)
                    for j in self._chunks.indices_from(bad):
                        self._chunks.pop(j)
                        requested.pop(j, None)
                    for j, (_, p) in list(requested.items()):
                        if p == bad:
                            requested.pop(j, None)
                    self.log.warn("banned snapshot sender", peer=bad)

                full_reset = resp.result == abci.APPLY_CHUNK_RETRY
                for j in resp.refetch_chunks:
                    if j < len(applied):
                        # an already-applied chunk cannot be re-applied
                        # mid-stream (the restore is strictly sequential):
                        # discard all progress, like RETRY
                        full_reset = True
                    self._chunks.pop(j, None)
                    requested.pop(j, None)

                bump_retry = full_reset or i in resp.refetch_chunks
                if bump_retry:
                    retries[i] = retries.get(i, 0) + 1
                    if retries[i] > self.chunk_retries:
                        raise StatesyncError(
                            f"chunk {i} refused {retries[i]} times")
                if full_reset:
                    # the app discarded its accumulated restore progress
                    # (e.g. whole-snapshot hash mismatch): refetch all.
                    # With a manifest active this path should be DEAD —
                    # corrupt bytes never reach the app — so the counter
                    # doubles as a fabric-regression alarm.
                    self._m.restore_resets.inc(node=self.name)
                    self.tallies["restore_resets"] += 1
                    applied.clear()
                    self._chunks.clear()
                    requested.clear()
                    break
                if resp.result == abci.APPLY_CHUNK_ACCEPT:
                    if i in resp.refetch_chunks:
                        break   # app wants this very chunk again: not
                                # applied; the outer loop re-requests it
                    applied.add(i)
                    self._chunks.pop(i)   # applied: free its spool ref
                else:
                    raise StatesyncError(
                        f"app aborted on chunk {i} ({resp.result})")


# re-exported for callers that bind the helper from this module
__all__ = ["Syncer", "StatesyncError", "StatesyncFatalError",
           "CHUNK_TIMEOUT", "MAX_INFLIGHT_PER_PEER", "DISCOVERY_TIME",
           "DISCOVERY_ROUNDS", "manifest_root"]
