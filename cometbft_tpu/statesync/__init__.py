from .reactor import CHUNK_CHANNEL, SNAPSHOT_CHANNEL, StatesyncReactor
from .stateprovider import StateProvider
from .syncer import StatesyncError, Syncer

__all__ = ["StatesyncReactor", "StateProvider", "Syncer", "StatesyncError",
           "SNAPSHOT_CHANNEL", "CHUNK_CHANNEL"]
