from .cache import AdmissionGate, ChunkLRU
from .manifest import ChunkManifest, hash_chunk, manifest_root
from .reactor import CHUNK_CHANNEL, SNAPSHOT_CHANNEL, StatesyncReactor
from .stateprovider import StateProvider
from .syncer import StatesyncError, StatesyncFatalError, Syncer

__all__ = ["StatesyncReactor", "StateProvider", "Syncer", "StatesyncError",
           "StatesyncFatalError", "ChunkManifest", "ChunkLRU",
           "AdmissionGate", "hash_chunk", "manifest_root",
           "SNAPSHOT_CHANNEL", "CHUNK_CHANNEL"]
