"""Content-addressed chunk manifests for the snapshot fabric.

The reference statesync protocol detects a corrupt chunk only when the
APP rejects it — for the kvstore (and most real apps) that is a
whole-snapshot hash check at the END of the restore, so one flipped
byte costs every chunk already applied (``APPLY_CHUNK_RETRY`` →
full reset).  A manifest moves integrity to the wire layer: the per-
chunk sha256 list, bound to the snapshot hash through a single root
digest, lets the fetcher verify every chunk BEFORE it is spooled and
re-request only the bad one from another holder.

Binding: ``root = sha256(DOMAIN || snapshot_hash || h_0 || h_1 ...)``.
The snapshot hash in the preimage means a manifest cannot be replayed
across snapshots; the domain tag keeps the digest from colliding with
any other sha256 use in the tree.  Offers (``sres``) advertise the
root; the hash list itself travels on demand (``mreq``/``mres``) so
the offer stays O(1) regardless of snapshot size.

The root is only as trustworthy as the peers advertising it — the
syncer takes the root advertised by the LARGEST set of offering peers
(deterministic tie-break on the digest), so a lone byzantine seed
lying about the root merely excludes itself from manifest service
while its chunks are still checked against the honest manifest.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

_DOMAIN = b"cmt-statesync-manifest/v1"
HASH_LEN = 32


def hash_chunk(data: bytes) -> bytes:
    """The per-chunk digest every fetched chunk is checked against."""
    return hashlib.sha256(data).digest()


def manifest_root(snapshot_hash: bytes, chunk_hashes) -> bytes:
    """Root digest binding an ordered chunk-hash list to a snapshot."""
    h = hashlib.sha256()
    h.update(_DOMAIN)
    h.update(bytes(snapshot_hash))
    for ch in chunk_hashes:
        h.update(bytes(ch))
    return h.digest()


def valid_hash_list(snapshot_hash: bytes, hashes, n_chunks: int,
                    expected_root: bytes) -> bool:
    """Full wire-side validation of a received ``mres`` hash list: the
    right shape (one 32-byte digest per chunk) AND the right binding
    (recomputed root matches the offer-advertised one)."""
    if not isinstance(hashes, (list, tuple)) or len(hashes) != n_chunks:
        return False
    for ch in hashes:
        if not isinstance(ch, (bytes, bytearray)) or len(ch) != HASH_LEN:
            return False
    return manifest_root(snapshot_hash, hashes) == expected_root


@dataclass(frozen=True)
class ChunkManifest:
    """An immutable verified manifest (serving-side cache value)."""

    snapshot_hash: bytes
    hashes: tuple = field(default_factory=tuple)   # per-chunk sha256

    @classmethod
    def from_chunks(cls, snapshot_hash: bytes, chunks) -> "ChunkManifest":
        return cls(snapshot_hash=bytes(snapshot_hash),
                   hashes=tuple(hash_chunk(c) for c in chunks))

    @property
    def root(self) -> bytes:
        return manifest_root(self.snapshot_hash, self.hashes)

    def __len__(self) -> int:
        return len(self.hashes)

    def verify_chunk(self, index: int, data: bytes) -> bool:
        if not 0 <= index < len(self.hashes):
            return False
        return hash_chunk(data) == self.hashes[index]
