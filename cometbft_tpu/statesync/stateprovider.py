"""State provider: trusted ``sm.State`` + ``Commit`` for a snapshot height
via the light client (reference: ``statesync/stateprovider.go:38-79``
lightClientStateProvider).

The state AFTER block h needs light blocks h, h+1 and h+2: the app hash
and last-results hash as of h live in header h+1, and the validator sets
rotate one height ahead (State.validators is the set for the NEXT
block).

Light-block fetches go over the network, so TRANSIENT provider failures
(timeouts, dropped connections) get a bounded exponential-backoff retry
— the same discipline as ``light/rpc_provider.py`` — instead of one
flaky fetch of ``app_hash(h)`` failing the whole snapshot round.
Verification failures (a bad or forked header) are NOT transient and
surface immediately: retrying cannot make a dishonest header honest."""

from __future__ import annotations

import asyncio

from ..libs import clock
from ..libs import log as tmlog

from ..light.client import Client
from ..storage.statestore import State
from ..types.commit import Commit

# Transient = the fetch itself failed, not what it fetched.
# ConnectionError is an OSError subclass; asyncio.TimeoutError aliases
# TimeoutError on modern Pythons but both spellings stay for clarity.
_TRANSIENT = (TimeoutError, asyncio.TimeoutError, OSError)


class StateProvider:
    def __init__(self, light_client: Client, genesis_doc, *,
                 retries: int = 2, backoff_s: float = 0.25):
        self.client = light_client
        self.genesis = genesis_doc
        self.retries = max(0, int(retries))
        self.backoff_s = float(backoff_s)
        self.log = tmlog.logger("statesync.provider")

    async def _verify(self, height: int):
        """``verify_light_block_at_height`` with bounded exponential
        backoff on transient failures."""
        delay = self.backoff_s
        for attempt in range(self.retries + 1):
            try:
                return await self.client.verify_light_block_at_height(
                    height)
            except _TRANSIENT as e:
                if attempt >= self.retries:
                    raise
                self.log.warn("transient light-block fetch failure; "
                              "retrying", height=height,
                              attempt=attempt + 1, err=repr(e))
                await clock.sleep(delay)
                delay *= 2

    async def app_hash(self, height: int) -> bytes:
        """App hash AFTER block ``height`` (stateprovider.go AppHash —
        header at height+1 carries it)."""
        nxt = await self._verify(height + 1)
        return nxt.header.app_hash

    async def commit(self, height: int) -> Commit:
        lb = await self._verify(height)
        return lb.commit

    async def state(self, height: int) -> State:
        """stateprovider.go State(): assemble the post-``height`` state."""
        cur = await self._verify(height)
        nxt = await self._verify(height + 1)
        nxt2 = await self._verify(height + 2)
        from ..types.block_id import BlockID

        return State(
            chain_id=self.genesis.chain_id,
            initial_height=self.genesis.initial_height,
            last_block_height=height,
            last_block_id=BlockID(cur.header.hash(),
                                  nxt.header.last_block_id.part_set_header),
            last_block_time_ns=cur.header.time_ns,
            validators=nxt.validators,
            next_validators=nxt2.validators,
            last_validators=cur.validators,
            last_height_validators_changed=height + 1,
            consensus_params=self.genesis.consensus_params,
            last_height_params_changed=self.genesis.initial_height,
            last_results_hash=nxt.header.last_results_hash,
            app_hash=nxt.header.app_hash,
        )
