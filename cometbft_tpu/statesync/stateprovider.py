"""State provider: trusted ``sm.State`` + ``Commit`` for a snapshot height
via the light client (reference: ``statesync/stateprovider.go:38-79``
lightClientStateProvider).

The state AFTER block h needs light blocks h, h+1 and h+2: the app hash
and last-results hash as of h live in header h+1, and the validator sets
rotate one height ahead (State.validators is the set for the NEXT
block)."""

from __future__ import annotations

from ..light.client import Client
from ..storage.statestore import State
from ..types.commit import Commit


class StateProvider:
    def __init__(self, light_client: Client, genesis_doc):
        self.client = light_client
        self.genesis = genesis_doc

    async def app_hash(self, height: int) -> bytes:
        """App hash AFTER block ``height`` (stateprovider.go AppHash —
        header at height+1 carries it)."""
        nxt = await self.client.verify_light_block_at_height(height + 1)
        return nxt.header.app_hash

    async def commit(self, height: int) -> Commit:
        lb = await self.client.verify_light_block_at_height(height)
        return lb.commit

    async def state(self, height: int) -> State:
        """stateprovider.go State(): assemble the post-``height`` state."""
        cur = await self.client.verify_light_block_at_height(height)
        nxt = await self.client.verify_light_block_at_height(height + 1)
        nxt2 = await self.client.verify_light_block_at_height(height + 2)
        from ..types.block_id import BlockID

        return State(
            chain_id=self.genesis.chain_id,
            initial_height=self.genesis.initial_height,
            last_block_height=height,
            last_block_id=BlockID(cur.header.hash(),
                                  nxt.header.last_block_id.part_set_header),
            last_block_time_ns=cur.header.time_ns,
            validators=nxt.validators,
            next_validators=nxt2.validators,
            last_validators=cur.validators,
            last_height_validators_changed=height + 1,
            consensus_params=self.genesis.consensus_params,
            last_height_params_changed=self.genesis.initial_height,
            last_results_hash=nxt.header.last_results_hash,
            app_hash=nxt.header.app_hash,
        )
