"""Serving-side protection for the snapshot fabric: a byte-budgeted
chunk LRU and an admission gate.

Forty concurrent bootstrappers all fetch the SAME snapshot — the newest
one every serving peer offers — so chunk loads are massively shared.
Without a cache each ``creq`` costs an ABCI ``load_snapshot_chunk``
round trip (for real apps: a disk read + serialization), multiplied by
every fetcher; with the LRU the fleet hits RAM.  The admission gate
bounds how many loads run concurrently and how many may queue — beyond
that the request is SHED (dropped; the fetcher's timeout/rotation
machinery re-requests elsewhere), because a slow answer to everyone is
strictly worse than a fast answer to most (PR 9 discipline)."""

from __future__ import annotations

import asyncio
import functools
from collections import OrderedDict


@functools.cache
def _serve_metrics():
    from types import SimpleNamespace

    from ..libs import metrics as m

    return SimpleNamespace(
        chunks_served=m.counter(
            "statesync_chunks_served_total",
            "snapshot chunks served to fetching peers"),
        manifests_served=m.counter(
            "statesync_manifests_served_total",
            "chunk manifests served to fetching peers"),
        cache_hits=m.counter(
            "statesync_chunk_cache_hits_total",
            "chunk requests answered from the serving LRU (no app "
            "round trip)"),
        cache_misses=m.counter(
            "statesync_chunk_cache_misses_total",
            "chunk requests that had to load from the app — a high "
            "miss ratio under concurrent bootstrap means the cache "
            "byte budget is too small for the snapshot"),
        shed=m.counter(
            "statesync_serve_shed_total",
            "serving requests shed by the admission gate (concurrency "
            "+ queue budget exhausted) — fetchers retry other peers, "
            "the local node keeps its event loop"))


class ChunkLRU:
    """Byte-budgeted LRU for served snapshot chunks, keyed by
    ``(height, format, index)`` (same shape as ``light/serve.py``'s
    header cache: count cap + byte cap, never evicts below one entry)."""

    __slots__ = ("max_size", "max_bytes", "d", "sizes", "bytes")

    def __init__(self, max_size: int = 1024, max_bytes: int = 0):
        self.max_size = max_size
        self.max_bytes = max_bytes          # 0 = no byte budget
        self.d: OrderedDict = OrderedDict()
        self.sizes: dict = {}
        self.bytes = 0

    def get(self, key):
        if key not in self.d:
            return None
        self.d.move_to_end(key)
        return self.d[key]

    def put(self, key, value: bytes) -> int:
        """Insert and evict down to budget; returns evictions."""
        nbytes = len(value)
        if key in self.d:
            self.bytes -= self.sizes.get(key, 0)
            del self.d[key]
        self.d[key] = value
        self.sizes[key] = nbytes
        self.bytes += nbytes
        evicted = 0
        while len(self.d) > self.max_size or \
                (self.max_bytes and self.bytes > self.max_bytes
                 and len(self.d) > 1):
            old, _ = self.d.popitem(last=False)
            self.bytes -= self.sizes.pop(old, 0)
            evicted += 1
        return evicted

    def __len__(self) -> int:
        return len(self.d)


class AdmissionGate:
    """Concurrency + queue-depth budget for serving work.

    ``try_queue()`` answers synchronously whether a new request may even
    WAIT: once ``max_queued`` requests are already parked behind a fully
    busy gate, further arrivals are shed at the door — queueing them
    would only grow latency for everyone (the fetcher side re-requests
    from another peer far sooner than a deep queue would drain)."""

    def __init__(self, concurrency: int = 8, max_queued: int = 64):
        self.concurrency = max(1, int(concurrency))
        self.max_queued = max(0, int(max_queued))
        self._sem = asyncio.Semaphore(self.concurrency)
        self.waiting = 0
        self.shed = 0

    def try_queue(self) -> bool:
        """Admit (True) or shed (False) a new serving request."""
        if self._sem.locked() and self.waiting >= self.max_queued:
            self.shed += 1
            return False
        return True

    async def __aenter__(self):
        self.waiting += 1
        try:
            await self._sem.acquire()
        finally:
            self.waiting -= 1
        return self

    async def __aexit__(self, *exc):
        self._sem.release()
        return False
