"""Statesync reactor: snapshot/chunk/manifest exchange over p2p
(reference: ``statesync/reactor.go:66,109,266``; channels 0x60/0x61 from
``statesync/reactor.go:23-25``).

Serving side answers from the local app's snapshot connection, through
a byte-budgeted chunk LRU behind an admission gate (``cache.py``) —
concurrent bootstrappers hit RAM, overload sheds instead of stalling
the event loop.  Snapshot offers additionally advertise the manifest
root (``mr``) binding per-chunk sha256 hashes to the snapshot hash;
fetchers pull the hash list itself with ``mreq``/``mres`` and verify
every chunk before spooling (``manifest.py``).  The syncing side
accumulates offers/manifests/chunks into the Syncer."""

from __future__ import annotations

import asyncio

from ..libs import aio, failures

import msgpack

from ..abci.types import Snapshot
from ..libs import log as tmlog
from ..p2p.reactor import ChannelDescriptor, Reactor
from .cache import AdmissionGate, ChunkLRU, _serve_metrics
from .manifest import ChunkManifest, hash_chunk

SNAPSHOT_CHANNEL = 0x60
CHUNK_CHANNEL = 0x61

# Serving-side defaults (config: [statesync] chunk_cache_bytes /
# serve_concurrency / serve_queue)
CHUNK_CACHE_BYTES = 64 * 1024 * 1024
SERVE_CONCURRENCY = 8
SERVE_QUEUE = 64
# Manifests are tiny (32 B / chunk) but computing one walks the whole
# snapshot; keep the last few snapshots' worth
_MANIFEST_CACHE_SIZE = 16


def _pack(tag: str, **fields) -> bytes:
    fields["@"] = tag
    return msgpack.packb(fields, use_bin_type=True)


class StatesyncReactor(Reactor):
    def __init__(self, app_conns, syncer=None, name: str = "ss", *,
                 chunk_cache_bytes: int = CHUNK_CACHE_BYTES,
                 serve_concurrency: int = SERVE_CONCURRENCY,
                 serve_queue: int = SERVE_QUEUE):
        super().__init__()
        self.app_conns = app_conns
        self.syncer = syncer          # set when this node is syncing
        self.name = name
        self.log = tmlog.logger("statesync.reactor", node=name)
        self._cache = ChunkLRU(max_size=4096, max_bytes=chunk_cache_bytes)
        self._gate = AdmissionGate(concurrency=serve_concurrency,
                                   max_queued=serve_queue)
        self._manifests: dict[tuple, ChunkManifest] = {}
        self._m = _serve_metrics()

    def get_channels(self):
        return [
            ChannelDescriptor(SNAPSHOT_CHANNEL, priority=5,
                              send_queue_capacity=10, name="snapshot"),
            ChannelDescriptor(CHUNK_CHANNEL, priority=3,
                              send_queue_capacity=20, name="chunk"),
        ]

    def add_peer(self, peer) -> None:
        if self.syncer is not None:
            peer.send(SNAPSHOT_CHANNEL, _pack("sreq"))

    def remove_peer(self, peer, reason=None) -> None:
        if self.syncer is not None:
            self.syncer.remove_peer(peer.id)

    def receive(self, channel_id: int, peer, msg: bytes) -> None:
        d = msgpack.unpackb(msg, raw=False)
        tag = d.get("@")
        if channel_id == SNAPSHOT_CHANNEL:
            if tag == "sreq":
                if self._gate.try_queue():
                    aio.spawn(self._serve_snapshots(peer))
                else:
                    self._m.shed.inc(node=self.name)
            elif tag == "sres" and self.syncer is not None:
                self.syncer.add_snapshot(peer.id, Snapshot(
                    height=d["h"], format=d["f"], chunks=d["c"],
                    hash=d["hash"], metadata=d.get("m", b"")),
                    manifest_root=d.get("mr"))
            elif tag == "mreq":
                if self._gate.try_queue():
                    aio.spawn(self._serve_manifest(peer, d))
                else:
                    self._m.shed.inc(node=self.name)
            elif tag == "mres" and self.syncer is not None:
                self.syncer.add_manifest(
                    peer.id, d["h"], d["f"], d.get("sh", b""),
                    list(d.get("hs", [])))
        elif channel_id == CHUNK_CHANNEL:
            if tag == "creq":
                if self._gate.try_queue():
                    aio.spawn(self._serve_chunk(peer, d))
                else:
                    self._m.shed.inc(node=self.name)
            elif tag == "cres" and self.syncer is not None:
                self.syncer.add_chunk(peer.id, d["h"], d["f"], d["i"],
                                      d["chunk"], d.get("sh", b""))

    # -------------------------------------------------------- serving

    async def _load_chunk(self, height: int, format_: int,
                          index: int) -> bytes | None:
        """Cache-through chunk load: the LRU key is (height, format,
        index) — content-addressing happens fetcher-side; here identity
        is cheap and correct because a snapshot is immutable."""
        key = (height, format_, index)
        cached = self._cache.get(key)
        if cached is not None:
            self._m.cache_hits.inc(node=self.name)
            return cached
        self._m.cache_misses.inc(node=self.name)
        chunk = await self.app_conns.snapshot.load_snapshot_chunk(
            height, format_, index)
        if isinstance(chunk, (bytes, bytearray)):
            chunk = bytes(chunk)
            self._cache.put(key, chunk)
        return chunk

    async def _manifest_for(self, snapshot) -> ChunkManifest:
        """Build (and cache) the chunk manifest for a local snapshot by
        hashing every chunk — also warms the chunk LRU, so the offer
        that advertises the root pre-pays the fetches that follow it."""
        key = (snapshot.height, snapshot.format, snapshot.hash)
        mf = self._manifests.get(key)
        if mf is not None:
            return mf
        hashes = []
        for i in range(snapshot.chunks):
            chunk = await self._load_chunk(snapshot.height,
                                           snapshot.format, i)
            if not isinstance(chunk, (bytes, bytearray)):
                raise ValueError(f"chunk {i} unavailable")
            hashes.append(hash_chunk(bytes(chunk)))
        mf = ChunkManifest(snapshot_hash=bytes(snapshot.hash),
                           hashes=tuple(hashes))
        while len(self._manifests) >= _MANIFEST_CACHE_SIZE:
            self._manifests.pop(next(iter(self._manifests)))
        self._manifests[key] = mf
        return mf

    async def _serve_snapshots(self, peer) -> None:
        """reactor.go Receive(SnapshotRequest) -> recentSnapshots, plus
        the manifest root per offer (omitted, not failed, if the chunks
        cannot be walked — the offer still works for legacy fetchers)."""
        async with self._gate:
            try:
                snaps = await self.app_conns.snapshot.list_snapshots()
            except Exception:
                return
            for s in snaps[-10:]:
                fields = dict(h=s.height, f=s.format, c=s.chunks,
                              hash=s.hash, m=s.metadata)
                try:
                    mf = await self._manifest_for(s)
                    fields["mr"] = mf.root
                except Exception:
                    self.log.warn("cannot build manifest for offer",
                                  height=s.height)
                peer.send(SNAPSHOT_CHANNEL, _pack("sres", **fields))

    async def _serve_manifest(self, peer, d) -> None:
        async with self._gate:
            key = (d["h"], d["f"], d.get("sh", b""))
            mf = self._manifests.get(key)
            if mf is None:
                # not cached (e.g. restarted since the offer): rebuild
                # from the app's snapshot list
                try:
                    snaps = await self.app_conns.snapshot.list_snapshots()
                    snap = next(s for s in snaps
                                if (s.height, s.format, s.hash) == key)
                    mf = await self._manifest_for(snap)
                except Exception:
                    return
            self._m.manifests_served.inc(node=self.name)
            peer.send(SNAPSHOT_CHANNEL, _pack(
                "mres", h=d["h"], f=d["f"], sh=d.get("sh", b""),
                hs=list(mf.hashes)))

    async def _serve_chunk(self, peer, d) -> None:
        async with self._gate:
            try:
                chunk = await self._load_chunk(d["h"], d["f"], d["i"])
            except Exception:
                return
            if chunk is None:
                return
            # chaos site: a byzantine/corrupting seed flips one bit in
            # the served chunk AFTER the cache (the cache keeps honest
            # bytes; every serve re-corrupts deterministically)
            f = failures.fire("statesync.serve.corrupt", node=self.name,
                              chan="chunk")
            if f is not None and len(chunk):
                data = bytearray(chunk)
                rng = failures.site_rng("statesync.serve.corrupt")
                data[rng.randrange(len(data))] ^= 1 << rng.randrange(8)
                chunk = bytes(data)
            self._m.chunks_served.inc(node=self.name)
            peer.send(CHUNK_CHANNEL, _pack(
                "cres", h=d["h"], f=d["f"], i=d["i"], chunk=chunk,
                sh=d.get("sh", b"")))

    # ------------------------------------------------------- fetching

    def request_chunk(self, peer_id: str, height: int, format_: int,
                      index: int, snapshot_hash: bytes = b"") -> bool:
        peer = self.switch.peers.get(peer_id) if self.switch else None
        if peer is None:
            return False
        return peer.send(CHUNK_CHANNEL, _pack(
            "creq", h=height, f=format_, i=index, sh=snapshot_hash))

    def request_manifest(self, peer_id: str, height: int, format_: int,
                         snapshot_hash: bytes = b"") -> bool:
        peer = self.switch.peers.get(peer_id) if self.switch else None
        if peer is None:
            return False
        return peer.send(SNAPSHOT_CHANNEL, _pack(
            "mreq", h=height, f=format_, sh=snapshot_hash))

    def broadcast_snapshot_request(self) -> None:
        if self.switch is not None:
            self.switch.broadcast(SNAPSHOT_CHANNEL, _pack("sreq"))
