"""Statesync reactor: snapshot/chunk exchange over p2p (reference:
``statesync/reactor.go:66,109,266``; channels 0x60/0x61 from
``statesync/reactor.go:23-25``).

Serving side answers from the local app's snapshot connection; the
syncing side accumulates offers/chunks into the Syncer."""

from __future__ import annotations

import asyncio

from ..libs import aio

import msgpack

from ..abci.types import Snapshot
from ..p2p.reactor import ChannelDescriptor, Reactor

SNAPSHOT_CHANNEL = 0x60
CHUNK_CHANNEL = 0x61


def _pack(tag: str, **fields) -> bytes:
    fields["@"] = tag
    return msgpack.packb(fields, use_bin_type=True)


class StatesyncReactor(Reactor):
    def __init__(self, app_conns, syncer=None, name: str = "ss"):
        super().__init__()
        self.app_conns = app_conns
        self.syncer = syncer          # set when this node is syncing
        self.name = name

    def get_channels(self):
        return [
            ChannelDescriptor(SNAPSHOT_CHANNEL, priority=5,
                              send_queue_capacity=10, name="snapshot"),
            ChannelDescriptor(CHUNK_CHANNEL, priority=3,
                              send_queue_capacity=20, name="chunk"),
        ]

    def add_peer(self, peer) -> None:
        if self.syncer is not None:
            peer.send(SNAPSHOT_CHANNEL, _pack("sreq"))

    def remove_peer(self, peer, reason=None) -> None:
        if self.syncer is not None:
            self.syncer.remove_peer(peer.id)

    def receive(self, channel_id: int, peer, msg: bytes) -> None:
        d = msgpack.unpackb(msg, raw=False)
        tag = d.get("@")
        if channel_id == SNAPSHOT_CHANNEL:
            if tag == "sreq":
                aio.spawn(self._serve_snapshots(peer))
            elif tag == "sres" and self.syncer is not None:
                self.syncer.add_snapshot(peer.id, Snapshot(
                    height=d["h"], format=d["f"], chunks=d["c"],
                    hash=d["hash"], metadata=d.get("m", b"")))
        elif channel_id == CHUNK_CHANNEL:
            if tag == "creq":
                aio.spawn(self._serve_chunk(peer, d))
            elif tag == "cres" and self.syncer is not None:
                self.syncer.add_chunk(peer.id, d["h"], d["f"], d["i"],
                                      d["chunk"], d.get("sh", b""))

    async def _serve_snapshots(self, peer) -> None:
        """reactor.go Receive(SnapshotRequest) -> recentSnapshots."""
        try:
            snaps = await self.app_conns.snapshot.list_snapshots()
        except Exception:
            return
        for s in snaps[-10:]:
            peer.send(SNAPSHOT_CHANNEL, _pack(
                "sres", h=s.height, f=s.format, c=s.chunks, hash=s.hash,
                m=s.metadata))

    async def _serve_chunk(self, peer, d) -> None:
        try:
            chunk = await self.app_conns.snapshot.load_snapshot_chunk(
                d["h"], d["f"], d["i"])
        except Exception:
            return
        peer.send(CHUNK_CHANNEL, _pack(
            "cres", h=d["h"], f=d["f"], i=d["i"], chunk=chunk,
            sh=d.get("sh", b"")))

    def request_chunk(self, peer_id: str, height: int, format_: int,
                      index: int, snapshot_hash: bytes = b"") -> bool:
        peer = self.switch.peers.get(peer_id) if self.switch else None
        if peer is None:
            return False
        return peer.send(CHUNK_CHANNEL, _pack(
            "creq", h=height, f=format_, i=index, sh=snapshot_hash))

    def broadcast_snapshot_request(self) -> None:
        if self.switch is not None:
            self.switch.broadcast(SNAPSHOT_CHANNEL, _pack("sreq"))
