import sys

from .cmd import main

sys.exit(main())
