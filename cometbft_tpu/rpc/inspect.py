"""Inspect mode: a read-only RPC server over a (possibly crashed) node's
data directory — no consensus, no p2p (reference:
``internal/inspect/inspect.go``).

Reuses the normal RPC server + routes against a shim exposing only the
stores; routes that need live subsystems (consensus introspection,
mempool, tx broadcast) answer with a clear error instead of hanging."""

from __future__ import annotations

from ..indexer import BlockIndexer, TxIndexer
from ..storage import BlockStore, StateStore, open_db


class _NoLiveSubsystem:
    def __getattr__(self, name):
        raise RuntimeError("not available in inspect mode (node offline)")

    def __bool__(self):
        # falsy so routes with their own `if node.consensus` guards
        # (status) degrade gracefully; everything else gets the loud error
        return False


class InspectNode:
    """The Environment-facing surface of a data directory."""

    def __init__(self, home: str, config, genesis_doc, name: str = "inspect"):
        import os

        self.config = config
        self.genesis = genesis_doc
        self.name = name
        self.home = home
        self.liveness_watchdog = None     # offline: list bundles only
        backend = config.storage.db_backend
        self.block_store = BlockStore(open_db(
            backend, os.path.join(home, "data", "blockstore.db")))
        self.state_store = StateStore(open_db(
            backend, os.path.join(home, "data", "state.db")))
        self.tx_indexer = None
        self.block_indexer = None
        if config.tx_index.indexer == "kv":
            self.tx_indexer = TxIndexer(open_db(
                backend, os.path.join(home, "data", "tx_index.db")))
            self.block_indexer = BlockIndexer(open_db(
                backend, os.path.join(home, "data", "block_index.db")))
        # report-only storage-doctor pass: a crashed node's store
        # inconsistency is exactly what inspect mode is for — never
        # repairs, never refuses (the report carries the refusal text)
        self.doctor_report = None
        try:
            from ..node.doctor import StorageDoctor

            self.doctor_report = StorageDoctor(
                self.block_store, self.state_store,
                wal_path=os.path.join(home, config.consensus.wal_path)
                if not os.path.isabs(config.consensus.wal_path)
                else config.consensus.wal_path,
                privval_state_path=os.path.join(
                    home, config.base.priv_validator_state_file)
                if not os.path.isabs(config.base.priv_validator_state_file)
                else config.base.priv_validator_state_file,
                deep_scan_window=config.storage.doctor_deep_scan_window,
                name=name).boot_check(repair=False,
                                      raise_on_refusal=False)
        except Exception:
            pass             # inspect must come up on ANY data dir
        # live-only surfaces: a falsy shim — `if node.consensus` guards
        # degrade gracefully, direct attribute access errors loudly
        self.consensus = _NoLiveSubsystem()
        self.mempool = _NoLiveSubsystem()
        self.app_conns = _NoLiveSubsystem()
        self.evidence_pool = _NoLiveSubsystem()
        self.switch = None
        self.node_key = None
        self.listen_addr = None
        self.blocksync_reactor = None
        self.pruner = None
        self.event_bus = _NoLiveSubsystem()

    def incident_dir(self) -> str | None:
        """Same resolution as Node.incident_dir: a crashed validator's
        black-box bundles are exactly what inspect mode is for."""
        from ..node.watchdog import resolve_incident_dir

        return resolve_incident_dir(self.config, self.home)


async def run_inspect(home: str, config, genesis_doc,
                      host: str = "127.0.0.1", port: int = 0):
    """Start the read-only RPC server; returns (server, (host, port))."""
    from .server import RPCServer

    node = InspectNode(home, config, genesis_doc)
    server = RPCServer(node)
    addr = await server.listen(host, port)
    return server, addr
