from .client import HTTPClient, WSClient
from .core import ROUTES, Environment, RPCError
from .server import RPCServer, parse_query

__all__ = ["RPCServer", "HTTPClient", "WSClient", "Environment", "ROUTES",
           "RPCError", "parse_query"]
