"""RPC core routes (reference: ``rpc/core/routes.go:15-62`` and the
handler files ``rpc/core/{status,blocks,mempool,consensus,abci,net,
evidence}.go``).

``Environment`` carries the node internals every handler reads
(``rpc/core/env.go``); ``ROUTES`` maps method name -> handler coroutine.
Handlers return plain JSON-able dicts (domain objects projected through
``rpc.json.jsonable``)."""

from __future__ import annotations

import asyncio

from ..libs import aio

from ..mempool.clist_mempool import TxRejectedError
from ..types import events as ev
from ..types.evidence import EvidenceError
from .json import from_jsonable, jsonable


class RPCError(Exception):
    def __init__(self, code: int, message: str, data: str = ""):
        self.code = code
        self.data = data
        super().__init__(message)
        self.message = message


class Environment:
    """rpc/core/env.go Environment: what routes need from the node."""

    def __init__(self, node):
        self.node = node
        self._gen_chunks: list[bytes] | None = None   # computed once

    @property
    def block_store(self):
        return self.node.block_store

    @property
    def state_store(self):
        return self.node.state_store


def _height_or_latest(env: Environment, height) -> int:
    if height in (None, 0, "0", ""):
        return env.block_store.height()
    h = int(height)
    if h < env.block_store.base() or h > env.block_store.height():
        raise RPCError(-32603, f"height {h} is not available "
                       f"(base {env.block_store.base()}, "
                       f"height {env.block_store.height()})")
    return h


# ------------------------------------------------------------------ info

async def health(env: Environment) -> dict:
    return {}


async def status(env: Environment) -> dict:
    """rpc/core/status.go Status, enriched with a live consensus-timeline
    block: current height/round/step plus how long the node has sat in
    that step and since its last commit (the flight-recorder's "is this
    node stuck RIGHT NOW" surface — see /dump_trace for the history)."""
    node = env.node
    h = env.block_store.height()
    meta = env.block_store.load_block_meta(h) if h else None
    pv = node.consensus.priv_validator if node.consensus else None
    consensus_info = None
    cs = node.consensus
    if cs:             # truthiness, not None-ness: the inspect-mode
        # offline shim is falsy so this block degrades away with it
        last_wall = getattr(cs, "_last_commit_wall_ns", 0)
        # the age must come from the SAME clock that stamped the commit:
        # cs.now_ns is injectable (deterministic harnesses), so
        # subtracting real wall time from it would be garbage
        consensus_info = {
            "height": cs.rs.height,
            "round": cs.rs.round,
            "step": cs.rs.step_name(),
            "step_age_s": round(cs.step_age_s(), 6),
            "last_commit_age_s": (
                round(max(cs.now_ns() - last_wall, 0) / 1e9, 6)
                if last_wall else None),
            "fatal_error": repr(cs.fatal_error) if cs.fatal_error else None,
        }
    return {
        "node_info": {
            "id": node.node_key.id if node.node_key else "",
            "listen_addr": node.listen_addr or "",
            "network": node.genesis.chain_id,
            "moniker": node.name,
        },
        "sync_info": {
            "latest_block_height": h,
            "latest_block_hash": meta.block_id.hash.hex() if meta else "",
            "latest_block_time_ns":
                env.block_store.load_block(h).header.time_ns if h else 0,
            "earliest_block_height": env.block_store.base(),
            "catching_up": not (node.blocksync_reactor is None
                                or node.blocksync_reactor.synced.is_set()),
        },
        "validator_info": {
            "address": pv.get_pub_key().address().hex() if pv else "",
            "pub_key": pv.get_pub_key().bytes().hex() if pv else "",
        },
        "consensus_info": consensus_info,
        # storage-doctor boot report (node/doctor.py): what the boot
        # consistency check found and repaired; also served by inspect
        # mode, where it is the post-mortem's first stop
        "doctor": (node.doctor_report.to_dict()
                   if getattr(node, "doctor_report", None) is not None
                   else None),
        # AOT compile-bundle state (crypto/aotbundle): version, plan
        # shape and per-bucket cold/warm — whether this node booted warm
        "compile_bundle": getattr(node, "compile_bundle_info", None),
        # light-serving tier tallies (light/serve.py): cache hit/miss/
        # eviction counts, proofs and blocks served, anchor verdicts
        "light_serve": (node.light_serve.stats()
                        if getattr(node, "light_serve", None) is not None
                        else None),
    }


async def net_info(env: Environment) -> dict:
    """rpc/core/net.go NetInfo, enriched with the live per-peer
    telemetry the p2p layer now keeps: per-channel bytes/msgs in both
    directions, send-queue depth/capacity and queue-full drops, the
    flowrate send/recv EMAs, last ping RTT, connection age, and the
    gossip useful/duplicate efficiency — so a bad gossip partner or a
    backpressured channel is visible from one curl, not a Prometheus
    deployment."""
    sw = env.node.switch
    peers = []
    if sw is not None and getattr(sw, "peer_snapshot", None) is not None:
        peers = sw.peer_snapshot()
    n_outbound = sum(1 for p in peers if p.get("outbound"))
    scorer = getattr(sw, "scorer", None)
    bans = scorer.bans_snapshot() if scorer is not None else []
    return {"listening": env.node.listen_addr is not None,
            "listen_addr": env.node.listen_addr or "",
            "n_peers": len(peers),
            "n_outbound": n_outbound,
            "n_inbound": len(peers) - n_outbound,
            "peers": peers,
            "bans": bans}


_GENESIS_CHUNK_SIZE = 16 * 1024 * 1024   # rpc/core/env.go:32


async def genesis(env: Environment) -> dict:
    import json as _json

    def _build():
        # serialize + size-check + decode all off the event loop: at
        # the 16MB ceiling even the to_json dump is a visible stall
        raw = env.node.genesis.to_json()
        if len(raw.encode()) > _GENESIS_CHUNK_SIZE:
            return None
        return _json.loads(raw)

    doc = await asyncio.to_thread(_build)
    if doc is None:
        raise RPCError(-32603, "genesis response is large, please use the "
                       "genesis_chunked API instead")
    return {"genesis": doc}


async def genesis_chunked(env: Environment, chunk=0) -> dict:
    """rpc/core/net.go:111 GenesisChunked: base64 16MB slices of the
    genesis JSON, so arbitrarily large app_state stays servable.  The
    chunk list is computed once (the genesis doc is immutable)."""
    import base64

    if env._gen_chunks is None:
        raw = env.node.genesis.to_json().encode()
        env._gen_chunks = [raw[i:i + _GENESIS_CHUNK_SIZE]
                           for i in range(0, len(raw),
                                          _GENESIS_CHUNK_SIZE)] or [b""]
    chunks = env._gen_chunks
    cid = int(chunk)
    if not 0 <= cid < len(chunks):
        raise RPCError(-32603, f"there are {len(chunks) - 1} chunks, "
                       f"{cid} is invalid")
    return {"chunk": cid, "total": len(chunks),
            "data": base64.b64encode(chunks[cid]).decode()}


# ---------------------------------------------------------------- blocks

async def block(env: Environment, height=None) -> dict:
    h = _height_or_latest(env, height)
    blk = env.block_store.load_block(h)
    meta = env.block_store.load_block_meta(h)
    if blk is None:
        raise RPCError(-32603, f"no block at height {h}")
    return {"block_id": jsonable(meta.block_id), "block": jsonable(blk)}


def _height_by_hash(env: Environment, hash) -> int:
    if hash is None:
        raise RPCError(-32602, "missing block hash")
    want = bytes.fromhex(hash) if isinstance(hash, str) else bytes(hash)
    bs = env.block_store
    for h in range(bs.height(), bs.base() - 1, -1):
        meta = bs.load_block_meta(h)
        if meta is not None and meta.block_id.hash == want:
            return h
    raise RPCError(-32603, f"block with hash {want.hex()} not found")


async def block_by_hash(env: Environment, hash=None) -> dict:
    return await block(env, _height_by_hash(env, hash))


async def header(env: Environment, height=None) -> dict:
    h = _height_or_latest(env, height)
    blk = env.block_store.load_block(h)
    if blk is None:
        raise RPCError(-32603, f"no block at height {h}")
    return {"header": jsonable(blk.header)}


async def header_by_hash(env: Environment, hash=None) -> dict:
    return await header(env, _height_by_hash(env, hash))


async def commit(env: Environment, height=None) -> dict:
    h = _height_or_latest(env, height)
    cmt = env.block_store.load_block_commit(h)
    canonical = True
    if cmt is None:
        seen = env.block_store.load_seen_commit()
        if seen is not None and seen.height == h:
            cmt, canonical = seen, False
    if cmt is None:
        raise RPCError(-32603, f"no commit for height {h}")
    blk = env.block_store.load_block(h)
    return {"header": jsonable(blk.header) if blk else None,
            "commit": jsonable(cmt), "canonical": canonical}


async def blockchain(env: Environment, min_height=None,
                     max_height=None) -> dict:
    """rpc/core/blocks.go BlockchainInfo: metas for a height range,
    newest first, capped at 20."""
    bs = env.block_store
    maxh = int(max_height) if max_height else bs.height()
    maxh = min(maxh, bs.height())
    minh = int(min_height) if min_height else max(bs.base(), maxh - 19)
    minh = max(minh, bs.base(), maxh - 19)
    metas = []
    for h in range(maxh, minh - 1, -1):
        m = bs.load_block_meta(h)
        if m is not None:
            metas.append({"block_id": jsonable(m.block_id),
                          "header_height": m.header_height,
                          "num_txs": m.num_txs,
                          "block_size": m.block_size})
    return {"last_height": bs.height(), "block_metas": metas}


def _events_jsonable(events) -> list:
    return [{"type": e.type,
             "attributes": [{"key": a.key, "value": a.value,
                             "index": a.index}
                            for a in e.attributes]}
            for e in events or []]


async def block_results(env: Environment, height=None) -> dict:
    """rpc/core/blocks.go BlockResults / ResultBlockResults
    (responses.go:54): full FinalizeBlock output at a height."""
    h = _height_or_latest(env, height)
    raw = env.state_store.load_finalize_block_response(h)
    if raw is None:
        raise RPCError(-32603, f"no results for height {h}")
    from ..sm.execution import unpack_finalize_response

    resp = unpack_finalize_response(raw)
    return {
        "height": h,
        "tx_results": [{"code": r.code, "data": r.data.hex(),
                        "log": r.log, "gas_used": r.gas_used,
                        "events": _events_jsonable(r.events)}
                       for r in resp.tx_results],
        "finalize_block_events": _events_jsonable(resp.events),
        "validator_updates": [{"pub_key_type": u.pub_key_type,
                               "pub_key": u.pub_key_bytes.hex(),
                               "power": u.power}
                              for u in resp.validator_updates],
        "consensus_param_updates": (
            None if resp.consensus_param_updates is None
            else _params_jsonable(resp.consensus_param_updates)),
        "app_hash": resp.app_hash.hex(),
    }


def paginate_validators(vals, height: int, page, per_page) -> dict:
    """Shared validator-page serializer (also used by the light proxy so
    a light client can point at either endpoint)."""
    page, per_page = max(1, int(page)), min(100, max(1, int(per_page)))
    start = (page - 1) * per_page
    sel = vals.validators[start:start + per_page]
    return {"block_height": height,
            "validators": [{"address": v.address.hex(),
                            "pub_key_type": v.pub_key.type(),
                            "pub_key": v.pub_key.bytes().hex(),
                            "voting_power": v.voting_power,
                            "proposer_priority": v.proposer_priority}
                           for v in sel],
            "count": len(sel), "total": vals.size()}


async def validators(env: Environment, height=None, page=1,
                     per_page=30) -> dict:
    h = _height_or_latest(env, height)
    vals = env.state_store.load_validators(h)
    if vals is None:
        raise RPCError(-32603, f"no validator set at height {h}")
    return paginate_validators(vals, h, page, per_page)


def _params_jsonable(params) -> dict:
    return {
        "block": {"max_bytes": params.block.max_bytes,
                  "max_gas": params.block.max_gas},
        "evidence": {"max_age_num_blocks":
                     params.evidence.max_age_num_blocks,
                     "max_age_duration_ns":
                     params.evidence.max_age_duration_ns,
                     "max_bytes": params.evidence.max_bytes},
        "validator": {"pub_key_types": params.validator.pub_key_types},
        "version": {"app": params.version.app},
        "feature": {"vote_extensions_enable_height":
                    params.feature.vote_extensions_enable_height,
                    "pbts_enable_height":
                    params.feature.pbts_enable_height},
        "synchrony": {"precision_ns": params.synchrony.precision_ns,
                      "message_delay_ns":
                      params.synchrony.message_delay_ns},
    }


async def consensus_params(env: Environment, height=None) -> dict:
    h = _height_or_latest(env, height)
    params = env.state_store.load_params(h)
    if params is None:
        raise RPCError(-32603, f"no consensus params at height {h}")
    return {"block_height": h, "consensus_params": _params_jsonable(params)}


# --------------------------------------------------------- light serving
# (light/serve.py LightServeTier: batched proof/header RPC for
# fleet-scale light-client bootstrap.  Every handler runs the tier's
# synchronous, thread-safe work in a worker thread — proof-tree builds
# and commit verification must never stall the event loop — and every
# route is behind the admission gate, so overload sheds with 503 +
# Retry-After while the diagnostics stay responsive.)

def _light_serve(env: Environment):
    tier = getattr(env.node, "light_serve", None)
    if tier is None:
        raise RPCError(-32601, "light-client serving tier is disabled "
                       "(lightserve.enable = false)")
    return tier


async def _ls_call(env: Environment, method: str, *args) -> dict:
    from ..light.serve import LightServeError

    tier = _light_serve(env)
    try:
        return await asyncio.to_thread(getattr(tier, method), *args)
    except LightServeError as e:
        raise RPCError(e.code, str(e)) from e


async def light_block(env: Environment, height=None) -> dict:
    """One signed header + canonical commit + validator set — everything
    a light client needs to verify a height — served out of the tier's
    trust-period LRU.  ``canonical: false`` marks a tip answered from the
    seen-commit (not yet superseded by the next block)."""
    return await _ls_call(env, "light_block", height)


async def light_blocks(env: Environment, heights=None) -> dict:
    """Batched light-block bootstrap: many heights in ONE request (list
    or comma-separated string), each entry either a light block or a
    per-height error.  Bounded by ``lightserve.max_batch``."""
    return await _ls_call(env, "light_blocks", heights)


async def light_proofs(env: Environment, height=None, kind="tx",
                       indexes=None) -> dict:
    """Batched merkle inclusion proofs for one block: the per-level node
    cache is built once per (height, kind) and every requested index is
    gathered out of it with zero re-hashing.  ``kind`` is ``tx`` (leaves
    under the header's data_hash) or ``validator`` (leaves under
    validators_hash); ``indexes`` is a list or comma-separated string
    (omitted = every leaf, bounded by ``lightserve.max_proofs``)."""
    return await _ls_call(env, "proofs", height, str(kind), indexes)


async def light_verify(env: Environment, anchors=None) -> dict:
    """Batched server-side verification of client-supplied trust
    anchors (``[{height, commit}, ...]``): per anchor, attest that the
    commit is a valid > 2/3 commit of THIS chain's block at that height.
    Same-valset anchors verify in single batched dispatches riding the
    verified-signature dedup cache; identical hot anchors hit a
    whole-commit verdict memo (``cached: true``)."""
    return await _ls_call(env, "verify_commits", anchors)


# ------------------------------------------------------------- consensus

async def consensus_state(env: Environment) -> dict:
    """Compact round-state view (rpc/core/consensus.go ConsensusState)."""
    cs = env.node.consensus
    rs = cs.rs
    return {"round_state": {
        "height": rs.height, "round": rs.round, "step": rs.step,
        "proposal": rs.proposal is not None,
        "proposal_block": rs.proposal_block is not None,
        "locked_round": rs.locked_round,
        "valid_round": rs.valid_round,
        "fatal_error": repr(cs.fatal_error) if cs.fatal_error else None,
    }}


async def dump_consensus_state(env: Environment) -> dict:
    cs = env.node.consensus
    rs = cs.rs
    out = await consensus_state(env)
    votes = []
    if rs.votes is not None:
        for r in range(rs.round + 1):
            pv_ = rs.votes.prevotes(r)
            pc = rs.votes.precommits(r)
            votes.append({
                "round": r,
                "prevotes": str(pv_.bit_array()) if pv_ else None,
                "precommits": str(pc.bit_array()) if pc else None,
            })
        out["round_state"]["height_vote_set"] = votes
    peers = []
    if env.node.switch is not None:
        for p in env.node.switch.peers.values():
            ps = p.get("cons_peer_state")
            if ps is not None:
                peers.append({"node_id": p.id, "height": ps.height,
                              "round": ps.round, "step": ps.step})
    out["peers"] = peers
    return out


# --------------------------------------------------------------- mempool

async def unconfirmed_txs(env: Environment, limit=30) -> dict:
    mp = env.node.mempool
    txs = mp.contents()[:min(100, int(limit))]
    return {"n_txs": len(txs), "total": mp.size(),
            "total_bytes": mp.size_bytes(),
            "txs": [t.hex() for t in txs]}


async def num_unconfirmed_txs(env: Environment) -> dict:
    mp = env.node.mempool
    return {"n_txs": mp.size(), "total": mp.size(),
            "total_bytes": mp.size_bytes()}


def _tx_bytes(tx) -> bytes:
    if isinstance(tx, str):
        return bytes.fromhex(tx)
    return bytes(tx)


from ..libs.metrics import counter as _counter

_shed_total = _counter("rpc_overload_shed_total",
                       "tx submissions rejected under loop overload")


def _check_overload(env: Environment) -> None:
    """Admission control for tx submission: when the event loop's
    scheduling lag exceeds the configured shed threshold, reject with a
    retryable error INSTEAD of queueing more CheckTx work — a sustained
    broadcast flood otherwise starves consensus timers into round churn
    and the node stalls entirely (observed on the one-core testnet
    bench; the reference sheds via 503s when its mempool/WS buffers
    fill).  0 disables."""
    node = env.node
    cfg = getattr(node, "config", None)
    thresh = getattr(getattr(cfg, "rpc", None), "overload_shed_lag_s", 0.0)
    wd = getattr(node, "loop_watchdog", None)
    if not thresh or wd is None:
        return
    lag = wd.last_lag_s
    if lag > thresh:
        _shed_total.inc()
        raise RPCError(-32099,
                       "server overloaded (event-loop lag "
                       f"{lag:.2f}s > {thresh:.2f}s); retry later")


async def broadcast_tx_async(env: Environment, tx=None) -> dict:
    _check_overload(env)
    raw = _tx_bytes(tx)

    async def _fire_and_forget():
        try:
            await env.node.mempool.check_tx(raw)
        except TxRejectedError:
            pass                 # async mode: rejection is not reported

    aio.spawn(_fire_and_forget())
    from ..mempool.mempool import TxKey

    return {"hash": TxKey(raw).hex(), "code": 0}


async def broadcast_tx_sync(env: Environment, tx=None) -> dict:
    """CheckTx ran, result returned (rpc/core/mempool.go)."""
    _check_overload(env)
    raw = _tx_bytes(tx)
    from ..mempool.mempool import TxKey

    try:
        await env.node.mempool.check_tx(raw)
    except TxRejectedError as e:
        return {"hash": TxKey(raw).hex(), "code": e.code, "log": e.log}
    return {"hash": TxKey(raw).hex(), "code": 0, "log": ""}


async def broadcast_tx_commit(env: Environment, tx=None,
                              timeout_s: float = 30.0) -> dict:
    """Submit and wait for the tx to land in a block (rpc/core/mempool.go
    BroadcastTxCommit; the reference subscribes to EventTx)."""
    _check_overload(env)
    raw = _tx_bytes(tx)
    from ..mempool.mempool import TxKey

    key = TxKey(raw).hex()
    sub_id = f"rpc-commit-{key}-{id(raw)}"
    sub = env.node.event_bus.subscribe(
        sub_id, {"tm.event": ev.EVENT_TX, ev.TX_HASH_KEY: key})
    try:
        try:
            await env.node.mempool.check_tx(raw)
        except TxRejectedError as e:
            return {"hash": key, "check_tx": {"code": e.code, "log": e.log}}
        msg = await asyncio.wait_for(sub.queue.get(), timeout_s)
        res = msg.data["result"]
        return {"hash": key, "check_tx": {"code": 0},
                "tx_result": {"code": res.code, "log": res.log,
                              "data": res.data.hex()},
                "height": msg.data["height"]}
    except asyncio.TimeoutError:
        raise RPCError(-32603,
                       "timed out waiting for tx to be included in a block")
    finally:
        env.node.event_bus.unsubscribe(sub_id)


async def check_tx(env: Environment, tx=None) -> dict:
    """rpc/core/mempool.go:215 CheckTx: run the app's CheckTx without
    adding the tx to the mempool."""
    res = await env.node.app_conns.mempool.check_tx(_tx_bytes(tx))
    return {"code": res.code, "data": res.data.hex(), "log": res.log,
            "gas_wanted": res.gas_wanted}


# ------------------------------------------------------------------ abci

async def abci_info(env: Environment) -> dict:
    resp = await env.node.app_conns.query.info()
    return {"response": {"data": resp.data, "version": resp.version,
                         "app_version": resp.app_version,
                         "last_block_height": resp.last_block_height,
                         "last_block_app_hash":
                         resp.last_block_app_hash.hex()}}


async def abci_query(env: Environment, path="", data=None, height=0,
                     prove=False) -> dict:
    raw = _tx_bytes(data) if data else b""
    resp = await env.node.app_conns.query.query(path, raw, int(height),
                                                bool(prove))
    return {"response": {"code": resp.code, "log": resp.log,
                         "key": resp.key.hex(), "value": resp.value.hex(),
                         "height": resp.height,
                         "proof_ops": [{"type": op["type"],
                                        "key": op["key"].hex(),
                                        "data": op["data"].hex()}
                                       for op in resp.proof_ops]}}


# -------------------------------------------------------------- evidence

async def broadcast_evidence(env: Environment, evidence=None) -> dict:
    ev_obj = from_jsonable(evidence)
    try:
        env.node.evidence_pool.add_evidence(ev_obj)
    except EvidenceError as e:
        raise RPCError(-32603, f"invalid evidence: {e}")
    return {"hash": ev_obj.hash().hex()}


# --------------------------------------------------------------- pruning

async def retain_heights(env: Environment) -> dict:
    """ADR-101 pruning-service introspection."""
    pruner = env.node.pruner
    if pruner is None:
        raise RPCError(-32603, "pruner not running")
    app, dc = pruner.retain_heights()
    return {"app_retain_height": app, "data_companion_retain_height": dc,
            "effective": pruner.effective_retain_height(),
            "store_base": env.block_store.base()}


async def set_companion_retain_height(env: Environment, height=0) -> dict:
    """ADR-101 data-companion SetBlockRetainHeight."""
    pruner = env.node.pruner
    if pruner is None:
        raise RPCError(-32603, "pruner not running")
    h = int(height)
    if h < 0:
        raise RPCError(-32602, "height must be >= 0")
    pruner.set_companion_retain_height(h)
    return {"data_companion_retain_height": h}


# --------------------------------------------------------------- indexer

def _check_order_by(order_by) -> str:
    if order_by not in ("", "asc", "desc"):
        raise RPCError(-32602, f"order_by must be asc|desc, "
                       f"got {order_by!r}")
    return order_by or "asc"


def _tx_proof_provider(env: Environment):
    """Per-request provider of tx inclusion proofs under the block's
    data_hash (rpc/core/tx.go:40 — Data.Txs proof at the tx's index).
    Caches the (root, proofs) tree per height so a search page touching
    one block hashes its tx tree once.  Returns None for pruned blocks
    (the reference skips the proof when the block is nil)."""
    from ..crypto import merkle
    from ..types.header import tx_hash as _txh

    trees: dict[int, tuple] = {}

    def prove(res: dict) -> dict | None:
        h = res["height"]
        if h not in trees:
            blk = env.block_store.load_block(h)
            trees[h] = (None if blk is None else
                        merkle.proofs_from_byte_slices(
                            [_txh(t) for t in blk.data.txs]))
        tree = trees[h]
        if tree is None:
            return None
        root, proofs = tree
        pf = proofs[res["index"]]
        return {"root_hash": root.hex(), "data": res["tx"],
                "proof": {"total": pf.total, "index": pf.index,
                          "leaf_hash": pf.leaf_hash.hex(),
                          "aunts": [a.hex() for a in pf.aunts]}}

    return prove


async def tx(env: Environment, hash=None, prove=False) -> dict:
    indexer = getattr(env.node, "tx_indexer", None)
    if indexer is None:
        raise RPCError(-32603, "transaction indexing is disabled")
    want = bytes.fromhex(hash) if isinstance(hash, str) else hash
    res = indexer.get(want)
    if res is None:
        raise RPCError(-32603, f"tx {want.hex()} not found")
    if prove:
        pf = _tx_proof_provider(env)(res)
        if pf is not None:
            res = dict(res, proof=pf)
    return res


async def tx_search(env: Environment, query="", page=1,
                    per_page=30, prove=False, order_by="") -> dict:
    from ..libs.query import QuerySyntaxError

    indexer = getattr(env.node, "tx_indexer", None)
    if indexer is None:
        raise RPCError(-32603, "transaction indexing is disabled")
    try:
        out = indexer.search(query, int(page), int(per_page),
                             order_by=_check_order_by(order_by))
    except QuerySyntaxError as e:
        raise RPCError(-32602, f"bad query: {e}") from e
    if prove:
        prover = _tx_proof_provider(env)
        out["txs"] = [dict(r, proof=pf) if (pf := prover(r)) is not None
                      else r for r in out["txs"]]
    return out


async def block_search(env: Environment, query="", page=1,
                       per_page=30, order_by="") -> dict:
    from ..libs.query import QuerySyntaxError

    indexer = getattr(env.node, "block_indexer", None)
    if indexer is None:
        raise RPCError(-32603, "block indexing is disabled")
    try:
        return indexer.search(query, int(page), int(per_page),
                              order_by=_check_order_by(order_by))
    except QuerySyntaxError as e:
        raise RPCError(-32602, f"bad query: {e}") from e


# --------------------------------------------------- flight recorder

async def dump_trace(env: Environment, limit=1000, sub=None,
                     height=None) -> dict:
    """Dump the node-wide flight recorder (``libs/tracing`` ring buffer)
    as JSON: the newest ``limit`` completed spans/events, in completion
    order.  Sort records by ``start_ns`` to reconstruct a timeline; a
    committed height shows its consensus step spans with the ABCI calls,
    WAL fsyncs and verify micro-batches that ran inside them.
    ``sub=consensus`` keeps one subsystem; ``height=H`` keeps records
    stamped with that height.  Empty (with ``enabled: false``) unless
    ``[instrumentation] tracing`` is on."""
    from ..libs import tracing

    lim = int(limit)
    if lim < 0:
        raise RPCError(-32602, "limit must be >= 0")
    st = tracing.stats()
    # a full 8192-record ring projects to megabytes of dicts: build
    # them OFF the event loop (the JSON encode is already off-loop via
    # _THREAD_ENCODE_METHODS — this moves the projection there too)
    records = await asyncio.to_thread(
        tracing.dump, lim, str(sub) if sub is not None else None,
        int(height) if height is not None else None)
    return {
        "enabled": st["enabled"],
        "ring_size": st["ring_size"],
        "buffered": st["buffered"],
        "records": records,
    }


async def consensus_timeline(env: Environment, height=0, n=8,
                             node=None) -> dict:
    """Per-height commit-latency waterfalls folded from the flight
    recorder (``libs/timeline``): ordered phases (propose -> gossip ->
    prevote -> precommit -> commit), emitter marks, and residual
    buckets (gossip_wait/verify/app/wal/idle) that sum exactly to the
    measured commit latency.  ``height=H`` selects one height,
    otherwise the newest ``n`` per node; ``node=`` filters an in-proc
    ensemble's shared ring.  Requires ``[instrumentation] tracing``."""
    from ..libs import timeline, tracing

    h = int(height)
    k = int(n)
    if h < 0 or k < 0:
        raise RPCError(-32602, "height and n must be >= 0")
    st = tracing.stats()
    waterfalls = await asyncio.to_thread(
        timeline.fold, tracing.snapshot(),
        node=str(node) if node is not None else None,
        height=h or None, limit=k)
    return {
        "enabled": st["enabled"],
        "buffered": st["buffered"],
        "phases": list(timeline.PHASES),
        "buckets": list(timeline.BUCKETS),
        "waterfalls": waterfalls,
    }


async def dump_incidents(env: Environment, limit=50, name=None) -> dict:
    """List the liveness watchdog's black-box incident bundles (newest
    first, metadata only — filenames carry timestamp + reasons, bodies
    can run megabytes of trace ring).  Pass ``name=<listing name>`` to
    fetch one parsed bundle inline.  Always answers, even with the
    watchdog disabled or no home dir: ``enabled: false`` + an empty
    list, so operator tooling can probe unconditionally."""
    from ..node.watchdog import list_incidents, load_incident

    node = env.node
    wd = getattr(node, "liveness_watchdog", None)
    incident_fn = getattr(node, "incident_dir", None)
    incident_dir = incident_fn() if callable(incident_fn) else None
    out = {
        "enabled": wd is not None,
        "incident_dir": incident_dir or "",
        "trips": wd.trips if wd is not None else 0,
        "incidents": (list_incidents(incident_dir, int(limit))
                      if incident_dir else []),
    }
    if name is not None:
        if not incident_dir:
            raise RPCError(-32603, "no incident directory on this node")
        # a bundle body can run megabytes of trace ring: read + parse
        # in a worker thread — this route bypasses the admission gate
        # (diagnostics must answer during overload), so it especially
        # must not stall the event loop
        bundle = await asyncio.to_thread(
            load_incident, incident_dir, str(name))
        if bundle is None:
            raise RPCError(-32603, f"no incident bundle {name!r}")
        out["bundle"] = bundle
    return out


# ---------------------------------------------------- unsafe (dev-only)

async def dial_seeds(env: Environment, seeds=None) -> dict:
    """rpc/core/net.go:46 UnsafeDialSeeds."""
    from ..libs import log as tmlog

    for addr in seeds or []:
        try:
            await env.node.switch.dial_peer(addr)
        except Exception as e:          # best-effort, like the reference
            tmlog.logger("rpc").error("dial_seeds", addr=addr, err=str(e))
    return {"log": "Dialing seeds in progress. See /net_info for details"}


async def dial_peers(env: Environment, peers=None,
                     persistent=False) -> dict:
    """rpc/core/net.go:59 UnsafeDialPeers."""
    from ..libs import log as tmlog

    for addr in peers or []:
        try:
            await env.node.switch.dial_peer(addr, persistent=bool(persistent))
        except Exception as e:
            tmlog.logger("rpc").error("dial_peers", addr=addr, err=str(e))
    return {"log": "Dialing peers in progress. See /net_info for details"}


async def unsafe_flush_mempool(env: Environment) -> dict:
    """rpc/core/dev.go:9 UnsafeFlushMempool."""
    await env.node.mempool.flush()
    return {}


ROUTES = {
    "health": health,
    "status": status,
    "net_info": net_info,
    "genesis": genesis,
    "block": block,
    "block_by_hash": block_by_hash,
    "header": header,
    "commit": commit,
    "blockchain": blockchain,
    "block_results": block_results,
    "validators": validators,
    "consensus_params": consensus_params,
    "consensus_state": consensus_state,
    "dump_consensus_state": dump_consensus_state,
    "unconfirmed_txs": unconfirmed_txs,
    "num_unconfirmed_txs": num_unconfirmed_txs,
    "broadcast_tx_async": broadcast_tx_async,
    "broadcast_tx_sync": broadcast_tx_sync,
    "broadcast_tx_commit": broadcast_tx_commit,
    "abci_info": abci_info,
    "abci_query": abci_query,
    "broadcast_evidence": broadcast_evidence,
    "retain_heights": retain_heights,
    "set_companion_retain_height": set_companion_retain_height,
    "tx": tx,
    "tx_search": tx_search,
    "block_search": block_search,
    "header_by_hash": header_by_hash,
    "genesis_chunked": genesis_chunked,
    "check_tx": check_tx,
    "dump_trace": dump_trace,
    "dump_incidents": dump_incidents,
    "consensus_timeline": consensus_timeline,
    "light_block": light_block,
    "light_blocks": light_blocks,
    "light_proofs": light_proofs,
    "light_verify": light_verify,
}

# registered only when config rpc.unsafe is set (rpc/core/routes.go:57-62)
UNSAFE_ROUTES = {
    "dial_seeds": dial_seeds,
    "dial_peers": dial_peers,
    "unsafe_flush_mempool": unsafe_flush_mempool,
}
