"""JSON projection of domain objects for the RPC surface.

The reference emits proto-JSON (``rpc/jsonrpc``); this framework's RPC is
only required to interop with its own clients (SURVEY §7 codec stance), so
the projection is the storage codec's dict form with bytes rendered as
hex — stable, self-describing, and round-trippable via ``from_json``."""

from __future__ import annotations

from ..types import codec


def jsonable(obj):
    """codec dict form with bytes -> hex strings (tagged for round-trip)."""
    return _hexify(codec.to_dict(obj))


def from_jsonable(data):
    """Inverse of :func:`jsonable`."""
    return codec.from_dict(_unhexify(data))


def _hexify(v):
    if isinstance(v, bytes):
        return {"~b": v.hex()}
    if isinstance(v, list):
        return [_hexify(x) for x in v]
    if isinstance(v, dict):
        return {k: _hexify(x) for k, x in v.items()}
    return v


def _unhexify(v):
    if isinstance(v, dict):
        if set(v.keys()) == {"~b"}:
            return bytes.fromhex(v["~b"])
        return {k: _unhexify(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_unhexify(x) for x in v]
    return v
