"""Node gRPC services (reference: ``rpc/grpc/server/services/``):
version, block, block-results, and the ADR-101 pruning service.

Same transport convention as ``abci/grpc.py``: generic handlers, msgpack
payload frames ``{ok, result|error}``, no protoc codegen.  The service and
method names mirror the reference's proto packages
(``cometbft.services.*.v1``) so a reference user finds the same surface.
"""

from __future__ import annotations

import itertools

import grpc
import grpc.aio
import msgpack

from . import core
from .core import Environment, RPCError
from .json import jsonable

_PREFIX = "cometbft.services"


def _pack(obj) -> bytes:
    return msgpack.packb(jsonable(obj), use_bin_type=True, default=str)


def _unpack(raw: bytes):
    return msgpack.unpackb(raw, raw=False, strict_map_key=False) if raw \
        else {}


class GRPCServices(grpc.GenericRpcHandler):
    """Routes ``/cometbft.services.<svc>.v1.<Svc>Service/<Method>`` to
    handlers over the same :class:`Environment` the JSON-RPC routes use."""

    def __init__(self, node):
        self.env = Environment(node)
        self.node = node
        self._stream_ids = itertools.count(1)
        self._unary = {
            f"/{_PREFIX}.version.v1.VersionService/GetVersion":
                self._get_version,
            f"/{_PREFIX}.block.v1.BlockService/GetByHeight":
                self._get_by_height,
            f"/{_PREFIX}.block_results.v1.BlockResultsService/"
            "GetBlockResults": self._get_block_results,
            f"/{_PREFIX}.pruning.v1.PruningService/SetBlockRetainHeight":
                self._set_retain,
            f"/{_PREFIX}.pruning.v1.PruningService/GetBlockRetainHeight":
                self._get_retain,
        }
        self._streaming = {
            f"/{_PREFIX}.block.v1.BlockService/GetLatestHeight":
                self._latest_heights,
        }

    # -- handlers --------------------------------------------------------

    async def _get_version(self, req: dict) -> dict:
        from .. import __version__

        return {"node": __version__, "abci": "2.0.0", "p2p": 9, "block": 11}

    async def _get_by_height(self, req: dict) -> dict:
        return await core.block(self.env, height=req.get("height"))

    async def _get_block_results(self, req: dict) -> dict:
        return await core.block_results(self.env,
                                        height=req.get("height"))

    async def _set_retain(self, req: dict) -> dict:
        return await core.set_companion_retain_height(
            self.env, height=req.get("height", 0))

    async def _get_retain(self, req: dict) -> dict:
        out = await core.retain_heights(self.env)
        return {"app_retain_height": out["app_retain_height"],
                "pruning_service_retain_height":
                    out["data_companion_retain_height"]}

    async def _latest_heights(self, req: dict):
        """Server-streaming: the committed height now, then every new one
        (reference GetLatestHeight streams from the NewBlock event)."""
        bus = getattr(self.node, "event_bus", None)
        store = self.env.block_store
        yield {"height": store.height()}
        if bus is None:
            return
        sid = f"grpc-latest-height-{next(self._stream_ids)}"
        sub = bus.subscribe(sid, {"tm.event": "NewBlock"})
        try:
            while True:
                msg = await sub.queue.get()
                yield {"height": msg.data["block"].header.height}
        finally:
            bus.unsubscribe(sid)

    # -- grpc plumbing ---------------------------------------------------

    def service(self, details):
        unary = self._unary.get(details.method)
        if unary is not None:
            async def handler(request: bytes, context):
                try:
                    return _pack({"ok": True,
                                  "result": await unary(_unpack(request))})
                except RPCError as e:
                    return _pack({"ok": False, "error": e.message,
                                  "code": e.code})
                except Exception as e:
                    return _pack({"ok": False, "error": repr(e)})
            return grpc.unary_unary_rpc_method_handler(handler)
        stream = self._streaming.get(details.method)
        if stream is not None:
            async def shandler(request: bytes, context):
                async for item in stream(_unpack(request)):
                    yield _pack({"ok": True, "result": item})
            return grpc.unary_stream_rpc_method_handler(shandler)
        return None


class GRPCServer:
    """The node's gRPC listener (started when ``rpc.grpc_laddr`` is
    set — reference ``node/node.go`` gRPC block/pruning services)."""

    def __init__(self, node, host: str = "127.0.0.1", port: int = 0):
        self.node = node
        self.host = host
        self.port = port
        self._server: grpc.aio.Server | None = None

    async def start(self) -> None:
        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers((GRPCServices(self.node),))
        self.port = self._server.add_insecure_port(
            f"{self.host}:{self.port}")
        await self._server.start()

    async def stop(self) -> None:
        if self._server is not None:
            await self._server.stop(grace=0.2)
            self._server = None


class GRPCServicesClient:
    """Client for :class:`GRPCServer` (reference
    ``rpc/grpc/client/client.go``)."""

    def __init__(self, channel: grpc.aio.Channel):
        self._channel = channel

    @classmethod
    async def connect(cls, host: str, port: int) -> "GRPCServicesClient":
        return cls(grpc.aio.insecure_channel(f"{host}:{port}"))

    async def _call(self, method: str, req: dict | None = None):
        stub = self._channel.unary_unary(method)
        frame = _unpack(await stub(_pack(req or {})))
        if not frame.get("ok", False):
            raise RPCError(frame.get("code", -32603), frame.get("error"))
        return frame["result"]

    async def get_version(self) -> dict:
        return await self._call(
            f"/{_PREFIX}.version.v1.VersionService/GetVersion")

    async def get_block_by_height(self, height: int | None = None) -> dict:
        return await self._call(
            f"/{_PREFIX}.block.v1.BlockService/GetByHeight",
            {"height": height})

    async def get_block_results(self, height: int | None = None) -> dict:
        return await self._call(
            f"/{_PREFIX}.block_results.v1.BlockResultsService/"
            "GetBlockResults", {"height": height})

    async def set_block_retain_height(self, height: int) -> dict:
        return await self._call(
            f"/{_PREFIX}.pruning.v1.PruningService/SetBlockRetainHeight",
            {"height": height})

    async def get_block_retain_height(self) -> dict:
        return await self._call(
            f"/{_PREFIX}.pruning.v1.PruningService/GetBlockRetainHeight")

    async def latest_height_stream(self):
        stub = self._channel.unary_stream(
            f"/{_PREFIX}.block.v1.BlockService/GetLatestHeight")
        async for raw in stub(_pack({})):
            yield _unpack(raw)["result"]

    async def close(self) -> None:
        await self._channel.close()
