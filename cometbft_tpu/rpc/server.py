"""JSON-RPC 2.0 server over HTTP + WebSocket subscriptions (reference:
``rpc/jsonrpc/server/{http_json_handler,http_uri_handler,ws_handler}.go``,
``WebsocketManager`` at ``ws_handler.go:32``).

Three access styles, like the reference:
- POST ``/`` with a JSON-RPC body ``{"jsonrpc":"2.0","id":..,"method":..,
  "params":{..}}``
- GET ``/<method>?param=value`` (URI style; ints, ``0x..`` hex and quoted
  strings are coerced)
- GET ``/websocket`` upgraded to a WebSocket carrying JSON-RPC frames,
  where ``subscribe``/``unsubscribe`` manage event-bus subscriptions with
  the ``tm.event='NewBlock' AND tx.hash='..'`` query syntax
  (``libs/pubsub/query``), and matching events are pushed as
  notifications.

The HTTP layer is hand-rolled on asyncio streams — no external web
framework exists in this image, and the surface needed (HTTP/1.1 POST/GET
+ RFC6455 text frames) is small."""

from __future__ import annotations

import asyncio
import base64
import functools
import hashlib
import json
import math
import struct
from urllib.parse import parse_qsl, unquote, urlsplit

from ..libs.query import Query, QuerySyntaxError
from .core import ROUTES, Environment, RPCError
from .json import jsonable

_WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"
MAX_BODY = 10 << 20

# Routes that bypass the overload-shedding admission gate: the node's
# own diagnostics MUST answer while the node sheds a request flood —
# an operator debugging the flood needs /status and /net_info most
# exactly then.  All stay off the event-loop's critical path: cheap
# in-memory reads, except dump_incidents' bundle fetch which runs its
# disk read in a worker thread.
UNGATED_METHODS = frozenset(
    {"health", "status", "net_info", "dump_trace", "dump_incidents",
     "consensus_timeline"})
# POST bodies up to this size are parsed BEFORE the gate to check the
# exemption; anything larger is gated unconditionally so a flood of fat
# bodies can't buy a 10MB json.loads per shed request
_GATE_PROBE_MAX_BODY = 4096
# responses that can run megabytes: serialize in a worker thread (the
# light-serve routes ship whole proof sets / light-block batches, and
# even a single light_block embeds the full valset JSON — ~1 MB at 10k
# validators on the provider's preferred single-round-trip path)
_THREAD_ENCODE_METHODS = frozenset(
    {"dump_incidents", "dump_trace", "consensus_timeline",
     "light_block", "light_blocks", "light_proofs", "light_verify",
     # block-/valset-scaled payloads (a 10k-validator commit alone is
     # ~MB of JSON): encoding them inline froze every other connection
     # — the thread-encode gap class the BLK001 sweep closed
     "block", "block_by_hash", "block_results", "blockchain", "commit",
     "validators", "genesis", "genesis_chunked", "tx_search",
     "block_search", "unconfirmed_txs", "dump_consensus_state"})


@functools.cache
def _gate_metrics():
    from ..libs import metrics as _m

    return _m.counter(
        "rpc_requests_shed_total",
        "HTTP requests rejected with 503 by the RPC admission gate "
        "(concurrency limit hit AND the wait queue full)")


def compile_query(q: str) -> Query:
    """Compile a query string with the full grammar of ``libs/query``
    (reference ``libs/pubsub/query``), mapping syntax errors to JSON-RPC
    invalid-params."""
    try:
        return Query.parse(q)
    except QuerySyntaxError as e:
        raise RPCError(-32602, f"bad query: {e}") from e


def parse_query(q: str) -> dict[str, str]:
    """``tm.event='NewBlock' AND tx.hash='AB12'`` -> equality dict.  Kept
    for callers that only need the posting-index subset: a query with any
    non-equality condition is REJECTED here (use ``compile_query`` for
    the full grammar) so an empty/partial dict can never silently match
    everything.  Bare ``=`` clauses without quotes are tolerated for CLI
    ergonomics."""
    try:
        compiled = compile_query(q)
    except RPCError:
        out = {}
        for clause in q.split(" AND "):
            clause = clause.strip()
            if not clause:
                continue
            if "=" not in clause:
                raise RPCError(-32602, f"bad query clause {clause!r}")
            k, v = clause.split("=", 1)
            out[k.strip()] = v.strip().strip("'\"")
        return out
    eq = compiled.equality_clauses()
    if len(eq) != len(compiled.conditions):
        raise RPCError(-32602,
                       "query has non-equality conditions; this endpoint "
                       "supports the equality subset only")
    return eq


def _coerce(v: str):
    v = unquote(v)
    if v.startswith('"') and v.endswith('"'):
        return v[1:-1]
    if v.startswith("0x"):
        return bytes.fromhex(v[2:])
    if v in ("true", "false"):
        return v == "true"
    try:
        return int(v)
    except ValueError:
        return v


class RPCServer:
    def __init__(self, node, routes: dict | None = None):
        """``routes`` overrides the default route table (the light proxy
        serves verified routes against a light client instead)."""
        self.env = Environment(node)
        cfg = getattr(node, "config", None)
        if routes is not None:
            self.routes = routes
        else:
            self.routes = dict(ROUTES)
            if cfg is not None and getattr(cfg.rpc, "unsafe", False):
                from .core import UNSAFE_ROUTES

                self.routes.update(UNSAFE_ROUTES)
        self._server: asyncio.Server | None = None
        self._conn_tasks: set[asyncio.Task] = set()
        self._ws_counter = 0
        # CORS + TLS from config (config/config.go:353-364,428-442); a
        # config-less node (light proxy shim) gets RPCConfig's defaults —
        # ONE source of truth, and an explicitly configured empty list
        # stays empty
        from ..config import RPCConfig

        rpc_cfg = getattr(cfg, "rpc", None)
        if rpc_cfg is None:
            rpc_cfg = RPCConfig()
        self._cors_origins = list(rpc_cfg.cors_allowed_origins)
        self._cors_methods = list(rpc_cfg.cors_allowed_methods)
        self._cors_headers = list(rpc_cfg.cors_allowed_headers)
        self._ssl_ctx = self._build_ssl(cfg)
        self._openapi_raw: bytes | None = None
        # ---- overload-shedding admission gate -------------------------
        # at most max_concurrent_requests handlers run at once; up to
        # max_queued_requests more wait on the semaphore; past that the
        # request is shed with 503 + Retry-After.  Diagnostic routes
        # (UNGATED_METHODS) bypass the gate entirely.
        self._gate_max = max(1, int(getattr(
            rpc_cfg, "max_concurrent_requests", 64)))
        self._gate_max_queued = max(0, int(getattr(
            rpc_cfg, "max_queued_requests", 256)))
        self._gate_retry_after = max(1, math.ceil(float(getattr(
            rpc_cfg, "shed_retry_after_s", 1.0)) or 1))
        self._gate_sem = asyncio.Semaphore(self._gate_max)
        self._gate_active = 0
        self._gate_queued = 0
        self._m_shed = _gate_metrics()

    @staticmethod
    def _build_ssl(cfg):
        """ssl.SSLContext when BOTH tls_cert_file and tls_key_file are
        configured (else plain HTTP), resolving relative paths against
        the config dir like the reference (config.go CertFile())."""
        import os
        import ssl

        rpc_cfg = getattr(cfg, "rpc", None)
        cert = getattr(rpc_cfg, "tls_cert_file", "") or ""
        key = getattr(rpc_cfg, "tls_key_file", "") or ""
        if not cert or not key:
            return None
        root = getattr(getattr(cfg, "base", None), "root_dir", ".") or "."
        conf_dir = os.path.join(root, "config")
        if not os.path.isabs(cert):
            cert = os.path.join(conf_dir, cert)
        if not os.path.isabs(key):
            key = os.path.join(conf_dir, key)
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(cert, key)
        return ctx

    def _origin_allowed(self, origin: str) -> str | None:
        """The Access-Control-Allow-Origin value for ``origin``, or None
        when CORS is off / the origin isn't allowed.  Each allowed origin
        may carry ONE ``*`` wildcard (rs/cors semantics the reference
        wires in rpc/jsonrpc/server)."""
        if not origin or not self._cors_origins:
            return None
        # the matched origin is echoed into a response header: control
        # characters (a smuggled bare CR especially) must never pass a
        # wildcard match into the response (header-injection vector)
        if any(ord(ch) < 0x20 or ord(ch) == 0x7F for ch in origin):
            return None
        for allowed in self._cors_origins:
            if allowed == "*":
                return "*"
            if "*" in allowed:
                head, _, tail = allowed.partition("*")
                if origin.startswith(head) and origin.endswith(tail) and \
                        len(origin) >= len(head) + len(tail):
                    return origin
            elif allowed == origin:
                return origin
        return None

    def _cors_response_headers(self, headers: dict) -> bytes:
        if not self._cors_origins:
            return b""
        allow = self._origin_allowed(headers.get("origin", ""))
        # Vary: Origin goes on EVERY response once CORS is on (match or
        # not) — a shared cache must never serve an Origin-less cached
        # response to a browser on an allowed origin (rs/cors behavior)
        out = "Vary: Origin\r\n"
        if allow is not None:
            out += f"Access-Control-Allow-Origin: {allow}\r\n"
        return out.encode()

    async def listen(self, host: str = "127.0.0.1",
                     port: int = 0) -> tuple[str, int]:
        self._server = await asyncio.start_server(self._serve, host, port,
                                                  ssl=self._ssl_ctx)
        addr = self._server.sockets[0].getsockname()
        return addr[0], addr[1]

    async def close(self) -> None:
        # cancel every live connection handler: Server.wait_closed() on
        # 3.12+ waits for them all, and an idle keep-alive client would
        # otherwise block shutdown forever
        for t in list(self._conn_tasks):
            t.cancel()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    def openapi_spec(self) -> dict:
        """OpenAPI 3.0 document derived from the LIVE route table by
        introspection (handler signatures + docstrings), the role of the
        reference's hand-written ``rpc/openapi/openapi.yaml``."""
        import inspect

        paths = {}
        for name in sorted(self.routes):
            fn = self.routes[name]
            params = []
            try:
                sig = inspect.signature(fn)
            except (TypeError, ValueError):
                sig = None
            if sig is not None:
                for pname, p in sig.parameters.items():
                    if pname == "env" or p.kind in (
                            p.VAR_POSITIONAL, p.VAR_KEYWORD):
                        continue
                    params.append({
                        "name": pname,
                        "in": "query",
                        "required": p.default is inspect.Parameter.empty,
                        "schema": {"type": "string"},
                    })
            doc = inspect.getdoc(fn) or ""
            paths[f"/{name}"] = {"get": {
                "operationId": name,
                "summary": doc.splitlines()[0] if doc else name,
                "description": doc,
                "parameters": params,
                "responses": {"200": {
                    "description": "JSON-RPC 2.0 envelope",
                    "content": {"application/json": {"schema": {
                        "type": "object"}}},
                }},
            }}
        return {
            "openapi": "3.0.0",
            "info": {
                "title": "cometbft-tpu RPC",
                "version": "1.0",
                "description": (
                    "JSON-RPC 2.0 over HTTP: every path also accepts "
                    "POST / with {jsonrpc, id, method, params}, and "
                    "/websocket carries the same methods plus "
                    "subscribe/unsubscribe."),
            },
            "paths": paths,
        }

    # ------------------------------------------------------- admission gate

    async def _gate_admit(self) -> bool:
        """Enter the concurrency gate: returns False (shed) when the
        run slots are full AND the wait queue is at capacity."""
        if self._gate_active >= self._gate_max and \
                self._gate_queued >= self._gate_max_queued:
            self._m_shed.inc()
            return False
        self._gate_queued += 1
        try:
            await self._gate_sem.acquire()
        finally:
            self._gate_queued -= 1
        self._gate_active += 1
        return True

    def _gate_done(self) -> None:
        self._gate_active -= 1
        self._gate_sem.release()

    def _write_503(self, writer: asyncio.StreamWriter, cors: bytes) -> None:
        body = json.dumps({
            "jsonrpc": "2.0", "id": None,
            "error": {"code": -32000,
                      "message": "server overloaded; retry later",
                      "data": ""}}).encode()
        writer.write(
            b"HTTP/1.1 503 Service Unavailable\r\n"
            b"Content-Type: application/json\r\n" + cors +
            b"Retry-After: " + str(self._gate_retry_after).encode() +
            b"\r\nContent-Length: " + str(len(body)).encode() +
            b"\r\nConnection: keep-alive\r\n\r\n" + body)

    # ------------------------------------------------------------- http

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        self._conn_tasks.add(task)
        try:
            while True:
                req_line = await reader.readline()
                if not req_line:
                    return
                try:
                    method, target, _version = \
                        req_line.decode().strip().split(" ", 2)
                except ValueError:
                    return
                headers = {}
                while True:
                    line = await reader.readline()
                    if line in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = line.decode().partition(":")
                    headers[k.strip().lower()] = v.strip()

                if headers.get("upgrade", "").lower() == "websocket":
                    await self._websocket(reader, writer, headers)
                    return

                body = b""
                try:
                    ln = int(headers.get("content-length", 0))
                except ValueError:
                    return          # unparseable framing: drop connection
                if ln:
                    if ln > MAX_BODY:
                        return
                    body = await reader.readexactly(ln)

                cors = self._cors_response_headers(headers)
                if method == "OPTIONS":
                    # CORS preflight: 204 with the allow-* set when the
                    # origin matches; bare 204 otherwise (rs/cors shape)
                    pre = b""
                    if cors:
                        pre = cors + (
                            "Access-Control-Allow-Methods: "
                            f"{', '.join(self._cors_methods)}\r\n"
                            "Access-Control-Allow-Headers: "
                            f"{', '.join(self._cors_headers)}\r\n"
                            "Access-Control-Max-Age: 600\r\n").encode()
                    writer.write(
                        b"HTTP/1.1 204 No Content\r\n" + pre +
                        b"Content-Length: 0\r\n"
                        b"Connection: keep-alive\r\n\r\n")
                    await writer.drain()
                    if headers.get("connection", "").lower() == "close":
                        return
                    continue
                path = urlsplit(target).path
                if method in ("GET", "HEAD") and path == "/metrics":
                    # Prometheus text exposition (the reference serves this
                    # on the instrumentation port; here it rides the RPC
                    # listener).  HEAD gets GET's headers, no body
                    # (RFC 9110 9.3.2).
                    from ..libs import metrics as _metrics

                    text = _metrics.DEFAULT.collect().encode()
                    writer.write(
                        b"HTTP/1.1 200 OK\r\n"
                        b"Content-Type: text/plain; version=0.0.4\r\n"
                        + cors +
                        b"Content-Length: " + str(len(text)).encode() +
                        b"\r\nConnection: keep-alive\r\n\r\n" +
                        (b"" if method == "HEAD" else text))
                    await writer.drain()
                    if headers.get("connection", "").lower() == "close":
                        return
                    continue
                if method in ("GET", "HEAD") and path == "/openapi":
                    # machine-readable route table (the reference ships
                    # rpc/openapi/openapi.yaml; here the spec is derived
                    # from the live table so it can never go stale);
                    # routes are fixed after __init__ so the serialized
                    # document is computed once
                    if self._openapi_raw is None:
                        # bftlint: disable=BLK001 -- one-time encode of the static route table (KBs), cached for the server's lifetime
                        self._openapi_raw = json.dumps(
                            self.openapi_spec()).encode()
                    text = self._openapi_raw
                    writer.write(
                        b"HTTP/1.1 200 OK\r\n"
                        b"Content-Type: application/json\r\n" + cors +
                        b"Content-Length: " + str(len(text)).encode() +
                        b"\r\nConnection: keep-alive\r\n\r\n" +
                        (b"" if method == "HEAD" else text))
                    await writer.drain()
                    if headers.get("connection", "").lower() == "close":
                        return
                    continue
                # overload shedding: every non-diagnostic request enters
                # the admission gate; at capacity it gets 503+Retry-After
                # while /status and friends keep answering.  The shed
                # decision must stay cheap: only SMALL POST bodies are
                # parsed pre-gate to check the exemption (a diagnostic
                # call is never megabytes) — large bodies are gated
                # unconditionally and parsed only once admitted.
                req = parse_err = None
                parsed = False
                rpc_method = None
                if method == "POST":
                    if len(body) <= _GATE_PROBE_MAX_BODY:
                        req, parse_err = self._parse_jsonrpc(body)
                        parsed = True
                        rpc_method = req.get("method") \
                            if isinstance(req, dict) else None
                        gated = parse_err is None and \
                            rpc_method not in UNGATED_METHODS
                    else:
                        gated = True
                elif method in ("GET", "HEAD"):
                    rpc_method = path.strip("/")
                    gated = rpc_method not in UNGATED_METHODS
                else:
                    gated = False        # error response, no handler runs
                if gated and not await self._gate_admit():
                    self._write_503(writer, cors)
                    await writer.drain()
                    if headers.get("connection", "").lower() == "close":
                        return
                    continue
                try:
                    if method == "POST":
                        if not parsed:
                            # only >probe-size bodies reach here
                            # unparsed — decode those off the loop
                            req, parse_err = await asyncio.to_thread(
                                self._parse_jsonrpc, body)
                            if isinstance(req, dict):
                                rpc_method = req.get("method")
                        resp = parse_err if parse_err is not None else \
                            await self._handle_jsonrpc_obj(req)
                    elif method in ("GET", "HEAD"):
                        resp = await self._handle_uri(target)
                    else:
                        resp = _rpc_error(None, -32600,
                                          f"unsupported method {method}")
                finally:
                    if gated:
                        self._gate_done()
                if rpc_method in _THREAD_ENCODE_METHODS or \
                        isinstance(req, list):
                    # multi-MB diagnostic payloads (incident bundles,
                    # trace dumps) serialize off the event loop — these
                    # routes bypass the gate, so their encode especially
                    # must not stall pings/consensus timers.  JSON-RPC
                    # BATCHES have no single method and can stack heavy
                    # calls, so they always thread-encode
                    raw = await asyncio.to_thread(json.dumps, resp)
                    raw = raw.encode()
                else:
                    # bftlint: disable=BLK001 -- small-payload path: block-/valset-/pool-scaled routes are in _THREAD_ENCODE_METHODS, batches thread-encode above
                    raw = json.dumps(resp).encode()
                writer.write(
                    b"HTTP/1.1 200 OK\r\n"
                    b"Content-Type: application/json\r\n" + cors +
                    b"Content-Length: " + str(len(raw)).encode() +
                    b"\r\nConnection: keep-alive\r\n\r\n" +
                    (b"" if method == "HEAD" else raw))
                await writer.drain()
                if headers.get("connection", "").lower() == "close":
                    return
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError):
            pass
        finally:
            self._conn_tasks.discard(task)
            writer.close()

    @staticmethod
    def _parse_jsonrpc(body: bytes):
        """(parsed request, None) or (None, error response)."""
        try:
            return json.loads(body), None
        except json.JSONDecodeError as e:
            return None, _rpc_error(None, -32700, f"parse error: {e}")

    async def _handle_jsonrpc_obj(self, req):
        if isinstance(req, list):
            # JSON-RPC batch (rpc/jsonrpc/server/http_json_handler.go:46);
            # notifications (no id) get no response entry
            out = []
            for r in req:
                if not isinstance(r, dict) or r.get("id") is None:
                    continue
                out.append(await self._dispatch(
                    r.get("id"), r.get("method", ""),
                    r.get("params") or {}))
            return out
        if not isinstance(req, dict):
            return _rpc_error(None, -32600,
                              f"invalid request: {type(req).__name__}")
        return await self._dispatch(req.get("id"), req.get("method", ""),
                                    req.get("params") or {})

    async def _handle_uri(self, target: str) -> dict:
        parts = urlsplit(target)
        method = parts.path.strip("/")
        if not method:
            return {"jsonrpc": "2.0", "id": -1,
                    "result": {"routes": sorted(self.routes)}}
        try:
            params = {k: _coerce(v) for k, v in parse_qsl(parts.query)}
        except ValueError as e:       # e.g. odd-length 0x hex
            return _rpc_error(-1, -32602, f"bad parameter: {e}")
        return await self._dispatch(-1, method, params)

    async def _dispatch(self, rid, method: str, params: dict) -> dict:
        handler = self.routes.get(method)
        if handler is None:
            return _rpc_error(rid, -32601, f"method {method!r} not found")
        try:
            result = await handler(self.env, **params)
            return {"jsonrpc": "2.0", "id": rid, "result": result}
        except RPCError as e:
            return _rpc_error(rid, e.code, e.message, e.data)
        except TypeError as e:
            return _rpc_error(rid, -32602, f"invalid params: {e}")
        except Exception as e:       # noqa: BLE001 — route bugs become errors
            return _rpc_error(rid, -32603, f"{type(e).__name__}: {e}")

    # -------------------------------------------------------- websocket

    async def _websocket(self, reader, writer, headers) -> None:
        key = headers.get("sec-websocket-key", "")
        accept = base64.b64encode(
            hashlib.sha1((key + _WS_GUID).encode()).digest()).decode()
        writer.write((
            "HTTP/1.1 101 Switching Protocols\r\n"
            "Upgrade: websocket\r\nConnection: Upgrade\r\n"
            f"Sec-WebSocket-Accept: {accept}\r\n\r\n").encode())
        await writer.drain()
        session = _WsSession(self, reader, writer)
        try:
            await session.run()
        finally:
            session.cleanup()


class _WsSession:
    """One WebSocket connection: JSON-RPC requests in, responses and
    subscription notifications out (ws_handler.go wsConnection)."""

    def __init__(self, server: RPCServer, reader, writer):
        self.server = server
        self.reader = reader
        self.writer = writer
        server._ws_counter += 1
        self.sid = f"ws-{server._ws_counter}"
        self.subs: dict[str, asyncio.Task] = {}   # query -> pump task

    def cleanup(self) -> None:
        if not self.subs:
            return              # never touch the bus if nothing subscribed
        bus = self.server.env.node.event_bus
        for query, task in self.subs.items():
            task.cancel()
            bus.unsubscribe(f"{self.sid}:{query}")
        self.subs.clear()

    async def run(self) -> None:
        try:
            while True:
                op, payload = await self._read_frame()
                if op == 8:                       # close
                    return
                if op == 9:                       # ping -> pong
                    await self._send_frame(10, payload)
                    continue
                if op not in (1, 2):
                    continue
                try:
                    if len(payload) > _GATE_PROBE_MAX_BODY:
                        # fat frames (tx broadcasts can ride ws) parse
                        # off the loop, like >4KB HTTP bodies
                        req = await asyncio.to_thread(json.loads, payload)
                    else:
                        # bftlint: disable=BLK001 -- <=4KB frame, same inline-parse bound as the HTTP gate probe
                        req = json.loads(payload)
                except json.JSONDecodeError:
                    await self._send_json(_rpc_error(None, -32700,
                                                     "parse error"))
                    continue
                if not isinstance(req, dict):
                    # subscribe/unsubscribe semantics don't compose with
                    # JSON-RPC batches; the HTTP path serves those
                    await self._send_json(_rpc_error(
                        None, -32600,
                        "websocket frames must carry a single "
                        "JSON-RPC object (use HTTP POST for batches)"))
                    continue
                await self._handle(req)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            self.writer.close()

    async def _handle(self, req: dict) -> None:
        rid = req.get("id")
        method = req.get("method", "")
        params = req.get("params") or {}
        if method == "subscribe":
            await self._subscribe(rid, params.get("query", ""))
        elif method == "unsubscribe":
            self._unsubscribe(params.get("query", ""))
            await self._send_json({"jsonrpc": "2.0", "id": rid,
                                   "result": {}})
        elif method == "unsubscribe_all":
            for q in list(self.subs):
                self._unsubscribe(q)
            await self._send_json({"jsonrpc": "2.0", "id": rid,
                                   "result": {}})
        elif method in UNGATED_METHODS:
            resp = await self.server._dispatch(rid, method, params)
            if method in _THREAD_ENCODE_METHODS:
                # multi-MB diagnostic payloads encode off the loop on
                # the ws path too
                raw = await asyncio.to_thread(json.dumps, resp)
                await self._send_frame(1, raw.encode())
            else:
                await self._send_json(resp)
        else:
            # the admission gate bounds handler concurrency NODE-WIDE:
            # a flood over websockets must shed like one over HTTP
            # (here as a JSON-RPC error — there is no 503 frame)
            if not await self.server._gate_admit():
                await self._send_json(_rpc_error(
                    rid, -32000, "server overloaded; retry later"))
                return
            try:
                resp = await self.server._dispatch(rid, method, params)
            finally:
                self.server._gate_done()
            if method in _THREAD_ENCODE_METHODS:
                # block-/valset-scaled payloads encode off the loop on
                # the gated ws path too
                raw = await asyncio.to_thread(json.dumps, resp)
                await self._send_frame(1, raw.encode())
            else:
                await self._send_json(resp)

    async def _subscribe(self, rid, query: str) -> None:
        try:
            compiled = compile_query(query)
        except RPCError as e:
            await self._send_json(_rpc_error(rid, e.code, e.message))
            return
        if query in self.subs:
            await self._send_json(_rpc_error(rid, -32603,
                                             "already subscribed"))
            return
        bus = getattr(self.server.env.node, "event_bus", None)
        if bus is None:
            await self._send_json(_rpc_error(
                rid, -32601, "subscriptions not supported on this server"))
            return
        sub = bus.subscribe(f"{self.sid}:{query}", compiled)
        self.subs[query] = asyncio.create_task(self._pump(query, sub))
        await self._send_json({"jsonrpc": "2.0", "id": rid, "result": {}})

    def _unsubscribe(self, query: str) -> None:
        task = self.subs.pop(query, None)
        if task is not None:
            task.cancel()
        self.server.env.node.event_bus.unsubscribe(f"{self.sid}:{query}")

    async def _pump(self, query: str, sub) -> None:
        """Push matching events as JSON-RPC notifications.  Event
        payloads carry whole blocks (NewBlock at 10k validators is MBs
        of JSON), so notifications thread-encode — the acks-only
        _send_json path stays inline."""
        try:
            while True:
                msg = await sub.queue.get()

                def _encode(m=msg, q=query):
                    # the jsonable projection of a whole block costs as
                    # much as the dumps — both belong off the loop
                    return json.dumps({
                        "jsonrpc": "2.0", "id": None,
                        "result": {"query": q,
                                   "data": {"type": m.event_type,
                                            "value": _event_value(m)},
                                   "events": m.attrs}})
                raw = await asyncio.to_thread(_encode)
                await self._send_frame(1, raw.encode())
        except (asyncio.CancelledError, ConnectionError):
            pass

    @staticmethod
    def _decode_len(b: int) -> int:
        return b & 0x7F

    async def _read_frame(self) -> tuple[int, bytes]:
        hdr = await self.reader.readexactly(2)
        op = hdr[0] & 0x0F
        masked = hdr[1] & 0x80
        ln = hdr[1] & 0x7F
        if ln == 126:
            (ln,) = struct.unpack(">H", await self.reader.readexactly(2))
        elif ln == 127:
            (ln,) = struct.unpack(">Q", await self.reader.readexactly(8))
        if ln > MAX_BODY:
            raise ConnectionError(f"oversized ws frame ({ln} bytes)")
        mask = await self.reader.readexactly(4) if masked else b"\x00" * 4
        data = await self.reader.readexactly(ln)
        if masked and ln:
            # bulk XOR via big-int: the per-byte Python loop burned ~1s
            # of event-loop time on a 10 MiB frame — C-speed keeps even
            # MAX_BODY frames in the low ms
            pad = mask * ((ln + 3) // 4)
            data = (int.from_bytes(data, "little") ^
                    int.from_bytes(pad[:ln], "little")
                    ).to_bytes(ln, "little")
        return op, bytes(data)

    async def _send_frame(self, op: int, payload: bytes) -> None:
        ln = len(payload)
        if ln < 126:
            hdr = bytes([0x80 | op, ln])
        elif ln < (1 << 16):
            hdr = bytes([0x80 | op, 126]) + struct.pack(">H", ln)
        else:
            hdr = bytes([0x80 | op, 127]) + struct.pack(">Q", ln)
        self.writer.write(hdr + payload)
        await self.writer.drain()

    async def _send_json(self, obj: dict) -> None:
        # bftlint: disable=BLK001 -- acks/errors only (bounded small); event payloads thread-encode in _pump, diagnostics in _handle
        await self._send_frame(1, json.dumps(obj).encode())


def _event_value(msg):
    """Project event payloads to JSON-able form."""
    data = msg.data
    if isinstance(data, dict):
        out = {}
        for k, v in data.items():
            try:
                out[k] = jsonable(v)
            except TypeError:
                out[k] = repr(v)
        return out
    try:
        return jsonable(data)
    except TypeError:
        return repr(data)


def _rpc_error(rid, code: int, message: str, data: str = "") -> dict:
    return {"jsonrpc": "2.0", "id": rid,
            "error": {"code": code, "message": message, "data": data}}
