"""JSON-RPC clients (reference: ``rpc/jsonrpc/client/{http_json_client,
ws_client}.go``): an HTTP client for request/response routes and a
WebSocket client for event subscriptions."""

from __future__ import annotations

import asyncio
import base64
import json
import os
import struct

from .core import RPCError


def _err(err: dict) -> "RPCError":
    return RPCError(err.get("code", -1), err.get("message", ""),
                    err.get("data", ""))


class HTTPClient:
    """Keep-alive JSON-RPC client: one persistent connection per client,
    requests serialized on it (the server speaks HTTP/1.1 keep-alive).
    Concurrency comes from using one client per task — see
    ``loadtime.generate``'s per-worker clients."""

    def __init__(self, host: str, port: int, *, tls: bool = False,
                 tls_verify: bool = True):
        """``tls=True`` speaks HTTPS to a server configured with
        tls_cert_file/tls_key_file (the reference's client accepts
        https:// addresses); ``tls_verify=False`` accepts self-signed
        certs (operator tooling against a node's own cert)."""
        self.host = host
        self.port = port
        self._ssl = None
        if tls:
            import ssl as _ssl

            ctx = _ssl.create_default_context()
            if not tls_verify:
                ctx.check_hostname = False
                ctx.verify_mode = _ssl.CERT_NONE
            self._ssl = ctx
        self._id = 0
        self._conn = None                  # (reader, writer) when alive
        self._lock = asyncio.Lock()        # one in-flight request/conn

    def clone(self) -> "HTTPClient":
        """A fresh client for the same endpoint WITH the same TLS
        settings (per-worker fan-out must not silently drop https)."""
        c = HTTPClient(self.host, self.port)
        c._ssl = self._ssl
        return c

    async def close(self) -> None:
        if self._conn is not None:
            _, writer = self._conn
            self._conn = None
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                pass

    async def call(self, method: str, **params):
        self._id += 1
        rid = self._id          # NOT self._id at response time: another
        #   task sharing this client may bump the counter while we await
        resp = await self._post(
            json.dumps({"jsonrpc": "2.0", "id": rid,
                        "method": method, "params": params}).encode(),
            retry_ok=not method.startswith("broadcast_"))
        if isinstance(resp, dict) and resp.get("id") not in (None, rid):
            # a desynced keep-alive stream answered with a stale
            # response: poison the connection and fail loudly
            await self.close()
            raise RPCError(-32000,
                           f"response id {resp.get('id')} != {rid}")
        if "error" in resp:
            raise _err(resp["error"])
        return resp["result"]

    async def call_batch(self, calls: list[tuple[str, dict]]) -> list:
        """JSON-RPC batch (rpc/jsonrpc/client BatchHTTPClient): one HTTP
        round-trip for many requests.  Returns per-call results in
        request order; an errored call's slot holds the RPCError."""
        reqs = []
        for method, params in calls:
            self._id += 1
            reqs.append({"jsonrpc": "2.0", "id": self._id,
                         "method": method, "params": params})
        resps = await self._post(
            json.dumps(reqs).encode(),
            retry_ok=all(not m.startswith("broadcast_")
                         for m, _ in calls))
        if not isinstance(resps, list):
            # whole-batch failure: the server answered with a single
            # error object (e.g. parse error) instead of an array
            if isinstance(resps, dict) and "error" in resps:
                raise _err(resps["error"])
            raise RPCError(-32700, f"malformed batch response: {resps!r}")
        by_id = {r.get("id"): r for r in resps if isinstance(r, dict)}
        matched = any(req["id"] in by_id for req in reqs)
        stale_ids = [r["id"] for r in resps if isinstance(r, dict)
                     and r.get("id") is not None
                     and not any(req["id"] == r["id"] for req in reqs)]
        if resps and not matched and stale_ids:
            # responses carry ids that belong to NO request: a desynced
            # stream answered with a stale batch — fail loudly
            await self.close()
            raise RPCError(-32000,
                           f"batch response ids {stale_ids[:3]} match "
                           f"no request")
        if not matched and len(reqs) == 1 and len(resps) == 1 and \
                isinstance(resps[0], dict) and "error" in resps[0]:
            # JSON-RPC answers an unprocessable entry with id null: for
            # a single-element batch that error is unambiguous — surface
            # it rather than a silent None slot
            return [_err(resps[0]["error"])]
        out = []
        for req in reqs:
            r = by_id.get(req["id"], {})
            out.append(_err(r["error"]) if "error" in r
                       else r.get("result"))
        return out

    async def _post(self, body: bytes, retry_ok: bool = True):
        async with self._lock:
            # one retry on a stale reused connection (server idle-closed
            # the keep-alive socket) — but NEVER for non-idempotent
            # requests (broadcast_*): a failure after the server accepted
            # the request would silently double-send the tx.  Failures on
            # a fresh connection always propagate.
            for attempt in (0, 1):
                reused = self._conn is not None
                if not reused:
                    self._conn = await asyncio.open_connection(
                        self.host, self.port, ssl=self._ssl)
                reader, writer = self._conn
                try:
                    return await self._roundtrip(reader, writer, body)
                except (ConnectionError, asyncio.IncompleteReadError,
                        OSError):
                    await self.close()
                    if not (reused and retry_ok) or attempt:
                        raise
                except BaseException:
                    # protocol failure OR cancellation (a timed-out
                    # wait_for cancels us mid-read): the stream position
                    # is unknown, so the connection must never be reused
                    # — a stale half-read response would answer the NEXT
                    # request
                    await self.close()
                    raise

    async def _roundtrip(self, reader, writer, body: bytes):
        writer.write(
            b"POST / HTTP/1.1\r\nHost: rpc\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: " + str(len(body)).encode() +
            b"\r\n\r\n" + body)
        await writer.drain()
        status = await reader.readline()
        if not status:
            raise ConnectionResetError("server closed the connection")
        if b"200" not in status:
            raise RPCError(-32000, f"http error: {status.decode()!r}")
        headers = {}
        while True:
            line = await reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            k, _, v = line.decode().partition(":")
            headers[k.strip().lower()] = v.strip()
        raw = await reader.readexactly(int(headers["content-length"]))
        return json.loads(raw)


class WSClient:
    """Minimal RFC6455 client for subscribe/notification flows."""

    def __init__(self, reader, writer):
        self.reader = reader
        self.writer = writer
        self._id = 0

    @classmethod
    async def connect(cls, host: str, port: int) -> "WSClient":
        reader, writer = await asyncio.open_connection(host, port)
        key = base64.b64encode(os.urandom(16)).decode()
        writer.write((
            f"GET /websocket HTTP/1.1\r\nHost: {host}\r\n"
            "Upgrade: websocket\r\nConnection: Upgrade\r\n"
            f"Sec-WebSocket-Key: {key}\r\n"
            "Sec-WebSocket-Version: 13\r\n\r\n").encode())
        await writer.drain()
        status = await reader.readline()
        if b"101" not in status:
            raise RPCError(-32000, f"ws upgrade failed: {status.decode()!r}")
        while True:
            line = await reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
        return cls(reader, writer)

    async def close(self) -> None:
        self.writer.close()

    async def send(self, method: str, **params) -> None:
        self._id += 1
        await self._send_frame(1, json.dumps(
            {"jsonrpc": "2.0", "id": self._id, "method": method,
             "params": params}).encode())

    async def recv(self) -> dict:
        while True:
            op, payload = await self._read_frame()
            if op == 8:
                raise ConnectionError("ws closed")
            if op == 9:
                await self._send_frame(10, payload)
                continue
            if op in (1, 2):
                return json.loads(payload)

    async def subscribe(self, query: str) -> None:
        await self.send("subscribe", query=query)
        resp = await self.recv()
        if "error" in resp:
            raise RPCError(-32000, str(resp["error"]))

    async def next_event(self, timeout: float = 10.0) -> dict:
        while True:
            resp = await asyncio.wait_for(self.recv(), timeout)
            if resp.get("id") is None and "result" in resp:
                return resp["result"]

    async def _send_frame(self, op: int, payload: bytes) -> None:
        mask = os.urandom(4)
        masked = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
        ln = len(payload)
        if ln < 126:
            hdr = bytes([0x80 | op, 0x80 | ln])
        elif ln < (1 << 16):
            hdr = bytes([0x80 | op, 0x80 | 126]) + struct.pack(">H", ln)
        else:
            hdr = bytes([0x80 | op, 0x80 | 127]) + struct.pack(">Q", ln)
        self.writer.write(hdr + mask + masked)
        await self.writer.drain()

    async def _read_frame(self) -> tuple[int, bytes]:
        hdr = await self.reader.readexactly(2)
        op = hdr[0] & 0x0F
        masked = hdr[1] & 0x80
        ln = hdr[1] & 0x7F
        if ln == 126:
            (ln,) = struct.unpack(">H", await self.reader.readexactly(2))
        elif ln == 127:
            (ln,) = struct.unpack(">Q", await self.reader.readexactly(8))
        mask = await self.reader.readexactly(4) if masked else None
        data = bytearray(await self.reader.readexactly(ln))
        if mask:
            for i in range(len(data)):
                data[i] ^= mask[i % 4]
        return op, bytes(data)
