"""Mempool (reference: ``mempool/``): the Mempool interface
(``mempool/mempool.go:26-100``), the CList FIFO implementation and the
disabled variant."""

from .mempool import Mempool, NopMempool, TxKey
from .clist_mempool import CListMempool

__all__ = ["Mempool", "NopMempool", "CListMempool", "TxKey"]
