"""Mempool interface + Nop variant (reference: ``mempool/mempool.go:26-100``,
``mempool/nop_mempool.go``)."""

from __future__ import annotations

from abc import ABC, abstractmethod

from ..crypto.tmhash import sum_sha256


def TxKey(tx: bytes) -> bytes:
    """Mempool identity of a tx — the same SHA-256 the tx merkle tree
    hashes, through the one crypto seam (``crypto/tmhash``) so a future
    batched tx-key path upgrades every caller at once."""
    return sum_sha256(tx)


class Mempool(ABC):
    @abstractmethod
    async def check_tx(self, tx: bytes): ...

    @abstractmethod
    def reap_max_bytes_max_gas(self, max_bytes: int,
                               max_gas: int) -> list[bytes]: ...

    @abstractmethod
    async def update(self, height: int, txs: list[bytes],
                     tx_results: list) -> None: ...

    @abstractmethod
    def lock(self): ...

    @abstractmethod
    def size(self) -> int: ...

    @abstractmethod
    async def flush(self) -> None: ...

    def size_bytes(self) -> int:
        """Total bytes of pooled txs (0 when unsupported)."""
        return 0

    def is_full(self, incoming_bytes: int = 0) -> bool:
        """Capacity probe across every bound the pool enforces."""
        return False

    def get_tx(self, key: bytes):
        """Body lookup by tx key — the content-addressed gossip reactor
        serves fetch requests from here.  None when absent/unsupported."""
        return None

    def txs_available(self):
        """Async event set when txs become available (may be unsupported)."""
        return None


class NopMempool(Mempool):
    """Disabled mempool for app-side mempools (``mempool/nop_mempool.go``)."""

    async def check_tx(self, tx):
        raise RuntimeError("mempool is disabled")

    def reap_max_bytes_max_gas(self, max_bytes, max_gas):
        return []

    async def update(self, height, txs, tx_results):
        pass

    def lock(self):
        import contextlib

        return contextlib.nullcontext()

    def size(self):
        return 0

    async def flush(self):
        pass
