"""FIFO mempool (reference: ``mempool/clist_mempool.go``).

The reference's concurrent linked list + mutexes collapse, under a
single-threaded asyncio runtime, to an ordered dict guarded by one async
lock for the update/recheck critical section.  Semantics kept: LRU cache
dedup (committed txs stay cached), post-block recheck of survivors through
the app's mempool connection, gas/byte-capped reaping, and an async
"txs available" signal for the consensus proposer
(``mempool/clist_mempool.go:241,307,383,497``).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass

from ..abci.client import ABCIClient
from ..libs import tracing
from .cache import LRUTxCache
from .mempool import Mempool, TxKey


@dataclass
class _MempoolTx:
    tx: bytes
    gas_wanted: int
    height: int          # height when first admitted
    seq: int = 0         # arrival order (assigned BEFORE the app
    #   round-trip, so concurrent admissions completing out of order
    #   still reap/gossip in arrival-FIFO order)


class TxRejectedError(Exception):
    def __init__(self, code: int, log: str):
        self.code = code
        self.log = log
        super().__init__(f"tx rejected: code={code} {log}")


class MempoolFullError(TxRejectedError):
    """Capacity rejection raised by the mempool itself (pool full).
    Its own type so the gossip reactor can tell OUR backpressure apart
    from an app rejection without parsing log strings the app
    controls."""


class _AdmissionGate:
    """Reader-writer gate for admission vs update.

    Readers are concurrent ``check_tx`` admissions: each spans an app
    round-trip, and serializing them on one lock lets a single slow
    CheckTx stall every other admission AND the gossip intake (the
    reference instead pipelines async CheckTx on a dedicated connection,
    ``mempool/clist_mempool.go:241``).  The writer is the executor's
    FinalizeBlock..Commit..update critical section (and flush), which
    must see no in-flight admissions.  Writer-preferring, so a stream of
    admissions can never starve block execution.

    Scope note: this removes the MEMPOOL's serialization.  How much
    actually overlaps depends on the app connection: SocketClient
    pipelines (futures matched by id), so concurrent admissions overlap
    transport latency and server queueing; LocalClient serializes on one
    lock because the ABCI app contract is serial per connection — the
    same bound the reference's mutex-guarded local client has."""

    def __init__(self):
        self._readers = 0
        self._writers_waiting = 0
        self._writer_active = False
        self._cond = asyncio.Condition()

    async def acquire_read(self):
        async with self._cond:
            while self._writer_active or self._writers_waiting:
                await self._cond.wait()
            self._readers += 1

    async def release_read(self):
        async with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    async def acquire_write(self):
        async with self._cond:
            self._writers_waiting += 1
            try:
                while self._writer_active or self._readers:
                    await self._cond.wait()
            finally:
                self._writers_waiting -= 1
            self._writer_active = True

    async def release_write(self):
        async with self._cond:
            self._writer_active = False
            self._cond.notify_all()

    def write_locked(self) -> "_WriteCtx":
        return _WriteCtx(self)


class _WriteCtx:
    __slots__ = ("_gate",)

    def __init__(self, gate: _AdmissionGate):
        self._gate = gate

    async def __aenter__(self):
        await self._gate.acquire_write()

    async def __aexit__(self, *exc):
        await self._gate.release_write()


class CListMempool(Mempool):
    def __init__(self, app_conn: ABCIClient, max_txs: int = 5000,
                 max_tx_bytes: int = 1024 * 1024, cache_size: int = 10_000,
                 keep_invalid_txs_in_cache: bool = False,
                 metrics_node: str = ""):
        self.app = app_conn
        self.max_txs = max_txs
        self.max_tx_bytes = max_tx_bytes
        self.cache = LRUTxCache(cache_size)
        self.keep_invalid = keep_invalid_txs_in_cache
        self._txs: dict[bytes, _MempoolTx] = {}      # arrival-seq FIFO
        self._gate = _AdmissionGate()
        self._arrival = 0                # next arrival sequence number
        from ..libs import metrics as _m

        # labeled per node: multi-node in-process ensembles (tier-1
        # tests) share the process-wide registry
        self._m_node = metrics_node
        self._m_size = _m.gauge("mempool_size",
                                "txs currently in the mempool")
        self._m_reap = _m.histogram(
            "mempool_reap_seconds",
            "proposal reap latency (mempool -> block tx list)",
            buckets=(0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025,
                     0.005, 0.01, 0.05, 0.1))
        self._m_recheck = _m.histogram(
            "mempool_recheck_seconds",
            "post-commit survivor recheck latency (whole pass)",
            buckets=(0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1,
                     0.5, 1, 5))
        self._txs_available = asyncio.Event()
        self._notified_available = False
        # edge callback fired once per height on the first admitted tx
        # (the reference's TxsAvailable channel consumer is consensus)
        self.on_txs_available = None
        self.height = 0

    # ------------------------------------------------------------- check_tx

    async def check_tx(self, tx: bytes) -> None:
        """Admit a tx (rpc broadcast_tx / p2p gossip entry).  Raises
        TxRejectedError on app rejection; silently ignores cache hits."""
        if len(tx) > self.max_tx_bytes:
            raise TxRejectedError(1, "tx too large")
        if len(self._txs) >= self.max_txs:
            raise MempoolFullError(1, "mempool is full")
        key = TxKey(tx)
        if not self.cache.push(key):
            return                       # seen before (maybe committed)
        # reader side of the gate: many admissions run their app
        # round-trips CONCURRENTLY (one slow CheckTx no longer stalls
        # every other admission); update/flush take the writer side
        await self._gate.acquire_read()
        try:
            self._arrival += 1
            seq = self._arrival          # before the await: arrival order
            res = await self.app.check_tx(tx, recheck=False)
            if not res.is_ok:
                if not self.keep_invalid:
                    self.cache.remove(key)
                raise TxRejectedError(res.code, res.log)
            if len(self._txs) >= self.max_txs:
                self.cache.remove(key)   # full while we were in flight
                raise MempoolFullError(1, "mempool is full")
            if key not in self._txs:
                self._txs[key] = _MempoolTx(tx, res.gas_wanted,
                                            self.height, seq)
                self._m_size.set(len(self._txs), node=self._m_node)
                self._notify_available()
        finally:
            await self._gate.release_read()

    def _notify_available(self):
        if self._txs and not self._notified_available:
            self._notified_available = True
            self._txs_available.set()
            if self.on_txs_available is not None:
                self.on_txs_available()

    def txs_available(self) -> asyncio.Event:
        return self._txs_available

    # --------------------------------------------------------------- reaping

    def _ordered(self) -> list:
        """Items in arrival order.  Insertion order usually IS arrival
        order; it diverges only when concurrent admissions complete out
        of order, so sort lazily (timsort on nearly-sorted is ~O(n))."""
        items = list(self._txs.values())
        for a, b in zip(items, items[1:]):
            if a.seq > b.seq:
                return sorted(items, key=lambda i: i.seq)
        return items

    def reap_max_bytes_max_gas(self, max_bytes: int,
                               max_gas: int) -> list[bytes]:
        t0 = time.perf_counter()
        out, total_bytes, total_gas = [], 0, 0
        for item in self._ordered():
            total_bytes += len(item.tx)
            if max_bytes >= 0 and total_bytes > max_bytes:
                break
            total_gas += item.gas_wanted
            if max_gas >= 0 and total_gas > max_gas:
                break
            out.append(item.tx)
        dt = time.perf_counter() - t0
        self._m_reap.observe(dt, node=self._m_node)
        tracing.event("mempool", "reap", node=self._m_node, txs=len(out),
                      pool=len(self._txs), dur_us=int(dt * 1e6))
        return out

    def reap_max_txs(self, n: int) -> list[bytes]:
        return [item.tx for item in self._ordered()[:n]]

    # ---------------------------------------------------------------- update

    def lock(self):
        """The executor holds this across FinalizeBlock-Commit-update
        (state/execution.go:295,391-460): the writer side of the
        admission gate — exclusive against in-flight check_tx readers."""
        return self._gate.write_locked()

    async def update(self, height: int, txs: list[bytes],
                     tx_results: list) -> None:
        """Remove committed txs, keep them cached, recheck survivors.
        Caller must hold lock() (like the reference's Lock/Update contract)."""
        self.height = height
        self._notified_available = False
        self._txs_available.clear()
        for i, tx in enumerate(txs):
            key = TxKey(tx)
            ok = i >= len(tx_results) or tx_results[i].is_ok
            if ok:
                self.cache.push(key)     # committed txs stay in cache
            elif not self.keep_invalid:
                self.cache.remove(key)
            self._txs.pop(key, None)
        # recheck survivors against the post-block app state
        t0 = time.perf_counter()
        rechecked = dropped = 0
        for key in list(self._txs.keys()):
            item = self._txs.get(key)
            if item is None:
                continue
            rechecked += 1
            res = await self.app.check_tx(item.tx, recheck=True)
            if not res.is_ok:
                del self._txs[key]
                dropped += 1
                if not self.keep_invalid:
                    self.cache.remove(key)
        if rechecked:
            dt = time.perf_counter() - t0
            self._m_recheck.observe(dt, node=self._m_node)
            tracing.event("mempool", "recheck", node=self._m_node,
                          height=height, rechecked=rechecked,
                          dropped=dropped, dur_us=int(dt * 1e6))
        self._m_size.set(len(self._txs), node=self._m_node)
        if self._txs:
            self._notify_available()

    def size(self) -> int:
        return len(self._txs)

    def size_bytes(self) -> int:
        return sum(len(i.tx) for i in self._txs.values())

    async def flush(self) -> None:
        async with self._gate.write_locked():
            self._txs.clear()
            self._m_size.set(0, node=self._m_node)
            self.cache.reset()
            self._txs_available.clear()
            self._notified_available = False

    def contents(self) -> list[bytes]:
        """Iteration snapshot for the gossip reactor (arrival order)."""
        return [i.tx for i in self._ordered()]
