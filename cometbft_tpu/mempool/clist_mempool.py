"""Sharded FIFO mempool (reference: ``mempool/clist_mempool.go``).

The reference's concurrent linked list + mutexes collapse, under a
single-threaded asyncio runtime, to tx maps guarded by admission gates.
Semantics kept: LRU cache dedup (committed txs stay cached), post-block
recheck of survivors through the app's mempool connection, gas/byte-capped
reaping, and an async "txs available" signal for the consensus proposer
(``mempool/clist_mempool.go:241,307,383,497``).

Since r16 the pool is **sharded by tx-hash prefix**: each shard owns its
tx map, running byte total, and admission gate, so concurrent CheckTx
admissions (and the post-block recheck) parallelize across shards instead
of serializing on one critical section.  A process-global arrival
sequence preserves proposer FIFO — reaping merges the shards by ``seq``,
so the block a proposer builds is identical to the single-dict pool's.

The app round trip is **coalesced**: a latency-bounded per-shard batcher
(same window/size-flush design as ``crypto/scheduler.py``, with the same
compile-bucket snapping so a size-flushed burst matches a batch shape the
verification pipeline has already compiled) turns K concurrent admissions
into one pipelined burst of CheckTx requests.  Where the app's tx
validation routes signature checks through the ``VerificationScheduler``,
the burst arrives inside one coalescing window and verifies as one
micro-batch instead of K scalar multiplications.  ``update()``'s recheck
is the same move applied to survivors: all CheckTx requests of a chunk
fire into the pipeline together and the per-item verdicts demux, instead
of one awaited round trip per tx.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass

from ..abci.client import ABCIClient
from ..libs import tracing
from .cache import LRUTxCache
from .mempool import Mempool, TxKey

DEFAULT_SHARDS = 4
DEFAULT_MAX_TXS_BYTES = 1 << 30          # reference config default: 1 GiB
DEFAULT_COALESCE_MS = 1.0
DEFAULT_COALESCE_MAX = 64


@dataclass
class _MempoolTx:
    tx: bytes
    gas_wanted: int
    height: int          # height when first admitted
    seq: int = 0         # arrival order (assigned BEFORE the app
    #   round-trip, so concurrent admissions completing out of order
    #   still reap/gossip in arrival-FIFO order)
    key: bytes = b""     # TxKey(tx), kept so the gossip walk never
    #   re-hashes the pool (it used to sha256 every tx per peer per pass)


class TxRejectedError(Exception):
    def __init__(self, code: int, log: str):
        self.code = code
        self.log = log
        super().__init__(f"tx rejected: code={code} {log}")


class MempoolFullError(TxRejectedError):
    """Capacity rejection raised by the mempool itself (pool full).
    Its own type so the gossip reactor can tell OUR backpressure apart
    from an app rejection without parsing log strings the app
    controls."""


class _AdmissionGate:
    """Reader-writer gate for admission vs update (one per shard).

    Readers are concurrent ``check_tx`` admissions: each spans an app
    round-trip, and serializing them on one lock lets a single slow
    CheckTx stall every other admission AND the gossip intake (the
    reference instead pipelines async CheckTx on a dedicated connection,
    ``mempool/clist_mempool.go:241``).  The writer is the executor's
    FinalizeBlock..Commit..update critical section (and flush), which
    must see no in-flight admissions.  Writer-preferring, so a stream of
    admissions can never starve block execution.

    Scope note: this removes the MEMPOOL's serialization.  How much
    actually overlaps depends on the app connection: SocketClient
    pipelines (futures matched by id), so concurrent admissions overlap
    transport latency and server queueing; LocalClient serializes on one
    lock because the ABCI app contract is serial per connection — the
    same bound the reference's mutex-guarded local client has."""

    def __init__(self):
        self._readers = 0
        self._writers_waiting = 0
        self._writer_active = False
        self._cond = asyncio.Condition()

    async def acquire_read(self):
        async with self._cond:
            while self._writer_active or self._writers_waiting:
                await self._cond.wait()
            self._readers += 1

    async def release_read(self):
        async with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    async def acquire_write(self):
        async with self._cond:
            self._writers_waiting += 1
            try:
                while self._writer_active or self._readers:
                    await self._cond.wait()
            finally:
                self._writers_waiting -= 1
            self._writer_active = True

    async def release_write(self):
        async with self._cond:
            self._writer_active = False
            self._cond.notify_all()


class _AllShardsWriteCtx:
    """The executor's critical section: the writer side of EVERY shard's
    gate, acquired in shard order (one fixed order — no lock cycles) and
    released in reverse."""

    __slots__ = ("_shards",)

    def __init__(self, shards: "list[_Shard]"):
        self._shards = shards

    async def __aenter__(self):
        acquired = 0
        try:
            for shard in self._shards:
                await shard.gate.acquire_write()
                acquired += 1
        except BaseException:
            # partial acquire (cancelled while waiting on shard k's
            # in-flight admissions): __aexit__ never runs when
            # __aenter__ raises, so release what we hold or every
            # later check_tx on those shards wedges forever
            for shard in reversed(self._shards[:acquired]):
                await shard.gate.release_write()
            raise

    async def __aexit__(self, *exc):
        for shard in reversed(self._shards):
            await shard.gate.release_write()


class _CheckTxCoalescer:
    """Latency-bounded CheckTx batcher — ``crypto/scheduler.py``'s
    window/size-flush design applied to app round trips.  Each shard
    owns one: requests park behind a future until either the oldest has
    waited ``window_s`` or ``max_lanes`` are pending, then the whole
    burst fires into the app connection CONCURRENTLY (SocketClient
    pipelines it as one wire burst; LocalClient drains it back-to-back
    without yielding to per-tx callers in between) and per-item results
    demux to the awaiting admissions.  An app whose CheckTx routes
    signature checks through the ``VerificationScheduler`` sees the
    burst inside one coalescing window — one verify micro-batch, not
    ``max_lanes`` single scalar multiplications."""

    __slots__ = ("app", "window_s", "max_lanes", "_pending", "_timer",
                 "_tasks", "_occ_hist")

    def __init__(self, app: ABCIClient, window_s: float, max_lanes: int,
                 occ_hist=None):
        self.app = app
        self.window_s = max(0.0, float(window_s))
        from ..crypto.plan import snap_lane_cap

        # snap DOWN to a crypto/batch compile bucket: a size-flushed
        # burst whose sig checks reach the VerificationScheduler fills a
        # batch shape XLA has already compiled instead of forcing a new
        # one
        self.max_lanes = snap_lane_cap(max_lanes)
        self._pending: list[tuple[bytes, bool, asyncio.Future]] = []
        self._timer: asyncio.TimerHandle | None = None
        self._tasks: set[asyncio.Task] = set()
        self._occ_hist = occ_hist

    async def check(self, tx: bytes, recheck: bool = False):
        """One coalesced CheckTx round trip (returns CheckTxResponse)."""
        if self.window_s <= 0:          # coalescing disabled: direct
            return await self.app.check_tx(tx, recheck=recheck)
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._pending.append((tx, recheck, fut))
        if len(self._pending) >= self.max_lanes:
            self._flush()
        elif self._timer is None:
            self._timer = loop.call_later(self.window_s, self._flush)
        return await fut

    def _flush(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if not self._pending:
            return
        batch, self._pending = self._pending, []
        if self._occ_hist is not None:
            self._occ_hist.observe(len(batch))
        task = asyncio.ensure_future(self._dispatch(batch))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _dispatch(self, batch) -> None:
        results = await asyncio.gather(
            *(self.app.check_tx(tx, recheck=rc) for tx, rc, _ in batch),
            return_exceptions=True)
        for (_, _, fut), res in zip(batch, results):
            if fut.done():              # caller gone (cancelled await)
                continue
            if isinstance(res, BaseException):
                fut.set_exception(res)
            else:
                fut.set_result(res)

    def drain(self) -> None:
        """Flush whatever is parked (update() about to wait on the
        writer gates: parked admissions hold reader slots and would
        deadlock the critical section if their window timer were the
        only thing that ever fired them ... it does fire, but draining
        eagerly keeps the writer wait bounded by the app RTT, not the
        window)."""
        self._flush()


class _Shard:
    """One admission shard: its own tx map, running byte total, gate,
    and CheckTx coalescer."""

    __slots__ = ("index", "txs", "bytes", "gate", "checker")

    def __init__(self, index: int, app: ABCIClient, window_s: float,
                 max_lanes: int, occ_hist=None):
        self.index = index
        self.txs: dict[bytes, _MempoolTx] = {}
        self.bytes = 0
        self.gate = _AdmissionGate()
        self.checker = _CheckTxCoalescer(app, window_s, max_lanes,
                                         occ_hist=occ_hist)

    def ordered(self) -> list[_MempoolTx]:
        """Shard items in arrival order.  Insertion order usually IS
        arrival order; it diverges only when concurrent admissions
        complete out of order, so sort lazily (timsort on nearly-sorted
        is ~O(n))."""
        items = list(self.txs.values())
        for a, b in zip(items, items[1:]):
            if a.seq > b.seq:
                items.sort(key=lambda i: i.seq)
                break
        return items


class CListMempool(Mempool):
    def __init__(self, app_conn: ABCIClient, max_txs: int = 5000,
                 max_tx_bytes: int = 1024 * 1024, cache_size: int = 10_000,
                 keep_invalid_txs_in_cache: bool = False,
                 metrics_node: str = "", shards: int = DEFAULT_SHARDS,
                 max_txs_bytes: int = DEFAULT_MAX_TXS_BYTES,
                 coalesce_ms: float = DEFAULT_COALESCE_MS,
                 coalesce_max: int = DEFAULT_COALESCE_MAX,
                 recheck: bool = True):
        self.app = app_conn
        self.max_txs = max_txs
        self.max_tx_bytes = max_tx_bytes
        self.max_txs_bytes = max(0, int(max_txs_bytes))
        self.recheck = recheck
        self.cache = LRUTxCache(cache_size)
        self.keep_invalid = keep_invalid_txs_in_cache
        self._arrival = 0                # next arrival sequence number
        self._size = 0                   # live txs across shards (O(1))
        self._bytes = 0                  # live tx bytes across shards (O(1))
        from ..libs import metrics as _m

        # labeled per node: multi-node in-process ensembles (tier-1
        # tests) share the process-wide registry
        self._m_node = metrics_node
        self._m_size = _m.gauge("mempool_size",
                                "txs currently in the mempool")
        self._m_bytes = _m.gauge("mempool_size_bytes",
                                 "bytes of txs currently in the mempool")
        self._m_shard = _m.gauge("mempool_shard_txs",
                                 "txs currently in one mempool shard")
        self._m_admit = _m.histogram(
            "mempool_admission_seconds",
            "CheckTx admission latency (entry -> admitted/rejected), "
            "including the coalescing window and the app round trip",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1, 2.5))
        self._m_coalesce = _m.histogram(
            "mempool_coalesce_lanes",
            "CheckTx burst occupancy at coalescer flush (txs per burst)",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256))
        self._m_reap = _m.histogram(
            "mempool_reap_seconds",
            "proposal reap latency (mempool -> block tx list)",
            buckets=(0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025,
                     0.005, 0.01, 0.05, 0.1))
        self._m_recheck = _m.histogram(
            "mempool_recheck_seconds",
            "post-commit survivor recheck latency (whole pass)",
            buckets=(0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1,
                     0.5, 1, 5))
        self._admit_b = self._m_admit.bind(node=metrics_node)
        coalesce_b = self._m_coalesce.bind(node=metrics_node)
        self.n_shards = max(1, int(shards))
        self._shards = [
            _Shard(i, app_conn, coalesce_ms / 1e3, coalesce_max,
                   occ_hist=coalesce_b)
            for i in range(self.n_shards)]
        self._shard_g = [self._m_shard.bind(node=metrics_node, shard=str(i))
                         for i in range(self.n_shards)]
        # recheck chunk: how many survivor CheckTx requests fire into
        # the pipeline per gather (bounds task fan-out at a 1M-tx
        # backlog while keeping each chunk a multiple of the verify
        # micro-batch shape — several scheduler flushes pipeline inside
        # one chunk, so the batch worker never idles at a barrier)
        from ..crypto.plan import snap_lane_cap

        self._recheck_chunk = snap_lane_cap(
            max(256, 4 * coalesce_max * self.n_shards))
        self._txs_available = asyncio.Event()
        self._notified_available = False
        # edge callback fired once per height on the first admitted tx
        # (the reference's TxsAvailable channel consumer is consensus)
        self.on_txs_available = None
        # removal hook: the gossip reactor prunes its per-tx maps
        # (senders, announcers) when txs leave the pool
        self.on_txs_removed = None
        self.height = 0

    # ------------------------------------------------------------ sharding

    def _shard_of(self, key: bytes) -> "_Shard":
        """Shard routing by tx-hash prefix: the key IS a sha256 digest,
        so its first bytes are uniform — no extra hashing needed."""
        return self._shards[int.from_bytes(key[:2], "big") % self.n_shards]

    # ------------------------------------------------------------- check_tx

    def is_full(self, incoming_bytes: int = 0) -> bool:
        """True when the pool cannot take ``incoming_bytes`` more: BOTH
        capacity axes (tx count and bytes).  The gossip reactor's shed
        paths consult this — byte-full must shed exactly like
        count-full."""
        if self._size >= self.max_txs:
            return True
        return (self.max_txs_bytes > 0
                and self._bytes + incoming_bytes > self.max_txs_bytes)


    async def check_tx(self, tx: bytes) -> None:
        """Admit a tx (rpc broadcast_tx / p2p gossip entry).  Raises
        TxRejectedError on app rejection; silently ignores cache hits."""
        t0 = time.perf_counter()
        if len(tx) > self.max_tx_bytes:
            raise TxRejectedError(1, "tx too large")
        if self.is_full(len(tx)):
            raise MempoolFullError(1, "mempool is full")
        key = TxKey(tx)
        if not self.cache.push(key):
            return                       # seen before (maybe committed)
        shard = self._shard_of(key)
        # reader side of the shard's gate: many admissions run their app
        # round-trips CONCURRENTLY (one slow CheckTx no longer stalls
        # every other admission); update/flush take the writer side
        await shard.gate.acquire_read()
        try:
            self._arrival += 1
            seq = self._arrival          # before the await: arrival order
            res = await shard.checker.check(tx, recheck=False)
            if not res.is_ok:
                if not self.keep_invalid:
                    self.cache.remove(key)
                raise TxRejectedError(res.code, res.log)
            if self.is_full(len(tx)):
                self.cache.remove(key)   # full while we were in flight
                raise MempoolFullError(1, "mempool is full")
            if key not in shard.txs:
                shard.txs[key] = _MempoolTx(tx, res.gas_wanted,
                                            self.height, seq, key)
                shard.bytes += len(tx)
                self._size += 1
                self._bytes += len(tx)
                self._set_gauges(shard)
                self._notify_available()
        finally:
            await shard.gate.release_read()
            self._admit_b.observe(time.perf_counter() - t0)

    def _set_gauges(self, shard: "_Shard | None" = None) -> None:
        self._m_size.set(self._size, node=self._m_node)
        self._m_bytes.set(self._bytes, node=self._m_node)
        if shard is not None:
            self._shard_g[shard.index].set(len(shard.txs))

    def _notify_available(self):
        if self._size and not self._notified_available:
            self._notified_available = True
            self._txs_available.set()
            if self.on_txs_available is not None:
                self.on_txs_available()

    def txs_available(self) -> asyncio.Event:
        return self._txs_available

    # --------------------------------------------------------------- reaping

    def _ordered(self) -> list[_MempoolTx]:
        """Items in global arrival order: per-shard FIFO lists merged by
        arrival seq (each shard list is sorted, so this is a k-way
        merge, not a full sort)."""
        per_shard = [s.ordered() for s in self._shards if s.txs]
        if not per_shard:
            return []
        if len(per_shard) == 1:
            return per_shard[0]
        import heapq

        return list(heapq.merge(*per_shard, key=lambda i: i.seq))

    def reap_max_bytes_max_gas(self, max_bytes: int,
                               max_gas: int) -> list[bytes]:
        t0 = time.perf_counter()
        out, total_bytes, total_gas = [], 0, 0
        for item in self._ordered():
            total_bytes += len(item.tx)
            if max_bytes >= 0 and total_bytes > max_bytes:
                break
            total_gas += item.gas_wanted
            if max_gas >= 0 and total_gas > max_gas:
                break
            out.append(item.tx)
        dt = time.perf_counter() - t0
        self._m_reap.observe(dt, node=self._m_node)
        tracing.event("mempool", "reap", node=self._m_node, txs=len(out),
                      pool=self._size, dur_us=int(dt * 1e6))
        return out

    def reap_max_txs(self, n: int) -> list[bytes]:
        return [item.tx for item in self._ordered()[:n]]

    # ---------------------------------------------------------------- update

    def lock(self):
        """The executor holds this across FinalizeBlock-Commit-update
        (state/execution.go:295,391-460): the writer side of EVERY
        shard's admission gate — exclusive against in-flight check_tx
        readers.  Parked coalescer windows are drained first so the
        writer wait is bounded by the app RTT, not the window timer."""
        for shard in self._shards:
            shard.checker.drain()
        return _AllShardsWriteCtx(self._shards)

    def _remove(self, key: bytes, removed: list[bytes]) -> "_MempoolTx | None":
        shard = self._shard_of(key)
        item = shard.txs.pop(key, None)
        if item is not None:
            shard.bytes -= len(item.tx)
            self._size -= 1
            self._bytes -= len(item.tx)
            removed.append(key)
        return item

    async def update(self, height: int, txs: list[bytes],
                     tx_results: list) -> None:
        """Remove committed txs, keep them cached, recheck survivors.
        Caller must hold lock() (like the reference's Lock/Update
        contract)."""
        self.height = height
        self._notified_available = False
        self._txs_available.clear()
        removed: list[bytes] = []
        for i, tx in enumerate(txs):
            key = TxKey(tx)
            ok = i >= len(tx_results) or tx_results[i].is_ok
            if ok:
                self.cache.push(key)     # committed txs stay in cache
            elif not self.keep_invalid:
                self.cache.remove(key)
            self._remove(key, removed)
        # batched recheck of survivors against the post-block app state:
        # fire a chunk of CheckTx requests into the pipeline together
        # and demux per-item verdicts, instead of one awaited round trip
        # per tx (the serial loop was the recheck bottleneck at scale)
        t0 = time.perf_counter()
        rechecked = dropped = 0
        try:
            if self.recheck and self._size:
                survivors: list[tuple[bytes, _MempoolTx]] = []
                for shard in self._shards:
                    survivors.extend(shard.txs.items())
                chunk = self._recheck_chunk
                for lo in range(0, len(survivors), chunk):
                    part = survivors[lo:lo + chunk]
                    results = await asyncio.gather(
                        *(self.app.check_tx(item.tx, recheck=True)
                          for _, item in part),
                        return_exceptions=True)
                    err: BaseException | None = None
                    for (key, item), res in zip(part, results):
                        if isinstance(res, BaseException):
                            # infra failure, not a verdict: keep the
                            # tx, surface the error after demuxing
                            # batchmates
                            err = err or res
                            continue
                        rechecked += 1
                        if not res.is_ok:
                            self._remove(key, removed)
                            dropped += 1
                            if not self.keep_invalid:
                                self.cache.remove(key)
                    if err is not None:
                        raise err
        finally:
            # a mid-pass infra error must not leave stale gauges or
            # unpruned gossip bookkeeping for txs ALREADY removed
            if rechecked:
                dt = time.perf_counter() - t0
                self._m_recheck.observe(dt, node=self._m_node)
                tracing.event("mempool", "recheck", node=self._m_node,
                              height=height, rechecked=rechecked,
                              dropped=dropped, dur_us=int(dt * 1e6))
            for shard in self._shards:
                self._shard_g[shard.index].set(len(shard.txs))
            self._set_gauges()
            if removed and self.on_txs_removed is not None:
                self.on_txs_removed(removed)
            if self._size:
                self._notify_available()

    def size(self) -> int:
        return self._size

    def size_bytes(self) -> int:
        """O(1): a running total maintained on admit/remove (was a full
        pool walk per call)."""
        return self._bytes

    async def flush(self) -> None:
        for shard in self._shards:      # same RTT-bounded writer wait
            shard.checker.drain()       # contract as lock()
        async with _AllShardsWriteCtx(self._shards):
            removed = [k for s in self._shards for k in s.txs]
            for i, shard in enumerate(self._shards):
                shard.txs.clear()
                shard.bytes = 0
                self._shard_g[i].set(0)
            self._size = 0
            self._bytes = 0
            self._set_gauges()
            self.cache.reset()
            self._txs_available.clear()
            self._notified_available = False
            if removed and self.on_txs_removed is not None:
                self.on_txs_removed(removed)

    def contents(self) -> list[bytes]:
        """Iteration snapshot for the gossip reactor (arrival order)."""
        return [i.tx for i in self._ordered()]

    def items(self) -> list[tuple[bytes, bytes]]:
        """``(tx key, tx)`` snapshot in arrival order — the gossip
        reactor's walk, WITHOUT re-hashing every tx per peer per pass
        (keys ride on the pool entries)."""
        return [(i.key, i.tx) for i in self._ordered()]

    def get_tx(self, key: bytes) -> bytes | None:
        """Body lookup by tx key (content-addressed gossip serves fetch
        requests from here)."""
        item = self._shard_of(key).txs.get(key)
        return None if item is None else item.tx

    def stats(self) -> dict:
        """Operator/bench surface."""
        return {
            "size": self._size,
            "size_bytes": self._bytes,
            "shards": [len(s.txs) for s in self._shards],
            "arrival_seq": self._arrival,
        }
