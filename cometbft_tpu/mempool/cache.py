"""Fixed-size LRU cache of tx keys (reference: ``mempool/cache.go``)."""

from __future__ import annotations

from collections import OrderedDict


class LRUTxCache:
    def __init__(self, size: int = 10_000):
        self.capacity = size
        self._map: OrderedDict[bytes, None] = OrderedDict()

    def push(self, key: bytes) -> bool:
        """Returns False if the key was already present."""
        if key in self._map:
            self._map.move_to_end(key)
            return False
        self._map[key] = None
        if len(self._map) > self.capacity:
            self._map.popitem(last=False)
        return True

    def remove(self, key: bytes) -> None:
        self._map.pop(key, None)

    def has(self, key: bytes) -> bool:
        """Membership probe, refreshing recency: the announce-dedup path
        consults this for every announced hash, and a tx that keeps
        being announced (recently committed, still flooding the net)
        should stay cached — evicting it would buy the next announce a
        pointless fetch round trip."""
        if key in self._map:
            self._map.move_to_end(key)
            return True
        return False

    def __len__(self) -> int:
        return len(self._map)

    def reset(self) -> None:
        self._map.clear()
