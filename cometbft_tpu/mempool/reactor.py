"""Mempool reactor: transaction gossip (reference: ``mempool/reactor.go:22,
137,198`` — per-peer broadcastTxRoutine walking the clist).

Each peer gets one gossip task that walks the mempool's FIFO contents and
sends txs the peer hasn't been seen to have (sender-set dedup: a tx is not
echoed back to the peer that delivered it, ``mempool/reactor.go`` senders
check).  Received txs enter the mempool through the normal async CheckTx
pipeline."""

from __future__ import annotations

import asyncio
import functools

from ..libs import aio, clock

import msgpack

from ..p2p.reactor import ChannelDescriptor, Reactor
from .clist_mempool import CListMempool, MempoolFullError, TxRejectedError
from .mempool import TxKey

MEMPOOL_CHANNEL = 0x30
GOSSIP_SLEEP = 0.02


@functools.cache
def _full_skips_metric():
    from ..libs import metrics as _m

    return _m.counter(
        "mempool_gossip_full_skips_total",
        "gossiped txs dropped WITHOUT CheckTx because the mempool was "
        "full (backpressure: a full pool must not buy every flooded tx "
        "an app round-trip)")


class MempoolReactor(Reactor):
    def __init__(self, mempool: CListMempool,
                 gossip_sleep: float = GOSSIP_SLEEP):
        super().__init__()
        self.mempool = mempool
        self.gossip_sleep = gossip_sleep
        self._peer_tasks: dict[str, asyncio.Task] = {}
        # tx hash -> set of peer ids that sent it to us (dedup/no-echo)
        self._senders: dict[bytes, set[str]] = {}
        self._m_full_skips = _full_skips_metric()

    def get_channels(self):
        return [ChannelDescriptor(MEMPOOL_CHANNEL, priority=5,
                                  send_queue_capacity=128, name="mempool")]

    def add_peer(self, peer) -> None:
        self._peer_tasks[peer.id] = asyncio.create_task(
            self._broadcast_tx_routine(peer))

    def remove_peer(self, peer, reason=None) -> None:
        task = self._peer_tasks.pop(peer.id, None)
        if task is not None:
            task.cancel()

    async def stop(self) -> None:
        for task in self._peer_tasks.values():
            task.cancel()
        self._peer_tasks.clear()

    def receive(self, channel_id: int, peer, msg: bytes) -> None:
        d = msgpack.unpackb(msg, raw=False)
        txs = d.get("txs", [])
        if txs and self.mempool.size() >= self.mempool.max_txs:
            # overload shedding: a full mempool drops gossiped txs at
            # the door instead of spawning a CheckTx app round-trip per
            # tx just to learn "mempool is full" (RPC submitters still
            # get the explicit rejection)
            self._m_full_skips.inc(len(txs),
                                   node=getattr(self.mempool, "_m_node", ""))
            return
        for tx in txs:
            self._senders.setdefault(TxKey(tx), set()).add(peer.id)
            aio.spawn(self._check_tx(tx, peer.id))

    async def _check_tx(self, tx: bytes, peer_id: str = "") -> None:
        try:
            await self.mempool.check_tx(tx)
        except MempoolFullError:
            pass        # our capacity problem, not the sender's
        except TxRejectedError as e:
            # app-rejected gossip is (feather-weight) peer misbehavior
            if peer_id and self.switch is not None and \
                    hasattr(self.switch, "report_peer"):
                self.switch.report_peer(peer_id, "invalid_tx",
                                        detail=e.log[:80])
        except Exception:
            pass

    async def _broadcast_tx_routine(self, peer) -> None:
        """Walk the mempool forever, sending each tx the peer didn't give
        us (broadcastTxRoutine reactor.go:198)."""
        sent: set[bytes] = set()
        try:
            while True:
                progressed = False
                for tx in self.mempool.contents():
                    key = TxKey(tx)
                    if key in sent:
                        continue
                    if peer.id in self._senders.get(key, ()):
                        sent.add(key)       # peer already has it
                        continue
                    if peer.send(MEMPOOL_CHANNEL, msgpack.packb(
                            {"txs": [tx]}, use_bin_type=True)):
                        sent.add(key)
                        progressed = True
                if not progressed:
                    await clock.sleep(self.gossip_sleep)
                # bound the sent-set: drop keys no longer in the mempool
                if len(sent) > 10000:
                    live = {TxKey(t) for t in self.mempool.contents()}
                    sent &= live
        except asyncio.CancelledError:
            raise
        except Exception:
            pass
