"""Mempool reactor: transaction gossip (reference: ``mempool/reactor.go:22,
137,198`` — per-peer broadcastTxRoutine walking the clist).

Two wire dialects share the mempool channel:

- **full-body** (the original protocol): ``{"txs": [tx, ...]}`` frames.
  Kept as the interop fallback — and upgraded to pack MANY txs per
  msgpack frame up to a byte budget instead of one ``peer.send`` per tx.
- **content-addressed** (r16): peers that greet with ``{"hi": 1}`` get
  announcements ``{"ann": [h, ...]}`` (32-byte tx keys), fetch missing
  bodies with ``{"req": [h, ...]}``, and receive them as ``{"txs": ...}``
  frames.  A tx the peer already holds (it announced it, sent it, or we
  saw their announce) costs 32 bytes on the wire instead of the body —
  the PR 4 verified-vote dedup idea applied to tx gossip.

A reactor that never sends ``hi`` (the pre-r16 code, or
``gossip_mode="full"``) keeps receiving full bodies: an old peer's
``receive`` reads ``d.get("txs", [])`` and silently ignores the new
keys, so mixed-version nets interoperate without negotiation.

Fetch discipline: one in-flight request per tx key, tracked with a
deadline; on timeout the key is re-requested from another announcer (and
the timeout counted).  Fetched bodies that fail CheckTx score
``invalid_tx`` on the sender through the PR 9 reputation ledger —
announcing garbage does not become a free amplification channel.

The per-tx bookkeeping maps (``_senders``, ``_announcers``) are bounded
and pruned on every mempool update/removal via
``mempool.on_txs_removed`` — entries used to pin a set per gossiped tx
forever."""

from __future__ import annotations

import asyncio
import functools

from ..libs import aio, clock

import msgpack

from ..p2p.reactor import ChannelDescriptor, Reactor
from .clist_mempool import CListMempool, MempoolFullError, TxRejectedError
from .mempool import TxKey

MEMPOOL_CHANNEL = 0x30
GOSSIP_SLEEP = 0.02
TX_KEY_LEN = 32
ANN_BATCH = 512                  # hashes per announce frame (16 KiB)
DEFAULT_BATCH_BYTES = 64 * 1024  # full-body / fetch-response frame budget
DEFAULT_FETCH_TIMEOUT_S = 2.0
SENT_SET_BOUND = 10000


@functools.cache
def _reactor_metrics():
    from ..libs import metrics as _m

    return (
        _m.counter(
            "mempool_gossip_full_skips_total",
            "gossiped txs dropped WITHOUT CheckTx because the mempool "
            "was full (backpressure: a full pool must not buy every "
            "flooded tx an app round-trip)"),
        _m.counter("mempool_announce_total",
                   "tx hashes announced to peers"),
        _m.counter("mempool_announce_dedup_total",
                   "announced hashes we already held (bodies NOT "
                   "re-fetched: the content-addressing win)"),
        _m.counter("mempool_fetch_requests_total",
                   "tx bodies requested from an announcer"),
        _m.counter("mempool_fetch_fulfilled_total",
                   "requested tx bodies that arrived"),
        _m.counter("mempool_fetch_timeouts_total",
                   "fetch requests that timed out (re-requested from "
                   "another announcer when one is known)"),
        _m.counter("mempool_gossip_bytes_total",
                   "mempool-channel payload bytes sent, by kind "
                   "(ann/req/body)"),
    )


class _Fetch:
    """One in-flight body fetch: who we asked, when it expires, who we
    already tried (timeout -> re-request from a fresh announcer)."""

    __slots__ = ("peer_id", "deadline", "tried")

    def __init__(self, peer_id: str, deadline: float):
        self.peer_id = peer_id
        self.deadline = deadline
        self.tried: set[str] = {peer_id}


class MempoolReactor(Reactor):
    def __init__(self, mempool: CListMempool,
                 gossip_sleep: float = GOSSIP_SLEEP,
                 gossip_mode: str = "announce",
                 fetch_timeout_s: float = DEFAULT_FETCH_TIMEOUT_S,
                 batch_bytes: int = DEFAULT_BATCH_BYTES):
        super().__init__()
        self.mempool = mempool
        self.gossip_sleep = gossip_sleep
        self.gossip_mode = gossip_mode
        self.fetch_timeout_s = max(0.05, float(fetch_timeout_s))
        self.batch_bytes = max(1024, int(batch_bytes))
        self._peer_tasks: dict[str, asyncio.Task] = {}
        # tx hash -> set of peer ids KNOWN to hold the tx (sent it to us
        # or announced it): dedup/no-echo.  Bounded; pruned on removal.
        self._senders: dict[bytes, set[str]] = {}
        # tx hash -> announcers we have NOT fetched from yet (candidates
        # for timeout re-request).  Bounded; entries die on admission.
        self._announcers: dict[bytes, set[str]] = {}
        self._requests: dict[bytes, _Fetch] = {}     # in-flight fetches
        self._capable: set[str] = set()   # peers speaking announce/fetch
        self._sweep_task: asyncio.Task | None = None
        # bookkeeping bound: ~2 pools' worth of keys, floored so tiny
        # test pools don't thrash
        self._map_bound = max(4096, 2 * getattr(mempool, "max_txs", 5000))
        (self._m_full_skips, self._m_ann, self._m_dedup, self._m_req,
         self._m_fulfilled, self._m_timeouts, bytes_c) = _reactor_metrics()
        self._b_ann = bytes_c.bind(kind="ann")
        self._b_req = bytes_c.bind(kind="req")
        self._b_body = bytes_c.bind(kind="body")
        # per-INSTANCE tallies: the metrics registry is process-global
        # (scenario verdicts must be a pure function of the run, and a
        # bench must not read a previous node's totals)
        self.tallies = {"full_skips": 0, "announced": 0, "ann_dedup": 0,
                        "fetch_requests": 0, "fetch_fulfilled": 0,
                        "fetch_timeouts": 0, "bytes_ann": 0,
                        "bytes_req": 0, "bytes_body": 0}
        mempool.on_txs_removed = self._on_txs_removed

    def get_channels(self):
        return [ChannelDescriptor(MEMPOOL_CHANNEL, priority=5,
                                  send_queue_capacity=128, name="mempool")]

    # ------------------------------------------------------------ lifecycle

    def add_peer(self, peer) -> None:
        if self.gossip_mode == "announce":
            # capability hello: an old reactor reads d.get("txs", [])
            # and ignores this; a new one marks us announce-capable
            peer.send(MEMPOOL_CHANNEL,
                      msgpack.packb({"hi": 1}, use_bin_type=True))
        self._peer_tasks[peer.id] = asyncio.create_task(
            self._broadcast_tx_routine(peer))
        if self._sweep_task is None:
            self._sweep_task = aio.spawn(self._sweep_requests())

    def remove_peer(self, peer, reason=None) -> None:
        task = self._peer_tasks.pop(peer.id, None)
        if task is not None:
            task.cancel()
        self._capable.discard(peer.id)

    async def stop(self) -> None:
        for task in self._peer_tasks.values():
            task.cancel()
        self._peer_tasks.clear()
        if self._sweep_task is not None:
            self._sweep_task.cancel()
            self._sweep_task = None

    # ------------------------------------------------------------- pruning

    def _on_txs_removed(self, keys: list[bytes]) -> None:
        """Mempool update/flush removed txs: drop their gossip
        bookkeeping (the map entries used to live forever)."""
        for key in keys:
            self._senders.pop(key, None)
            self._announcers.pop(key, None)

    def _bounded_add(self, mapping: dict[bytes, set[str]], key: bytes,
                     peer_id: str) -> None:
        s = mapping.get(key)
        if s is None:
            while len(mapping) >= self._map_bound:
                # FIFO eviction — but never a key still IN THE POOL: its
                # no-echo entry is load-bearing (without it the routine
                # re-sends the tx to the peer that delivered it, exactly
                # under a junk-announce storm).  Live keys rotate to the
                # back instead; they are pruned on removal anyway, and
                # live keys < pool size < the bound, so a non-live entry
                # always exists.
                old = next(iter(mapping))
                if self.mempool.get_tx(old) is None:
                    mapping.pop(old)
                else:
                    mapping[old] = mapping.pop(old)
            mapping[key] = s = set()
        s.add(peer_id)

    def _send_req(self, peer, keys: list[bytes]) -> bool:
        """Send fetch frames (chunked at ANN_BATCH keys so one frame can
        never breach the channel's message-size limit) and install/
        refresh the in-flight tracking + counters for every key sent —
        the ONE copy of this bookkeeping: announce, timeout-retry, and
        backlog sweep all route here."""
        any_sent = False
        for lo in range(0, len(keys), ANN_BATCH):
            part = keys[lo:lo + ANN_BATCH]
            frame = msgpack.packb({"req": part}, use_bin_type=True)
            if not peer.send(MEMPOOL_CHANNEL, frame):
                break                   # queue full: the sweeper retries
            any_sent = True
            deadline = clock.monotonic() + self.fetch_timeout_s
            for h in part:
                fr = self._requests.get(h)
                if fr is None:
                    self._requests[h] = _Fetch(peer.id, deadline)
                else:
                    fr.peer_id = peer.id
                    fr.tried.add(peer.id)
                    fr.deadline = deadline
            self._m_req.inc(len(part))
            self.tallies["fetch_requests"] += len(part)
            self._b_req.inc(len(frame))
            self.tallies["bytes_req"] += len(frame)
        return any_sent

    # -------------------------------------------------------------- receive

    def receive(self, channel_id: int, peer, msg: bytes) -> None:
        d = msgpack.unpackb(msg, raw=False)
        if "hi" in d:
            self._capable.add(peer.id)
        ann = d.get("ann")
        if ann:
            self._on_announce(peer, ann)
        req = d.get("req")
        if req:
            self._on_request(peer, req)
        txs = d.get("txs", [])
        if txs:
            self._on_bodies(peer, txs)

    def _on_announce(self, peer, hashes) -> None:
        """Peer holds these txs.  Fetch the ones we miss (one in-flight
        request per key); remember every announcer for no-echo and for
        timeout re-requests."""
        self._capable.add(peer.id)
        want: list[bytes] = []
        seen: set[bytes] = set()         # dedup WITHIN the frame too: a
        # repeated hash must not inflate req bytes or the fetch counters
        full = self.mempool.is_full()    # BOTH capacity axes (bytes too)
        # intake cap (like _on_request): one fat announce frame must not
        # install tens of thousands of _Fetch entries in one call
        for h in hashes[:2 * ANN_BATCH]:
            if not isinstance(h, bytes) or len(h) != TX_KEY_LEN \
                    or h in seen:
                continue
            seen.add(h)
            self._bounded_add(self._senders, h, peer.id)
            if self.mempool.get_tx(h) is not None or self.mempool.cache.has(h):
                self._m_dedup.inc()
                self.tallies["ann_dedup"] += 1
                continue
            self._bounded_add(self._announcers, h, peer.id)
            if h in self._requests:
                continue                 # already fetching from someone
            if full:
                # overload shedding: a full pool must not buy a flooded
                # announcement a fetch round-trip it would drop anyway
                self._m_full_skips.inc(
                    node=getattr(self.mempool, "_m_node", ""))
                self.tallies["full_skips"] += 1
                continue
            want.append(h)
        if want:
            self._send_req(peer, want)

    def _on_request(self, peer, hashes) -> None:
        """Serve fetches from the pool, packing bodies up to the frame
        budget.  Deduped and capped per frame: a request repeating one
        hash of a big pooled tx must not buy len(req) copies of the
        body (amplification), only one."""
        batch: list[bytes] = []
        size = 0
        seen: set[bytes] = set()
        for h in hashes[:2 * ANN_BATCH]:
            if not isinstance(h, bytes) or h in seen:
                continue
            seen.add(h)
            tx = self.mempool.get_tx(h)
            if tx is None:
                continue                 # gone (committed/evicted): the
                #   requester's timeout re-request handles it
            if batch and size + len(tx) > self.batch_bytes:
                self._send_bodies(peer, batch)
                batch, size = [], 0
            batch.append(tx)
            size += len(tx)
        if batch:
            self._send_bodies(peer, batch)

    def _send_bodies(self, peer, txs: list[bytes]) -> bool:
        frame = msgpack.packb({"txs": txs}, use_bin_type=True)
        ok = peer.send(MEMPOOL_CHANNEL, frame)
        if ok:
            self._b_body.inc(len(frame))
            self.tallies["bytes_body"] += len(frame)
        return ok

    def _on_bodies(self, peer, txs) -> None:
        if self.mempool.is_full():
            # overload shedding: a full mempool drops gossiped txs at
            # the door instead of spawning a CheckTx app round-trip per
            # tx just to learn "mempool is full" (RPC submitters still
            # get the explicit rejection)
            self._m_full_skips.inc(len(txs),
                                   node=getattr(self.mempool, "_m_node", ""))
            self.tallies["full_skips"] += len(txs)
            for tx in txs:
                self._requests.pop(TxKey(tx), None)
            return
        for tx in txs:
            key = TxKey(tx)
            self._bounded_add(self._senders, key, peer.id)
            fr = self._requests.pop(key, None)
            if fr is not None:
                self._m_fulfilled.inc()
                self.tallies["fetch_fulfilled"] += 1
            self._announcers.pop(key, None)
            aio.spawn(self._check_tx(tx, peer.id))

    async def _check_tx(self, tx: bytes, peer_id: str = "") -> None:
        try:
            await self.mempool.check_tx(tx)
        except MempoolFullError:
            pass        # our capacity problem, not the sender's
        except TxRejectedError as e:
            # app-rejected gossip is (feather-weight) peer misbehavior —
            # this covers FETCHED bodies too: announcing garbage and
            # serving it on request scores exactly like pushing it
            if peer_id and self.switch is not None and \
                    hasattr(self.switch, "report_peer"):
                self.switch.report_peer(peer_id, "invalid_tx",
                                        detail=e.log[:80])
        except Exception:
            pass

    # ---------------------------------------------------------- fetch sweep

    async def _sweep_requests(self) -> None:
        """One reactor-wide timer: expire overdue fetches and re-request
        from another announcer (a peer that announced but never served
        must not be able to black-hole a tx)."""
        interval = max(0.05, self.fetch_timeout_s / 4)
        while True:
            await clock.sleep(interval)
            # per-TICK error containment: one bad peer.send (half-closed
            # transport, etc.) must not kill the reactor-wide sweeper —
            # with it dead, stale _requests entries block re-fetch of
            # their keys forever (receive skips hashes in _requests)
            try:
                self._sweep_backlog()
                if not self._requests:
                    continue
                now = clock.monotonic()
                expired = [(h, fr) for h, fr in self._requests.items()
                           if fr.deadline <= now]
                for h, fr in expired:
                    self._m_timeouts.inc()
                    self.tallies["fetch_timeouts"] += 1
                    retry = None
                    # sorted: announcer choice must not ride on set hash
                    # order (scenario replay is cross-process too)
                    for pid in sorted(self._announcers.get(h, ())):
                        if pid not in fr.tried and \
                                pid in self._peer_tasks:
                            retry = pid
                            break
                    if retry is None:
                        del self._requests[h]    # re-announce re-arms it
                        self._announcers.pop(h, None)
                        continue
                    peer = self._get_peer(retry)
                    if peer is None or not self._send_req(peer, [h]):
                        del self._requests[h]
            except asyncio.CancelledError:
                raise
            except Exception:
                continue

    def _sweep_backlog(self, cap: int = 256) -> None:
        """Announced keys with NO in-flight request (initial request
        send failed, or the pool was full when the announce arrived):
        fetch them now that there is room.  Bounded per sweep."""
        if not self._announcers or self.mempool.is_full():
            return
        want: list[bytes] = []
        for h in self._announcers:
            if h in self._requests:
                continue
            if self.mempool.get_tx(h) is not None or self.mempool.cache.has(h):
                continue
            want.append(h)
            if len(want) >= cap:
                break
        by_peer: dict[str, tuple[object, list[bytes]]] = {}
        for h in want:
            peer = None
            for pid in sorted(self._announcers.get(h, ())):
                if pid in self._peer_tasks:
                    peer = self._get_peer(pid)
                    if peer is not None:
                        break
            if peer is None:
                self._announcers.pop(h, None)    # no live announcer left
                continue
            by_peer.setdefault(peer.id, (peer, []))[1].append(h)
        for peer, keys in by_peer.values():      # one frame per peer,
            self._send_req(peer, keys)           # not one per key

    def _get_peer(self, peer_id: str):
        sw = self.switch
        if sw is None:
            return None
        return getattr(sw, "peers", {}).get(peer_id)

    # ------------------------------------------------------------ broadcast

    async def _broadcast_tx_routine(self, peer) -> None:
        """Walk the mempool forever (broadcastTxRoutine reactor.go:198).
        To an announce-capable peer: batched hash announcements.  To an
        old-protocol peer: full bodies, MANY per frame up to the byte
        budget (it used to be one tx per ``peer.send``)."""
        sent: set[bytes] = set()
        try:
            if self.gossip_mode == "announce":
                # capability grace: our hello and the peer's cross on
                # the wire, and the first walk racing the peer's "hi"
                # would ship the whole pool as full bodies — the exact
                # re-flood announcing exists to avoid.  A new-protocol
                # peer identifies itself within a round trip; an old
                # one just gets its first bodies a beat later.
                grace = clock.monotonic() + max(0.1, 4 * self.gossip_sleep)
                while peer.id not in self._capable and \
                        clock.monotonic() < grace:
                    await clock.sleep(self.gossip_sleep)
            while True:
                progressed = False
                announce = (self.gossip_mode == "announce"
                            and peer.id in self._capable)
                ann_batch: list[bytes] = []
                body_batch: list[bytes] = []
                body_keys: list[bytes] = []
                body_size = 0
                blocked = False
                for key, tx in self.mempool.items():
                    if key in sent:
                        continue
                    if peer.id in self._senders.get(key, ()):
                        sent.add(key)       # peer already has it
                        continue
                    if announce:
                        ann_batch.append(key)
                        if len(ann_batch) >= ANN_BATCH:
                            if self._send_ann(peer, ann_batch, sent):
                                progressed = True
                            else:
                                blocked = True
                                break
                            ann_batch = []
                    else:
                        if body_batch and \
                                body_size + len(tx) > self.batch_bytes:
                            if self._send_full(peer, body_batch,
                                               body_keys, sent):
                                progressed = True
                            else:
                                blocked = True
                                break
                            body_batch, body_keys, body_size = [], [], 0
                        body_batch.append(tx)
                        body_keys.append(key)
                        body_size += len(tx)
                if not blocked:
                    if ann_batch and self._send_ann(peer, ann_batch, sent):
                        progressed = True
                    if body_batch and self._send_full(peer, body_batch,
                                                      body_keys, sent):
                        progressed = True
                if not progressed:
                    await clock.sleep(self.gossip_sleep)
                # bound the sent-set: drop keys no longer in the mempool
                if len(sent) > SENT_SET_BOUND:
                    live = {k for k, _ in self.mempool.items()}
                    sent &= live
        except asyncio.CancelledError:
            raise
        except Exception:
            pass

    def _send_ann(self, peer, keys: list[bytes], sent: set[bytes]) -> bool:
        frame = msgpack.packb({"ann": keys}, use_bin_type=True)
        if not peer.send(MEMPOOL_CHANNEL, frame):
            return False
        sent.update(keys)
        self._m_ann.inc(len(keys))
        self.tallies["announced"] += len(keys)
        self._b_ann.inc(len(frame))
        self.tallies["bytes_ann"] += len(frame)
        return True

    def _send_full(self, peer, txs: list[bytes], keys: list[bytes],
                   sent: set[bytes]) -> bool:
        if not self._send_bodies(peer, txs):
            return False
        sent.update(keys)
        return True
