"""BlockExecutor (reference: ``state/execution.go:24-460``): proposal
creation, proposal processing, block application, state transitions and
event firing.  The ABCI boundary runs through the consensus connection of
``proxy.AppConns``."""

from __future__ import annotations

from dataclasses import replace

from ..abci import types as abci
from ..abci.client import ABCIClient

from ..libs.pubsub import EventBus
from ..mempool.mempool import Mempool
from ..storage.blockstore import BlockStore
from ..storage.statestore import State, StateStore
from ..types import events as ev
from ..types.block_id import BlockID
from ..types.commit import Commit, ExtendedCommit
from ..types.header import Block, Data, Header
from ..types.part_set import PartSet
from ..types.validator_set import Validator
from ..types.vote import Vote
from .validation import BlockValidationError, median_time, validate_block


class NopEvidencePool:
    def pending_evidence(self, max_bytes: int) -> list:
        return []

    def check_evidence(self, evidence: list) -> None:
        pass

    def update(self, state: State, evidence: list) -> None:
        pass

    def abci_evidence(self, evidence: list, state: State) -> list:
        return []


class BlockExecutor:
    def __init__(self, state_store: StateStore, block_store: BlockStore,
                 app_conn: ABCIClient, mempool: Mempool,
                 evidence_pool=None, event_bus: EventBus | None = None,
                 backend: str | None = None, pruner=None):
        self.state_store = state_store
        self.block_store = block_store
        self.app = app_conn
        self.mempool = mempool
        self.evidence_pool = evidence_pool or NopEvidencePool()
        self.event_bus = event_bus or EventBus()
        self.backend = backend
        self.pruner = pruner

    # ----------------------------------------------------------- proposals

    async def create_proposal_block(self, height: int, state: State,
                                    last_ext_commit: ExtendedCommit,
                                    proposer_addr: bytes,
                                    now_ns: int) -> tuple[Block, PartSet]:
        """Reap mempool + evidence, run ABCI PrepareProposal, assemble the
        block (state/execution.go:108 CreateProposalBlock)."""
        max_bytes = state.consensus_params.block.max_bytes
        max_gas = state.consensus_params.block.max_gas
        evidence = self.evidence_pool.pending_evidence(
            state.consensus_params.evidence.max_bytes)
        max_data = max_bytes - 2048 if max_bytes > 0 else -1
        txs = self.mempool.reap_max_bytes_max_gas(max_data, max_gas)
        from ..types.commit import aggregate_commit

        # fold the BLS for-block cohort into the aggregate lane block
        # (one signature + signer bitmap); deterministic, so every
        # correct proposer derives the identical last_commit bytes
        last_commit = aggregate_commit(
            last_ext_commit.to_commit(),
            state.last_validators or state.validators)

        if height == state.initial_height:
            block_time = max(state.last_block_time_ns + 1, now_ns)
        elif state.consensus_params.feature.pbts_enabled(height):
            block_time = now_ns
        else:
            # BFT time over the authenticated (Ed25519) lanes only; a
            # commit with none (pure-BLS valset) falls back to the
            # deterministic minimum advance, matching validate_block
            block_time = median_time(
                last_commit, state.last_validators or state.validators) \
                or state.last_block_time_ns + 1

        req = abci.PrepareProposalRequest(
            max_tx_bytes=max_data, txs=txs, height=height,
            time_ns=block_time, proposer_address=proposer_addr,
            local_last_commit=last_ext_commit,
            misbehavior=self.evidence_pool.abci_evidence(evidence, state))
        resp = await self.app.prepare_proposal(req)

        header = Header(
            chain_id=state.chain_id, height=height, time_ns=block_time,
            last_block_id=state.last_block_id,
            validators_hash=state.validators.hash(),
            next_validators_hash=state.next_validators.hash(),
            consensus_hash=state.consensus_params.hash(),
            app_hash=state.app_hash,
            last_results_hash=state.last_results_hash,
            proposer_address=proposer_addr)
        block = Block(header=header, data=Data(txs=list(resp.txs)),
                      evidence=evidence,
                      last_commit=last_commit if height > state.initial_height
                      else None)
        block.fill_hashes()
        from ..types import codec

        parts = PartSet.from_data(codec.pack(block))
        return block, parts

    async def process_proposal(self, block: Block, state: State) -> bool:
        """ABCI ProcessProposal (state/execution.go:168)."""
        req = abci.ProcessProposalRequest(
            txs=list(block.data.txs), height=block.header.height,
            time_ns=block.header.time_ns, hash=block.hash(),
            proposer_address=block.header.proposer_address,
            misbehavior=self.evidence_pool.abci_evidence(
                block.evidence, state))
        status = await self.app.process_proposal(req)
        return status == abci.PROCESS_PROPOSAL_ACCEPT

    # ----------------------------------------------------------- validation

    def validate_block(self, state: State, block: Block) -> None:
        validate_block(state, block, backend=self.backend)
        self.evidence_pool.check_evidence(block.evidence)

    # ------------------------------------------------------------ execution

    async def apply_block(self, state: State, block_id: BlockID,
                          block: Block, syncing_to_height: int = 0,
                          verified: bool = False) -> State:
        """FinalizeBlock -> updateState -> Commit(+mempool update) -> prune
        -> events (state/execution.go:227 applyBlock).  ``verified`` skips
        re-validation (ApplyVerifiedBlock, :217)."""
        if not verified:
            self.validate_block(state, block)

        req = abci.FinalizeBlockRequest(
            txs=list(block.data.txs), height=block.header.height,
            time_ns=block.header.time_ns, hash=block.hash(),
            proposer_address=block.header.proposer_address,
            decided_last_commit=block.last_commit,
            misbehavior=self.evidence_pool.abci_evidence(
                block.evidence, state),
            syncing_to_height=syncing_to_height or block.header.height)
        resp = await self.app.finalize_block(req)
        if len(resp.tx_results) != len(block.data.txs):
            raise BlockValidationError(
                f"app returned {len(resp.tx_results)} tx results for "
                f"{len(block.data.txs)} txs")

        from ..libs.fail import fail_point

        fail_point("exec:after-finalize-block")   # execution.go:261-311
        self.state_store.save_finalize_block_response(
            block.header.height, _pack_finalize_response(resp))
        fail_point("exec:after-save-response")

        new_state = self._update_state(state, block_id, block, resp)

        # Commit: lock mempool across app Commit + mempool update
        # (state/execution.go:391-460)
        async with self.mempool.lock():
            commit_resp = await self.app.commit()
            await self.mempool.update(block.header.height,
                                      list(block.data.txs), resp.tx_results)
        fail_point("exec:after-app-commit")
        self.state_store.save(new_state)
        fail_point("exec:after-state-save")
        self.evidence_pool.update(new_state, block.evidence)

        retain = commit_resp.retain_height
        if retain > 0:
            if self.pruner is not None:
                # async: the background pruner honors the companion
                # retain height too (state/pruner.go)
                self.pruner.set_app_retain_height(retain)
            else:
                try:
                    self.block_store.prune_blocks(
                        min(retain, self.block_store.height()))
                    self.state_store.prune_states(retain)
                except ValueError:
                    pass

        self._fire_events(block, block_id, resp)
        return new_state

    def _update_state(self, state: State, block_id: BlockID, block: Block,
                      resp: abci.FinalizeBlockResponse) -> State:
        """state/execution.go updateState: rotate validator sets, apply
        updates, bump proposer priorities."""
        height = block.header.height
        next_vals = state.next_validators.copy()
        changed_height = state.last_height_validators_changed
        if resp.validator_updates:
            from ..crypto.keys import pub_key_from_type_bytes

            changes = []
            for vu in resp.validator_updates:
                allowed = state.consensus_params.validator.pub_key_types
                if vu.pub_key_type not in allowed:
                    raise BlockValidationError(
                        f"validator key type {vu.pub_key_type} not in "
                        f"allowed {allowed}")
                try:
                    key = pub_key_from_type_bytes(vu.pub_key_type,
                                                  vu.pub_key_bytes)
                except ValueError as e:
                    raise BlockValidationError(str(e)) from e
                # rogue-key gate at ADMISSION: a bls12_381 key entering
                # the set must prove possession of its secret, or
                # basic-ciphersuite aggregation over the shared
                # zero-timestamp message is forgeable.  Removals
                # (power 0) and power changes of already-admitted keys
                # (address = hash(pubkey), so same address = same key)
                # need no fresh proof.
                if (vu.pub_key_type == "bls12_381" and vu.power > 0
                        and not next_vals.has_address(key.address())):
                    from ..crypto import bls12381 as _bls

                    if not vu.pop:
                        raise BlockValidationError(
                            "bls12_381 validator update admits key "
                            f"{key.bytes().hex()[:16]}… without a proof "
                            "of possession")
                    if not _bls.pop_verify(key.bytes(), vu.pop):
                        raise BlockValidationError(
                            "bls12_381 validator update for key "
                            f"{key.bytes().hex()[:16]}…: proof of "
                            "possession failed to verify")
                changes.append(Validator(key, vu.power))
            next_vals.update_with_change_set(changes)
            changed_height = height + 1
        next_vals.increment_proposer_priority(1)

        params = state.consensus_params
        params_height = state.last_height_params_changed
        if resp.consensus_param_updates is not None:
            params = resp.consensus_param_updates
            params_height = height + 1

        return replace(
            state,
            last_block_height=height,
            last_block_id=block_id,
            last_block_time_ns=block.header.time_ns,
            validators=state.next_validators.copy(),
            next_validators=next_vals,
            last_validators=state.validators.copy(),
            last_height_validators_changed=changed_height,
            consensus_params=params,
            last_height_params_changed=params_height,
            last_results_hash=resp.results_hash(),
            app_hash=resp.app_hash,
        )

    def _fire_events(self, block: Block, block_id: BlockID,
                     resp: abci.FinalizeBlockResponse) -> None:
        h = str(block.header.height)
        self.event_bus.publish(ev.EVENT_NEW_BLOCK,
                               {"block": block, "block_id": block_id,
                                "result": resp},
                               {ev.BLOCK_HEIGHT_KEY: h})
        self.event_bus.publish(ev.EVENT_NEW_BLOCK_HEADER,
                               {"header": block.header},
                               {ev.BLOCK_HEIGHT_KEY: h})
        self.event_bus.publish(ev.EVENT_NEW_BLOCK_EVENTS,
                               {"events": resp.events, "height": h},
                               {ev.BLOCK_HEIGHT_KEY: h})
        from ..mempool.mempool import TxKey

        for i, tx in enumerate(block.data.txs):
            self.event_bus.publish(
                ev.EVENT_TX,
                {"tx": tx, "result": resp.tx_results[i],
                 "height": block.header.height, "index": i},
                {ev.TX_HASH_KEY: TxKey(tx).hex(), ev.TX_HEIGHT_KEY: h})
        if resp.validator_updates:
            self.event_bus.publish(ev.EVENT_VALIDATOR_SET_UPDATES,
                                   {"updates": resp.validator_updates})

    # ------------------------------------------------------ vote extensions

    async def extend_vote(self, vote: Vote) -> bytes:
        resp = await self.app.extend_vote(vote.height, vote.round,
                                          vote.block_id.hash)
        return resp.vote_extension

    async def verify_vote_extension(self, vote: Vote) -> bool:
        resp = await self.app.verify_vote_extension(
            vote.height, vote.round, vote.validator_address,
            vote.block_id.hash, vote.extension)
        return resp.accepted


def _pack_finalize_response(resp: abci.FinalizeBlockResponse) -> bytes:
    from ..abci.client import _encode_value
    import msgpack

    return msgpack.packb(_encode_value(resp), use_bin_type=True)


def unpack_finalize_response(raw: bytes) -> abci.FinalizeBlockResponse:
    from ..abci.client import _decode_value
    import msgpack

    return _decode_value(msgpack.unpackb(raw, raw=False))
