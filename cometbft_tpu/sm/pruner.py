"""Background pruner honoring app + data-companion retain heights
(reference: ``state/pruner.go``; the companion height is ADR-101's
data-companion pull API, surfaced here through an RPC route).

Blocks and state below min(app_retain, companion_retain) are eligible;
either height being unset (0) blocks pruning on that axis only if the
companion feature is in use — an unset companion means "no companion,
app decides" like the reference default."""

from __future__ import annotations

import asyncio

from ..libs import log as tmlog
from ..libs import metrics
from ..libs.service import BaseService


class Pruner(BaseService):
    def __init__(self, state_store, block_store, interval: float = 10.0,
                 name: str = "pruner"):
        super().__init__(name=f"pruner:{name}")
        self.state_store = state_store
        self.block_store = block_store
        self.interval = interval
        self.log = tmlog.logger("pruner", node=name)
        self.m_pruned = metrics.counter("pruner_blocks_pruned_total",
                                        "blocks removed by the pruner")
        self._task: asyncio.Task | None = None
        self._wake = asyncio.Event()

    # ------------------------------------------------------ retain heights
    # persisted through the StateStore's retain-height record
    # (state/store.go:112-152) — one source of truth

    def set_app_retain_height(self, height: int) -> None:
        _, dc = self.retain_heights()
        self.state_store.set_retain_heights(height, dc)
        self._wake.set()

    def set_companion_retain_height(self, height: int) -> None:
        app, _ = self.retain_heights()
        self.state_store.set_retain_heights(app, height)
        self._wake.set()

    def retain_heights(self) -> tuple[int, int]:
        import msgpack

        from ..storage.statestore import K_RETAIN

        raw = self.state_store.db.get(K_RETAIN)
        if not raw:
            return 0, 0
        d = msgpack.unpackb(raw, raw=False)
        return d["app"], d["dc"]

    def effective_retain_height(self) -> int:
        return self.state_store.get_retain_height()

    # ------------------------------------------- lifecycle (BaseService)

    async def on_start(self) -> None:
        self._task = asyncio.create_task(self._routine())

    async def on_stop(self) -> None:
        if self._task is not None:
            self._task.cancel()

    async def _routine(self) -> None:
        while True:
            self._wake.clear()
            try:
                await asyncio.wait_for(self._wake.wait(), self.interval)
            except asyncio.TimeoutError:
                pass
            try:
                self.prune_once()
            except Exception as e:
                self.log.warn("prune failed", err=repr(e))

    def prune_once(self) -> int:
        target = self.effective_retain_height()
        if target <= self.block_store.base():
            return 0
        target = min(target, self.block_store.height())
        pruned = self.block_store.prune_blocks(target)
        self.state_store.prune_states(target)
        if pruned:
            self.m_pruned.inc(pruned)
            self.log.debug("pruned", blocks=pruned, new_base=target)
        return pruned
