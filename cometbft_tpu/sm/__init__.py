"""State-machine replication core (reference: ``state/`` — BlockExecutor,
block validation, state transitions)."""

from .execution import BlockExecutor, NopEvidencePool
from .validation import validate_block, BlockValidationError

__all__ = ["BlockExecutor", "NopEvidencePool", "validate_block",
           "BlockValidationError"]
