"""Block validation against committed state (reference:
``state/validation.go``).  The LastCommit check at the bottom is THE
batch hot path — ``state/validation.go:94`` → ``types/validation.go:28`` →
the TPU batch verifier."""

from __future__ import annotations

from ..storage.statestore import State
from ..types.commit import Commit
from ..types.header import BLOCK_PROTOCOL_VERSION, Block
from ..types.validation import VerifyCommit


class BlockValidationError(Exception):
    pass


def median_time(commit: Commit, validators) -> int:
    """Voting-power-weighted median of commit timestamps — BFT time
    (types/block.go:949 MedianTime).

    Only AUTHENTICATED timestamps count: BLS validators sign the
    zero-timestamp aggregation domain (types/vote.py sign_bytes_for), so
    the timestamps riding in their commit lanes are proposer-editable
    and must not influence block time — BFT time draws from the Ed25519
    cohort only.  Returns 0 when the commit carries no authenticated
    lane (pure-BLS valsets); callers fall back to
    ``last_block_time_ns + 1``, which is deterministic and denies the
    proposer any control over block time."""
    pairs = []
    total = 0
    for i, cs in enumerate(commit.signatures):
        if cs.is_absent():
            continue
        _, val = validators.get_by_address(cs.validator_address)
        if val is None:
            continue
        if val.pub_key.type() == "bls12_381":
            continue        # timestamp not covered by the signature
        pairs.append((cs.timestamp_ns, val.voting_power))
        total += val.voting_power
    if not pairs:
        return 0
    pairs.sort()
    mid = total // 2
    acc = 0
    for ts, power in pairs:
        acc += power
        if acc >= mid:
            return ts
    return pairs[-1][0]


def validate_block(state: State, block: Block,
                   backend: str | None = None,
                   verify_last_commit_sigs: bool = True) -> None:
    """Raises BlockValidationError; mirrors state/validation.go checks.

    ``verify_last_commit_sigs=False`` keeps the structural last-commit
    checks but skips signature verification — for blocksync, where the
    commit was already proven inside a cross-block device batch and
    re-verifying per block would undo the batching win."""
    err = block.validate_basic()
    if err:
        raise BlockValidationError(f"invalid block: {err}")
    h = block.header

    if h.version_block != BLOCK_PROTOCOL_VERSION:
        raise BlockValidationError("wrong block protocol version")
    if h.chain_id != state.chain_id:
        raise BlockValidationError(
            f"wrong chain id {h.chain_id!r} != {state.chain_id!r}")
    want_height = state.last_block_height + 1 \
        if state.last_block_height else state.initial_height
    if h.height != want_height:
        raise BlockValidationError(
            f"wrong height {h.height}, expected {want_height}")
    if h.last_block_id != state.last_block_id:
        raise BlockValidationError("wrong last_block_id")
    if h.validators_hash != state.validators.hash():
        raise BlockValidationError("wrong validators_hash")
    if h.next_validators_hash != state.next_validators.hash():
        raise BlockValidationError("wrong next_validators_hash")
    if h.consensus_hash != state.consensus_params.hash():
        raise BlockValidationError("wrong consensus_hash")
    if h.app_hash != state.app_hash:
        raise BlockValidationError("wrong app_hash")
    if h.last_results_hash != state.last_results_hash:
        raise BlockValidationError("wrong last_results_hash")
    if not state.validators.has_address(h.proposer_address):
        raise BlockValidationError("proposer not in validator set")

    is_initial = h.height == state.initial_height
    if is_initial:
        if block.last_commit is not None and block.last_commit.size() > 0:
            raise BlockValidationError(
                "initial block cannot have a last commit")
    else:
        if block.last_commit is None:
            raise BlockValidationError("missing last commit")
        if state.last_validators is None:
            raise BlockValidationError("no last validators to verify commit")
        if verify_last_commit_sigs:
            # ---- THE batch-verification hot path ----
            VerifyCommit(state.chain_id, state.last_validators,
                         state.last_block_id, h.height - 1,
                         block.last_commit, backend=backend)
        else:
            from ..types.validation import _check_commit_basics

            _check_commit_basics(state.last_validators, block.last_commit,
                                 h.height - 1, state.last_block_id)
        # BFT time: block time advances monotonically past the last block
        if h.time_ns <= state.last_block_time_ns:
            raise BlockValidationError("block time not monotonic")
        if not state.consensus_params.feature.pbts_enabled(h.height):
            # no authenticated (Ed25519) timestamp in the commit → the
            # deterministic fallback the proposer used (BLS-only valset)
            want = median_time(block.last_commit, state.last_validators) \
                or state.last_block_time_ns + 1
            if h.time_ns != want:
                raise BlockValidationError(
                    f"block time {h.time_ns} != median time {want}")
