"""ABCI request/response types (reference: ``abci/types/types.pb.go``
surface, slimmed to the fields consensus/mempool/sync actually use)."""

from __future__ import annotations

from dataclasses import dataclass, field

CODE_TYPE_OK = 0

PROCESS_PROPOSAL_ACCEPT = 1
PROCESS_PROPOSAL_REJECT = 2
VERIFY_VOTE_EXT_ACCEPT = 1
VERIFY_VOTE_EXT_REJECT = 2
OFFER_SNAPSHOT_ACCEPT = 1
OFFER_SNAPSHOT_REJECT = 2
OFFER_SNAPSHOT_REJECT_FORMAT = 3
OFFER_SNAPSHOT_REJECT_SENDER = 4
APPLY_CHUNK_ACCEPT = 1
APPLY_CHUNK_ABORT = 2
APPLY_CHUNK_RETRY = 3


@dataclass
class ApplySnapshotChunkResponse:
    """Full reference shape (abci ApplySnapshotChunkResponse:
    result + refetch_chunks + reject_senders).  Apps may return a bare
    status int instead; the statesync syncer normalizes."""

    result: int = APPLY_CHUNK_ACCEPT
    refetch_chunks: list = field(default_factory=list)   # indexes
    reject_senders: list = field(default_factory=list)   # peer ids


@dataclass
class EventAttribute:
    key: str
    value: str
    index: bool = True


@dataclass
class Event:
    type: str
    attributes: list[EventAttribute] = field(default_factory=list)


@dataclass
class ExecTxResult:
    code: int = CODE_TYPE_OK
    data: bytes = b""
    log: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: list[Event] = field(default_factory=list)

    @property
    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK

    def encode(self) -> bytes:
        """Deterministic encoding for last_results_hash."""
        from ..types import wire

        return (wire.field_varint(1, self.code)
                + wire.field_bytes(2, self.data)
                + wire.field_varint(5, self.gas_wanted)
                + wire.field_varint(6, self.gas_used))


@dataclass
class ValidatorUpdate:
    pub_key_type: str
    pub_key_bytes: bytes
    power: int
    # proof of possession — REQUIRED when admitting a new bls12_381 key
    # (the rogue-key defense the aggregate-commit fast path rests on);
    # ignored for other key types, removals, and power changes of
    # already-admitted keys.  sm/execution.py rejects the update when
    # the proof is missing or fails bls12381.pop_verify.
    pop: bytes = b""


@dataclass
class Misbehavior:
    type: str                 # "DUPLICATE_VOTE" | "LIGHT_CLIENT_ATTACK"
    validator_address: bytes
    validator_power: int
    height: int
    time_ns: int
    total_voting_power: int


@dataclass
class Snapshot:
    height: int
    format: int
    chunks: int
    hash: bytes
    metadata: bytes = b""


@dataclass
class InfoResponse:
    data: str = ""
    version: str = ""
    app_version: int = 0
    last_block_height: int = 0
    last_block_app_hash: bytes = b""


@dataclass
class QueryResponse:
    code: int = CODE_TYPE_OK
    log: str = ""
    key: bytes = b""
    value: bytes = b""
    height: int = 0
    # merkle proof op chain as wire dicts {"type","key","data"}
    # (abci ProofOps; verified against the header app_hash at height+1)
    proof_ops: list = field(default_factory=list)


@dataclass
class CheckTxResponse:
    code: int = CODE_TYPE_OK
    data: bytes = b""
    log: str = ""
    gas_wanted: int = 0

    @property
    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK


@dataclass
class InitChainRequest:
    chain_id: str
    initial_height: int
    time_ns: int
    validators: list[ValidatorUpdate] = field(default_factory=list)
    app_state_bytes: bytes = b""
    consensus_params: object = None


@dataclass
class InitChainResponse:
    validators: list[ValidatorUpdate] = field(default_factory=list)
    app_hash: bytes = b""
    consensus_params: object = None


@dataclass
class PrepareProposalRequest:
    max_tx_bytes: int
    txs: list[bytes]
    height: int
    time_ns: int
    proposer_address: bytes = b""
    local_last_commit: object = None
    misbehavior: list[Misbehavior] = field(default_factory=list)


@dataclass
class PrepareProposalResponse:
    txs: list[bytes] = field(default_factory=list)


@dataclass
class ProcessProposalRequest:
    txs: list[bytes]
    height: int
    time_ns: int
    hash: bytes = b""
    proposer_address: bytes = b""
    misbehavior: list[Misbehavior] = field(default_factory=list)


@dataclass
class FinalizeBlockRequest:
    txs: list[bytes]
    height: int
    time_ns: int
    hash: bytes = b""
    proposer_address: bytes = b""
    decided_last_commit: object = None
    misbehavior: list[Misbehavior] = field(default_factory=list)
    syncing_to_height: int = 0


@dataclass
class FinalizeBlockResponse:
    events: list[Event] = field(default_factory=list)
    tx_results: list[ExecTxResult] = field(default_factory=list)
    validator_updates: list[ValidatorUpdate] = field(default_factory=list)
    consensus_param_updates: object = None
    app_hash: bytes = b""

    def results_hash(self) -> bytes:
        from ..crypto import merkle

        return merkle.hash_from_byte_slices_fast(
            [r.encode() for r in self.tx_results])


@dataclass
class ExtendVoteResponse:
    vote_extension: bytes = b""


@dataclass
class VerifyVoteExtensionResponse:
    status: int = VERIFY_VOTE_EXT_ACCEPT

    @property
    def accepted(self) -> bool:
        return self.status == VERIFY_VOTE_EXT_ACCEPT


@dataclass
class CommitResponse:
    retain_height: int = 0
