"""ABCI clients (reference: ``abci/client/``): in-process local client and
an async socket client with a pipelined request queue
(``abci/client/socket_client.go``).  Wire frames are length-prefixed
msgpack ``{id, method, params}`` / ``{id, ok, result|error}`` — self-interop
protocol (SURVEY.md §7.5), not Go-compatible."""

from __future__ import annotations

import asyncio
import itertools
import struct
from abc import ABC, abstractmethod
from dataclasses import fields, is_dataclass

import msgpack

from . import types as t
from ..types import params as _params
from .application import Application

_LEN = struct.Struct(">I")


class ABCIClientError(Exception):
    pass


class ABCIClient(ABC):
    """One method per ABCI call; all awaitable."""

    @abstractmethod
    async def call(self, method: str, **params): ...

    async def echo(self, msg: str):
        return await self.call("echo", msg=msg)

    async def info(self) -> t.InfoResponse:
        return await self.call("info")

    async def query(self, path: str, data: bytes, height: int = 0,
                    prove: bool = False) -> t.QueryResponse:
        return await self.call("query", path=path, data=data, height=height,
                               prove=prove)

    async def check_tx(self, tx: bytes, recheck: bool = False
                       ) -> t.CheckTxResponse:
        return await self.call("check_tx", tx=tx, recheck=recheck)

    async def init_chain(self, req: t.InitChainRequest) -> t.InitChainResponse:
        return await self.call("init_chain", req=req)

    async def prepare_proposal(self, req: t.PrepareProposalRequest
                               ) -> t.PrepareProposalResponse:
        return await self.call("prepare_proposal", req=req)

    async def process_proposal(self, req: t.ProcessProposalRequest) -> int:
        return await self.call("process_proposal", req=req)

    async def finalize_block(self, req: t.FinalizeBlockRequest
                             ) -> t.FinalizeBlockResponse:
        return await self.call("finalize_block", req=req)

    async def extend_vote(self, height: int, round_: int, block_hash: bytes
                          ) -> t.ExtendVoteResponse:
        return await self.call("extend_vote", height=height, round_=round_,
                               block_hash=block_hash)

    async def verify_vote_extension(self, height: int, round_: int,
                                    validator_address: bytes,
                                    block_hash: bytes, extension: bytes
                                    ) -> t.VerifyVoteExtensionResponse:
        return await self.call("verify_vote_extension", height=height,
                               round_=round_,
                               validator_address=validator_address,
                               block_hash=block_hash, extension=extension)

    async def commit(self) -> t.CommitResponse:
        return await self.call("commit")

    async def list_snapshots(self) -> list[t.Snapshot]:
        return await self.call("list_snapshots")

    async def offer_snapshot(self, snapshot: t.Snapshot,
                             app_hash: bytes) -> int:
        return await self.call("offer_snapshot", snapshot=snapshot,
                               app_hash=app_hash)

    async def load_snapshot_chunk(self, height: int, format_: int,
                                  chunk: int) -> bytes:
        return await self.call("load_snapshot_chunk", height=height,
                               format_=format_, chunk=chunk)

    async def apply_snapshot_chunk(self, index: int, chunk: bytes,
                                   sender: str) -> int:
        return await self.call("apply_snapshot_chunk", index=index,
                               chunk=chunk, sender=sender)

    async def close(self) -> None:
        pass


async def dispatch_to_app(app: Application, method: str, params: dict):
    """Shared method dispatch used by the local client and the socket
    server."""
    if method == "echo":
        return params["msg"]
    if method == "query":
        return await app.query(params["path"], params["data"],
                               params["height"], params["prove"])
    if method == "check_tx":
        return await app.check_tx(params["tx"], params["recheck"])
    if method == "extend_vote":
        return await app.extend_vote(params["height"], params["round_"],
                                     params["block_hash"])
    if method == "verify_vote_extension":
        return await app.verify_vote_extension(
            params["height"], params["round_"],
            params["validator_address"], params["block_hash"],
            params["extension"])
    if method == "load_snapshot_chunk":
        return await app.load_snapshot_chunk(params["height"],
                                             params["format_"],
                                             params["chunk"])
    if method == "apply_snapshot_chunk":
        return await app.apply_snapshot_chunk(params["index"],
                                              params["chunk"],
                                              params["sender"])
    if method == "offer_snapshot":
        return await app.offer_snapshot(params["snapshot"],
                                        params["app_hash"])
    if method in ("info", "commit", "list_snapshots"):
        return await getattr(app, method)()
    if method in ("init_chain", "prepare_proposal", "process_proposal",
                  "finalize_block"):
        return await getattr(app, method)(params["req"])
    raise ABCIClientError(f"unknown ABCI method {method!r}")


class LocalClient(ABCIClient):
    """In-process client (``abci/client/local_client.go``): serializes calls
    with one lock, like the reference's mutex-guarded local client."""

    def __init__(self, app: Application):
        self.app = app
        self._lock = asyncio.Lock()

    async def call(self, method: str, **params):
        async with self._lock:
            return await dispatch_to_app(self.app, method, params)


# ------------------------------------------------------------ socket client

# per-type field-name cache: dataclasses.fields() reflection per VALUE
# made this encoder ~10% of a loaded node's core (every block's
# FinalizeBlockResponse is persisted through it); False = not a dataclass
_DC_FIELDS: dict[type, tuple | bool] = {}


def _encode_value(v):
    """Shallow per-level dataclass encoding so nested dataclasses keep their
    own __dc__ tags (asdict would flatten them into anonymous dicts)."""
    t = type(v)
    names = _DC_FIELDS.get(t)
    if names is None:
        names = tuple(f.name for f in fields(v)) \
            if is_dataclass(v) and not isinstance(v, type) else False
        _DC_FIELDS[t] = names
    if names is not False:
        out = {"__dc__": t.__name__}
        for n in names:
            out[n] = _encode_value(getattr(v, n))
        return out
    if isinstance(v, (list, tuple)):
        return [_encode_value(x) for x in v]
    if isinstance(v, dict):
        return {k: _encode_value(x) for k, x in v.items()}
    return v


def _domain_types():
    """Domain types that ride inside ABCI requests: the commit /
    extended-commit trees of PrepareProposal.local_last_commit and
    FinalizeBlock.decided_last_commit."""
    from ..types.block_id import BlockID, PartSetHeader
    from ..types.commit import (Commit, CommitSig, ExtendedCommit,
                                ExtendedCommitSig)

    return (BlockID, PartSetHeader, Commit, CommitSig, ExtendedCommit,
            ExtendedCommitSig)


_DC_TYPES = {cls.__name__: cls for cls in (
    t.EventAttribute, t.Event, t.ExecTxResult, t.ValidatorUpdate,
    t.Misbehavior, t.Snapshot, t.InfoResponse, t.QueryResponse,
    t.CheckTxResponse, t.InitChainRequest, t.InitChainResponse,
    t.PrepareProposalRequest, t.PrepareProposalResponse,
    t.ProcessProposalRequest, t.FinalizeBlockRequest,
    t.FinalizeBlockResponse, t.ExtendVoteResponse,
    t.VerifyVoteExtensionResponse, t.CommitResponse,
    t.ApplySnapshotChunkResponse,
    _params.ConsensusParams, _params.BlockParams, _params.EvidenceParams,
    _params.ValidatorParams, _params.VersionParams, _params.FeatureParams,
    _params.SynchronyParams) + _domain_types()}


def _decode_value(v):
    if isinstance(v, dict) and "__dc__" in v:
        name = v.pop("__dc__")
        cls = _DC_TYPES[name]
        kwargs = {k: _decode_value(x) for k, x in v.items()}
        return cls(**kwargs)
    if isinstance(v, list):
        return [_decode_value(x) for x in v]
    return v


async def read_frame(reader: asyncio.StreamReader):
    hdr = await reader.readexactly(_LEN.size)
    (n,) = _LEN.unpack(hdr)
    return msgpack.unpackb(await reader.readexactly(n), raw=False,
                           strict_map_key=False)


def write_frame(writer: asyncio.StreamWriter, obj) -> None:
    raw = msgpack.packb(obj, use_bin_type=True, default=_encode_value)
    writer.write(_LEN.pack(len(raw)) + raw)


class SocketClient(ABCIClient):
    """Pipelined socket client (``abci/client/socket_client.go``): requests
    stream out with sequence ids; a reader task resolves futures in order."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        self._seq = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._reader_task = asyncio.create_task(self._read_loop())
        self._err: Exception | None = None

    @classmethod
    async def connect(cls, host: str = "127.0.0.1", port: int = 26658,
                      unix_path: str | None = None) -> "SocketClient":
        if unix_path:
            reader, writer = await asyncio.open_unix_connection(unix_path)
        else:
            reader, writer = await asyncio.open_connection(host, port)
        return cls(reader, writer)

    async def _read_loop(self):
        try:
            while True:
                frame = await read_frame(self.reader)
                fut = self._pending.pop(frame["id"], None)
                if fut is None or fut.done():
                    continue
                if frame.get("ok", False):
                    fut.set_result(_decode_value(frame["result"]))
                else:
                    fut.set_exception(ABCIClientError(frame.get("error")))
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError) as e:
            self._err = ABCIClientError(f"connection lost: {e!r}")
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(self._err)
            self._pending.clear()

    async def call(self, method: str, **params):
        if self._err:
            raise self._err
        rid = next(self._seq)
        fut = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        # the read loop may have died between the _err check and this
        # registration — re-check so the future cannot be stranded
        if self._err or self._reader_task.done():
            self._pending.pop(rid, None)
            raise self._err or ABCIClientError("connection closed")
        write_frame(self.writer, {"id": rid, "method": method,
                                  "params": _encode_value(params)})
        await self.writer.drain()
        return await fut

    async def close(self):
        self._reader_task.cancel()
        self.writer.close()
        try:
            await self.writer.wait_closed()
        except Exception:
            pass
