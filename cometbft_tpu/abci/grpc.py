"""ABCI over gRPC (reference: ``abci/client/grpc_client.go`` +
``abci/server/grpc_server.go``).

One unary RPC per ABCI method on ``cometbft.abci.v1.ABCIService`` (the
reference's service shape), HTTP/2 via grpc.aio.  Payloads are the same
msgpack frames as the socket transport (self-interop, like the socket
protocol — the framework is not Go-wire-compatible by design), carried as
raw bytes through gRPC's generic handlers, so no protoc codegen is needed.
"""

from __future__ import annotations

import asyncio

import grpc
import grpc.aio
import msgpack

from .application import Application
from .client import (ABCIClient, ABCIClientError, _decode_value,
                     _encode_value, dispatch_to_app)

SERVICE = "cometbft.abci.v1.ABCIService"

# snake_case dispatch names <-> CamelCase wire method names
_METHODS = [
    "echo", "info", "query", "check_tx", "init_chain",
    "prepare_proposal", "process_proposal", "finalize_block",
    "extend_vote", "verify_vote_extension", "commit",
    "list_snapshots", "offer_snapshot", "load_snapshot_chunk",
    "apply_snapshot_chunk", "flush",
]


def _camel(name: str) -> str:
    return "".join(p.capitalize() for p in name.split("_"))


_WIRE_TO_SNAKE = {_camel(m): m for m in _METHODS}


def _pack(obj) -> bytes:
    return msgpack.packb(obj, use_bin_type=True, default=_encode_value)


def _unpack(raw: bytes):
    return msgpack.unpackb(raw, raw=False, strict_map_key=False)


class _ABCIHandler(grpc.GenericRpcHandler):
    def __init__(self, app: Application, lock: asyncio.Lock):
        self._app = app
        self._lock = lock

    def service(self, details):
        prefix = f"/{SERVICE}/"
        if not details.method.startswith(prefix):
            return None
        snake = _WIRE_TO_SNAKE.get(details.method[len(prefix):])
        if snake is None:
            return None

        async def handler(request: bytes, context):
            try:
                params = {k: _decode_value(v)
                          for k, v in _unpack(request).items()}
                # app calls serialized like the socket server's lock
                async with self._lock:
                    if snake == "flush":
                        result = None
                    else:
                        result = await dispatch_to_app(
                            self._app, snake, params)
                return _pack({"ok": True, "result": _encode_value(result)})
            except Exception as e:  # app errors propagate to the client
                return _pack({"ok": False, "error": repr(e)})

        return grpc.unary_unary_rpc_method_handler(handler)


class GRPCABCIServer:
    """Serves an :class:`Application` over gRPC
    (``abci/server/grpc_server.go``)."""

    def __init__(self, app: Application, host: str = "127.0.0.1",
                 port: int = 0):
        self.app = app
        self.host = host
        self.port = port
        self._server: grpc.aio.Server | None = None

    async def start(self) -> None:
        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers(
            (_ABCIHandler(self.app, asyncio.Lock()),))
        self.port = self._server.add_insecure_port(
            f"{self.host}:{self.port}")
        await self._server.start()

    async def stop(self) -> None:
        if self._server is not None:
            await self._server.stop(grace=0.2)
            self._server = None


class GRPCClient(ABCIClient):
    """gRPC ABCI client (``abci/client/grpc_client.go``); one HTTP/2
    channel, calls pipelined by gRPC itself (no explicit request queue
    needed — stream multiplexing replaces the socket client's id map)."""

    def __init__(self, channel: grpc.aio.Channel):
        self._channel = channel
        self._stubs = {
            m: channel.unary_unary(f"/{SERVICE}/{_camel(m)}")
            for m in _METHODS
        }

    @classmethod
    async def connect(cls, host: str = "127.0.0.1",
                      port: int = 26658) -> "GRPCClient":
        channel = grpc.aio.insecure_channel(f"{host}:{port}")
        return cls(channel)

    async def call(self, method: str, **params):
        stub = self._stubs.get(method)
        if stub is None:
            raise ABCIClientError(f"unknown ABCI method {method!r}")
        try:
            raw = await stub(_pack(_encode_value(params)))
        except grpc.aio.AioRpcError as e:
            raise ABCIClientError(
                f"grpc transport error: {e.code()}: {e.details()}") from e
        frame = _unpack(raw)
        if not frame.get("ok", False):
            raise ABCIClientError(frame.get("error"))
        return _decode_value(frame["result"])

    async def close(self) -> None:
        await self._channel.close()
