"""ABCI 2.0: the application interface (reference: ``abci/``).

14 methods (``abci/types/application.go:9-35``): Info, Query, CheckTx,
InitChain, PrepareProposal, ProcessProposal, FinalizeBlock, ExtendVote,
VerifyVoteExtension, Commit, ListSnapshots, OfferSnapshot,
LoadSnapshotChunk, ApplySnapshotChunk.
"""

from .types import (CheckTxResponse, CommitResponse, Event, EventAttribute,
                    ExecTxResult, ExtendVoteResponse, FinalizeBlockRequest,
                    FinalizeBlockResponse, InfoResponse, InitChainRequest,
                    InitChainResponse, Misbehavior, PrepareProposalRequest,
                    PrepareProposalResponse, ProcessProposalRequest,
                    QueryResponse, Snapshot, ValidatorUpdate,
                    VerifyVoteExtensionResponse, CODE_TYPE_OK,
                    PROCESS_PROPOSAL_ACCEPT, PROCESS_PROPOSAL_REJECT,
                    VERIFY_VOTE_EXT_ACCEPT, VERIFY_VOTE_EXT_REJECT,
                    OFFER_SNAPSHOT_ACCEPT, OFFER_SNAPSHOT_REJECT,
                    APPLY_CHUNK_ACCEPT)
from .application import Application

__all__ = [
    "Application", "CheckTxResponse", "CommitResponse", "Event",
    "EventAttribute", "ExecTxResult", "ExtendVoteResponse",
    "FinalizeBlockRequest", "FinalizeBlockResponse", "InfoResponse",
    "InitChainRequest", "InitChainResponse", "Misbehavior",
    "PrepareProposalRequest", "PrepareProposalResponse",
    "ProcessProposalRequest", "QueryResponse", "Snapshot",
    "ValidatorUpdate", "VerifyVoteExtensionResponse", "CODE_TYPE_OK",
    "PROCESS_PROPOSAL_ACCEPT", "PROCESS_PROPOSAL_REJECT",
    "VERIFY_VOTE_EXT_ACCEPT", "VERIFY_VOTE_EXT_REJECT",
    "OFFER_SNAPSHOT_ACCEPT", "OFFER_SNAPSHOT_REJECT", "APPLY_CHUNK_ACCEPT",
]
