"""Example kvstore application (reference: ``abci/example/kvstore/kvstore.go``).

Transactions are ``key=value`` bytes; state is a dict with a deterministic
app hash; InitChain installs genesis validators; ``val:<pubkey_b64>!<power>``
transactions update the validator set (like the reference's
``MakeValSetChangeTx``); vote extensions carry a height-tagged payload;
snapshots serialize the full state in fixed-size chunks.
"""

from __future__ import annotations

import base64
import hashlib
import json
import struct

import msgpack

from . import types as t
from .application import Application

SNAPSHOT_CHUNK_SIZE = 64 * 1024
VALSET_PREFIX = b"val:"


class KVStoreApplication(Application):
    def __init__(self):
        self.state: dict[bytes, bytes] = {}
        self._leaves: dict[bytes, bytes] = {}   # key -> kv_leaf, lazily
        self.height = 0
        self.app_hash = self._compute_app_hash()
        self.validators: dict[bytes, int] = {}     # pubkey bytes -> power
        self.pending_updates: list[t.ValidatorUpdate] = []
        self.misbehavior_seen: list[t.Misbehavior] = []   # punished offenders
        self.snapshots: dict[int, object] = {}     # height -> state copy | serialized bytes (lazy)
        self._restore_chunks: dict[int, bytes] = {}
        self._restoring: t.Snapshot | None = None

    # ----------------------------------------------------------------- info

    async def info(self) -> t.InfoResponse:
        return t.InfoResponse(data="kvstore", version="0.1.0",
                              app_version=1,
                              last_block_height=self.height,
                              last_block_app_hash=self.app_hash)

    async def query(self, path: str, data: bytes, height: int,
                    prove: bool) -> t.QueryResponse:
        value = self.state.get(data, b"")
        resp = t.QueryResponse(key=data, value=value, height=self.height,
                               log="exists" if value else "does not exist")
        if prove and value:
            from ..crypto.merkle import ValueOp

            index, proofs = self._ensure_proof_cache()
            op = ValueOp(data, proofs[index[data]]).proof_op()
            resp.proof_ops = [{"type": op.type, "key": op.key,
                               "data": op.data}]
        return resp

    # -------------------------------------------------------------- mempool

    async def check_tx(self, tx: bytes, recheck: bool = False
                       ) -> t.CheckTxResponse:
        if self._parse_tx(tx) is None:
            return t.CheckTxResponse(code=1, log="malformed tx")
        return t.CheckTxResponse(gas_wanted=1)

    @staticmethod
    def _parse_tx(tx: bytes):
        if tx.startswith(VALSET_PREFIX):
            body = tx[len(VALSET_PREFIX):]
            if b"!" not in body:
                return None
            pk_b64, power = body.split(b"!", 1)
            try:
                pk = base64.b64decode(pk_b64, validate=True)
                return ("val", pk, int(power))
            except Exception:
                return None
        if b"=" not in tx:
            return None
        k, v = tx.split(b"=", 1)
        return ("set", k, v)

    # ------------------------------------------------------------ consensus

    async def init_chain(self, req: t.InitChainRequest) -> t.InitChainResponse:
        for vu in req.validators:
            self.validators[vu.pub_key_bytes] = vu.power
        if req.app_state_bytes and req.app_state_bytes != b"{}":
            # genesis app_state is JSON (types/genesis.go AppState semantics)
            d = json.loads(req.app_state_bytes)
            self.state = {str(k).encode(): str(v).encode()
                          for k, v in d.items()}
            self._leaves.clear()
        self.app_hash = self._compute_app_hash()
        return t.InitChainResponse(app_hash=self.app_hash)

    async def process_proposal(self, req: t.ProcessProposalRequest) -> int:
        for tx in req.txs:
            if self._parse_tx(tx) is None:
                return t.PROCESS_PROPOSAL_REJECT
        return t.PROCESS_PROPOSAL_ACCEPT

    async def finalize_block(self, req: t.FinalizeBlockRequest
                             ) -> t.FinalizeBlockResponse:
        self.misbehavior_seen.extend(req.misbehavior)
        results, updates = [], []
        for tx in req.txs:
            parsed = self._parse_tx(tx)
            if parsed is None:
                results.append(t.ExecTxResult(code=1, log="malformed tx"))
                continue
            if parsed[0] == "val":
                _, pk, power = parsed
                if power > 0:
                    self.validators[pk] = power
                else:
                    self.validators.pop(pk, None)
                updates.append(t.ValidatorUpdate("ed25519", pk, power))
                results.append(t.ExecTxResult(
                    events=[t.Event("valset", [
                        t.EventAttribute("pubkey",
                                         base64.b64encode(pk).decode()),
                        t.EventAttribute("power", str(power))])]))
            else:
                _, k, v = parsed
                self.state[k] = v
                self._leaves.pop(k, None)   # leaf recomputed at hash time
                results.append(t.ExecTxResult(
                    gas_used=1,
                    events=[t.Event("app", [
                        t.EventAttribute("key", k.decode("utf-8", "replace")),
                    ])]))
        self.height = req.height
        self.app_hash = self._compute_app_hash()
        return t.FinalizeBlockResponse(tx_results=results,
                                       validator_updates=updates,
                                       app_hash=self.app_hash)

    async def extend_vote(self, height: int, round_: int,
                          block_hash: bytes) -> t.ExtendVoteResponse:
        return t.ExtendVoteResponse(
            vote_extension=b"ext" + struct.pack(">q", height))

    async def verify_vote_extension(self, height, round_, validator_address,
                                    block_hash, extension
                                    ) -> t.VerifyVoteExtensionResponse:
        want = b"ext" + struct.pack(">q", height)
        ok = extension == want
        return t.VerifyVoteExtensionResponse(
            status=t.VERIFY_VOTE_EXT_ACCEPT if ok
            else t.VERIFY_VOTE_EXT_REJECT)

    async def commit(self) -> t.CommitResponse:
        # a CHEAP dict copy per height; msgpack+hash happen lazily in
        # _snapshot_raw when a statesync peer actually lists/fetches —
        # serializing the whole store every block was a top-3 cost in
        # the e2e throughput profile (the reference kvstore has no
        # snapshot support at all; this keeps it without the per-block
        # tax)
        self.snapshots[self.height] = (dict(self.state),
                                       dict(self.validators), self.height)
        # retention must outlive a statesyncer's offer->fetch window even
        # on fast test chains
        for h in sorted(self.snapshots)[:-16]:
            del self.snapshots[h]
        return t.CommitResponse(retain_height=0)

    # ------------------------------------------------------------ snapshots

    def _snapshot_raw(self, height: int) -> bytes:
        """Serialized snapshot bytes for a height, computed on first use
        from the stored state copy and cached."""
        v = self.snapshots.get(height)
        if v is None:
            return b""
        if isinstance(v, bytes):
            return v
        state, vals, h = v
        raw = msgpack.packb({"state": sorted(state.items()),
                             "vals": sorted(vals.items()),
                             "height": h}, use_bin_type=True)
        self.snapshots[height] = raw
        return raw

    def _compute_app_hash(self) -> bytes:
        """Merkle root over key-bound leaves: queries are PROVABLE against
        the app hash in the next block header (crypto/merkle ValueOp).

        Root-only, through the native tree when available: building the
        per-key PROOFS here made this the single hottest function in the
        end-to-end throughput profile (it ran every block while only
        ``query(prove=True)`` ever needs proofs — those are built lazily
        in :meth:`_ensure_proof_cache` and invalidated on mutation).
        The reference kvstore's app hash is just the store size
        (``abci/example/kvstore/kvstore.go:556``); this one keeps the
        provable-query extension without paying for it per block.

        Leaf bytes are cached per key (``_leaves``; writers invalidate
        the touched key): each block re-hashes the tree but not the
        untouched leaves' value digests."""
        from ..crypto.merkle import hash_from_byte_slices_fast, kv_leaf

        self._proof_cache = None           # state changed: proofs stale
        leaves = self._leaves
        return hash_from_byte_slices_fast(
            [leaves.get(k) or
             leaves.setdefault(k, kv_leaf(k, self.state[k]))
             for k in sorted(self.state)])

    def _ensure_proof_cache(self):
        """Build (lazily) the per-key inclusion proofs for proven
        queries; valid until the next state mutation."""
        if self._proof_cache is None:
            from ..crypto.merkle import kv_leaf, proofs_from_byte_slices

            keys = sorted(self.state)
            leaves = self._leaves
            _, proofs = proofs_from_byte_slices(
                [leaves.get(k) or
                 leaves.setdefault(k, kv_leaf(k, self.state[k]))
                 for k in keys])
            self._proof_cache = ({k: i for i, k in enumerate(keys)},
                                 proofs)
        return self._proof_cache

    async def list_snapshots(self) -> list[t.Snapshot]:
        out = []
        for h in sorted(self.snapshots):
            raw = self._snapshot_raw(h)
            nchunks = (len(raw) + SNAPSHOT_CHUNK_SIZE - 1) \
                // SNAPSHOT_CHUNK_SIZE or 1
            out.append(t.Snapshot(height=h, format=1, chunks=nchunks,
                                  hash=hashlib.sha256(raw).digest()))
        return out

    async def offer_snapshot(self, snapshot: t.Snapshot,
                             app_hash: bytes) -> int:
        if snapshot.format != 1:
            return t.OFFER_SNAPSHOT_REJECT_FORMAT
        self._restoring = snapshot
        self._restore_chunks = {}
        return t.OFFER_SNAPSHOT_ACCEPT

    async def load_snapshot_chunk(self, height: int, format_: int,
                                  chunk: int) -> bytes:
        raw = self._snapshot_raw(height)
        off = chunk * SNAPSHOT_CHUNK_SIZE
        return raw[off:off + SNAPSHOT_CHUNK_SIZE]

    async def apply_snapshot_chunk(self, index: int, chunk: bytes,
                                   sender: str) -> int:
        """Chunks are keyed by index: duplicates/re-sends and out-of-order
        delivery (statesync retries) are harmless."""
        if self._restoring is None:
            return t.APPLY_CHUNK_ABORT
        self._restore_chunks[index] = chunk
        if len(self._restore_chunks) == self._restoring.chunks and \
                all(i in self._restore_chunks
                    for i in range(self._restoring.chunks)):
            raw = b"".join(self._restore_chunks[i]
                           for i in range(self._restoring.chunks))
            if hashlib.sha256(raw).digest() != self._restoring.hash:
                # keep _restoring: the syncer refetches and re-applies —
                # dropping it here would turn the retry into an abort
                self._restore_chunks = {}
                return t.APPLY_CHUNK_RETRY
            d = msgpack.unpackb(raw, raw=False)
            self.state = dict(d["state"])
            self._leaves.clear()
            self.validators = dict(d["vals"])
            self.height = d["height"]
            self.app_hash = self._compute_app_hash()
            self._restoring = None
        return t.APPLY_CHUNK_ACCEPT
