"""ABCI socket server for out-of-process applications
(reference: ``abci/server/socket_server.go``)."""

from __future__ import annotations

import asyncio

from .application import Application
from .client import (dispatch_to_app, read_frame, write_frame,
                     _decode_value, _encode_value)


class ABCIServer:
    def __init__(self, app: Application, host: str = "127.0.0.1",
                 port: int = 26658, unix_path: str | None = None):
        self.app = app
        self.host = host
        self.port = port
        self.unix_path = unix_path
        self._server: asyncio.AbstractServer | None = None
        self._lock = asyncio.Lock()      # app calls serialized like local

    async def start(self) -> None:
        if self.unix_path:
            self._server = await asyncio.start_unix_server(
                self._handle, path=self.unix_path)
        else:
            self._server = await asyncio.start_server(
                self._handle, self.host, self.port)
            self.port = self._server.sockets[0].getsockname()[1]

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                frame = await read_frame(reader)
                rid = frame["id"]
                try:
                    params = {k: _decode_value(v)
                              for k, v in frame["params"].items()}
                    async with self._lock:
                        result = await dispatch_to_app(
                            self.app, frame["method"], params)
                    write_frame(writer, {"id": rid, "ok": True,
                                         "result": _encode_value(result)})
                except Exception as e:  # app errors propagate to the client
                    write_frame(writer, {"id": rid, "ok": False,
                                         "error": repr(e)})
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            await self._server.wait_closed()
