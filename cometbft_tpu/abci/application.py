"""Application base class — the 14-method ABCI 2.0 interface
(reference: ``abci/types/application.go:9-35``).  All methods are async
(the socket client pipeline is async; local apps just run inline)."""

from __future__ import annotations

from . import types as t


class Application:
    """Override what you need; defaults are legal no-ops."""

    # ------------------------------------------------------------- info/query

    async def info(self) -> t.InfoResponse:
        return t.InfoResponse()

    async def query(self, path: str, data: bytes, height: int,
                    prove: bool) -> t.QueryResponse:
        return t.QueryResponse()

    # --------------------------------------------------------------- mempool

    async def check_tx(self, tx: bytes, recheck: bool = False
                       ) -> t.CheckTxResponse:
        return t.CheckTxResponse()

    # ------------------------------------------------------------- consensus

    async def init_chain(self, req: t.InitChainRequest) -> t.InitChainResponse:
        return t.InitChainResponse()

    async def prepare_proposal(self, req: t.PrepareProposalRequest
                               ) -> t.PrepareProposalResponse:
        # default: include txs up to the size limit (like the reference's
        # default PrepareProposal tx selection)
        total, out = 0, []
        for tx in req.txs:
            total += len(tx)
            if req.max_tx_bytes >= 0 and total > req.max_tx_bytes:
                break
            out.append(tx)
        return t.PrepareProposalResponse(txs=out)

    async def process_proposal(self, req: t.ProcessProposalRequest) -> int:
        return t.PROCESS_PROPOSAL_ACCEPT

    async def finalize_block(self, req: t.FinalizeBlockRequest
                             ) -> t.FinalizeBlockResponse:
        return t.FinalizeBlockResponse(
            tx_results=[t.ExecTxResult() for _ in req.txs])

    async def extend_vote(self, height: int, round_: int,
                          block_hash: bytes) -> t.ExtendVoteResponse:
        return t.ExtendVoteResponse()

    async def verify_vote_extension(self, height: int, round_: int,
                                    validator_address: bytes,
                                    block_hash: bytes, extension: bytes
                                    ) -> t.VerifyVoteExtensionResponse:
        return t.VerifyVoteExtensionResponse()

    async def commit(self) -> t.CommitResponse:
        return t.CommitResponse()

    # ------------------------------------------------------------- snapshots

    async def list_snapshots(self) -> list[t.Snapshot]:
        return []

    async def offer_snapshot(self, snapshot: t.Snapshot,
                             app_hash: bytes) -> int:
        return t.OFFER_SNAPSHOT_REJECT

    async def load_snapshot_chunk(self, height: int, format_: int,
                                  chunk: int) -> bytes:
        return b""

    async def apply_snapshot_chunk(self, index: int, chunk: bytes,
                                   sender: str):
        """Return a status int or a full t.ApplySnapshotChunkResponse
        (refetch_chunks / reject_senders honored by the syncer)."""
        return t.APPLY_CHUNK_ABORT
