"""ABCI 2.0 call-sequence grammar checker (reference:
``test/e2e/pkg/grammar/checker.go`` + ``abci_grammar.md``): the e2e tier
records every ABCI call a node makes and validates the ordering against
the legal protocol grammar.

Grammar (consensus + statesync surface)::

    start      := init | statesync | recovery
    init       := InitChain height*
    statesync  := OfferSnapshot ApplySnapshotChunk* height*
    recovery   := height*                     (replay after restart)
    height     := proposal* FinalizeBlock Commit
    proposal   := PrepareProposal | ProcessProposal

Mempool (CheckTx) and query (Info/Query/Echo) calls ride separate logical
connections and may interleave anywhere; vote-extension calls
(ExtendVote / VerifyVoteExtension) may appear between proposals and
FinalizeBlock of their height."""

from __future__ import annotations

from .application import Application

# calls checked by the grammar (consensus + statesync connections)
_SEQUENCED = {
    "init_chain", "prepare_proposal", "process_proposal",
    "finalize_block", "commit", "offer_snapshot", "apply_snapshot_chunk",
}
# free interleave (mempool/query conns + vote extensions + snapshot serving)
_FREE = {
    "echo", "info", "query", "check_tx", "list_snapshots",
    "load_snapshot_chunk", "extend_vote", "verify_vote_extension",
}


class GrammarError(Exception):
    def __init__(self, pos: int, call: str, state: str, seq: list[str]):
        self.pos = pos
        window = seq[max(0, pos - 4):pos + 3]
        super().__init__(
            f"illegal ABCI call {call!r} at position {pos} in state "
            f"{state!r} (context: {window})")


def check_sequence(calls: list[str]) -> int:
    """Validate a recorded call sequence; returns the number of completed
    heights.  Raises GrammarError on the first illegal transition."""
    seq = [c for c in calls if c in _SEQUENCED]
    state = "start"
    heights = 0
    for pos, call in enumerate(seq):
        if state == "start":
            if call == "init_chain":
                state = "chain"
                continue
            if call == "offer_snapshot":
                state = "restoring"
                continue
            # recovery: straight into the height loop
            state = "chain"
        if state == "restoring":
            if call == "apply_snapshot_chunk":
                continue
            if call == "offer_snapshot":
                continue               # retry with the next snapshot
            state = "chain"            # restore done; fall into heights
        if state == "chain":
            if call in ("prepare_proposal", "process_proposal"):
                state = "proposing"
                continue
            if call == "finalize_block":
                state = "finalized"
                continue
            raise GrammarError(pos, call, state, seq)
        if state == "proposing":
            if call in ("prepare_proposal", "process_proposal"):
                continue
            if call == "finalize_block":
                state = "finalized"
                continue
            raise GrammarError(pos, call, state, seq)
        if state == "finalized":
            if call == "commit":
                heights += 1
                state = "chain"
                continue
            raise GrammarError(pos, call, state, seq)
        raise GrammarError(pos, call, state, seq)
    return heights


class RecordingApp:
    """Wrap an application; record the name of every ABCI call in order
    (the e2e node's call logger).

    Deliberately NOT an Application subclass: the base class ships concrete
    no-op methods, which would shadow ``__getattr__`` delegation and record
    nothing."""

    def __init__(self, inner: Application):
        self.inner = inner
        self.calls: list[str] = []

    def __getattr__(self, name):
        target = getattr(self.inner, name)
        if not callable(target):
            return target

        import inspect as _inspect

        if not _inspect.iscoroutinefunction(target):
            return target

        async def recorded(*args, **kwargs):
            self.calls.append(name)
            return await target(*args, **kwargs)

        return recorded

    def check(self) -> int:
        return check_sequence(self.calls)
