from .node import Node

__all__ = ["Node"]
