"""Node assembly (reference: ``node/node.go:275,303-576`` NewNode +
OnStart): wires DBs -> state/genesis -> ABCI connections + handshake ->
mempool -> consensus (+WAL) -> reactors -> transport/switch.

The reference's two-phase construction (create everything, then OnStart
starts services in dependency order) is kept; RPC attaches on top via
``rpc.server`` when configured.
"""

from __future__ import annotations

import asyncio
import os

from ..abci.application import Application
from ..blocksync.reactor import BlocksyncReactor
from ..config import Config, test_consensus_config
from ..evidence import EvidencePool, EvidenceReactor
from ..consensus.reactor import ConsensusReactor
from ..consensus.replay import Handshaker
from ..consensus.state import ConsensusState
from ..consensus.wal import WAL
from ..libs.pubsub import EventBus
from ..mempool.clist_mempool import CListMempool
from ..mempool.reactor import MempoolReactor
from ..p2p import AddrBook, NodeInfo, NodeKey, PexReactor, Switch, Transport
from ..proxy.multi_app_conn import (AppConns, local_client_creator,
                                    socket_client_creator)
from ..sm.execution import BlockExecutor
from ..storage import BlockStore, LogDB, MemDB, State, StateStore
from ..types.genesis import GenesisDoc
from ..types.priv_validator import PrivValidator


def _parse_laddr(laddr: str) -> tuple[str, int]:
    addr = laddr.removeprefix("tcp://")
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


async def _serve_prometheus(laddr: str):
    """Standalone Prometheus exposition listener (reference:
    ``node/node.go`` Prometheus server on instrumentation.prometheus);
    the JSON-RPC server also serves ``GET /metrics``, this is the
    dedicated scrape port."""
    import asyncio as _aio

    from ..libs import metrics as _metrics

    host, port = _parse_laddr(laddr)

    async def handle(reader, writer):
        try:
            # bounded reads: a silent client must not pin the handler
            await _aio.wait_for(reader.readline(), 10)   # request line
            while (await _aio.wait_for(reader.readline(), 10)).strip():
                pass                                     # drain headers
            body = _metrics.DEFAULT.collect().encode()
            writer.write(b"HTTP/1.1 200 OK\r\nContent-Type: text/plain; "
                         b"version=0.0.4\r\nContent-Length: "
                         + str(len(body)).encode() + b"\r\n\r\n" + body)
            await writer.drain()
        except (ConnectionError, _aio.IncompleteReadError,
                _aio.TimeoutError):
            pass
        finally:
            writer.close()

    return await _aio.start_server(handle, host, port)


class Node:
    def __init__(self):
        # populated by create(); kept flat for introspection/RPC
        self.config: Config | None = None
        self.genesis: GenesisDoc | None = None
        self.block_store: BlockStore | None = None
        self.state_store: StateStore | None = None
        self.app_conns: AppConns | None = None
        self.event_bus: EventBus | None = None
        self.mempool: CListMempool | None = None
        self.block_exec: BlockExecutor | None = None
        self.consensus: ConsensusState | None = None
        self.consensus_reactor: ConsensusReactor | None = None
        self.mempool_reactor: MempoolReactor | None = None
        self.blocksync_reactor: BlocksyncReactor | None = None
        self.evidence_pool: EvidencePool | None = None
        self.evidence_reactor: EvidenceReactor | None = None
        self.fast_sync = False
        self.node_key: NodeKey | None = None
        self.transport: Transport | None = None
        self.switch: Switch | None = None
        self.listen_addr: str | None = None
        self.rpc_server = None
        self.rpc_addr: tuple[str, int] | None = None
        self.grpc_server = None
        self.prometheus_server = None
        self.loop_watchdog = None
        self.liveness_watchdog = None
        self.home: str | None = None
        self.tx_indexer = None
        self.block_indexer = None
        self.indexer_service = None
        self.statesync_reactor = None
        self.addr_book = None
        self.pex_reactor = None
        self.pruner = None
        self.syncer = None
        self.statesync_done = None
        self.statesync_error = None
        self.name = "node"
        self.doctor_report = None
        self.compile_bundle_info = None
        self.light_serve = None
        self._started = False
        self._data_lock = None
        self._vote_sched = None

    # ------------------------------------------------------------- create

    @classmethod
    async def create(cls, genesis_doc: GenesisDoc, app: Application,
                     priv_validator: PrivValidator | None = None,
                     config: Config | None = None,
                     node_key: NodeKey | None = None,
                     home: str | None = None,
                     fast_sync: bool = False,
                     state_sync_provider=None,
                     name: str = "node") -> "Node":
        self = cls()
        self.name = name
        self.home = home
        self.fast_sync = fast_sync or state_sync_provider is not None
        cfg = config or Config(consensus=test_consensus_config())
        self.config = cfg
        self.genesis = genesis_doc

        # arm the fault-injection plane BEFORE the stores open: sites
        # that fire at open time (db.replay.corrupt feeding the salvage
        # + doctor pipeline) must see a subprocess node's CMT_CHAOS env
        # — start() would be too late (same process-wide/sticky
        # discipline as tracing)
        from ..libs import failures as _failures

        _failures.configure_from_config(cfg.chaos)

        from ..storage import open_db

        def make_db(filename: str):
            if home is None:
                return MemDB()
            return open_db(cfg.storage.db_backend,
                           os.path.join(home, "data", filename))

        if home is not None:
            from ..storage.db import DataDirLock

            os.makedirs(os.path.join(home, "data"), exist_ok=True)
            # refuse to double-open a home (and make offline tooling
            # refuse while this node runs)
            self._data_lock = DataDirLock(os.path.join(home, "data"))
            wal_path = os.path.join(home, "data", "cs.wal")
        else:
            wal_path = None
        bs_db = make_db("blockstore.db")
        ss_db = make_db("state.db")
        self.block_store = BlockStore(bs_db)
        self.state_store = StateStore(ss_db)

        # storage integrity doctor: cross-store boot consistency (+ the
        # deep hash-chain scan when a store was salvaged) BEFORE the WAL
        # opens — a repair may quarantine WAL segments, and the check
        # must see the on-disk lineage, not a fresh append handle.
        # Raises DoctorError on the dangerous cases (privval sign state
        # ahead of a clean store = double-sign tripwire).
        if cfg.storage.doctor_enable:
            from .doctor import DoctorError, StorageDoctor

            try:
                self.doctor_report = StorageDoctor(
                    self.block_store, self.state_store, wal_path=wal_path,
                    priv_validator=priv_validator,
                    deep_scan_window=cfg.storage.doctor_deep_scan_window,
                    name=name).boot_check(repair=True)
            except DoctorError:
                # refusal: close the store handles and release the home
                # so inspect mode / the doctor CLI (and a fixed retry)
                # can open it without racing two live append handles
                for db_ in (bs_db, ss_db):
                    try:
                        db_.close()
                    except Exception:
                        pass
                if self._data_lock is not None:
                    self._data_lock.release()
                    self._data_lock = None
                raise
        wal = WAL(wal_path) if wal_path is not None else None

        state = self.state_store.load() or State.from_genesis(genesis_doc)

        if app is not None:
            creator = local_client_creator(app)
        elif cfg.base.abci == "socket":
            # out-of-process app over the ABCI socket protocol
            # (proxy/client.go remote creator)
            shost, sport = _parse_laddr(cfg.base.proxy_app)
            creator = socket_client_creator(shost, sport)
        elif cfg.base.abci == "grpc":
            from ..proxy.multi_app_conn import grpc_client_creator

            ghost, gport = _parse_laddr(cfg.base.proxy_app)
            creator = grpc_client_creator(ghost, gport)
        else:
            raise ValueError("no application: pass app or configure "
                             "base.abci='socket'|'grpc' with "
                             "base.proxy_app addr")
        self.app_conns = AppConns(creator, node=self.name)
        await self.app_conns.start()
        self.event_bus = EventBus()
        self.mempool = CListMempool(
            self.app_conns.mempool, max_txs=cfg.mempool.size,
            max_tx_bytes=cfg.mempool.max_tx_bytes,
            max_txs_bytes=cfg.mempool.max_txs_bytes,
            cache_size=cfg.mempool.cache_size,
            keep_invalid_txs_in_cache=cfg.mempool.keep_invalid_txs_in_cache,
            shards=cfg.mempool.shards,
            coalesce_ms=cfg.mempool.coalesce_ms,
            coalesce_max=cfg.mempool.coalesce_max,
            recheck=cfg.mempool.recheck,
            metrics_node=name)
        ev_db = make_db("evidence.db")
        self.evidence_pool = EvidencePool(
            ev_db, state_store=self.state_store,
            block_store=self.block_store,
            backend=cfg.base.signature_backend)
        self.evidence_pool.state = state
        from ..sm.pruner import Pruner

        self.pruner = Pruner(self.state_store, self.block_store, name=name)
        self.block_exec = BlockExecutor(
            self.state_store, self.block_store, self.app_conns.consensus,
            self.mempool, evidence_pool=self.evidence_pool,
            event_bus=self.event_bus,
            backend=cfg.base.signature_backend, pruner=self.pruner)

        self._state_syncing = (state_sync_provider is not None
                               and self.block_store.height() == 0)
        if not self._state_syncing:
            # statesync replaces the handshake: the app gets its state
            # from the snapshot, not InitChain/replay (node/node.go note
            # "the Handshaker is not used when state syncing")
            state = await Handshaker(
                self.state_store, self.block_store, genesis_doc).handshake(
                state, self.app_conns, self.block_exec)

        self.consensus = ConsensusState(
            cfg.consensus, state, self.block_exec, self.block_store,
            wal=wal, priv_validator=priv_validator,
            event_bus=self.event_bus, name=name)
        self.consensus.on_conflicting_vote = \
            self.evidence_pool.report_conflicting_votes

        gossip_sleep = cfg.consensus.peer_gossip_sleep_duration / 1e9
        self.consensus_reactor = ConsensusReactor(
            self.consensus, gossip_sleep=gossip_sleep)
        self.mempool_reactor = MempoolReactor(
            self.mempool, gossip_sleep=gossip_sleep,
            gossip_mode=cfg.mempool.gossip_mode,
            fetch_timeout_s=cfg.mempool.fetch_timeout_s,
            batch_bytes=cfg.mempool.gossip_batch_bytes)

        self.blocksync_reactor = BlocksyncReactor(
            self.block_exec, self.block_store, state,
            fast_sync=self.fast_sync,
            switch_to_consensus=self._switch_to_consensus,
            backend=cfg.base.signature_backend,
            verify_window=cfg.blocksync.verify_window,
            name=f"{name}.bs")
        if self.fast_sync:
            self.consensus_reactor.wait_sync = True

        from ..statesync import StatesyncReactor, Syncer

        self.statesync_reactor = StatesyncReactor(
            self.app_conns, name=f"{name}.ss",
            chunk_cache_bytes=cfg.statesync.chunk_cache_bytes,
            serve_concurrency=cfg.statesync.serve_concurrency,
            serve_queue=cfg.statesync.serve_queue)
        if self._state_syncing:
            self.syncer = Syncer(
                self.app_conns, state_sync_provider,
                reactor=self.statesync_reactor, name=name,
                chunk_timeout=cfg.statesync.chunk_timeout_s,
                max_inflight_per_peer=cfg.statesync.max_inflight_per_peer,
                discovery_time=cfg.statesync.discovery_time_s,
                discovery_rounds=cfg.statesync.discovery_rounds,
                chunk_retries=cfg.statesync.chunk_retries,
                spool_retain_bytes=cfg.statesync.spool_retain_bytes)
            self.statesync_reactor.syncer = self.syncer
            self.blocksync_reactor.hold = True

        self.node_key = node_key or NodeKey.generate()
        fuzz_cfg = None
        if cfg.p2p.test_fuzz:
            from ..p2p.fuzz import FuzzConnConfig

            fuzz_cfg = FuzzConnConfig(
                mode=cfg.p2p.fuzz_mode,
                max_delay_s=cfg.p2p.fuzz_max_delay_s,
                prob_drop_rw=cfg.p2p.fuzz_prob_drop_rw,
                prob_drop_conn=cfg.p2p.fuzz_prob_drop_conn,
                prob_sleep=cfg.p2p.fuzz_prob_sleep,
                start_after_s=cfg.p2p.fuzz_start_after_s,
                seed=cfg.p2p.fuzz_seed)
        self.transport = Transport(self.node_key, self._node_info,
                                   fuzz_config=fuzz_cfg)
        # addrbook before the switch: the peer-quality scorer records
        # its timed bans there (persisted across restarts); without pex
        # the scorer keeps bans in-memory only
        if cfg.p2p.pex:
            book_path = None
            if home is not None:
                book_path = os.path.join(home, cfg.p2p.addr_book_path) \
                    if not os.path.isabs(cfg.p2p.addr_book_path) \
                    else cfg.p2p.addr_book_path
            self.addr_book = AddrBook(book_path)
        from ..p2p.quality import PeerScorer

        scorer = PeerScorer(
            addr_book=self.addr_book,
            enabled=cfg.p2p.quality_enable,
            disconnect_score=cfg.p2p.quality_disconnect_score,
            ban_score=cfg.p2p.quality_ban_score,
            half_life_s=cfg.p2p.quality_half_life_s,
            ban_ttl_s=cfg.p2p.quality_ban_ttl_s,
            ban_ttl_max_s=cfg.p2p.quality_ban_ttl_max_s)
        self.switch = Switch(
            self.transport,
            emulated_latency=cfg.p2p.emulated_latency_ms / 1e3,
            telemetry_interval=cfg.p2p.telemetry_flush_interval_s,
            scorer=scorer, chaos_scope=name)
        if cfg.tx_index.indexer == "kv":
            from ..indexer import BlockIndexer, IndexerService, TxIndexer

            self.tx_indexer = TxIndexer(make_db("tx_index.db"))
            self.block_indexer = BlockIndexer(make_db("block_index.db"))
            self.indexer_service = IndexerService(
                self.event_bus, self.tx_indexer, self.block_indexer,
                name=f"{name}.idx")
        elif cfg.tx_index.indexer == "psql":
            # external SQL sink (state/indexer/sink/psql): same pump,
            # rows instead of kv postings; write-only from the node
            from ..indexer import IndexerService
            from ..indexer.psql import PsqlEventSink

            sink = PsqlEventSink(dsn=cfg.tx_index.psql_conn,
                                 chain_id=genesis_doc.chain_id)
            self.tx_indexer = sink
            self.block_indexer = sink.block_indexer()
            self.indexer_service = IndexerService(
                self.event_bus, sink, self.block_indexer,
                name=f"{name}.idx")

        if cfg.lightserve.enable:
            # light-client serving tier (light/serve.py): passive — no
            # background tasks, read by the light_* RPC routes in worker
            # threads.  Constructed here (not at RPC start) so in-proc
            # tooling can drive it without a listener.
            from ..light.serve import LightServeTier

            ls_cfg = cfg.lightserve
            self.light_serve = LightServeTier(
                self.block_store, self.state_store, genesis_doc.chain_id,
                backend=cfg.base.signature_backend,
                header_cache_size=ls_cfg.header_cache_size,
                header_cache_bytes=ls_cfg.header_cache_bytes,
                proof_cache_blocks=ls_cfg.proof_cache_blocks,
                verify_cache_size=ls_cfg.verify_cache_size,
                trust_period_ns=ls_cfg.trust_period_ns,
                max_batch=ls_cfg.max_batch,
                max_proofs=ls_cfg.max_proofs,
                name=name)

        self.evidence_reactor = EvidenceReactor(self.evidence_pool)
        self.switch.add_reactor("consensus", self.consensus_reactor)
        self.switch.add_reactor("mempool", self.mempool_reactor)
        self.switch.add_reactor("blocksync", self.blocksync_reactor)
        self.switch.add_reactor("evidence", self.evidence_reactor)
        self.switch.add_reactor("statesync", self.statesync_reactor)
        if cfg.p2p.pex:
            self.pex_reactor = PexReactor(
                self.addr_book, self.node_key.id,
                max_outbound=cfg.p2p.max_num_outbound_peers,
                request_interval=cfg.p2p.pex_interval_seconds,
                seed_mode=cfg.p2p.seed_mode)
            self.switch.add_reactor("pex", self.pex_reactor)
        return self

    async def _run_statesync(self) -> None:
        """node.go OnStart startStateSync: snapshot restore -> bootstrap
        stores -> hand off to blocksync."""
        from ..libs import log as tmlog

        lg = tmlog.logger("statesync", node=self.name)
        try:
            state, commit = await self.syncer.sync()
            self.state_store.bootstrap(state)
            self.block_store.bootstrap_statesync(state.last_block_height,
                                                 commit)
            self.evidence_pool.state = state
            self.blocksync_reactor.state = state
        except asyncio.CancelledError:
            raise
        except Exception as e:
            # fall back to syncing from genesis (InitChain was skipped in
            # anticipation of the snapshot: run the handshake now).  If
            # the app already restored part of a snapshot the handshake
            # itself fails — that is unrecoverable without a reset, but
            # it must be LOUD, not a silently-dead task.
            lg.error("statesync failed; falling back to blocksync",
                     err=repr(e))
            try:
                state = State.from_genesis(self.genesis)
                state = await Handshaker(
                    self.state_store, self.block_store,
                    self.genesis).handshake(
                    state, self.app_conns, self.block_exec)
                self.blocksync_reactor.state = state
            except Exception as e2:
                self.statesync_error = e2
                lg.error("statesync fallback failed; node needs "
                         "unsafe-reset-all", err=repr(e2))
                return
        self.blocksync_reactor.hold = False
        await self.blocksync_reactor.start_sync()

    async def _switch_to_consensus(self, state) -> None:
        """Blocksync caught up: adopt the synced state and start consensus
        (reference consensus Reactor.SwitchToConsensus)."""
        self.consensus._update_to_state(state)
        await self.consensus.start()
        self.consensus_reactor.switch_to_consensus()

    def _node_info(self) -> NodeInfo:
        return NodeInfo(
            node_id=self.node_key.id,
            listen_addr=self.listen_addr or "",
            network=self.genesis.chain_id,
            channels=self.switch.channel_ids if self.switch else b"",
            moniker=self.name)

    # ---------------------------------------------------------- lifecycle

    async def start(self) -> None:
        """node.go:579 OnStart: listen, start reactors, start consensus."""
        if self.config.instrumentation.tracing:
            # flip the process-wide flight recorder on BEFORE any
            # subsystem starts so the first height is fully traced;
            # never flipped off at stop (in-proc ensembles share it, and
            # the ring of a stopped node is still dumpable post-mortem)
            from ..libs import tracing as _tracing

            _tracing.configure(
                enabled=True,
                ring_size=self.config.instrumentation.tracing_ring_size)
        # (the fault-injection plane was armed in create(), before the
        # stores opened — open-time sites must see the schedule)
        host, port = _parse_laddr(self.config.p2p.laddr) \
            if self.config.p2p.laddr else ("127.0.0.1", 0)
        self.listen_addr = await self.transport.listen(host, port)
        await self.switch.start()
        if self.indexer_service is not None:
            await self.indexer_service.start()
        if self.pruner is not None:
            await self.pruner.start()
        if self.config.rpc.laddr:
            from ..rpc import RPCServer

            rhost, rport = _parse_laddr(self.config.rpc.laddr)
            self.rpc_server = RPCServer(self)
            self.rpc_addr = await self.rpc_server.listen(rhost, rport)
        if self.config.rpc.grpc_laddr:
            from ..rpc.grpc import GRPCServer

            ghost, gport = _parse_laddr(self.config.rpc.grpc_laddr)
            self.grpc_server = GRPCServer(self, ghost, gport)
            await self.grpc_server.start()
        if self.config.instrumentation.prometheus:
            self.prometheus_server = await _serve_prometheus(
                self.config.instrumentation.prometheus_listen_addr)
        if self.config.instrumentation.loop_stall_threshold_s > 0:
            from ..libs.loopwatch import LoopWatchdog

            self.loop_watchdog = LoopWatchdog(
                asyncio.get_running_loop(),
                stall_threshold_s=(
                    self.config.instrumentation.loop_stall_threshold_s),
                name=self.name)
            self.loop_watchdog.start()
        elif getattr(self.config.rpc, "overload_shed_lag_s", 0) > 0:
            # shedding reads the watchdog's lag — with the watchdog off
            # the knob is dead, which an operator should hear about once
            from ..libs import log as _tmlog

            _tmlog.logger("node", node=self.name).warn(
                "rpc.overload_shed_lag_s is set but the loop watchdog is "
                "disabled (instrumentation.loop_stall_threshold_s = 0): "
                "overload shedding is inactive")
        from ..crypto import batch as cryptobatch
        from ..crypto import plan as deviceplan

        # the declarative device plan drives the batched verifier AND
        # the coalescing scheduler (and is what the AOT bundle below is
        # keyed by) — config lands here, not in per-module hooks
        deviceplan.configure(
            min_device_lanes=self.config.base.min_device_lanes)
        if self.config.base.device_wait_s > 0:
            cryptobatch.set_device_wait(self.config.base.device_wait_s)
        from ..crypto import merkle as cryptomerkle

        cryptomerkle.set_merkle_kernel_min(
            self.config.base.merkle_kernel_min_leaves)
        if self.config.base.vote_sched_enable:
            # process-wide coalescing vote-verification scheduler:
            # in-proc ensembles share one (refcounted) instance — the
            # verified-signature cache holds universal verdicts and
            # cross-node coalescing only improves batch occupancy
            from ..crypto import scheduler as vsched

            self._vote_sched = await vsched.acquire_scheduler(
                backend=self.config.base.signature_backend,
                max_wait_ms=self.config.base.vote_sched_max_wait_ms,
                max_lanes=self.config.base.vote_sched_max_lanes,
                cache_size=self.config.base.vote_sched_cache_size,
                verify_timeout_s=(
                    self.config.base.vote_sched_verify_timeout_s))

        def _warm_native():
            # build/load the C++ verifiers off the event loop so a fresh
            # checkout's first commit verification doesn't eat a
            # multi-second g++ compile on the consensus hot path
            from ..crypto import _native_ed25519 as nat
            from ..crypto import secp256k1 as secp

            nat.available()
            secp._native_lib()

        asyncio.get_running_loop().run_in_executor(None, _warm_native)
        if self.config.base.device_warmup and \
                self.config.base.signature_backend in ("tpu", "jax",
                                                       "auto"):
            # pre-compile hot bucket shapes off the event loop so the
            # first commit verification doesn't stall consensus; under
            # "auto" the device probe itself runs in the executor too
            # (it may block on accelerator discovery)
            backend = self.config.base.signature_backend
            bundle_on = self.config.base.compile_bundle_enable
            bundle_dir = self.config.base.compile_bundle_dir or None

            def _warm():
                if backend == "auto" and \
                        cryptobatch._accelerator_device() is None:
                    self.compile_bundle_info = {
                        "status": "skipped_no_device"}
                    return          # CPU-only: nothing to pre-compile
                from ..crypto import aotbundle

                # default hot shapes, plus the buckets the CURRENT
                # valset actually dispatches — a large network's first
                # commit must not pay a cold XLA compile (VERDICT r3
                # weak 1a).  The same shapes become the plan's warm set
                # so the bundle covers the cached-gather route (the
                # real commit hot path), keyed to this valset's TABLE
                # bucket.
                lanes = {256, 1024}
                vsizes = ()
                try:
                    st = self.state_store.load()
                    if st is not None:
                        n_vals = len(st.validators.validators)
                        if n_vals:
                            lanes.update(
                                cryptobatch.buckets_for_batch(n_vals))
                            # the dense Light path dispatches the
                            # ~2/3-power scope, not the full set
                            lanes.update(cryptobatch.buckets_for_batch(
                                (2 * n_vals) // 3 + 1))
                            if n_vals > max(lanes):
                                vsizes = (n_vals,)
                            table = deviceplan.bucket(
                                n_vals,
                                deviceplan.active().table_buckets)
                            deviceplan.configure(
                                warm_lanes=tuple(sorted(lanes)),
                                warm_tables=(table,))
                except Exception:
                    pass
                if bundle_on:
                    # warm boot: load the versioned AOT bundle FIRST so
                    # the warmup below (and the first real commit) finds
                    # pre-compiled executables instead of paying
                    # trace+lower+compile per shape
                    try:
                        self.compile_bundle_info = aotbundle.load(
                            path=aotbundle.default_path(bundle_dir))
                    except Exception as e:
                        self.compile_bundle_info = {"status": "error",
                                                    "error": repr(e)}
                else:
                    self.compile_bundle_info = {"status": "disabled"}
                cryptobatch.warmup_device(
                    lane_buckets=tuple(sorted(lanes)),
                    valset_sizes=vsizes)
                if bundle_on and \
                        self.compile_bundle_info.get("status") != "loaded":
                    # cold machine: build + save the bundle AFTER warmup
                    # (consensus is already served by the jit caches) so
                    # the NEXT boot — or a verify node spun up for a
                    # traffic spike — starts warm
                    try:
                        self.compile_bundle_info = aotbundle.build(
                            path=aotbundle.default_path(bundle_dir))
                    except Exception as e:
                        self.compile_bundle_info = {"status": "error",
                                                    "error": repr(e)}

            asyncio.get_running_loop().run_in_executor(None, _warm)
        if self.syncer is not None:
            self.statesync_done = asyncio.create_task(
                self._run_statesync())
        if not self.fast_sync:
            # fast-sync defers consensus start to the blocksync handoff
            await self.consensus.start()
        inst = self.config.instrumentation
        if inst.watchdog_stall_threshold_s > 0:
            incident_dir = self.incident_dir()
            if incident_dir is not None:
                from .watchdog import LivenessWatchdog

                self.liveness_watchdog = LivenessWatchdog(
                    self, incident_dir,
                    stall_threshold_s=inst.watchdog_stall_threshold_s,
                    check_interval_s=inst.watchdog_check_interval_s,
                    min_interval_s=inst.watchdog_min_interval_s,
                    max_bundles=inst.watchdog_max_bundles,
                    wal_tail_records=inst.watchdog_wal_tail)
                await self.liveness_watchdog.start()
        self._started = True

    async def stop(self) -> None:
        if self.statesync_done is not None:
            self.statesync_done.cancel()
        if self.liveness_watchdog is not None:
            await self.liveness_watchdog.stop()
            self.liveness_watchdog = None
        if self.rpc_server is not None:
            await self.rpc_server.close()
        if self.grpc_server is not None:
            await self.grpc_server.stop()
        if self.prometheus_server is not None:
            self.prometheus_server.close()
            await self.prometheus_server.wait_closed()
        if self.loop_watchdog is not None:
            self.loop_watchdog.stop()
        if self._data_lock is not None:
            self._data_lock.release()
            self._data_lock = None
        if self.indexer_service is not None:
            await self.indexer_service.stop()
        if self.pruner is not None:
            await self.pruner.stop()
        if self.blocksync_reactor is not None:
            await self.blocksync_reactor.stop()
        if self.consensus is not None:
            await self.consensus.stop()
        if self._vote_sched is not None:
            from ..crypto import scheduler as vsched

            self._vote_sched = None
            await vsched.release_scheduler()
        if self.switch is not None:
            await self.switch.stop()
        if self.app_conns is not None:
            await self.app_conns.stop()
        self._started = False

    def incident_dir(self) -> str | None:
        """Where watchdog incident bundles live (see
        ``watchdog.resolve_incident_dir``)."""
        from .watchdog import resolve_incident_dir

        return resolve_incident_dir(self.config, self.home)

    async def dial_peer(self, addr: str, persistent: bool = True):
        return await self.switch.dial_peer(addr, persistent=persistent)

    # ------------------------------------------------------------- status

    def height(self) -> int:
        return self.block_store.height()
