"""Liveness watchdog: the node diagnoses its own stalls.

PR 5 gave operators ``step_age_s``/``last_commit_age_s`` on ``/status``
— but a human still had to be polling when the stall happened, and by
the time they dump ``/dump_trace`` the flight-recorder ring has often
rolled past the interesting window.  This service closes that loop: it
periodically evaluates three stall conditions and, when one fires,
writes a rate-limited **black-box incident bundle** to disk while the
evidence is still hot:

- ``consensus_step_stalled`` — the state machine has sat in one step
  past the threshold (a wedged round: lost proposer, split vote, ...),
- ``no_recent_commit`` — commits stopped arriving even though steps may
  still churn (round thrash without progress),
- ``peers_quiet`` — connected peers exist but none has produced a
  packet within the threshold (network partition / silent death the
  pong timeout has not caught yet),
- ``consensus_fatal_error`` — the state machine recorded a fatal error.

A bundle is one JSON file carrying the flight-recorder ring dump, the
per-peer telemetry snapshot (`Switch.peer_snapshot`), a consensus state
summary, and the newest WAL records — everything a post-mortem needs,
captured at trip time.  ``GET /dump_incidents`` lists and serves them.

Disk discipline: bundles are rate-limited (``watchdog_min_interval_s``
between writes; a persisting stall re-dumps at that cadence, not per
check tick), written via tmp+rename so readers never see a torn file,
and pruned to the newest ``watchdog_max_bundles``.
"""

from __future__ import annotations

import asyncio
import functools
import json
import os

from ..consensus.wal import wal_segments, _iter_segment_file
from ..libs import clock, tracing
from ..libs.service import BaseService

BUNDLE_PREFIX = "incident-"
BUNDLE_SUFFIX = ".json"
TRACE_DUMP_LIMIT = 4000         # newest flight-recorder records bundled


@functools.cache
def _watchdog_metrics():
    from ..libs import metrics as m

    return (
        m.counter("watchdog_trips_total",
                  "liveness watchdog stall detections, by reason (one "
                  "inc per reason per evaluation that found it)"),
        m.counter("watchdog_bundles_written_total",
                  "incident bundles written to disk"),
        m.counter("watchdog_suppressed_total",
                  "stall detections that wrote no bundle (rate limit)"),
    )


def resolve_incident_dir(config, home: str | None) -> str | None:
    """Where bundles live: the configured path, resolved against the
    node home when relative.  A home-less node (pure in-memory test
    assembly) gets None unless the operator pointed at an absolute
    directory — bundles are a disk artifact by design and an implicit
    cwd-relative dump would litter.  Shared by the live Node and
    inspect mode so both views resolve the same data directory."""
    path = config.instrumentation.watchdog_incident_dir
    if os.path.isabs(path):
        return path
    if home is None:
        return None
    return os.path.join(home, path)


def _jsonable(v):
    """Best-effort JSON projection for bundle payloads (WAL records are
    msgpack dicts that may carry raw bytes)."""
    if isinstance(v, (bytes, bytearray)):
        return v.hex()
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return repr(v)


def wal_tail(wal, limit: int) -> list[dict]:
    """The newest ``limit`` records of a live WAL, read-only (walks the
    segment files backward from the active one until the quota fills;
    flushes the append buffer first so the tail is current).  Returns []
    on any trouble — the bundle must never fail because the WAL is
    mid-rotation."""
    if wal is None or limit <= 0:
        return []
    try:
        f = getattr(wal, "_f", None)
        if f is not None:
            f.flush()
        groups: list[list] = []       # oldest-first record groups
        remaining = limit
        for seg in reversed(wal_segments(wal.path)):
            seg_records = []
            for item in _iter_segment_file(seg):
                if isinstance(item, bool):
                    break
                seg_records.append(item)
            take = seg_records[-remaining:]
            groups.insert(0, take)
            remaining -= len(take)
            if remaining <= 0:
                break
        return [_jsonable(r) for group in groups for r in group]
    except Exception:
        return []


class LivenessWatchdog(BaseService):
    """Rides the node: reads consensus/step ages and p2p liveness, never
    writes to either.  All thresholds come from
    ``[instrumentation] watchdog_*`` (see config.py)."""

    def __init__(self, node, incident_dir: str,
                 stall_threshold_s: float = 60.0,
                 check_interval_s: float = 5.0,
                 min_interval_s: float = 300.0,
                 max_bundles: int = 16,
                 wal_tail_records: int = 200):
        super().__init__(name=f"{getattr(node, 'name', 'node')}.watchdog")
        self.node = node
        self.incident_dir = incident_dir
        self.stall_threshold_s = stall_threshold_s
        self.check_interval_s = check_interval_s
        self.min_interval_s = min_interval_s
        self.max_bundles = max_bundles
        self.wal_tail_records = wal_tail_records
        self.trips = 0                    # detections (pre rate limit)
        self.bundles_written = 0
        self.last_reasons: list[str] = []
        self._last_bundle_mono: float | None = None
        self._task: asyncio.Task | None = None
        self._seq = 0

    # ----------------------------------------------------------- service

    async def on_start(self) -> None:
        os.makedirs(self.incident_dir, exist_ok=True)
        self._task = asyncio.create_task(self._run())

    async def on_stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None

    async def _run(self) -> None:
        try:
            while True:
                await clock.sleep(self.check_interval_s)
                try:
                    reasons = self._evaluate()
                    if reasons is not None:
                        # snapshot the live state on the loop (cheap:
                        # attribute reads + a ring copy), then push the
                        # disk work — WAL segment reads, JSON encode of
                        # a possibly-multi-MB bundle, fsync-adjacent
                        # writes — off the loop so the diagnostic never
                        # causes the pong timeouts it would then report
                        bundle = self.build_bundle(reasons)
                        await asyncio.to_thread(
                            self._write_bundle_file, bundle)
                except asyncio.CancelledError:
                    raise
                except Exception as e:   # diagnosing must never harm
                    self.log.error("watchdog check failed", err=repr(e))
        except asyncio.CancelledError:
            raise

    # ------------------------------------------------------------ checks

    def _evaluate(self) -> list[str] | None:
        """Detection + rate limiting; returns the reasons when a bundle
        is due, None otherwise (no stall, or suppressed)."""
        reasons = self.stall_reasons()
        if not reasons:
            return None
        self.trips += 1
        self.last_reasons = reasons
        trips, _, suppressed = _watchdog_metrics()
        for r in reasons:
            trips.inc(reason=r, node=self.node.name)
        if self._last_bundle_mono is not None and \
                clock.monotonic() - self._last_bundle_mono \
                < self.min_interval_s:
            suppressed.inc(node=self.node.name)
            return None
        return reasons

    def check(self) -> str | None:
        """One synchronous evaluation (tests, tooling): returns the
        bundle path if one was written."""
        reasons = self._evaluate()
        if reasons is None:
            return None
        return self.write_bundle(reasons)

    def stall_reasons(self) -> list[str]:
        thr = self.stall_threshold_s
        reasons = []
        node = self.node
        cs = node.consensus
        # truthiness, not None-ness: inspect-mode shims are falsy.  Only
        # a STARTED state machine can stall (blocksync/statesync phases
        # park consensus legitimately).
        if cs and getattr(cs, "_task", None) is not None:
            if getattr(cs, "fatal_error", None) is not None:
                reasons.append("consensus_fatal_error")
            if cs.step_age_s() > thr:
                reasons.append("consensus_step_stalled")
            last_wall = getattr(cs, "_last_commit_wall_ns", 0)
            if last_wall and \
                    (cs.now_ns() - last_wall) / 1e9 > thr:
                # only after a first commit: a net that never committed
                # is a bootstrap problem the step age already covers
                reasons.append("no_recent_commit")
        sw = node.switch
        if sw is not None and getattr(sw, "peers", None):
            quiet = sw.quietest_peer_recv_age_s()
            if quiet is not None and quiet > thr:
                reasons.append("peers_quiet")
        return reasons

    # ------------------------------------------------------------ bundle

    def build_bundle(self, reasons: list[str]) -> dict:
        node = self.node
        cs = node.consensus
        consensus = None
        if cs:
            last_wall = getattr(cs, "_last_commit_wall_ns", 0)
            consensus = {
                "height": cs.rs.height,
                "round": cs.rs.round,
                "step": cs.rs.step_name(),
                "step_age_s": round(cs.step_age_s(), 6),
                "last_commit_age_s": (
                    round(max(cs.now_ns() - last_wall, 0) / 1e9, 6)
                    if last_wall else None),
                "fatal_error": (repr(cs.fatal_error)
                                if cs.fatal_error else None),
            }
        sw = node.switch
        tstats = tracing.stats()
        return {
            "version": 1,
            "node": node.name,
            "reasons": reasons,
            "wall_time_ns": clock.walltime_ns(),
            "stall_threshold_s": self.stall_threshold_s,
            "height": (node.block_store.height()
                       if node.block_store is not None else None),
            "consensus": consensus,
            "peers": sw.peer_snapshot() if sw is not None else [],
            "peer_quality": (sw.scorer.snapshot()
                             if sw is not None
                             and getattr(sw, "scorer", None) is not None
                             else None),
            "trace": {
                "enabled": tstats["enabled"],
                "buffered": tstats["buffered"],
                "records": tracing.dump(TRACE_DUMP_LIMIT),
            },
        }

    def write_bundle(self, reasons: list[str]) -> str:
        return self._write_bundle_file(self.build_bundle(reasons))

    def _write_bundle_file(self, bundle: dict) -> str:
        """Disk half (runs in a worker thread from the service loop):
        WAL-tail capture, JSON encode, tmp+rename write, pruning.  The
        rate-limit clock advances only on success — a full disk must not
        buy the NEXT trip a 5-minute silence on top of this one."""
        cs = self.node.consensus
        bundle["wal_tail"] = wal_tail(
            getattr(cs, "wal", None) if cs else None,
            self.wal_tail_records)
        self._seq += 1
        # '.' joins reasons: it survives a URL query string verbatim
        # ('+' would decode as a space in GET /dump_incidents?name=...)
        reason_slug = ".".join(bundle["reasons"])[:80].replace("/", "_")
        name = (f"{BUNDLE_PREFIX}{bundle['wall_time_ns']}"
                f"-{self._seq:03d}-{reason_slug}{BUNDLE_SUFFIX}")
        path = os.path.join(self.incident_dir, name)
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(bundle, f, separators=(",", ":"))
                f.write("\n")
            os.replace(tmp, path)   # readers never see a torn bundle
        except BaseException:
            # a torn .tmp must not compound the disk pressure that
            # likely caused the failure (pruning skips non-.json names)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self._last_bundle_mono = clock.monotonic()
        self.bundles_written += 1
        _watchdog_metrics()[1].inc(node=self.node.name)
        self._prune()
        self.log.error("liveness stall: incident bundle written",
                       reasons=",".join(bundle["reasons"]), path=path)
        return path

    def _prune(self) -> None:
        try:
            listing = os.listdir(self.incident_dir)
        except OSError:
            return
        names = sorted(n for n in listing
                       if n.startswith(BUNDLE_PREFIX)
                       and n.endswith(BUNDLE_SUFFIX))
        stale = names[:-self.max_bundles]
        # orphaned .tmp files (a crash mid-write) are always stale
        stale += [n for n in listing if n.startswith(BUNDLE_PREFIX)
                  and n.endswith(BUNDLE_SUFFIX + ".tmp")]
        for name in stale:
            try:
                os.unlink(os.path.join(self.incident_dir, name))
            except OSError:
                pass


def list_incidents(incident_dir: str, limit: int = 50) -> list[dict]:
    """Bundle metadata, newest first, WITHOUT parsing bundle bodies (a
    ring dump can run megabytes; the listing must stay cheap).  The
    filename carries the wall timestamp and reasons."""
    try:
        names = [n for n in os.listdir(incident_dir)
                 if n.startswith(BUNDLE_PREFIX)
                 and n.endswith(BUNDLE_SUFFIX)]
    except OSError:
        return []
    out = []
    for name in sorted(names, reverse=True)[:max(0, int(limit))]:
        path = os.path.join(incident_dir, name)
        try:
            st = os.stat(path)
        except OSError:
            continue
        body = name[len(BUNDLE_PREFIX):-len(BUNDLE_SUFFIX)]
        parts = body.split("-", 2)
        wall_ns = int(parts[0]) if parts and parts[0].isdigit() else None
        reasons = parts[2].split(".") if len(parts) == 3 else []
        out.append({"name": name, "size_bytes": st.st_size,
                    "wall_time_ns": wall_ns, "reasons": reasons})
    return out


def load_incident(incident_dir: str, name: str) -> dict | None:
    """One parsed bundle by listing name; None if absent.  The name is
    validated against the bundle pattern — this is reachable from RPC,
    so no path components may sneak in."""
    if (os.sep in name or (os.altsep and os.altsep in name)
            or not name.startswith(BUNDLE_PREFIX)
            or not name.endswith(BUNDLE_SUFFIX)):
        return None
    path = os.path.join(incident_dir, name)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
