"""Storage integrity doctor: cross-store boot consistency, deep
hash-chain verification, and repair.

The chaos plane (PR 8) made the node *fail-stop* on storage errors;
salvage (storage/db.py) makes a corrupted store *readable* again.
Neither makes the survivors *trustworthy*: nothing verified that the
blockstore, statestore, WAL and privval last-sign-state still agree
after a crash, and a salvaged log can silently resurrect stale records
or lose tombstones.  The doctor closes that loop at every boot:

1. **Cross-store consistency** (`boot_check`): blockstore height/base
   vs statestore height vs WAL EndHeight lineage vs the privval
   last-sign-state, with the dangerous cases distinguished:

   - *privval ahead of everything* (sign state claims heights the
     stores never saw, and no in-flight corruption repair explains it):
     REFUSE to start.  The data dir regressed under a key that kept
     signing — the one recovery an operator must not reach for is
     resetting the sign state, because that is how validators
     double-sign.
   - *stores disagreeing*: roll the ahead store's view back to the max
     mutually-consistent height (blockstore tip truncation, or a
     statestore rebuild from the per-height validator/params/ABCI
     records) and let blocksync re-fetch the difference.
   - *WAL lineage ahead of the repaired stores*: quarantine the WAL —
     replaying records from a discarded timeline would feed consensus
     garbage; double-sign safety lives in the privval state, not the
     WAL.

2. **Deep scan** (`deep_scan`): walk the block hash chain
   (``header.last_block_id`` -> parent hash), the per-height
   meta/commit cross-references and the app-hash lineage (stored
   FinalizeBlock response vs the next header) over a configurable
   window back from the tip, and truncate to the last *verified* height
   on any mismatch.  Runs automatically whenever a store was salvaged
   (its ``.dirty`` marker is cleared only by a passing scan) and on
   demand via the offline ``doctor`` CLI subcommand.

The ABCI application is NOT rolled back by the doctor (same caveat as
the ``rollback`` command): after a truncating repair, a persistent app
that already executed the truncated heights needs its own rollback or a
resync.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass, field, replace as dc_replace


class DoctorError(Exception):
    """Refusal to start (or an unrepairable inconsistency).  Carries the
    report built so far in ``.report`` when available."""

    def __init__(self, msg: str, report: "DoctorReport | None" = None):
        super().__init__(msg)
        self.report = report


@functools.cache
def _doctor_metrics():
    from ..libs import metrics as m

    return m.counter("doctor_repairs_total",
                     "storage-doctor repair actions, by kind")


@dataclass
class DoctorReport:
    """What the doctor found and did, surfaced via ``/status`` (live and
    inspect mode) and the ``doctor`` CLI."""

    ok: bool = True
    refused: str | None = None
    heights: dict = field(default_factory=dict)
    salvage: dict = field(default_factory=dict)
    actions: list = field(default_factory=list)
    findings: list = field(default_factory=list)
    deep_scan: dict | None = None

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "refused": self.refused,
            "heights": dict(self.heights),
            "salvage": dict(self.salvage),
            "actions": list(self.actions),
            "findings": list(self.findings),
            "deep_scan": dict(self.deep_scan)
            if self.deep_scan is not None else None,
        }


class StorageDoctor:
    def __init__(self, block_store, state_store, *, wal_path: str | None
                 = None, priv_validator=None,
                 privval_state_path: str | None = None,
                 deep_scan_window: int = 128, name: str = "node"):
        self.block_store = block_store
        self.state_store = state_store
        self.wal_path = wal_path
        self.priv_validator = priv_validator
        self.privval_state_path = privval_state_path
        self.deep_scan_window = deep_scan_window
        from ..libs import log as tmlog

        self.log = tmlog.logger("doctor", node=name)

    # ------------------------------------------------------------ helpers

    def _privval_height(self, report: DoctorReport) -> int | None:
        """Last-sign height: from the live PrivValidator when it carries
        one (FilePV), else leniently from the state file (inspect/CLI
        mode, where an unreadable file is a FINDING, not a crash)."""
        if self.priv_validator is not None:
            h = getattr(self.priv_validator, "height", None)
            return h if isinstance(h, int) else None
        path = self.privval_state_path
        if path and os.path.exists(path):
            import json

            try:
                with open(path) as f:
                    return int(json.load(f)["height"])
            except (OSError, ValueError, KeyError, TypeError) as e:
                report.findings.append(
                    f"privval state file unreadable: {e!r} (do NOT reset "
                    f"it — restore from backup)")
        return None

    def _db_salvage_info(self, store_db) -> dict | None:
        salvaged = bool(getattr(store_db, "salvaged", False))
        dirty = getattr(store_db, "is_dirty", None)
        dirty = bool(dirty is not None and dirty())
        if not (salvaged or dirty):
            return None
        info = {"salvaged_this_open": salvaged, "dirty": dirty}
        spans = getattr(store_db, "salvage_spans", None)
        if spans:
            info["spans"] = [[lo, hi] for lo, hi in spans]
        get_info = getattr(store_db, "dirty_info", None)
        if not spans and get_info is not None:
            prev = get_info()
            if prev and prev.get("spans"):
                info["spans"] = prev["spans"]
        return info

    def _clear_dirty(self, which=("block", "state")) -> None:
        if "block" in which:
            self.block_store.clear_dirty()
        if "state" in which:
            fn = getattr(self.state_store.db, "clear_dirty", None)
            if fn is not None:
                fn()

    def _repair(self, report: DoctorReport, action: str, kind: str) -> None:
        report.actions.append(action)
        _doctor_metrics().inc(kind=kind)
        self.log.warn("storage doctor repair", action=action)

    # --------------------------------------------------------- boot check

    def boot_check(self, repair: bool = True,
                   raise_on_refusal: bool | None = None,
                   force_deep: bool = False,
                   deep_window: int | None = None) -> DoctorReport:
        """Fast cross-store consistency pass.  ``repair=True`` (node
        boot) fixes what it can and raises :class:`DoctorError` on the
        dangerous cases; ``repair=False`` (inspect / ``doctor`` without
        ``--repair``) only reports.  ``raise_on_refusal`` defaults to
        ``repair``.  ``force_deep`` runs the deep scan even on a clean
        store (the offline CLI always walks the chain); either way the
        WAL-lineage check runs LAST, against the post-repair heights."""
        if raise_on_refusal is None:
            raise_on_refusal = repair
        report = DoctorReport()
        bs, ss = self.block_store, self.state_store

        bs_salv = self._db_salvage_info(bs.db)
        ss_salv = self._db_salvage_info(ss.db)
        if bs_salv:
            report.salvage["blockstore"] = bs_salv
        if ss_salv:
            report.salvage["statestore"] = ss_salv
        any_dirty = bool(bs_salv or ss_salv)

        try:
            state = ss.load()
        except Exception as e:
            return self._refuse(report, raise_on_refusal,
                                f"statestore state record undecodable "
                                f"({e!r}): resync this node")
        bs_h, bs_base = bs.height(), bs.base()
        st_h = state.last_block_height if state is not None else 0
        wal_eh = None
        if self.wal_path:
            from ..consensus.wal import last_end_height

            wal_eh = last_end_height(self.wal_path)
        pv_h = self._privval_height(report)
        report.heights = {"blockstore": bs_h, "blockstore_base": bs_base,
                          "state": st_h, "wal_end_height": wal_eh,
                          "privval": pv_h}

        # ---- the double-sign tripwire: privval ahead of everything.
        # Tolerates the normal +1 (the signer votes for height h+1
        # while the stores still hold h).  A salvaged store explains a
        # larger gap (the repair below/deep scan re-fetches); a CLEAN
        # store that is behind what this key signed means the data dir
        # regressed underneath a live key — refuse, loudly.
        if pv_h is not None and pv_h > max(bs_h, st_h) + 1 and not any_dirty:
            return self._refuse(
                report, raise_on_refusal,
                f"privval last-sign state is at height {pv_h} but the "
                f"stores only reach {max(bs_h, st_h)}: the data dir "
                f"regressed under a key that kept signing (restored "
                f"backup?).  REFUSING to start.  Do NOT reset the "
                f"priv_validator state file to \"fix\" this — resetting "
                f"sign state is how validators double-sign.  Restore a "
                f"data dir that matches the sign state, or move this key "
                f"only after the network is provably past height {pv_h}.")

        if state is None and bs_h > 0:
            return self._refuse(
                report, raise_on_refusal,
                f"statestore is empty but the blockstore reaches {bs_h}: "
                f"state cannot be rebuilt locally — statesync or resync "
                f"this node")

        # ---- cross-store reconcile: roll the ahead store's view back
        # to the max mutually-consistent height; blocksync re-fetches.
        if state is not None and bs_h > st_h + 1:
            # blockstore ahead beyond the one-block crash window the
            # Handshaker covers: state for those blocks never persisted
            if st_h + 1 < bs_base:
                # a (possibly stale-resurrected) state below the pruned
                # base: truncating there would leave a store claiming a
                # tip it holds no blocks for
                return self._refuse(
                    report, raise_on_refusal,
                    f"state height {st_h} is below the blockstore base "
                    f"{bs_base}: cannot truncate below a pruned base — "
                    f"statesync or resync this node")
            if repair:
                removed = bs.truncate_above(st_h + 1)
                self._repair(
                    report,
                    f"blockstore ahead of state ({bs_h} > {st_h}+1): "
                    f"truncated {removed} blocks to {st_h + 1}; blocksync "
                    f"re-fetches", "truncate_ahead_blockstore")
                bs_h = bs.height()
            else:
                report.findings.append(
                    f"blockstore ahead of state ({bs_h} > {st_h}+1)")
        if state is not None and st_h > bs_h:
            # statestore ahead: the blockstore lost its tip (salvage
            # data loss).  Rebuild the state snapshot at the blockstore
            # tip from the per-height records.
            if repair:
                state = self._rebuild_state_at(report, state, bs_h,
                                               raise_on_refusal)
                if report.refused:
                    return report
                self._repair(
                    report,
                    f"state ahead of blockstore ({st_h} > {bs_h}): state "
                    f"rebuilt at {bs_h} from per-height records",
                    "rewind_state")
                st_h = bs_h
            else:
                report.findings.append(
                    f"state ahead of blockstore ({st_h} > {bs_h})")

        # ---- a salvaged store is only trustworthy after the deep
        # hash-chain walk: salvage can resurrect stale values or lose
        # tombstones that no per-record CRC can see.
        if any_dirty or force_deep:
            report.deep_scan = self.deep_scan(
                window=deep_window, repair=repair, report=report)
            if report.refused:
                report.ok = False
                if raise_on_refusal:
                    raise DoctorError(report.refused, report)
                return report
            if repair and report.deep_scan.get("ok") and any_dirty:
                self._clear_dirty()
                report.actions.append(
                    "deep scan verified the salvaged store; dirty "
                    "markers cleared")

        # ---- WAL lineage against the final (possibly repaired) view
        final_h = bs.height()
        if wal_eh is not None and wal_eh > final_h:
            if repair:
                from ..consensus.wal import quarantine_wal

                moved = quarantine_wal(self.wal_path)
                self._repair(
                    report,
                    f"WAL EndHeight {wal_eh} ahead of stores at {final_h}: "
                    f"{len(moved)} segments quarantined (replay from a "
                    f"discarded timeline is unsafe; privval state guards "
                    f"double-signing)", "quarantine_wal")
            else:
                report.findings.append(
                    f"WAL EndHeight {wal_eh} ahead of stores at {final_h}")

        report.ok = report.refused is None and (
            repair or (not report.findings
                       and not (report.deep_scan or {}).get("bad")))
        return report

    def _refuse(self, report: DoctorReport, raise_on_refusal: bool,
                msg: str) -> DoctorReport:
        report.refused = msg
        report.ok = False
        self.log.error("storage doctor refusal", reason=msg)
        if raise_on_refusal:
            raise DoctorError(msg, report)
        return report

    # ---------------------------------------------------------- deep scan

    def deep_scan(self, window: int | None = None, repair: bool = False,
                  report: DoctorReport | None = None) -> dict:
        """Walk the hash chain and app-hash lineage over ``window``
        heights back from the tip (0/None = config default; the config's
        0 means the whole store).  On mismatch with ``repair``: truncate
        the blockstore to the last verified height below the FIRST bad
        one (keeping the chain contiguous for app replay) and rebuild
        the state snapshot there; blocksync re-fetches the rest."""
        bs, ss = self.block_store, self.state_store
        if report is None:
            report = DoctorReport()
        if window is None:
            window = self.deep_scan_window
        top, base = bs.height(), max(bs.base(), 1)
        out: dict = {"window": [base, top], "scanned": 0, "bad": [],
                     "verified_to": None, "truncated_to": None, "ok": True}
        if top == 0:
            return out
        lo = base if window <= 0 else max(base, top - window + 1)
        out["window"] = [lo, top]

        if bs.load_block(top) is None and top == bs.base() \
                and bs.load_seen_commit() is not None:
            # statesync anchor: bookkeeping + trusted commit, no blocks.
            # Nothing to walk — and nothing this store can mis-serve.
            out["anchor_only"] = True
            return out

        try:
            state = ss.load()
        except Exception:
            state = None

        # the blockstore hash chain cannot vouch for the statestore's
        # per-height records — but the headers CAN: validators_hash and
        # consensus_hash commit to the validator-set and params records.
        # A salvaged statestore (dirty) gets that check; a stale
        # resurrected record is unrepairable locally (the content behind
        # the hash is gone), so a mismatch keeps the marker/refuses.
        ss_dirty = getattr(ss.db, "is_dirty", None)
        verify_state = bool(ss_dirty is not None and ss_dirty())
        state_ok = True

        bad: set[int] = set()
        upper_block = None          # block at h+1 (walking downward)
        upper_ok = False
        for h in range(top, lo - 1, -1):
            out["scanned"] += 1
            upper, upper_was_ok = upper_block, upper_ok
            upper_block, upper_ok = None, False     # until h verifies
            block = meta = None
            try:
                block = bs.load_block(h)
                meta = bs.load_block_meta(h)
            except Exception as e:
                report.findings.append(f"height {h}: undecodable ({e!r})")
            if block is None or meta is None:
                bad.add(h)
                report.findings.append(
                    f"height {h}: missing "
                    f"{'block' if block is None else 'meta'} record")
                continue
            bhash = block.hash()
            if meta.block_id.hash != bhash or block.header.height != h:
                bad.add(h)
                report.findings.append(
                    f"height {h}: block/meta mismatch (meta "
                    f"{meta.block_id.hash.hex()[:12]} vs header "
                    f"{bhash.hex()[:12]})")
                continue
            try:
                commit = bs.load_block_commit(h)
            except Exception:
                commit = False          # undecodable commit record
            if commit is False or (commit is not None
                                   and commit.block_id.hash != bhash):
                bad.add(h)
                report.findings.append(
                    f"height {h}: canonical commit does not certify the "
                    f"stored block")
                continue
            if commit is None and h < top:
                # save_block writes the canonical commit for h when
                # block h+1 lands, so below the tip its absence means a
                # lost record (the tip's commit legitimately lives only
                # in the seen-commit slot)
                bad.add(h)
                report.findings.append(
                    f"height {h}: canonical commit record missing")
                continue
            if upper is not None and upper_was_ok and h + 1 not in bad:
                # hash chain: the child header vouches for the parent
                if upper.header.last_block_id.hash != bhash:
                    bad.add(h + 1)
                    report.findings.append(
                        f"height {h + 1}: last_block_id does not match "
                        f"block {h} (hash chain broken)")
                else:
                    # app-hash lineage via the stored FinalizeBlock
                    # response, when one is present (they are optional:
                    # discard_abci_responses / pruned)
                    resp_app = self._resp_app_hash(h)
                    if resp_app is not None and \
                            upper.header.app_hash != resp_app:
                        bad.add(h + 1)
                        report.findings.append(
                            f"height {h + 1}: header app_hash breaks the "
                            f"stored response lineage at {h}")
            if verify_state:
                # the header commits to the per-height statestore
                # records: validators_hash / consensus_hash.  A missing
                # record degrades like pruning; a PRESENT-but-different
                # one is a stale resurrection
                try:
                    vals = ss.load_validators(h)
                except Exception:
                    vals = False
                if vals is False or (
                        vals is not None
                        and vals.hash() != block.header.validators_hash):
                    state_ok = False
                    report.findings.append(
                        f"height {h}: statestore validator-set record "
                        f"contradicts header validators_hash")
                try:
                    params = ss.load_params(h)
                except Exception:
                    params = False
                if params is False or (
                        params is not None
                        and params.hash() != block.header.consensus_hash):
                    state_ok = False
                    report.findings.append(
                        f"height {h}: statestore params record "
                        f"contradicts header consensus_hash")
            if h == top and state is not None and \
                    state.last_block_height == top and \
                    state.last_block_id.hash != bhash:
                bad.add(h)
                report.findings.append(
                    f"height {h}: state.last_block_id does not match the "
                    f"stored tip block")
            upper_block, upper_ok = block, True

        if verify_state:
            out["state_records_ok"] = state_ok
            if not state_ok:
                # unrepairable locally: the content behind the header
                # hashes is gone — never clear the dirty marker, and in
                # repair mode refuse outright (resync)
                out["ok"] = False
                out["bad"] = sorted(bad)
                if repair:
                    self._refuse(
                        report, False,
                        "salvaged statestore records contradict the "
                        "header hashes (stale resurrection): cannot be "
                        "rebuilt locally — statesync or resync this node")
                return out

        out["bad"] = sorted(bad)
        if not bad:
            out["verified_to"] = lo
            return out
        out["ok"] = False
        first_bad = min(bad)
        # the verified SUFFIX starts above the highest bad height (a
        # lower first_bad does not vouch for the corrupt ones above it)
        max_bad = max(bad)
        out["verified_to"] = max_bad + 1 if max_bad < top else None
        if not repair:
            return out

        target = first_bad - 1
        if first_bad <= bs.base() and bs.base() > 1:
            # the corruption reaches a pruned/statesync'd base: there is
            # nothing below to truncate to — only a resync recovers
            self._refuse(
                report, False,
                f"deep scan found corruption at height {first_bad}, at or "
                f"below the store base {bs.base()}: cannot truncate below "
                f"a pruned base — statesync or resync this node")
            out["ok"] = False
            return out
        removed = bs.truncate_above(target)
        if state is not None and state.last_block_height > target:
            state = self._rebuild_state_at(report, state, target,
                                           raise_on_refusal=False)
            if report.refused:
                out["ok"] = False
                return out
        self._repair(
            report,
            f"deep scan: heights {sorted(bad)} failed verification; "
            f"truncated {removed} blocks to last verified height "
            f"{target}; blocksync re-fetches", "truncate_unverified")
        out["truncated_to"] = target
        out["ok"] = True
        return out

    def _resp_app_hash(self, height: int) -> bytes | None:
        try:
            raw = self.state_store.load_finalize_block_response(height)
            if raw is None:
                return None
            from ..sm.execution import unpack_finalize_response

            return unpack_finalize_response(raw).app_hash
        except Exception:
            return None

    # ------------------------------------------------------- state rebuild

    def _rebuild_state_at(self, report: DoctorReport, state, target: int,
                          raise_on_refusal: bool):
        """Reconstruct and persist the state snapshot as of ``target``
        from the per-height records (validator sets, params, the stored
        FinalizeBlock response, the block meta) — the doctor's analogue
        of ``rollback_state`` for targets whose upper blocks are GONE
        (ordinary rollback needs the block being undone; a salvaged
        store lost it)."""
        bs, ss = self.block_store, self.state_store
        if target == 0:
            ss.clear_state()
            self._repair(report,
                         "state reset to genesis (no verified height "
                         "left); the node resyncs from scratch",
                         "reset_state")
            return None
        if target < bs.base():
            self._refuse(
                report, raise_on_refusal,
                f"cannot rebuild state at {target}: below the store base "
                f"{bs.base()} — statesync or resync this node")
            return state
        try:
            vals = ss.load_validators(target + 1)
            nvals = ss.load_validators(target + 2)
            lvals = ss.load_validators(target)
            params = ss.load_params(target + 1)
            meta = bs.load_block_meta(target)
            block = bs.load_block(target)
            raw = ss.load_finalize_block_response(target)
        except Exception as e:
            self._refuse(report, raise_on_refusal,
                         f"cannot rebuild state at {target}: per-height "
                         f"records undecodable ({e!r}) — resync this node")
            return state
        if vals is None or nvals is None or meta is None or block is None \
                or raw is None:
            self._refuse(
                report, raise_on_refusal,
                f"cannot rebuild state at {target}: missing per-height "
                f"records (validators/meta/block/ABCI response) — "
                f"statesync or resync this node")
            return state
        if meta.block_id.hash != block.hash():
            self._refuse(
                report, raise_on_refusal,
                f"cannot rebuild state at {target}: block/meta mismatch "
                f"at the rebuild anchor — resync this node")
            return state
        from ..sm.execution import unpack_finalize_response

        resp = unpack_finalize_response(raw)
        new_state = dc_replace(
            state,
            last_block_height=target,
            last_block_id=meta.block_id,
            last_block_time_ns=block.header.time_ns,
            validators=vals,
            next_validators=nvals,
            last_validators=lvals,
            last_height_validators_changed=min(
                state.last_height_validators_changed, target + 1),
            consensus_params=params if params is not None
            else state.consensus_params,
            last_height_params_changed=min(
                state.last_height_params_changed, target + 1),
            last_results_hash=resp.results_hash(),
            app_hash=resp.app_hash,
        )
        ss.save(new_state)
        return new_state
