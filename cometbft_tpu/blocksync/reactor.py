"""Blocksync reactor: fast-sync a lagging node from its peers' block
stores (reference: ``internal/blocksync/reactor.go:55,319,495,548``).

Channel 0x40, five messages (StatusRequest/StatusResponse, BlockRequest/
BlockResponse/NoBlockResponse — ``proto/cometbft/blocksync``).

The TPU-first redesign is in the apply loop: where the reference verifies
one commit per block sequentially (``reactor.go:495`` VerifyCommitLight per
PeekTwoBlocks pair), this reactor accumulates a contiguous *window* of
fetched blocks and proves all their commits in ONE device batch
(``types.validation.verify_commits_light_batched``), then applies them
back-to-back with signature re-verification elided.  Cross-block batching
is BASELINE configs[4] and the flagship throughput win of the port.

Since r13 the window is a double-buffered pipeline (ROADMAP item 1):
while window K verifies on the dispatch worker (``asyncio.to_thread`` →
the device-owner thread, ``patient`` queueing), the apply loop stages
window K+1 — host packing (part sets, sign-bytes rows) and host→device
transfer overlap the previous window's compute, so the mesh never idles
between windows.  The window depth is the ``blocksync.verify_window``
config knob (default ``BATCH_WINDOW``); verdicts demux per item, so one
bad block costs the redo of exactly that height (+ its voucher) while
the proven prefix still applies and the offending peer is scored
through ``Switch.report_peer`` (``bad_block``)."""

from __future__ import annotations

import asyncio

from ..crypto import plan as deviceplan
from ..libs import aio, clock

import msgpack

from ..sm.validation import BlockValidationError, validate_block
from ..types import codec
from ..types.block_id import BlockID
from ..types.part_set import PartSet
from ..types.validation import (CommitVerificationError, ErrBatchItemInvalid,
                                ErrInvalidSignature,
                                verify_commits_light_batched)
from ..p2p.reactor import ChannelDescriptor, Reactor
from .pool import BlockPool

BLOCKSYNC_CHANNEL = 0x40
STATUS_UPDATE_INTERVAL = 3.0     # reference statusUpdateIntervalSeconds (10)
SWITCH_CHECK_INTERVAL = 0.2      # reference switchToConsensusIntervalSeconds
# default blocks per device batch (+1 for the vouching tail) — the
# config knob blocksync.verify_window overrides per deployment
BATCH_WINDOW = 32


def _pack(tag: str, **fields) -> bytes:
    fields["@"] = tag
    return msgpack.packb(fields, use_bin_type=True)


class BlocksyncReactor(Reactor):
    def __init__(self, block_exec, block_store, state, *,
                 fast_sync: bool = False, switch_to_consensus=None,
                 backend: str | None = None,
                 no_peers_grace: float = 5.0,
                 verify_window: int | None = None, name: str = "bs"):
        super().__init__()
        self.block_exec = block_exec
        self.block_store = block_store
        self.state = state
        self.fast_sync = fast_sync
        self.switch_to_consensus = switch_to_consensus
        self.backend = backend
        self.no_peers_grace = no_peers_grace
        # accumulator depth: blocks whose commits fill one device batch
        # (config blocksync.verify_window; Config.validate bounds it)
        self.verify_window = max(2, int(verify_window or BATCH_WINDOW))
        self.name = name
        self.pool: BlockPool | None = None
        self._tasks: list[asyncio.Task] = []
        self.synced = asyncio.Event()
        self.hold = False        # statesync runs first; node releases us
        if not fast_sync:
            self.synced.set()

    def get_channels(self):
        return [ChannelDescriptor(BLOCKSYNC_CHANNEL, priority=5,
                                  send_queue_capacity=1000,
                                  name="blocksync")]

    # ----------------------------------------------------------- lifecycle

    async def start(self) -> None:
        if not self.fast_sync or self.hold:
            return
        await self.start_sync()

    async def start_sync(self) -> None:
        """Launch the pool + apply loop (deferred when statesync runs
        first — reference node startup order statesync -> blocksync)."""
        self.pool = BlockPool(
            self.block_store.height() + 1
            if self.block_store.height() else self.state.initial_height,
            self._send_block_request, self._on_pool_peer_error)
        self.pool.start()
        self._tasks = [
            asyncio.create_task(self._apply_routine()),
            asyncio.create_task(self._status_routine()),
        ]

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        self._tasks = []
        if self.pool is not None:
            await self.pool.stop()

    # ---------------------------------------------------------------- p2p

    def add_peer(self, peer) -> None:
        peer.send(BLOCKSYNC_CHANNEL, _pack(
            "sres", h=self.block_store.height(), b=self.block_store.base()))
        if self.pool is not None:
            peer.send(BLOCKSYNC_CHANNEL, _pack("sreq"))

    def remove_peer(self, peer, reason=None) -> None:
        if self.pool is not None:
            self.pool.remove_peer(peer.id, str(reason or ""))

    def receive(self, channel_id: int, peer, msg: bytes) -> None:
        d = msgpack.unpackb(msg, raw=False)
        tag = d.get("@")
        if tag == "sreq":
            peer.send(BLOCKSYNC_CHANNEL, _pack(
                "sres", h=self.block_store.height(),
                b=self.block_store.base()))
        elif tag == "sres":
            if self.pool is not None:
                self.pool.set_peer_range(peer.id, d["b"], d["h"])
        elif tag == "breq":
            self._serve_block(peer, d["h"])
        elif tag == "bres":
            if self.pool is not None:
                block = codec.unpack(d["blk"])
                ext = codec.unpack(d["ext"]) if d.get("ext") else None
                self.pool.add_block(peer.id, block, ext)
        elif tag == "nores":
            pass    # requester timeout will redo with another peer

    def _serve_block(self, peer, height: int) -> None:
        """reactor.go respondToPeer."""
        if getattr(self.block_store, "is_dirty", None) is not None and \
                self.block_store.is_dirty():
            # salvaged-but-unverified store: salvage can resurrect stale
            # records, so nothing here may be served until the doctor's
            # deep verification clears the dirty marker
            peer.send(BLOCKSYNC_CHANNEL, _pack("nores", h=height))
            return
        block = self.block_store.load_block(height)
        if block is None:
            peer.send(BLOCKSYNC_CHANNEL, _pack("nores", h=height))
            return
        ext = None
        if self.state.consensus_params.feature.vote_extensions_enabled(
                height):
            ext = self.block_store.load_block_extended_commit(height)
        peer.send(BLOCKSYNC_CHANNEL, _pack(
            "bres", h=height, blk=codec.pack(block),
            ext=codec.pack(ext) if ext is not None else None))

    def _send_block_request(self, peer_id: str, height: int) -> None:
        peer = self.switch.peers.get(peer_id) if self.switch else None
        if peer is not None:
            peer.send(BLOCKSYNC_CHANNEL, _pack("breq", h=height))

    def _on_pool_peer_error(self, peer_id: str, reason: str,
                            event: str = "block_timeout") -> None:
        if self.switch is None:
            return
        if hasattr(self.switch, "report_peer"):
            # score the typed event (bad_block bans on repetition) AND
            # drop the peer — the pool already decided it must go
            self.switch.report_peer(peer_id, event, detail=reason,
                                    disconnect=True)
            return
        peer = self.switch.peers.get(peer_id)
        if peer is not None:
            aio.spawn(self.switch.stop_peer_for_error(peer, reason))

    # ------------------------------------------------------- status gossip

    async def _status_routine(self) -> None:
        while True:
            await clock.sleep(STATUS_UPDATE_INTERVAL)
            if self.switch is not None:
                self.switch.broadcast(BLOCKSYNC_CHANNEL, _pack(
                    "sres", h=self.block_store.height(),
                    b=self.block_store.base()))

    # ---------------------------------------------------------- apply loop

    async def _apply_routine(self) -> None:
        """reactor.go:319 poolRoutine, rebuilt as a double-buffered
        cross-block pipeline: one window verifies on the dispatch worker
        while the next window is peeked, packed and dispatched behind it
        — host staging overlaps device compute, so consecutive windows
        keep the mesh full during catch-up."""
        pool = self.pool
        started = clock.monotonic()
        staged: _StagedWindow | None = None
        while True:
            if self._should_switch(started):
                self._discard_staged(staged)
                await self._do_switch()
                return
            if staged is None:
                try:
                    staged = self._stage_window(0)
                except _RedoBlock as e:
                    pool.redo_request(e.height)
                    pool.redo_request(e.height + 1)
                    continue
            if staged is None:
                await clock.sleep(SWITCH_CHECK_INTERVAL)
                continue
            # double-buffer: stage the window BEHIND the in-flight one
            # (its packing + host->device staging run while the first
            # window's signatures verify; a valset boundary or an empty
            # pool tail simply yields None — partial windows flush, they
            # never wait for a full buffer; skip>0 never raises)
            nxt = self._stage_window(staged.n_blocks)
            try:
                applied = await self._apply_staged(staged)
            except _RedoBlock as e:
                # both the block AND the next block (whose last_commit
                # vouched for it) are suspect (reference poolRoutine
                # redoes first.Height and second.Height,
                # reactor.go:505-512).  redo_request scores the serving
                # peer (bad_block -> Switch.report_peer via the pool's
                # error hook) and refetches; the speculative next window
                # verified against heights now being refetched, so it is
                # discarded wholesale.
                pool.redo_request(e.height)
                pool.redo_request(e.height + 1)
                self._discard_staged(nxt)
                staged = None
                continue
            staged = nxt
            if applied == 0 and staged is None:
                await clock.sleep(SWITCH_CHECK_INTERVAL)

    def _should_switch(self, started: float) -> bool:
        pool = self.pool
        if pool.is_caught_up():
            return True
        if not pool.peers and \
                clock.monotonic() - started > self.no_peers_grace:
            return True          # nobody to sync from: just run consensus
        return False

    async def _do_switch(self) -> None:
        """reactor.go:421-431 SwitchToConsensus."""
        await self.pool.stop()
        self.synced.set()
        if self.switch_to_consensus is not None:
            await self.switch_to_consensus(self.state)

    # ------------------------------------------------- window accumulator

    def _stage_window(self, skip: int) -> "_StagedWindow | None":
        """Collect the longest same-valset run of fetched blocks starting
        ``skip`` blocks past the pool head (skip>0 = the speculative
        second buffer) and hand it to the dispatch worker: packing (part
        sets, dense sign-bytes rows) and the device batch run off the
        event loop while this loop keeps applying.

        Returns None when there is nothing to stage.  Raises _RedoBlock
        only for skip=0 with a valset mismatch at the very next block to
        apply (the header lies or the chain advanced validators); at
        skip>0 the same mismatch is just the rotation boundary the next
        loop iteration handles with fresh state."""
        state = self.state
        # mesh-aware window depth: with a device mesh active, one staged
        # window should fill the WHOLE mesh in a single sharded dispatch
        # — snap the block count up so window_lanes ~= mesh x lane_bucket
        # (plan.window_blocks; the base verify_window stands off-mesh)
        blocks = deviceplan.window_blocks(
            self.verify_window, len(state.validators.validators))
        window = self.pool.peek_window(skip + blocks + 1)[skip:]
        if len(window) < 2:
            return None
        vals_hash = state.validators.hash()
        raw = []                 # (block, vouching commit, ext)
        for i in range(len(window) - 1):
            first, ext = window[i]
            second, _ = window[i + 1]
            if first.header.validators_hash != vals_hash or \
                    second.last_commit is None:
                break
            raw.append((first, second.last_commit, ext))
        if not raw:
            if skip == 0:
                raise _RedoBlock(self.pool.height)
            return None
        task = asyncio.create_task(asyncio.to_thread(
            self._pack_verify_window, state, raw))
        # a discarded buffer (redo, switch-over) must not surface
        # "exception never retrieved" — reading the exception in a done
        # callback is harmless for the awaited case
        task.add_done_callback(lambda t: t.cancelled() or t.exception())
        return _StagedWindow(task=task, n_blocks=len(raw),
                             first_height=raw[0][0].header.height)

    def _pack_verify_window(self, state, raw):
        """Worker-thread body: pack part sets + block IDs, then prove
        every commit of the window in one batched dispatch (``patient``:
        queue behind the previous window on the device — that queueing
        IS the transfer/compute overlap).  Returns ``(prefix, err)``
        where prefix entries are apply-ready and ``err`` (an
        ErrBatchItemInvalid with window-relative ``item``) marks the
        first UNPROVEN item; entries before ``err.item`` are proven, so
        the caller can apply them before redoing the bad height."""
        prefix = []              # (block, parts, block_id, commit, ext)
        items = []
        for first, commit, ext in raw:
            parts = PartSet.from_data(codec.pack(first))
            fid = BlockID(first.hash(), parts.header())
            items.append((fid, first.header.height, commit))
            prefix.append((first, parts, fid, commit, ext))
        err = None
        try:
            verify_commits_light_batched(
                state.chain_id, state.validators, items,
                backend=self.backend, patient=True)
        except ErrBatchItemInvalid as e:
            err = e
            if e.item > 0 and not isinstance(e.cause, ErrInvalidSignature):
                # pre-dispatch basics/tally failure: NO lane of any item
                # was verified.  Prove the prefix separately so per-item
                # demux can still apply the good blocks.  (A signature
                # failure needs no second pass — the dense dispatch
                # computes every verdict before raising, so items before
                # the offender are already proven.)
                try:
                    verify_commits_light_batched(
                        state.chain_id, state.validators, items[:e.item],
                        backend=self.backend, patient=True)
                except ErrBatchItemInvalid as e2:
                    err = e2
        return prefix, err

    async def _apply_staged(self, staged: "_StagedWindow") -> int:
        """Await the window's verdicts and apply the proven prefix
        (reactor.go:495-548).  Per-item demux: a bad commit raises
        _RedoBlock for exactly its height AFTER the proven neighbors
        applied — one lying peer costs one refetch, not the window."""
        prefix, err = await staged.task
        good = prefix if err is None else prefix[:err.item]
        applied = 0
        state = self.state
        for first, parts, fid, commit, ext in good:
            h = first.header.height
            try:
                # structural checks only: sigs proven in the batch above
                validate_block(state, first, backend=self.backend,
                               verify_last_commit_sigs=False)
                self.block_exec.evidence_pool.check_evidence(first.evidence)
            except (BlockValidationError, CommitVerificationError) as e:
                raise _RedoBlock(h) from e
            ext_enabled = state.consensus_params.feature \
                .vote_extensions_enabled(h)
            if ext_enabled:
                if ext is None or ext.height != h or \
                        not ext.ensure_extensions(True):
                    raise _RedoBlock(h)
                self.block_store.save_block_with_extended_commit(
                    first, parts, ext)
            else:
                self.block_store.save_block(first, parts, commit)
            state = await self.block_exec.apply_block(
                state, fid, first, verified=True)
            self.state = state
            self.pool.pop_request()
            applied += 1
        if err is not None:
            raise _RedoBlock(err.height) from err
        return applied

    @staticmethod
    def _discard_staged(staged: "_StagedWindow | None") -> None:
        """Drop a speculative buffer whose heights are being refetched
        (or whose reactor is switching over).  The to_thread body cannot
        be interrupted mid-dispatch; the done callback attached at stage
        time consumes its result/exception."""
        if staged is not None:
            staged.task.cancel()


class _StagedWindow:
    """One buffer of the double-buffered verify pipeline: a window of
    contiguous fetched blocks whose packing + batched commit
    verification run on the dispatch worker."""

    __slots__ = ("task", "n_blocks", "first_height")

    def __init__(self, task, n_blocks: int, first_height: int):
        self.task = task
        self.n_blocks = n_blocks
        self.first_height = first_height


class _RedoBlock(Exception):
    def __init__(self, height: int):
        self.height = height
        super().__init__(f"redo block {height}")
