"""Blocksync reactor: fast-sync a lagging node from its peers' block
stores (reference: ``internal/blocksync/reactor.go:55,319,495,548``).

Channel 0x40, five messages (StatusRequest/StatusResponse, BlockRequest/
BlockResponse/NoBlockResponse — ``proto/cometbft/blocksync``).

The TPU-first redesign is in the apply loop: where the reference verifies
one commit per block sequentially (``reactor.go:495`` VerifyCommitLight per
PeekTwoBlocks pair), this reactor peeks a contiguous *window* of fetched
blocks and proves all their commits in ONE device batch
(``types.validation.verify_commits_light_batched``), then applies them
back-to-back with signature re-verification elided.  Cross-block batching
is BASELINE configs[4] and the flagship throughput win of the port."""

from __future__ import annotations

import asyncio

from ..libs import aio
import time

import msgpack

from ..sm.validation import BlockValidationError, validate_block
from ..types import codec
from ..types.block_id import BlockID
from ..types.part_set import PartSet
from ..types.validation import (CommitVerificationError, ErrBatchItemInvalid,
                                verify_commits_light_batched)
from ..p2p.reactor import ChannelDescriptor, Reactor
from .pool import BlockPool

BLOCKSYNC_CHANNEL = 0x40
STATUS_UPDATE_INTERVAL = 3.0     # reference statusUpdateIntervalSeconds (10)
SWITCH_CHECK_INTERVAL = 0.2      # reference switchToConsensusIntervalSeconds
BATCH_WINDOW = 32                # blocks per device batch (+1 for the tail)


def _pack(tag: str, **fields) -> bytes:
    fields["@"] = tag
    return msgpack.packb(fields, use_bin_type=True)


class BlocksyncReactor(Reactor):
    def __init__(self, block_exec, block_store, state, *,
                 fast_sync: bool = False, switch_to_consensus=None,
                 backend: str | None = None,
                 no_peers_grace: float = 5.0, name: str = "bs"):
        super().__init__()
        self.block_exec = block_exec
        self.block_store = block_store
        self.state = state
        self.fast_sync = fast_sync
        self.switch_to_consensus = switch_to_consensus
        self.backend = backend
        self.no_peers_grace = no_peers_grace
        self.name = name
        self.pool: BlockPool | None = None
        self._tasks: list[asyncio.Task] = []
        self.synced = asyncio.Event()
        self.hold = False        # statesync runs first; node releases us
        if not fast_sync:
            self.synced.set()

    def get_channels(self):
        return [ChannelDescriptor(BLOCKSYNC_CHANNEL, priority=5,
                                  send_queue_capacity=1000,
                                  name="blocksync")]

    # ----------------------------------------------------------- lifecycle

    async def start(self) -> None:
        if not self.fast_sync or self.hold:
            return
        await self.start_sync()

    async def start_sync(self) -> None:
        """Launch the pool + apply loop (deferred when statesync runs
        first — reference node startup order statesync -> blocksync)."""
        self.pool = BlockPool(
            self.block_store.height() + 1
            if self.block_store.height() else self.state.initial_height,
            self._send_block_request, self._on_pool_peer_error)
        self.pool.start()
        self._tasks = [
            asyncio.create_task(self._apply_routine()),
            asyncio.create_task(self._status_routine()),
        ]

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        self._tasks = []
        if self.pool is not None:
            await self.pool.stop()

    # ---------------------------------------------------------------- p2p

    def add_peer(self, peer) -> None:
        peer.send(BLOCKSYNC_CHANNEL, _pack(
            "sres", h=self.block_store.height(), b=self.block_store.base()))
        if self.pool is not None:
            peer.send(BLOCKSYNC_CHANNEL, _pack("sreq"))

    def remove_peer(self, peer, reason=None) -> None:
        if self.pool is not None:
            self.pool.remove_peer(peer.id, str(reason or ""))

    def receive(self, channel_id: int, peer, msg: bytes) -> None:
        d = msgpack.unpackb(msg, raw=False)
        tag = d.get("@")
        if tag == "sreq":
            peer.send(BLOCKSYNC_CHANNEL, _pack(
                "sres", h=self.block_store.height(),
                b=self.block_store.base()))
        elif tag == "sres":
            if self.pool is not None:
                self.pool.set_peer_range(peer.id, d["b"], d["h"])
        elif tag == "breq":
            self._serve_block(peer, d["h"])
        elif tag == "bres":
            if self.pool is not None:
                block = codec.unpack(d["blk"])
                ext = codec.unpack(d["ext"]) if d.get("ext") else None
                self.pool.add_block(peer.id, block, ext)
        elif tag == "nores":
            pass    # requester timeout will redo with another peer

    def _serve_block(self, peer, height: int) -> None:
        """reactor.go respondToPeer."""
        if getattr(self.block_store, "is_dirty", None) is not None and \
                self.block_store.is_dirty():
            # salvaged-but-unverified store: salvage can resurrect stale
            # records, so nothing here may be served until the doctor's
            # deep verification clears the dirty marker
            peer.send(BLOCKSYNC_CHANNEL, _pack("nores", h=height))
            return
        block = self.block_store.load_block(height)
        if block is None:
            peer.send(BLOCKSYNC_CHANNEL, _pack("nores", h=height))
            return
        ext = None
        if self.state.consensus_params.feature.vote_extensions_enabled(
                height):
            ext = self.block_store.load_block_extended_commit(height)
        peer.send(BLOCKSYNC_CHANNEL, _pack(
            "bres", h=height, blk=codec.pack(block),
            ext=codec.pack(ext) if ext is not None else None))

    def _send_block_request(self, peer_id: str, height: int) -> None:
        peer = self.switch.peers.get(peer_id) if self.switch else None
        if peer is not None:
            peer.send(BLOCKSYNC_CHANNEL, _pack("breq", h=height))

    def _on_pool_peer_error(self, peer_id: str, reason: str,
                            event: str = "block_timeout") -> None:
        if self.switch is None:
            return
        if hasattr(self.switch, "report_peer"):
            # score the typed event (bad_block bans on repetition) AND
            # drop the peer — the pool already decided it must go
            self.switch.report_peer(peer_id, event, detail=reason,
                                    disconnect=True)
            return
        peer = self.switch.peers.get(peer_id)
        if peer is not None:
            aio.spawn(self.switch.stop_peer_for_error(peer, reason))

    # ------------------------------------------------------- status gossip

    async def _status_routine(self) -> None:
        while True:
            await asyncio.sleep(STATUS_UPDATE_INTERVAL)
            if self.switch is not None:
                self.switch.broadcast(BLOCKSYNC_CHANNEL, _pack(
                    "sres", h=self.block_store.height(),
                    b=self.block_store.base()))

    # ---------------------------------------------------------- apply loop

    async def _apply_routine(self) -> None:
        """reactor.go:319 poolRoutine, with windowed batch verification."""
        pool = self.pool
        started = time.monotonic()
        while True:
            if self._should_switch(started):
                await self._do_switch()
                return
            window = pool.peek_window(BATCH_WINDOW + 1)
            if len(window) < 2:
                await asyncio.sleep(SWITCH_CHECK_INTERVAL)
                continue
            try:
                applied = await self._verify_apply_window(window)
            except _RedoBlock as e:
                # both the block AND the next block (whose last_commit
                # vouched for it) are suspect (reference poolRoutine redoes
                # first.Height and second.Height, reactor.go:505-512)
                pool.redo_request(e.height)
                pool.redo_request(e.height + 1)
                continue
            if applied == 0:
                await asyncio.sleep(SWITCH_CHECK_INTERVAL)

    def _should_switch(self, started: float) -> bool:
        pool = self.pool
        if pool.is_caught_up():
            return True
        if not pool.peers and \
                time.monotonic() - started > self.no_peers_grace:
            return True          # nobody to sync from: just run consensus
        return False

    async def _do_switch(self) -> None:
        """reactor.go:421-431 SwitchToConsensus."""
        await self.pool.stop()
        self.synced.set()
        if self.switch_to_consensus is not None:
            await self.switch_to_consensus(self.state)

    async def _verify_apply_window(self, window) -> int:
        """Batch-verify the longest same-valset prefix of ``window`` in one
        device call, then apply those blocks (reactor.go:495-548; one
        dispatch instead of len(window)-1)."""
        state = self.state
        vals_hash = state.validators.hash()
        prefix = []          # (block, parts, block_id, commit, ext)
        items = []
        for i in range(len(window) - 1):
            first, ext = window[i]
            second, _ = window[i + 1]
            if first.header.validators_hash != vals_hash or \
                    second.last_commit is None:
                break
            parts = PartSet.from_data(codec.pack(first))
            fid = BlockID(first.hash(), parts.header())
            items.append((fid, first.header.height, second.last_commit))
            prefix.append((first, parts, fid, second.last_commit, ext))
        if not prefix:
            # valset rotates at the very next block — the header lies or the
            # chain advanced validators; fall back to redoing this height
            raise _RedoBlock(self.pool.height)
        try:
            verify_commits_light_batched(
                state.chain_id, state.validators,
                items, backend=self.backend)
        except ErrBatchItemInvalid as e:
            raise _RedoBlock(self.pool.height + e.item) from e

        applied = 0
        for first, parts, fid, commit, ext in prefix:
            h = first.header.height
            try:
                # structural checks only: sigs proven in the batch above
                validate_block(state, first, backend=self.backend,
                               verify_last_commit_sigs=False)
                self.block_exec.evidence_pool.check_evidence(first.evidence)
            except (BlockValidationError, CommitVerificationError) as e:
                raise _RedoBlock(h) from e
            ext_enabled = state.consensus_params.feature \
                .vote_extensions_enabled(h)
            if ext_enabled:
                if ext is None or ext.height != h or \
                        not ext.ensure_extensions(True):
                    raise _RedoBlock(h)
                self.block_store.save_block_with_extended_commit(
                    first, parts, ext)
            else:
                self.block_store.save_block(first, parts, commit)
            state = await self.block_exec.apply_block(
                state, fid, first, verified=True)
            self.state = state
            self.pool.pop_request()
            applied += 1
        return applied


class _RedoBlock(Exception):
    def __init__(self, height: int):
        self.height = height
        super().__init__(f"redo block {height}")
