"""BlockPool: parallel per-height block fetching for fast sync
(reference: ``internal/blocksync/pool.go:72,116,218,296,438``).

The reference runs one requester goroutine per in-flight height, bounded by
a total request cap and a per-peer pending cap; blocks accumulate in the
pool and the reactor's apply loop consumes them contiguously from
``height``.  Here each requester is one asyncio task on the node's event
loop — same single-writer discipline as the rest of the stack, so the pool
needs no locks.

The apply loop consumes *windows* of contiguous blocks instead of the
reference's PeekTwoBlocks pairs: the window is what fills one device batch
(cross-block commit verification, BASELINE configs[4])."""

from __future__ import annotations

import asyncio

from ..libs import clock
from typing import Callable

REQUEST_TIMEOUT = 15.0          # pool.go requestRetrySeconds
MAX_TOTAL_REQUESTERS = 64       # pool.go maxTotalRequesters (600) scaled down
MAX_PENDING_PER_PEER = 20       # pool.go maxPendingRequestsPerPeer


class _BsPeer:
    def __init__(self, peer_id: str, base: int, height: int):
        self.id = peer_id
        self.base = base
        self.height = height
        self.pending = 0            # outstanding block requests


class _Requester:
    """Owns fetching one height (pool.go bpRequester)."""

    def __init__(self, pool: "BlockPool", height: int):
        self.pool = pool
        self.height = height
        self.peer_id: str | None = None
        self.block = None
        self.ext_commit = None
        self.got_block = asyncio.Event()
        self.redo = asyncio.Event()
        self.task = asyncio.create_task(self._run())

    async def _run(self) -> None:
        while True:
            # pick a peer that has our height and spare pending capacity
            peer = None
            while peer is None:
                peer = self.pool._pick_peer(self.height)
                if peer is None:
                    await clock.sleep(0.05)
                    if self.pool._stopped:
                        return
            self.peer_id = peer.id
            peer.pending += 1
            self.pool.send_request(peer.id, self.height)
            try:
                await clock.wait_for(self._wait_block_or_redo(),
                                      REQUEST_TIMEOUT)
            except asyncio.TimeoutError:
                # peer too slow: drop it (pool.go:153 timeout → RemovePeer)
                self.pool.remove_peer(peer.id, reason="block request timeout",
                                      event="block_timeout")
            finally:
                peer.pending = max(0, peer.pending - 1)
            if self.block is not None and not self.redo.is_set():
                return                          # done; pool consumes us
            # redo: try again with a different peer
            self.redo.clear()
            self.block = None
            self.ext_commit = None
            self.got_block.clear()

    async def _wait_block_or_redo(self) -> None:
        waits = [asyncio.create_task(self.got_block.wait()),
                 asyncio.create_task(self.redo.wait())]
        try:
            await asyncio.wait(waits, return_when=asyncio.FIRST_COMPLETED)
        finally:
            # also on cancellation (request timeout): asyncio.wait does not
            # cancel its waiters for us
            for t in waits:
                t.cancel()

    def give_block(self, peer_id: str, block, ext_commit) -> bool:
        if self.peer_id != peer_id or self.block is not None:
            return False
        self.block = block
        self.ext_commit = ext_commit
        self.got_block.set()
        return True

    def refetch(self) -> None:
        """Discard any held block and fetch again from another peer (the
        redo path of pool.go bpRequester.redo)."""
        self.block = None
        self.ext_commit = None
        self.peer_id = None
        if self.task.done():
            self.got_block = asyncio.Event()
            self.redo = asyncio.Event()
            self.task = asyncio.create_task(self._run())
        else:
            self.redo.set()
            self.got_block.set()

    def stop(self) -> None:
        self.task.cancel()


class BlockPool:
    def __init__(self, start_height: int,
                 send_request: Callable[[str, int], None],
                 on_peer_error: Callable[[str, str, str], None] =
                 lambda p, r, e: None):
        self.height = start_height          # next height to consume
        self.send_request = send_request
        self.on_peer_error = on_peer_error
        self.peers: dict[str, _BsPeer] = {}
        self.requesters: dict[int, _Requester] = {}
        self.max_peer_height = 0
        self._stopped = False
        self._spawn_task: asyncio.Task | None = None

    def start(self) -> None:
        self._spawn_task = asyncio.create_task(self._make_requesters())

    async def stop(self) -> None:
        self._stopped = True
        if self._spawn_task is not None:
            self._spawn_task.cancel()
        for r in self.requesters.values():
            r.stop()
        self.requesters.clear()

    # ------------------------------------------------------------- peers

    def set_peer_range(self, peer_id: str, base: int, height: int) -> None:
        """StatusResponse from a peer (pool.go SetPeerRange)."""
        p = self.peers.get(peer_id)
        if p is None:
            p = self.peers[peer_id] = _BsPeer(peer_id, base, height)
        else:
            p.base, p.height = base, height
        self.max_peer_height = max(self.max_peer_height, height)

    def remove_peer(self, peer_id: str, reason: str = "",
                    event: str | None = None) -> None:
        """Drop a peer from the pool.  ``event`` names the misbehavior
        to report upstream (``block_timeout`` / ``bad_block``); None
        means the peer simply went away (switch-initiated removal) and
        must NOT be scored."""
        p = self.peers.pop(peer_id, None)
        if p is None:
            return
        for r in self.requesters.values():
            if r.peer_id == peer_id:
                r.refetch()     # pending AND already-delivered are suspect
        # a gone (or lying) tall peer must not pin the catch-up target
        # (pool.go removePeer -> updateMaxPeerHeight)
        self.max_peer_height = max(
            (q.height for q in self.peers.values()), default=0)
        if event is not None:
            self.on_peer_error(peer_id, reason, event)

    def _pick_peer(self, height: int) -> _BsPeer | None:
        best = None
        for p in self.peers.values():
            if p.base <= height <= p.height and \
                    p.pending < MAX_PENDING_PER_PEER and \
                    (best is None or p.pending < best.pending):
                best = p
        return best

    # --------------------------------------------------------- requesters

    async def _make_requesters(self) -> None:
        """pool.go:116 makeRequestersRoutine."""
        while not self._stopped:
            next_h = self.height + len(self.requesters)
            if len(self.requesters) < MAX_TOTAL_REQUESTERS and \
                    next_h <= self.max_peer_height:
                # skip heights already consumed below self.height
                if next_h not in self.requesters and next_h >= self.height:
                    self.requesters[next_h] = _Requester(self, next_h)
                    continue
            await clock.sleep(0.02)

    def add_block(self, peer_id: str, block, ext_commit=None) -> bool:
        """BlockResponse arrived (pool.go:296 AddBlock)."""
        r = self.requesters.get(block.header.height)
        if r is None:
            return False
        return r.give_block(peer_id, block, ext_commit)

    # ------------------------------------------------------------ consume

    def peek_window(self, max_blocks: int) -> list[tuple[object, object]]:
        """Longest contiguous run of fetched blocks from ``height``
        (generalizes pool.go PeekTwoBlocks to a device-batch window).
        Returns [(block, ext_commit)]."""
        out = []
        h = self.height
        while len(out) < max_blocks:
            r = self.requesters.get(h)
            if r is None or r.block is None:
                break
            out.append((r.block, r.ext_commit))
            h += 1
        return out

    def pop_request(self) -> None:
        """Block at ``height`` applied; advance (pool.go PopRequest)."""
        r = self.requesters.pop(self.height, None)
        if r is not None:
            r.stop()
        self.height += 1

    def redo_request(self, height: int) -> str | None:
        """Verification downstream failed: penalize the peer that served
        this height and refetch every block it delivered (pool.go
        RedoRequest).  ``bad_block`` is the heaviest misbehavior event —
        the peer-quality scorer bans on repetition."""
        r = self.requesters.get(height)
        bad_peer = r.peer_id if r is not None else None
        if bad_peer is not None:
            self.remove_peer(bad_peer, reason=f"bad block at {height}",
                             event="bad_block")
        elif r is not None:
            r.refetch()
        return bad_peer

    # ------------------------------------------------------------- status

    def is_caught_up(self) -> bool:
        """pool.go IsCaughtUp: we have peers and consumed to within one
        block of the best peer height."""
        if not self.peers:
            return False
        return self.height >= self.max_peer_height
