from .pool import BlockPool
from .reactor import BLOCKSYNC_CHANNEL, BlocksyncReactor

__all__ = ["BlockPool", "BlocksyncReactor", "BLOCKSYNC_CHANNEL"]
