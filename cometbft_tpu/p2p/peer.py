"""Peer: a connected, handshaked remote node (reference: ``p2p/peer.go``).

Binds an MConnection's channels to the Switch's reactor dispatch and keeps
per-peer metadata (NodeInfo, outbound/persistent flags, an arbitrary
key-value store used by reactors for per-peer state — PeerState lives
there, like the reference's ``Peer.Set``/``Get``)."""

from __future__ import annotations

from .conn import MConnection
from .node_info import NodeInfo


class Peer:
    def __init__(self, node_info: NodeInfo, mconn: MConnection,
                 outbound: bool, persistent: bool = False,
                 dial_addr: str | None = None):
        self.node_info = node_info
        self.mconn = mconn
        self.outbound = outbound
        self.persistent = persistent
        self.dial_addr = dial_addr          # for persistent reconnect
        self._data: dict = {}               # reactor-attached state

    @property
    def remote_addr(self) -> str:
        """Proven socket-level address of the peer (empty if unknown)."""
        conn = getattr(self.mconn, "conn", None)
        return getattr(conn, "remote_addr", "") or ""

    @property
    def id(self) -> str:
        return self.node_info.node_id

    def send(self, channel_id: int, msg: bytes) -> bool:
        return self.mconn.send(channel_id, msg)

    def try_send(self, channel_id: int, msg: bytes) -> bool:
        return self.mconn.send(channel_id, msg)

    def get(self, key: str):
        return self._data.get(key)

    def set(self, key: str, value) -> None:
        self._data[key] = value

    async def stop(self) -> None:
        await self.mconn.stop()

    def status(self) -> dict:
        return self.mconn.status()

    def __repr__(self):
        arrow = "->" if self.outbound else "<-"
        return f"Peer{{{arrow}{self.id[:12]}}}"
