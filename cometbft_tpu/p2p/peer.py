"""Peer: a connected, handshaked remote node (reference: ``p2p/peer.go``).

Binds an MConnection's channels to the Switch's reactor dispatch and keeps
per-peer metadata (NodeInfo, outbound/persistent flags, an arbitrary
key-value store used by reactors for per-peer state — PeerState lives
there, like the reference's ``Peer.Set``/``Get``)."""

from __future__ import annotations

from .conn import MConnection
from .node_info import NodeInfo


class GossipStats:
    """Per-peer gossip efficiency tallies, incremented by the consensus
    reactor (plain ints — the Prometheus children are bound separately).
    ``useful`` = votes we did not already hold; ``duplicate`` = re-gossip
    dropped at the reactor.  A partner whose ratio trends toward zero is
    mostly re-sending what we have."""

    __slots__ = ("useful", "duplicate")

    def __init__(self):
        self.useful = 0
        self.duplicate = 0

    def ratio(self) -> float | None:
        total = self.useful + self.duplicate
        if total == 0:
            return None
        return self.useful / total

    def as_dict(self) -> dict:
        r = self.ratio()
        return {"useful_votes": self.useful,
                "duplicate_votes": self.duplicate,
                "useful_ratio": None if r is None else round(r, 4)}


class Peer:
    def __init__(self, node_info: NodeInfo, mconn: MConnection,
                 outbound: bool, persistent: bool = False,
                 dial_addr: str | None = None):
        self.node_info = node_info
        self.mconn = mconn
        self.outbound = outbound
        self.persistent = persistent
        self.dial_addr = dial_addr          # for persistent reconnect
        self.gossip = GossipStats()
        self._data: dict = {}               # reactor-attached state

    @property
    def remote_addr(self) -> str:
        """Proven socket-level address of the peer (empty if unknown)."""
        conn = getattr(self.mconn, "conn", None)
        return getattr(conn, "remote_addr", "") or ""

    @property
    def id(self) -> str:
        return self.node_info.node_id

    def has_channel(self, channel_id: int) -> bool:
        """Whether the REMOTE advertised this channel in its handshake
        NodeInfo (reference peer.go hasChannel).  An empty advertisement
        means a pre-channels peer: allow, for wire compat."""
        chans = self.node_info.channels
        return not chans or channel_id in chans

    def send(self, channel_id: int, msg: bytes) -> bool:
        # sending on a channel the remote lacks would be a protocol
        # error THERE (unknown-channel frame kills the connection):
        # heterogeneous peers — e.g. statesync-only bootstrappers —
        # simply don't receive gossip they can't parse
        if not self.has_channel(channel_id):
            return False
        return self.mconn.send(channel_id, msg)

    def try_send(self, channel_id: int, msg: bytes) -> bool:
        return self.send(channel_id, msg)

    def get(self, key: str):
        return self._data.get(key)

    def set(self, key: str, value) -> None:
        self._data[key] = value

    async def stop(self) -> None:
        await self.mconn.stop()

    def status(self) -> dict:
        return self.mconn.status()

    def telemetry(self) -> dict:
        """The per-peer snapshot `/net_info` and the liveness watchdog's
        incident bundles serve: identity + direction + the MConnection's
        per-channel counters/flowrate/RTT + gossip efficiency."""
        return {
            "node_id": self.id,
            "moniker": self.node_info.moniker,
            "remote_addr": self.remote_addr,
            "outbound": self.outbound,
            "persistent": self.persistent,
            "connection_status": self.mconn.telemetry(),
            "gossip": self.gossip.as_dict(),
        }

    def __repr__(self):
        arrow = "->" if self.outbound else "<-"
        return f"Peer{{{arrow}{self.id[:12]}}}"
