"""MConnection: N prioritized channels multiplexed over one
SecretConnection (reference: ``p2p/conn/connection.go:80,549,748``).

Structure kept from the reference, mapped to asyncio: per-channel bounded
send queues; a send task picking the channel with the lowest
recently-sent/priority ratio (``selectChannelToGossipOn``
connection.go:549); packets of <= ``PACKET_PAYLOAD`` bytes with an eof bit
for message re-assembly; ping/pong keepalive with a pong deadline; flowrate
metering on both directions.
"""

from __future__ import annotations

import asyncio
import struct

from ..libs import clock, failures
from ..libs.flowrate import Monitor
from .reactor import ChannelDescriptor
from .secret_connection import SecretConnection

# a packet (3-byte header + payload) fits a single AEAD frame
# (DATA_LEN=1024) with headroom
PACKET_PAYLOAD = 1000
SEND_BATCH_PACKETS = 10             # connection.go:30 numBatchPacketMsgs
DEFAULT_PING_INTERVAL = 10.0
DEFAULT_PONG_TIMEOUT = 5.0

# Wire frames: a `<I` length prefix, then a 1-byte packet type; message
# packets add a 1-byte channel id, a 1-byte eof flag, and the payload
# chunk.  Struct-packed in ONE call on the hot path — the per-packet
# msgpack dict envelope this replaced was the profile harness's top
# allocator and a top-3 CPU sink across a fleet run
# (docs/bench/r21-profile-*.json); the chaos fault sites still pass
# packets around in dict form and encode late (_write_packet).
_T_PING, _T_PONG, _T_MSG = 0x69, 0x6F, 0x6D        # 'i', 'o', 'm'
_MSG_HDR = struct.Struct("<IBBB")                  # len | type chan eof
_LEN = struct.Struct("<I")
_PING_FRAME = _LEN.pack(1) + bytes((_T_PING,))
_PONG_FRAME = _LEN.pack(1) + bytes((_T_PONG,))


class MConnectionError(Exception):
    pass


class PongTimeoutError(MConnectionError):
    """A ping went unanswered past the pong deadline — its own type so
    the Switch can count silent-death disconnects separately from
    protocol/transport errors."""


class ConnectionLostError(MConnectionError):
    """The underlying transport died (reset/EOF/OS error) — its own
    type so the Switch's misbehavior classifier never scores a plain
    network failure as peer misbehavior."""


class _Channel:
    def __init__(self, desc: ChannelDescriptor):
        self.desc = desc
        self.queue: asyncio.Queue[bytes] = asyncio.Queue(
            desc.send_queue_capacity)
        self.sending: bytes | None = None      # partially-sent message
        self.sent_off = 0
        self.recent = 0.0                      # recently-sent counter
        self.recv_buf = bytearray()            # re-assembly buffer
        # plain-int telemetry, flushed to Prometheus by the Switch's
        # periodic sampler (never a labeled metric call per packet)
        self.sent_bytes = 0
        self.sent_msgs = 0
        self.recv_bytes = 0
        self.recv_msgs = 0
        self.queue_full_drops = 0

    @property
    def display_name(self) -> str:
        """The channel's telemetry key — /net_info dict keys, incident
        bundles and the Prometheus ``channel`` label all use this ONE
        spelling (gauge cleanup at peer removal matches on it)."""
        return self.desc.name or f"0x{self.desc.channel_id:02x}"

    def next_packet(self) -> tuple[bytes, bool]:
        """Carve the next <=PACKET_PAYLOAD chunk off the in-flight msg."""
        chunk = self.sending[self.sent_off:self.sent_off + PACKET_PAYLOAD]
        self.sent_off += len(chunk)
        eof = self.sent_off >= len(self.sending)
        if eof:
            self.sending = None
            self.sent_off = 0
        return chunk, eof

    def has_data(self) -> bool:
        return self.sending is not None or not self.queue.empty()


class MConnection:
    def __init__(self, conn: SecretConnection,
                 channels: list[ChannelDescriptor],
                 on_receive, on_error,
                 ping_interval: float = DEFAULT_PING_INTERVAL,
                 pong_timeout: float = DEFAULT_PONG_TIMEOUT,
                 send_rate: float | None = None,
                 recv_rate: float | None = None,
                 emulated_latency: float = 0.0):
        self.conn = conn
        self.channels: dict[int, _Channel] = {
            d.channel_id: _Channel(d) for d in channels}
        self.on_receive = on_receive          # (chan_id, msg_bytes) -> None
        self.on_error = on_error              # (exc) -> None
        self.ping_interval = ping_interval
        self.pong_timeout = pong_timeout
        self.send_monitor = Monitor()
        self.recv_monitor = Monitor()
        self.send_rate = send_rate
        self.recv_rate = recv_rate
        # one-way latency emulation (the reference injects tc-netem delays
        # between e2e containers, test/e2e/runner/latency_emulation.go;
        # here completed messages are dispatched after a timer so latency
        # rises without throttling bandwidth)
        self.emulated_latency = emulated_latency
        self._send_wakeup = asyncio.Event()
        self._pong_due: float | None = None
        self._pong_to_send = False
        # one packet held back by the p2p.send.reorder fault site; None
        # on every un-chaosed connection
        self._chaos_held: dict | None = None
        # fault-site selector scope (the Switch stamps its node name so
        # a [chaos] spec with node=<name> arms ONE node's links in an
        # in-proc ensemble; empty matches only selector-less rules)
        self.chaos_scope = ""
        self._tasks: list[asyncio.Task] = []
        self._stopped = False
        # --- telemetry (plain attrs; see telemetry()) -------------------
        now = clock.monotonic()
        self.created_mono = now
        self.last_recv_mono = now       # any complete packet counts
        self.last_msg_recv_mono = now   # complete channel messages only
        self.last_rtt_s: float | None = None
        self.pong_timeouts = 0
        self._ping_sent_mono: float | None = None
        # hook: Switch observes RTT samples into the node-labeled
        # histogram without MConnection knowing about metric labels
        self.on_rtt = None              # (rtt_seconds: float) -> None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._tasks = [
            asyncio.create_task(self._send_routine()),
            asyncio.create_task(self._recv_routine()),
            asyncio.create_task(self._ping_routine()),
        ]

    async def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self.conn.close()

    def _fail(self, exc: Exception) -> None:
        if self._stopped:
            return
        self._stopped = True
        for t in self._tasks:
            if t is not asyncio.current_task():
                t.cancel()
        self.conn.close()
        try:
            self.on_error(exc)
        except Exception:
            pass

    # ----------------------------------------------------------------- send

    def send(self, chan_id: int, msg: bytes) -> bool:
        """Enqueue; False if the channel is unknown or its queue is full
        (Peer.TrySend semantics — callers treat False as backpressure)."""
        ch = self.channels.get(chan_id)
        if ch is None or self._stopped:
            return False
        try:
            ch.queue.put_nowait(bytes(msg))
        except asyncio.QueueFull:
            ch.queue_full_drops += 1
            return False
        self._send_wakeup.set()
        return True

    async def send_blocking(self, chan_id: int, msg: bytes) -> bool:
        ch = self.channels.get(chan_id)
        if ch is None or self._stopped:
            return False
        await ch.queue.put(bytes(msg))
        self._send_wakeup.set()
        return True

    def _select_channel(self) -> _Channel | None:
        """Lowest recently-sent/priority ratio wins (connection.go:549)."""
        best, best_ratio = None, None
        for ch in self.channels.values():
            if not ch.has_data():
                continue
            ratio = ch.recent / max(ch.desc.priority, 1)
            if best_ratio is None or ratio < best_ratio:
                best, best_ratio = ch, ratio
        return best

    async def _send_routine(self) -> None:
        try:
            # the loop re-checks _stopped rather than running until
            # cancelled: on Python < 3.12 asyncio.wait_for (the idle wait
            # below) can swallow a cancellation that races with the
            # wakeup event (bpo-42130), leaving this task alive after
            # stop() cancelled it — stop()'s `await t` then never
            # returns and Node.stop wedges mid-shutdown
            while not self._stopped:
                self._send_wakeup.clear()
                if self._pong_to_send:
                    self._pong_to_send = False
                    await self._write_frame(_PONG_FRAME)
                batch = 0
                while batch < SEND_BATCH_PACKETS:
                    ch = self._select_channel()
                    if ch is None:
                        break
                    if ch.sending is None:
                        ch.sending = ch.queue.get_nowait()
                        ch.sent_off = 0
                    chunk, eof = ch.next_packet()
                    if failures.armed_prefix("p2p.send.") or \
                            self._chaos_held is not None:
                        # the held-packet check keeps the release-after-
                        # next-packet contract when the last p2p.send.*
                        # rule is disarmed while a reordered packet is
                        # parked — it must ride out with the next send,
                        # not wait for a fully idle wire
                        await self._chaos_send_packet(
                            ch, {"t": "m", "c": ch.desc.channel_id,
                                 "e": eof, "d": chunk})
                    else:
                        await self._write_frame(
                            _MSG_HDR.pack(len(chunk) + 3, _T_MSG,
                                          ch.desc.channel_id, eof) + chunk)
                    ch.recent += len(chunk)
                    ch.sent_bytes += len(chunk)
                    if eof:
                        ch.sent_msgs += 1
                    batch += 1
                # decay recently-sent so idle channels regain priority
                for ch in self.channels.values():
                    ch.recent *= 0.8
                if not any(c.has_data() for c in self.channels.values()) \
                        and not self._pong_to_send:
                    if self._chaos_held is not None:
                        # an idle wire must not strand a reordered packet
                        held, self._chaos_held = self._chaos_held, None
                        await self._write_packet(held)
                    try:
                        await clock.wait_for(self._send_wakeup.wait(), 0.5)
                    except asyncio.TimeoutError:
                        pass
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._fail(e)

    async def _chaos_send_packet(self, ch: _Channel, pkt: dict) -> None:
        """Per-channel send-side fault sites (active only while the
        fault plane is armed; the caller takes the zero-cost direct
        write otherwise).  Semantics per packet, in order:

        - ``p2p.send.drop`` — swallow it (the AEAD stream stays in sync
          because the frame is never encrypted, but the peer's message
          re-assembly sees a hole: a multi-packet message decodes
          corrupt, a single-packet message silently vanishes),
        - ``p2p.send.corrupt`` — flip one seeded bit of the payload
          (arrives authenticated, decodes garbage — message-level
          corruption, the class ``p2p/fuzz.py`` cannot produce),
        - ``p2p.send.delay`` — sleep ``delay`` (default 50 ms) before
          the write,
        - ``p2p.send.reorder`` — hold the packet and release it after
          the next one (or at wire idle),
        - ``p2p.send.duplicate`` — write it twice.

        Accounting in the caller proceeds regardless: the node believes
        it sent, which is exactly the telemetry skew a real lossy link
        produces."""
        name = ch.display_name
        scope = self.chaos_scope
        if failures.fire("p2p.send.drop", chan=name,
                         node=scope) is not None:
            return
        f = failures.fire("p2p.send.corrupt", chan=name, node=scope)
        if f is not None and pkt["d"]:
            data = bytearray(pkt["d"])
            rng = failures.site_rng("p2p.send.corrupt")
            data[rng.randrange(len(data))] ^= 1 << rng.randrange(8)
            pkt = dict(pkt, d=bytes(data))
        f = failures.fire("p2p.send.delay", chan=name, node=scope)
        if f is not None:
            await clock.sleep(float(f.get("delay", 0.05)))
        f = failures.fire("p2p.send.reorder", chan=name, node=scope)
        if f is not None and self._chaos_held is None:
            self._chaos_held = pkt      # released after the NEXT packet
            return
        await self._write_packet(pkt)
        if failures.fire("p2p.send.duplicate", chan=name,
                         node=scope) is not None:
            await self._write_packet(pkt)
        if self._chaos_held is not None:
            held, self._chaos_held = self._chaos_held, None
            await self._write_packet(held)

    async def _write_packet(self, packet: dict) -> None:
        """Late encoder for the chaos path: fault sites hold, corrupt
        and duplicate packets in dict form; the wire sees the same
        struct-packed frames the hot path emits."""
        t = packet["t"]
        if t == "m":
            d = packet.get("d", b"")
            await self._write_frame(
                _MSG_HDR.pack(len(d) + 3, _T_MSG, packet["c"],
                              1 if packet.get("e") else 0) + d)
        else:
            await self._write_frame(
                _PING_FRAME if t == "i" else _PONG_FRAME)

    async def _write_frame(self, data: bytes) -> None:
        if self.send_rate:
            while self.send_monitor.limit(len(data), self.send_rate) \
                    < len(data):
                await clock.sleep(0.01)
        await self.conn.write(data)
        self.send_monitor.update(len(data))

    # ----------------------------------------------------------------- recv

    async def _recv_routine(self) -> None:
        try:
            while True:
                (n,) = _LEN.unpack(await self.conn.read(4))
                if n < 1 or n > PACKET_PAYLOAD + 256:
                    raise MConnectionError(f"bad packet length: {n}")
                raw = await self.conn.read(n)
                self.recv_monitor.update(n + 4)
                self.last_recv_mono = clock.monotonic()
                if self.recv_rate:
                    while self.recv_monitor.limit(1, self.recv_rate) < 1:
                        await clock.sleep(0.01)
                t = raw[0]
                if t == _T_MSG:
                    if n < 3:
                        raise MConnectionError("truncated message packet")
                    self._on_packet_msg(raw[1], raw[2], raw[3:])
                elif t == _T_PING:
                    self._pong_to_send = True
                    self._send_wakeup.set()
                elif t == _T_PONG:
                    self._pong_due = None
                    if self._ping_sent_mono is not None:
                        rtt = clock.monotonic() - self._ping_sent_mono
                        self._ping_sent_mono = None
                        self.last_rtt_s = rtt
                        if self.on_rtt is not None:
                            try:
                                self.on_rtt(rtt)
                            except Exception:
                                pass
                else:
                    raise MConnectionError(f"unknown packet type {t:#x}")
        except asyncio.CancelledError:
            raise
        except (asyncio.IncompleteReadError, ConnectionError, OSError) as e:
            self._fail(ConnectionLostError(f"connection lost: {e}"))
        except Exception as e:
            self._fail(e)

    def _on_packet_msg(self, chan_id: int, eof: int, data: bytes) -> None:
        ch = self.channels.get(chan_id)
        if ch is None:
            raise MConnectionError(f"unknown channel {chan_id}")
        ch.recv_buf.extend(data)
        ch.recv_bytes += len(data)
        if len(ch.recv_buf) > ch.desc.max_msg_size:
            raise MConnectionError(
                f"message exceeds max size on channel {ch.desc.channel_id}")
        if eof:
            msg = bytes(ch.recv_buf)
            ch.recv_buf.clear()
            ch.recv_msgs += 1
            self.last_msg_recv_mono = clock.monotonic()
            if failures.armed_prefix("p2p.recv."):
                # receive-side faults operate on COMPLETE messages (the
                # unit the reactor sees): drop it, or flip one seeded
                # bit so the codec/handler rejects it downstream
                if failures.fire("p2p.recv.drop", chan=ch.display_name,
                                 node=self.chaos_scope) is not None:
                    return
                f = failures.fire("p2p.recv.corrupt",
                                  chan=ch.display_name,
                                  node=self.chaos_scope)
                if f is not None and msg:
                    data = bytearray(msg)
                    rng = failures.site_rng("p2p.recv.corrupt")
                    data[rng.randrange(len(data))] ^= \
                        1 << rng.randrange(8)
                    msg = bytes(data)
            if self.emulated_latency > 0:
                # equal delays preserve delivery order (asyncio timer
                # heap breaks ties by schedule sequence)
                asyncio.get_running_loop().call_later(
                    self.emulated_latency, self._deliver_delayed,
                    ch.desc.channel_id, msg)
            else:
                self.on_receive(ch.desc.channel_id, msg)

    def _deliver_delayed(self, chan_id: int, msg: bytes) -> None:
        """Latency-emulated delivery with the same error semantics as the
        inline path: reactor exceptions fail the connection, and nothing
        is delivered after the connection stopped."""
        if self._stopped:
            return
        try:
            self.on_receive(chan_id, msg)
        except Exception as e:
            self._fail(e)

    # ----------------------------------------------------------------- ping

    async def _ping_routine(self) -> None:
        try:
            while True:
                await clock.sleep(self.ping_interval)
                await self._write_frame(_PING_FRAME)
                self._ping_sent_mono = clock.monotonic()
                self._pong_due = clock.monotonic() + self.pong_timeout
                await clock.sleep(self.pong_timeout)
                if self._pong_due is not None and \
                        clock.monotonic() >= self._pong_due:
                    self.pong_timeouts += 1
                    raise PongTimeoutError("pong timeout")
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._fail(e)

    def status(self) -> dict:
        return {"send": self.send_monitor.status(),
                "recv": self.recv_monitor.status()}

    def telemetry(self) -> dict:
        """Full per-connection snapshot: per-channel counters + queue
        occupancy, flowrate on both directions, ping RTT and liveness
        ages.  Read-only over plain attrs — safe to call from RPC
        handlers and the watchdog while the connection runs."""
        now = clock.monotonic()
        channels = {}
        for ch in self.channels.values():
            channels[ch.display_name] = {
                "channel_id": ch.desc.channel_id,
                "sent_bytes": ch.sent_bytes,
                "sent_msgs": ch.sent_msgs,
                "recv_bytes": ch.recv_bytes,
                "recv_msgs": ch.recv_msgs,
                "send_queue": ch.queue.qsize(),
                "send_queue_capacity": ch.desc.send_queue_capacity,
                "queue_full_drops": ch.queue_full_drops,
            }
        return {
            "age_s": round(now - self.created_mono, 3),
            "send_bytes_total": self.send_monitor.total,
            "recv_bytes_total": self.recv_monitor.total,
            "send_rate": round(self.send_monitor.rate, 1),
            "recv_rate": round(self.recv_monitor.rate, 1),
            "last_recv_age_s": round(now - self.last_recv_mono, 3),
            "last_msg_recv_age_s": round(now - self.last_msg_recv_mono, 3),
            "last_rtt_s": (round(self.last_rtt_s, 6)
                           if self.last_rtt_s is not None else None),
            "pong_timeouts": self.pong_timeouts,
            "channels": channels,
        }
