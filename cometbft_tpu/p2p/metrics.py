"""P2P metric set (reference: ``p2p/metrics.go`` — Peers, message
send/receive byte counters by channel and message type).

One lazily-built process-wide set: multi-node in-proc ensembles share
the default registry, so every series carries a ``node`` label.  Two
cardinality tiers:

- **node-labeled** series (dial failures, handshake latency, ping RTT,
  reactor dispatch counts) are cheap and closed — bounded by the number
  of in-proc nodes x a closed enum.
- **peer-labeled** series (per-peer per-channel throughput, queue depth,
  rates, RTT) are open-ended under churn, so they are created against an
  explicit label budget (:data:`PEER_LABEL_BUDGET`, times the channel
  count for channel-split series) and the metric-level cardinality guard
  (``libs.metrics.DEFAULT_MAX_LABEL_SETS`` machinery) evicts the oldest
  child when a long-lived node outlives its budget.  Peer labels use
  the 12-char id prefix the log lines already use.

The per-peer series are written by the Switch's telemetry sampler (a
slow periodic flush of the MConnection's plain-int counters), never from
the packet hot path.
"""

from __future__ import annotations

import functools
from types import SimpleNamespace

from ..libs import metrics as m

# Distinct peers a node's per-peer series may track concurrently
# (default p2p config tops out at 40 inbound + 10 outbound; the budget
# leaves headroom for churn between sampler flushes).
PEER_LABEL_BUDGET = 128
# Channel-split per-peer series carry peer x channel children.
_CHANNELS_PER_PEER = 8


def peer_label(peer_id: str) -> str:
    """The bounded peer-label value: the same 12-char prefix the
    ``Peer.__repr__``/log lines use."""
    return peer_id[:12]


@functools.cache
def p2p_metrics() -> SimpleNamespace:
    chan_budget = PEER_LABEL_BUDGET * _CHANNELS_PER_PEER
    return SimpleNamespace(
        # ---------------------------------------------- node-labeled
        peers=m.gauge(
            "p2p_peers",
            "connected peers by direction (inbound|outbound)"),
        dial_failures=m.counter(
            "p2p_dial_failures_total",
            "outbound dial attempts that failed before a peer was added"),
        handshake_failures=m.counter(
            "p2p_handshake_failures_total",
            "transport upgrades (secret handshake + NodeInfo exchange) "
            "that failed, by direction"),
        handshake_seconds=m.histogram(
            "p2p_handshake_seconds",
            "transport upgrade latency: TCP established -> peer proven "
            "and compatible, by direction",
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1.0, 2.5, 5.0, 10.0)),
        ping_rtt_seconds=m.histogram(
            "p2p_ping_rtt_seconds",
            "MConnection ping->pong round-trip time",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5, 5.0)),
        pong_timeouts=m.counter(
            "p2p_pong_timeouts_total",
            "peers dropped because a ping went unanswered past the pong "
            "deadline"),
        reactor_msgs=m.counter(
            "p2p_reactor_msgs_total",
            "complete messages dispatched to each reactor"),
        queue_full_drops=m.counter(
            "p2p_send_queue_full_total",
            "sends refused because the per-channel send queue was full, "
            "by channel (backpressure visible per channel, node-wide)"),
        misbehavior=m.counter(
            "p2p_peer_misbehavior_total",
            "misbehavior events reported to the peer scorer, by typed "
            "event (see p2p/quality.py taxonomy)"),
        peer_bans=m.counter(
            "p2p_peer_bans_total",
            "timed bans issued by the peer scorer, by the event that "
            "tipped the score over the ban threshold"),
        reconnect_giveups=m.counter(
            "p2p_reconnect_giveups_total",
            "persistent-peer reconnect loops that exhausted the "
            "exponential backoff budget (they keep retrying at the max "
            "delay; this counts the downshifts)"),
        # ---------------------------------------------- peer-labeled
        peer_send_bytes=m.counter(
            "p2p_peer_send_bytes_total",
            "bytes of message payload sent to a peer, by channel",
            max_label_sets=chan_budget),
        peer_recv_bytes=m.counter(
            "p2p_peer_recv_bytes_total",
            "bytes of message payload received from a peer, by channel",
            max_label_sets=chan_budget),
        peer_send_msgs=m.counter(
            "p2p_peer_send_msgs_total",
            "complete messages sent to a peer, by channel",
            max_label_sets=chan_budget),
        peer_recv_msgs=m.counter(
            "p2p_peer_recv_msgs_total",
            "complete messages received from a peer, by channel",
            max_label_sets=chan_budget),
        peer_queue_depth=m.gauge(
            "p2p_peer_send_queue",
            "send-queue depth (messages waiting) per peer channel",
            max_label_sets=chan_budget),
        peer_queue_drops=m.counter(
            "p2p_peer_send_queue_full_total",
            "queue-full send drops per peer channel",
            max_label_sets=chan_budget),
        peer_send_rate=m.gauge(
            "p2p_peer_send_rate_bytes",
            "flowrate send EMA (bytes/sec, idle-decaying) per peer",
            max_label_sets=PEER_LABEL_BUDGET),
        peer_recv_rate=m.gauge(
            "p2p_peer_recv_rate_bytes",
            "flowrate recv EMA (bytes/sec, idle-decaying) per peer",
            max_label_sets=PEER_LABEL_BUDGET),
        peer_rtt=m.gauge(
            "p2p_peer_rtt_seconds",
            "last measured ping RTT per peer",
            max_label_sets=PEER_LABEL_BUDGET),
        peer_score=m.gauge(
            "p2p_peer_score",
            "decaying misbehavior score per connected peer (0 = clean; "
            "crossing the configured thresholds disconnects / bans)",
            max_label_sets=PEER_LABEL_BUDGET),
    )
