"""TCP transport with connection upgrade (reference:
``p2p/transport.go:137,194,212,410`` MultiplexTransport).

Upgrade sequence on every raw TCP connection, dialed or accepted:
SecretConnection handshake (authenticated encryption) -> NodeInfo exchange
-> validation (declared id matches the handshake-proven pubkey,
compatibility).  Only then does the Switch see the peer.
"""

from __future__ import annotations

import asyncio
import time

from ..libs import clock
from .key import NodeKey, node_id
from .metrics import p2p_metrics
from .node_info import NodeInfo, NodeInfoError
from .secret_connection import SecretConnection, handshake

HANDSHAKE_TIMEOUT = 8.0


class TransportError(Exception):
    pass


class Transport:
    def __init__(self, node_key: NodeKey, node_info_fn,
                 handshake_timeout: float = HANDSHAKE_TIMEOUT,
                 fuzz_config=None):
        self.node_key = node_key
        self.node_info_fn = node_info_fn      # () -> NodeInfo (fresh copy)
        self.handshake_timeout = handshake_timeout
        # p2p/transport.go:223 — fault-injection wrapper around every raw
        # stream pair (a p2p.fuzz.FuzzConnConfig, or None)
        self.fuzz_config = fuzz_config
        self._server: asyncio.AbstractServer | None = None
        self.listen_addr: str | None = None
        self.on_accept = None   # async (SecretConnection, NodeInfo) -> None

    def _maybe_fuzz(self, reader, writer):
        if self.fuzz_config is None:
            return reader, writer
        from .fuzz import fuzz_streams

        return fuzz_streams(reader, writer, self.fuzz_config)

    # ------------------------------------------------------------- listen

    async def listen(self, host: str = "127.0.0.1", port: int = 0) -> str:
        self._server = await asyncio.start_server(
            self._handle_accept, host, port)
        sock = self._server.sockets[0]
        addr = sock.getsockname()
        self.listen_addr = f"{addr[0]}:{addr[1]}"
        return self.listen_addr

    async def _handle_accept(self, reader, writer) -> None:
        try:
            freader, fwriter = self._maybe_fuzz(reader, writer)
            conn, ni = await self._timed_upgrade(freader, fwriter,
                                                 "inbound")
        except Exception:
            writer.close()
            return
        if self.on_accept is not None:
            await self.on_accept(conn, ni)

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    # --------------------------------------------------------------- dial

    async def dial(self, addr: str) -> tuple[SecretConnection, NodeInfo]:
        host, port = addr.removeprefix("tcp://").rsplit(":", 1)
        reader, writer = await asyncio.open_connection(host, int(port))
        try:
            freader, fwriter = self._maybe_fuzz(reader, writer)
            return await self._timed_upgrade(freader, fwriter, "outbound")
        except Exception:
            writer.close()
            raise

    # ------------------------------------------------------------ upgrade

    async def _timed_upgrade(self, reader, writer, direction: str) \
            -> tuple[SecretConnection, NodeInfo]:
        """The upgrade under its timeout, metered: handshake latency by
        direction on success, a failure counter otherwise (an operator
        watching a validator fail to join a network sees WHERE — dials
        that never complete the upgrade, or inbound peers that do not)."""
        mets = p2p_metrics()
        node = self.node_key.id[:8]
        t0 = time.perf_counter()
        try:
            out = await clock.wait_for(
                self._upgrade(reader, writer), self.handshake_timeout)
        except asyncio.CancelledError:
            raise                 # shutdown, not a handshake failure
        except Exception:
            mets.handshake_failures.inc(direction=direction, node=node)
            raise
        mets.handshake_seconds.observe(time.perf_counter() - t0,
                                       direction=direction, node=node)
        return out

    async def _upgrade(self, reader, writer) \
            -> tuple[SecretConnection, NodeInfo]:
        conn = await handshake(reader, writer, self.node_key.priv_key)
        await conn.write_msg(self.node_info_fn().encode())
        their_info = NodeInfo.decode(await conn.read_msg(max_size=10240))
        their_info.validate_basic()
        proven_id = node_id(conn.remote_pub_key)
        if their_info.node_id != proven_id:
            raise TransportError(
                f"peer declared id {their_info.node_id} but proved "
                f"{proven_id}")
        try:
            self.node_info_fn().compatible_with(their_info)
        except NodeInfoError as e:
            raise TransportError(f"incompatible peer: {e}")
        return conn, their_info
