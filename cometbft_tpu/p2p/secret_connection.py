"""Authenticated-encryption connection upgrade (reference:
``p2p/conn/secret_connection.go:33-80`` — the STS protocol).

Same shape as the reference, re-derived with the host ``cryptography``
primitives (interop target is this framework itself, not Go wire format —
SURVEY.md §7.5): X25519 ephemeral ECDH -> HKDF-SHA256 transcript ->
two ChaCha20-Poly1305 AEADs (one per direction) over fixed-size frames ->
ed25519 challenge signature authenticating the persistent node key.

Frame layout: every sealed frame carries exactly ``DATA_LEN`` plaintext
bytes of which the first two are the LE payload length (0..DATA_LEN-2);
nonces are 12-byte little-endian send counters, never reused because each
direction has its own key and counter.
"""

from __future__ import annotations

import asyncio
import hashlib
import struct

try:
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey, X25519PublicKey)
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
except ImportError:              # no `cryptography` wheel on this image:
    # the pure-Python RFC 7748/8439 stand-ins keep the handshake and
    # frame protocol byte-identical (MB/s-grade throughput — the test
    # nets and small deployments; installs with the wheel get OpenSSL)
    from ..crypto._sc_fallback import (ChaCha20Poly1305, X25519PrivateKey,
                                       X25519PublicKey)

from ..crypto.keys import Ed25519PrivKey, Ed25519PubKey

DATA_LEN = 1024                     # plaintext bytes per frame (incl. 2-len)
DATA_MAX = DATA_LEN - 2
FRAME_LEN = DATA_LEN + 16           # + poly1305 tag
HKDF_INFO = b"TPU_BFT_SECRET_CONNECTION_KEY_AND_CHALLENGE_GEN"


class SecretConnectionError(Exception):
    pass


def _hkdf_sha256(ikm: bytes, salt: bytes, info: bytes, length: int) -> bytes:
    prk = hashlib.sha256(salt + ikm).digest()
    out, t, i = b"", b"", 1
    while len(out) < length:
        t = hashlib.sha256(prk + t + info + bytes([i])).digest()
        out += t
        i += 1
    return out[:length]


class SecretConnection:
    """Byte-stream over AEAD frames.  Use :meth:`handshake` to construct."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter,
                 send_aead: ChaCha20Poly1305, recv_aead: ChaCha20Poly1305,
                 remote_pub_key: Ed25519PubKey):
        self._reader = reader
        self._writer = writer
        self._send = send_aead
        self._recv = recv_aead
        self._send_nonce = 0
        self._recv_nonce = 0
        self._buf = bytearray()
        self.remote_pub_key = remote_pub_key

    @property
    def remote_addr(self) -> str:
        """The socket-level remote ``host:port`` — the only address an
        inbound peer has actually PROVEN (its self-advertised listen_addr
        is hearsay; PEX source attribution must use this)."""
        try:
            peername = self._writer.get_extra_info("peername")
            if peername:
                return f"{peername[0]}:{peername[1]}"
        except Exception:
            pass
        return ""

    # -------------------------------------------------------------- frames

    def _nonce(self, counter: int) -> bytes:
        return struct.pack("<Q", counter) + b"\x00\x00\x00\x00"

    async def _write_frame(self, payload: bytes) -> None:
        assert len(payload) <= DATA_MAX
        frame = struct.pack("<H", len(payload)) + payload
        frame += b"\x00" * (DATA_LEN - len(frame))
        sealed = self._send.encrypt(self._nonce(self._send_nonce), frame,
                                    None)
        self._send_nonce += 1
        self._writer.write(sealed)

    async def _read_frame(self) -> bytes:
        sealed = await self._reader.readexactly(FRAME_LEN)
        try:
            frame = self._recv.decrypt(self._nonce(self._recv_nonce),
                                       sealed, None)
        except Exception as e:
            raise SecretConnectionError(f"frame decryption failed: {e}")
        self._recv_nonce += 1
        (n,) = struct.unpack_from("<H", frame)
        if n > DATA_MAX:
            raise SecretConnectionError("corrupt frame length")
        return frame[2:2 + n]

    # -------------------------------------------------------- byte stream

    async def write(self, data: bytes) -> None:
        for off in range(0, len(data), DATA_MAX):
            await self._write_frame(data[off:off + DATA_MAX])
        await self._writer.drain()

    async def read(self, n: int) -> bytes:
        while len(self._buf) < n:
            self._buf.extend(await self._read_frame())
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out

    # ------------------------------------------------------- msg framing

    async def write_msg(self, msg: bytes) -> None:
        await self.write(struct.pack("<I", len(msg)) + msg)

    async def read_msg(self, max_size: int = 1 << 22) -> bytes:
        (n,) = struct.unpack("<I", await self.read(4))
        if n > max_size:
            raise SecretConnectionError(f"message too large: {n}")
        return await self.read(n)

    def close(self) -> None:
        try:
            self._writer.close()
        except Exception:
            pass


async def handshake(reader: asyncio.StreamReader,
                    writer: asyncio.StreamWriter,
                    priv_key: Ed25519PrivKey) -> SecretConnection:
    """Upgrade a raw TCP stream (secret_connection.go MakeSecretConnection).

    1. swap ephemeral X25519 pubkeys (the only plaintext on the wire);
    2. HKDF(shared, salt=sorted eph pubs) -> two keys + challenge;
       low-sorted eph pub gets key A for sending, high gets key B —
       role assignment needs no dialer/listener flag;
    3. inside the encrypted channel, swap (node pubkey, sig(challenge))
       and verify — authenticates the persistent identity (STS).
    """
    eph_priv = X25519PrivateKey.generate()
    eph_pub = eph_priv.public_key().public_bytes_raw()
    writer.write(eph_pub)
    await writer.drain()
    their_eph_pub = await reader.readexactly(32)
    if their_eph_pub == eph_pub:
        raise SecretConnectionError("identical ephemeral keys (reflection?)")
    shared = eph_priv.exchange(
        X25519PublicKey.from_public_bytes(their_eph_pub))

    lo, hi = sorted((eph_pub, their_eph_pub))
    okm = _hkdf_sha256(shared, salt=lo + hi, info=HKDF_INFO, length=96)
    key_a, key_b, challenge = okm[:32], okm[32:64], okm[64:]
    if eph_pub == lo:
        send_key, recv_key = key_a, key_b
    else:
        send_key, recv_key = key_b, key_a

    conn = SecretConnection(reader, writer,
                            ChaCha20Poly1305(send_key),
                            ChaCha20Poly1305(recv_key),
                            remote_pub_key=None)

    sig = priv_key.sign(challenge)
    await conn.write_msg(priv_key.pub_key().bytes() + sig)
    auth = await conn.read_msg(max_size=96)
    if len(auth) != 96:
        raise SecretConnectionError("bad auth message size")
    remote_pub, remote_sig = Ed25519PubKey(auth[:32]), auth[32:]
    if not remote_pub.verify_signature(challenge, remote_sig):
        raise SecretConnectionError("challenge signature verification failed")
    conn.remote_pub_key = remote_pub
    return conn
