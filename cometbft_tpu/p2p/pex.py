"""Peer exchange (PEX) + seed crawling (reference: ``p2p/pex/pex_reactor.go``;
channel 0x00 from ``pex_reactor.go:22``).

The address book lives in :mod:`cometbft_tpu.p2p.addrbook` — a bucketed
old/new design with hashed placement that bounds how much of the book an
address-flooding peer can touch.  The reactor asks peers for addresses
when connectivity is low and dials newly learned peers, so a node
bootstraps the full mesh from one seed; successful connections promote
entries to the vetted tier (``mark_good``), failed dials count attempts.

Seed crawling (``pex_reactor.go crawlPeersRoutine``): a node in
``seed_mode`` continuously dials book addresses, handshakes, exchanges
address books, and hangs up — it exists to harvest and serve addresses,
not to hold connections.
"""

from __future__ import annotations

import asyncio

from ..libs import clock
from ..libs import aio
import random

import msgpack

from ..libs import log as tmlog
from .addrbook import AddrBook
from .reactor import ChannelDescriptor, Reactor

__all__ = ["AddrBook", "PexReactor", "PEX_CHANNEL"]

PEX_CHANNEL = 0x00
REQUEST_INTERVAL = 30.0          # ensurePeersPeriod (pex_reactor.go)
MAX_ADDRS_PER_RESPONSE = 32
CRAWL_LINGER = 3.0               # seed mode: seconds before hanging up


class PexReactor(Reactor):
    def __init__(self, book: AddrBook, own_id: str,
                 max_outbound: int = 10,
                 request_interval: float = REQUEST_INTERVAL,
                 seed_mode: bool = False):
        super().__init__()
        self.book = book
        self.own_id = own_id
        self.max_outbound = max_outbound
        self.request_interval = request_interval
        self.seed_mode = seed_mode
        self.log = tmlog.logger("pex", node=own_id[:8])
        self._task: asyncio.Task | None = None
        self._dialing: set[str] = set()
        self._requested: set[str] = set()    # peers we asked for addrs
        # strong refs: the loop only weakly references tasks, so hangup
        # timers and dial attempts could be GC'd mid-flight otherwise
        self._bg_tasks: set[asyncio.Task] = set()

    def get_channels(self):
        return [ChannelDescriptor(PEX_CHANNEL, priority=1,
                                  send_queue_capacity=10, name="pex")]

    async def start(self) -> None:
        routine = self._crawl_routine if self.seed_mode \
            else self._ensure_peers_routine
        self._task = asyncio.create_task(routine())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
        for t in self._bg_tasks:
            t.cancel()
        self.book.save()

    def _spawn(self, coro) -> None:
        aio.spawn(coro, self._bg_tasks)

    def add_peer(self, peer) -> None:
        if peer.outbound:
            # the address WE successfully dialed is proven: record and
            # vet exactly that one (addrbook MarkGood), replacing any
            # stale vetted address (the peer moved)
            addr = peer.dial_addr or peer.node_info.listen_addr
            if addr:
                self.book.add(peer.id, addr, persist=False,
                              source=peer.remote_addr, proven=True)
            self.book.mark_good(peer.id)
        else:
            # an inbound handshake proves nothing about the listen_addr
            # it advertises — hearsay into the new tier only, attributed
            # to the proven socket address; promoting it would let an
            # attacker fill the protected old tier with invented
            # addresses
            addr = peer.node_info.listen_addr
            if addr:
                self.book.add(peer.id, addr, persist=False,
                              source=peer.remote_addr)
        if self.seed_mode:
            # harvest the newcomer's book, then hang up shortly: a seed
            # serves addresses, it doesn't hold connections
            self._request_addrs(peer)
            self._schedule_hangup(peer)
        elif self._wants_peers():
            # under-connected: ask the newcomer for addresses NOW rather
            # than on the next ensure-peers tick — a crawling seed hangs
            # up within CRAWL_LINGER, long before a 30s interval fires
            # (pex_reactor.go sends the first request on peer add too)
            self._request_addrs(peer)

    def _request_addrs(self, peer) -> None:
        """Send pex_req AND register the solicitation — receive() drops
        any pex_res we didn't register (the anti-poisoning gate), so the
        two must never be separated."""
        self._requested.add(peer.id)
        peer.send(PEX_CHANNEL, msgpack.packb({"@": "pex_req"},
                                             use_bin_type=True))

    def _wants_peers(self) -> bool:
        sw = self.switch
        if sw is None:
            return False
        outbound = sum(1 for p in sw.peers.values() if p.outbound)
        return outbound < self.max_outbound

    def _schedule_hangup(self, peer) -> None:
        # one timer per peer OBJECT (add_peer fires once per connection);
        # the identity check means a stale timer from a dropped
        # connection can never evict a reconnect, and the reconnect's
        # own timer still hangs it up
        async def hangup():
            await clock.sleep(CRAWL_LINGER)
            if self.switch is not None and \
                    getattr(self.switch, "peers", {}).get(
                        peer.id) is peer:
                await self.switch.stop_peer_gracefully(peer)

        self._spawn(hangup())

    def remove_peer(self, peer, reason) -> None:
        # a disconnect revokes any outstanding address-request
        # authorization (otherwise _requested grows forever on a
        # long-lived seed and a reconnecting peer could answer a
        # request it was never re-sent)
        self._requested.discard(peer.id)

    def receive(self, channel_id: int, peer, msg: bytes) -> None:
        d = msgpack.unpackb(msg, raw=False)
        tag = d.get("@")
        if tag == "pex_req":
            peer.send(PEX_CHANNEL, msgpack.packb(
                {"@": "pex_res",
                 "addrs": [{"id": i, "addr": a}
                           for i, a in self.book.sample(
                               MAX_ADDRS_PER_RESPONSE)]},
                use_bin_type=True))
        elif tag == "pex_res":
            # only accept what we asked for: unsolicited responses are the
            # address-poisoning vector (pex_reactor.go requestsSent)
            if peer.id not in self._requested:
                self.log.debug("unsolicited pex_res dropped",
                               peer=peer.id[:8])
                return
            self._requested.discard(peer.id)
            # the advertiser's PROVEN socket address scopes bucket
            # placement: one source can only thrash the buckets its
            # group hashes to.  (Never the self-advertised listen_addr,
            # and never empty — an un-attributable response would let
            # each invented address become its own source group.)
            source = peer.remote_addr
            if not source:
                self.log.debug("pex_res without proven source dropped",
                               peer=peer.id[:8])
                return
            changed = False
            for entry in d.get("addrs", [])[:MAX_ADDRS_PER_RESPONSE]:
                nid, addr = entry.get("id", ""), entry.get("addr", "")
                if nid and nid != self.own_id:
                    changed |= self.book.add(nid, addr, persist=False,
                                             source=source)
            if changed:
                self.book.save_debounced()   # throttled full-book dump

    # ------------------------------------------------------- ensure peers

    async def _ensure_peers_routine(self) -> None:
        """pex_reactor.go ensurePeersRoutine: keep outbound connectivity
        up by asking for and dialing new addresses."""
        while True:
            await clock.sleep(self.request_interval
                                * (0.75 + 0.5 * random.random()))
            try:
                self._ensure_peers()
            except Exception as e:
                self.log.warn("ensure peers failed", err=repr(e))

    def _ensure_peers(self) -> None:
        sw = self.switch
        if sw is None:
            return
        connected = set(sw.peers)
        if not self._wants_peers():
            return
        outbound = sum(1 for p in sw.peers.values() if p.outbound)
        # ask a random connected peer for more addresses
        if sw.peers:
            self._request_addrs(random.choice(list(sw.peers.values())))
        # dial someone new
        for nid, addr in self.book.pick(connected | self._dialing
                                        | {self.own_id},
                                        n=self.max_outbound - outbound):
            self._dialing.add(nid)
            self._spawn(self._dial(nid, addr))

    # ------------------------------------------------------------ crawling

    async def _crawl_routine(self) -> None:
        """Seed-node loop (pex_reactor.go crawlPeersRoutine): dial book
        entries round after round — connections harvest addresses via
        ``add_peer`` and hang up after CRAWL_LINGER — so the book stays
        fresh and every inbound node gets a broad sample."""
        while True:
            try:
                self._crawl()
            except Exception as e:
                self.log.warn("crawl failed", err=repr(e))
            await clock.sleep(self.request_interval
                                * (0.75 + 0.5 * random.random()))

    def _crawl(self) -> None:
        sw = self.switch
        if sw is None:
            return
        exclude = set(sw.peers) | self._dialing | {self.own_id}
        for nid, addr in self.book.pick(exclude, n=4):
            self._dialing.add(nid)
            self._spawn(self._dial(nid, addr))

    async def _dial(self, nid: str, addr: str) -> None:
        try:
            await self.switch.dial_peer(addr)
            self.log.debug("pex dialed", peer=nid[:8], addr=addr)
        except Exception as e:
            if "duplicate peer" not in str(e):
                self.book.mark_attempt(nid)
                self.log.debug("pex dial failed", addr=addr, err=repr(e))
        finally:
            self._dialing.discard(nid)
