"""Peer exchange (PEX) + address book (reference: ``p2p/pex/pex_reactor.go``
and ``p2p/pex/addrbook.go``; channel 0x00 from ``pex_reactor.go:22``).

The address book persists known ``node_id -> dialable address`` entries as
JSON (the reference's old/new bucket machinery guards against address
poisoning at internet scale; this book keeps the same interface —
add/pick/mark good/bad — with a flat store and ban-on-bad semantics).
The reactor asks peers for addresses when connectivity is low and dials
newly learned peers, so a node bootstraps the full mesh from one seed."""

from __future__ import annotations

import asyncio
import json
import os
import random

import msgpack

from ..libs import log as tmlog
from .reactor import ChannelDescriptor, Reactor

PEX_CHANNEL = 0x00
REQUEST_INTERVAL = 30.0          # ensurePeersPeriod (pex_reactor.go)
MAX_ADDRS_PER_RESPONSE = 32
MAX_BOOK_SIZE = 1000


class AddrBook:
    def __init__(self, path: str | None = None):
        self.path = path
        self._addrs: dict[str, str] = {}       # node_id -> "host:port"
        self._banned: set[str] = set()
        if path and os.path.exists(path):
            self._load()

    def _load(self) -> None:
        try:
            with open(self.path) as f:
                d = json.load(f)
            self._addrs = dict(d.get("addrs", {}))
            self._banned = set(d.get("banned", []))
        except (OSError, json.JSONDecodeError):
            self._addrs = {}

    def save(self) -> None:
        if not self.path:
            return
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"addrs": self._addrs,
                       "banned": sorted(self._banned)}, f, indent=2)
        os.replace(tmp, self.path)

    def add(self, node_id: str, addr: str, persist: bool = True) -> bool:
        """``persist=False`` defers the disk write — callers processing a
        batch (a PEX response) save once at the end, not per address."""
        if not addr or node_id in self._banned:
            return False
        if self._addrs.get(node_id) == addr:
            return False
        if node_id not in self._addrs and len(self._addrs) >= MAX_BOOK_SIZE:
            return False
        self._addrs[node_id] = addr
        if persist:
            self.save()
        return True

    def mark_bad(self, node_id: str) -> None:
        """addrbook MarkBad: ban and forget."""
        self._banned.add(node_id)
        self._addrs.pop(node_id, None)
        self.save()

    def pick(self, exclude: set[str], n: int = 1) -> list[tuple[str, str]]:
        cands = [(i, a) for i, a in self._addrs.items()
                 if i not in exclude]
        random.shuffle(cands)
        return cands[:n]

    def sample(self, n: int = MAX_ADDRS_PER_RESPONSE) -> list[tuple[str, str]]:
        cands = list(self._addrs.items())
        random.shuffle(cands)
        return cands[:n]

    def size(self) -> int:
        return len(self._addrs)


class PexReactor(Reactor):
    def __init__(self, book: AddrBook, own_id: str,
                 max_outbound: int = 10,
                 request_interval: float = REQUEST_INTERVAL):
        super().__init__()
        self.book = book
        self.own_id = own_id
        self.max_outbound = max_outbound
        self.request_interval = request_interval
        self.log = tmlog.logger("pex", node=own_id[:8])
        self._task: asyncio.Task | None = None
        self._dialing: set[str] = set()
        self._requested: set[str] = set()    # peers we asked for addrs

    def get_channels(self):
        return [ChannelDescriptor(PEX_CHANNEL, priority=1,
                                  send_queue_capacity=10, name="pex")]

    async def start(self) -> None:
        self._task = asyncio.create_task(self._ensure_peers_routine())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
        self.book.save()

    def add_peer(self, peer) -> None:
        # learn the peer's self-advertised dial-back address
        addr = peer.node_info.listen_addr
        if addr:
            self.book.add(peer.id, addr)

    def receive(self, channel_id: int, peer, msg: bytes) -> None:
        d = msgpack.unpackb(msg, raw=False)
        tag = d.get("@")
        if tag == "pex_req":
            peer.send(PEX_CHANNEL, msgpack.packb(
                {"@": "pex_res",
                 "addrs": [{"id": i, "addr": a}
                           for i, a in self.book.sample()]},
                use_bin_type=True))
        elif tag == "pex_res":
            # only accept what we asked for: unsolicited responses are the
            # address-poisoning vector (pex_reactor.go requestsSent)
            if peer.id not in self._requested:
                self.log.debug("unsolicited pex_res dropped",
                               peer=peer.id[:8])
                return
            self._requested.discard(peer.id)
            changed = False
            for entry in d.get("addrs", [])[:MAX_ADDRS_PER_RESPONSE]:
                nid, addr = entry.get("id", ""), entry.get("addr", "")
                if nid and nid != self.own_id:
                    changed |= self.book.add(nid, addr, persist=False)
            if changed:
                self.book.save()     # one write per response, not per addr

    # ------------------------------------------------------- ensure peers

    async def _ensure_peers_routine(self) -> None:
        """pex_reactor.go ensurePeersRoutine: keep outbound connectivity
        up by asking for and dialing new addresses."""
        while True:
            await asyncio.sleep(self.request_interval
                                * (0.75 + 0.5 * random.random()))
            try:
                self._ensure_peers()
            except Exception as e:
                self.log.warn("ensure peers failed", err=repr(e))

    def _ensure_peers(self) -> None:
        sw = self.switch
        if sw is None:
            return
        connected = set(sw.peers)
        outbound = sum(1 for p in sw.peers.values() if p.outbound)
        if outbound >= self.max_outbound:
            return
        # ask a random connected peer for more addresses
        if sw.peers:
            peer = random.choice(list(sw.peers.values()))
            self._requested.add(peer.id)
            peer.send(PEX_CHANNEL, msgpack.packb({"@": "pex_req"},
                                                 use_bin_type=True))
        # dial someone new
        for nid, addr in self.book.pick(connected | self._dialing
                                        | {self.own_id},
                                        n=self.max_outbound - outbound):
            self._dialing.add(nid)
            asyncio.ensure_future(self._dial(nid, addr))

    async def _dial(self, nid: str, addr: str) -> None:
        try:
            await self.switch.dial_peer(addr)
            self.log.debug("pex dialed", peer=nid[:8], addr=addr)
        except Exception as e:
            if "duplicate peer" not in str(e):
                self.log.debug("pex dial failed", addr=addr, err=repr(e))
        finally:
            self._dialing.discard(nid)
