"""Peer quality scoring: one node-wide reputation ledger fed by every
layer that detects misbehavior (reference: the *idea* of
``p2p/peer_set`` bans + reactor ``StopPeerForError`` calls, unified —
the Go reference scatters punishment across reactors and bans forever;
here every detection funnels through :class:`PeerScorer` so responses
are proportional, decaying, and timed).

Design:

- **Typed events.**  Each misbehavior class carries a severity weight
  (:data:`EVENT_WEIGHTS`): a blocksync block that fails commit
  verification is near-certain malice (heavy), one rejected gossiped tx
  is routine app-level noise (feather-weight).  Unknown event names get
  :data:`DEFAULT_WEIGHT` so a new call site can never crash scoring.
- **Decaying score.**  A peer's score is the sum of its event weights
  decayed exponentially with half-life ``half_life_s``: an old offense
  fades, a burst accumulates.  Scores only move on report/read — no
  background task.
- **Two thresholds.**  Crossing ``disconnect_score`` disconnects the
  peer (the Switch re-admits it on the next dial); crossing
  ``ban_score`` issues a **timed** ban — TTL ``ban_ttl_s`` doubling per
  repeat offense up to ``ban_ttl_max_s`` — recorded in the addrbook
  (persisted across restarts) or a local map when no book exists.
- **Persistent peers are exempt from bans** (an operator pinned them on
  purpose): they are scored and disconnected like anyone else, and the
  Switch's persistent-reconnect machinery re-dials them.

The Switch owns the one scorer instance and is the only caller of
``report`` (reactors go through ``Switch.report_peer``); everything
here is synchronous, event-loop-thread-only state.
"""

from __future__ import annotations

from ..libs import clock

# Event taxonomy: every layer that detects misbehavior reports one of
# these (severity-weighted; see docs/explanation/peer-quality.md for
# the rationale per event).  The default thresholds are 5 (disconnect)
# and 10 (ban): e.g. two bad blocks ban, five invalid votes disconnect.
EVENT_WEIGHTS: dict[str, float] = {
    # blocksync (pool.remove_peer / redo_request)
    "bad_block": 5.0,          # served a block that failed verification
    "block_timeout": 1.0,      # block request timed out (slow, not evil)
    # consensus reactor / state machine handler errors
    "invalid_vote": 2.0,       # bad signature / vote-set violation
    "invalid_part": 3.0,       # block part with a bad merkle proof
    "invalid_proposal": 3.0,   # bad proposal signature / shape
    # MConnection / switch dispatch
    "malformed_frame": 2.0,    # post-AEAD garbage: decode/oversize/chan
    "pong_timeout": 0.5,       # silent death; mostly a network signal
    "protocol_error": 2.0,     # reactor receive raised on peer input
    # mempool gossip
    "invalid_tx": 0.25,        # app-rejected gossiped tx
    # evidence gossip
    "bad_evidence": 5.0,       # unverifiable gossiped evidence
    # statesync
    "bad_snapshot_chunk": 5.0,  # manifest/app rejected this sender's
    #   chunks: provably bad bytes, two strikes is a ban
    "snapshot_timeout": 0.5,    # chunk request aged out: slow, not
    #   (provably) malicious — persistent molasses still adds up
}
DEFAULT_WEIGHT = 1.0

DISCONNECT_SCORE = 5.0
BAN_SCORE = 10.0
HALF_LIFE_S = 120.0
BAN_TTL_S = 60.0
BAN_TTL_MAX_S = 3600.0
MAX_TRACKED = 1024


class PeerMisbehaviorError(Exception):
    """Marker passed to ``Switch.stop_peer_for_error`` for disconnects
    the scorer itself ordered — the error classifier maps it to "already
    scored" so one offense is never double-counted."""

    def __init__(self, event: str, detail: str = ""):
        self.event = event
        self.detail = detail
        super().__init__(f"peer misbehavior: {event}"
                         + (f" ({detail})" if detail else ""))


class _PeerQ:
    __slots__ = ("score", "last_mono", "events", "total", "ban_count",
                 "last_event", "last_detail", "last_wall")

    def __init__(self):
        self.score = 0.0
        self.last_mono = 0.0
        self.events: dict[str, int] = {}
        self.total = 0
        self.ban_count = 0
        self.last_event = ""
        self.last_detail = ""
        self.last_wall = 0.0


class PeerScorer:
    def __init__(self, addr_book=None, *, enabled: bool = True,
                 disconnect_score: float = DISCONNECT_SCORE,
                 ban_score: float = BAN_SCORE,
                 half_life_s: float = HALF_LIFE_S,
                 ban_ttl_s: float = BAN_TTL_S,
                 ban_ttl_max_s: float = BAN_TTL_MAX_S,
                 max_tracked: int = MAX_TRACKED):
        self.book = addr_book
        self.enabled = enabled
        self.disconnect_score = disconnect_score
        self.ban_score = ban_score
        self.half_life_s = max(half_life_s, 1e-3)
        self.ban_ttl_s = ban_ttl_s
        self.ban_ttl_max_s = ban_ttl_max_s
        self.max_tracked = max_tracked
        self._peers: dict[str, _PeerQ] = {}
        # ban mirror: reason + expiry for reporting; the addrbook (when
        # present) is the durable/admission-authoritative copy
        self._bans: dict[str, dict] = {}
        self.bans_total = 0

    # ------------------------------------------------------------ scoring

    def _decayed(self, rec: _PeerQ, now: float) -> float:
        dt = now - rec.last_mono
        if dt <= 0:
            return rec.score
        return rec.score * 0.5 ** (dt / self.half_life_s)

    def report(self, peer_id: str, event: str, *, weight: float | None = None,
               persistent: bool = False, detail: str = "") -> str | None:
        """Record one misbehavior event.  Returns the ordered action:
        ``"ban"`` (threshold crossed, timed ban recorded here),
        ``"disconnect"``, or None (tolerated for now)."""
        if not self.enabled:
            return None
        now = clock.monotonic()
        rec = self._peers.get(peer_id)
        if rec is None:
            if len(self._peers) >= self.max_tracked:
                self._prune(now)
            rec = self._peers[peer_id] = _PeerQ()
            rec.last_mono = now
        w = EVENT_WEIGHTS.get(event, DEFAULT_WEIGHT) \
            if weight is None else weight
        rec.score = self._decayed(rec, now) + w
        rec.last_mono = now
        rec.total += 1
        rec.events[event] = rec.events.get(event, 0) + 1
        rec.last_event = event
        rec.last_detail = detail[:160]
        rec.last_wall = clock.walltime()
        # relative epsilon: the score decays over the (sub-ms) gap
        # between accumulation and compare, so a sum that lands exactly
        # ON a threshold must still count as crossing it
        if rec.score >= self.ban_score * (1.0 - 1e-3) and not persistent:
            ttl = min(self.ban_ttl_s * (2 ** rec.ban_count),
                      self.ban_ttl_max_s)
            rec.ban_count += 1
            rec.score = 0.0     # readmission starts from a clean slate
            self._ban(peer_id, ttl, event)
            return "ban"
        if rec.score >= self.disconnect_score * (1.0 - 1e-3):
            return "disconnect"
        return None

    def _prune(self, now: float) -> None:
        """Drop the stalest record so an id-churning attacker can't grow
        the ledger without bound.  Banned/repeat offenders are kept in
        preference to clean-slate entries."""
        victim = min(self._peers.items(),
                     key=lambda kv: (kv[1].ban_count > 0,
                                     self._decayed(kv[1], now),
                                     kv[1].last_mono))
        self._peers.pop(victim[0], None)

    def score(self, peer_id: str) -> float:
        rec = self._peers.get(peer_id)
        if rec is None:
            return 0.0
        return self._decayed(rec, clock.monotonic())

    # --------------------------------------------------------------- bans

    def _ban(self, peer_id: str, ttl: float, reason: str) -> None:
        expiry = clock.walltime() + ttl
        self.bans_total += 1
        self._bans[peer_id] = {"reason": reason, "expiry": expiry,
                               "ttl_s": ttl}
        if self.book is not None:
            try:
                self.book.mark_bad(peer_id, ttl=ttl)
            except TypeError:        # pre-timed-ban book shim in tests
                self.book.mark_bad(peer_id)

    def is_banned(self, peer_id: str) -> bool:
        if self.book is not None and self.book.is_banned(peer_id):
            return True
        ban = self._bans.get(peer_id)
        if ban is None:
            return False
        if ban["expiry"] <= clock.walltime():
            self._bans.pop(peer_id, None)
            return False
        # the mirror only rules when there is no book (the book may have
        # expired the ban early — e.g. a clamped TTL — and wins then)
        return self.book is None

    # ---------------------------------------------------------- reporting

    def peer_info(self, peer_id: str) -> dict:
        """Per-peer quality block for `/net_info` / incident bundles."""
        rec = self._peers.get(peer_id)
        if rec is None:
            return {"score": 0.0, "events_total": 0}
        return {
            "score": round(self._decayed(rec, clock.monotonic()), 3),
            "events_total": rec.total,
            "events": dict(rec.events),
            "ban_count": rec.ban_count,
            "last_event": rec.last_event or None,
            "last_detail": rec.last_detail or None,
        }

    def bans_snapshot(self) -> list[dict]:
        """Active bans (expired entries are dropped as a side effect)."""
        now = clock.walltime()
        out = []
        for pid in list(self._bans):
            ban = self._bans[pid]
            if ban["expiry"] <= now:
                self._bans.pop(pid, None)
                continue
            out.append({"node_id": pid, "reason": ban["reason"],
                        "expires_in_s": round(ban["expiry"] - now, 1),
                        "ttl_s": ban["ttl_s"]})
        if self.book is not None:
            # bans loaded from a persisted book (prior process) have no
            # mirror entry; surface them too
            seen = {b["node_id"] for b in out}
            for pid, expiry in self.book.banned().items():
                if pid not in seen:
                    out.append({"node_id": pid, "reason": "persisted",
                                "expires_in_s": round(expiry - now, 1),
                                "ttl_s": None})
        return out

    def snapshot(self) -> dict:
        """Whole-ledger view for incident bundles and debugging."""
        now = clock.monotonic()
        return {
            "peers": {pid: {"score": round(self._decayed(r, now), 3),
                            "events": dict(r.events),
                            "ban_count": r.ban_count,
                            "last_event": r.last_event or None}
                      for pid, r in self._peers.items()},
            "bans": self.bans_snapshot(),
            "bans_total": self.bans_total,
        }
