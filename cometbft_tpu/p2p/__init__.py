"""TPU-native p2p stack (reference: ``p2p/`` — SURVEY.md §2.7).

Host-side networking is asyncio TCP (the consensus workload's device story
is batching, not transport): an authenticated-encryption SecretConnection,
an MConnection channel multiplexer, and a Switch owning peers + reactors,
with a node-wide peer-reputation scorer (quality.py) gating admission.
"""

from .key import NodeKey
from .pex import AddrBook, PexReactor
from .node_info import NodeInfo
from .peer import Peer
from .quality import PeerScorer
from .reactor import ChannelDescriptor, Reactor
from .switch import Switch
from .transport import Transport

__all__ = ["NodeKey", "NodeInfo", "Peer", "ChannelDescriptor", "Reactor",
           "Switch", "Transport", "AddrBook", "PexReactor", "PeerScorer"]
