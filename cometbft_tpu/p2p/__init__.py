"""TPU-native p2p stack (reference: ``p2p/`` — SURVEY.md §2.7).

Host-side networking is asyncio TCP (the consensus workload's device story
is batching, not transport): an authenticated-encryption SecretConnection,
an MConnection channel multiplexer, and a Switch owning peers + reactors.
"""

from .key import NodeKey
from .pex import AddrBook, PexReactor
from .node_info import NodeInfo
from .peer import Peer
from .reactor import ChannelDescriptor, Reactor
from .switch import Switch
from .transport import Transport

__all__ = ["NodeKey", "NodeInfo", "Peer", "ChannelDescriptor", "Reactor",
           "Switch", "Transport", "AddrBook", "PexReactor"]
