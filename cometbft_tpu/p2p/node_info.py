"""NodeInfo: the post-handshake identity/compatibility exchange
(reference: ``p2p/node_info.go`` DefaultNodeInfo + CompatibleWith)."""

from __future__ import annotations

from dataclasses import dataclass, field

import msgpack

P2P_PROTOCOL_VERSION = 1
MAX_NODE_INFO_SIZE = 10240


class NodeInfoError(Exception):
    pass


@dataclass
class NodeInfo:
    node_id: str = ""
    listen_addr: str = ""           # "host:port" we accept connections on
    network: str = ""               # chain id
    version: str = "tpu-bft/0.2"
    channels: bytes = b""           # supported channel ids
    moniker: str = ""
    protocol_version: int = P2P_PROTOCOL_VERSION

    def validate_basic(self) -> None:
        if not self.node_id:
            raise NodeInfoError("empty node id")
        if len(self.channels) > 64:
            raise NodeInfoError("too many channels")
        if len(set(self.channels)) != len(self.channels):
            raise NodeInfoError("duplicate channel ids")

    def compatible_with(self, other: "NodeInfo") -> None:
        """Raises NodeInfoError unless the peers can talk
        (node_info.go CompatibleWith: same block version/network, >=1
        common channel)."""
        if self.protocol_version != other.protocol_version:
            raise NodeInfoError(
                f"protocol version mismatch: {self.protocol_version} "
                f"!= {other.protocol_version}")
        if self.network != other.network:
            raise NodeInfoError(
                f"network mismatch: {self.network!r} != {other.network!r}")
        if self.channels and other.channels and \
                not set(self.channels) & set(other.channels):
            raise NodeInfoError("no common channels")

    # ------------------------------------------------------------- codec

    def encode(self) -> bytes:
        return msgpack.packb({
            "id": self.node_id, "addr": self.listen_addr,
            "net": self.network, "ver": self.version,
            "ch": self.channels, "mon": self.moniker,
            "pv": self.protocol_version}, use_bin_type=True)

    @classmethod
    def decode(cls, raw: bytes) -> "NodeInfo":
        if len(raw) > MAX_NODE_INFO_SIZE:
            raise NodeInfoError("node info too large")
        d = msgpack.unpackb(raw, raw=False)
        return cls(node_id=d["id"], listen_addr=d["addr"], network=d["net"],
                   version=d["ver"], channels=d["ch"], moniker=d["mon"],
                   protocol_version=d["pv"])
