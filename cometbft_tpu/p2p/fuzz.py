"""Fuzzed peer connections (reference: ``p2p/fuzz.go`` FuzzedConnection
+ ``config.FuzzConnConfig``): wrap the raw stream pair under the
SecretConnection and, per IO, randomly delay, drop writes, or kill the
connection.

Dropping an *encrypted frame* write desynchronizes the AEAD nonce
sequence, so the peer's next decrypt fails and the connection tears down
through the real error path — exactly the class of fault the production
stack must absorb (switch reconnect with backoff, mempool/consensus
gossip resume).

Determinism: decisions come from an injected ``random.Random(seed)``
(config knob ``p2p.fuzz_seed``), never the module-global ``random`` —
same seed, same per-connection decision stream.  When the fault plane
(``libs/failures``) is armed, the sites ``p2p.fuzz.drop`` /
``p2p.fuzz.delay`` / ``p2p.fuzz.kill`` take precedence over the local
probabilities, so connection fuzzing composes with (and is recorded in
the event log of) seeded chaos schedules."""

from __future__ import annotations

import asyncio
import random

from ..libs import clock, failures

MODE_DROP = "drop"
MODE_DELAY = "delay"


class FuzzConnConfig:
    """config.FuzzConnConfig defaults (config/config.go
    DefaultFuzzConnConfig): drop mode, 3s max delay, 1% drop/kill."""

    def __init__(self, mode: str = MODE_DROP,
                 max_delay_s: float = 3.0,
                 prob_drop_rw: float = 0.01,
                 prob_drop_conn: float = 0.0,
                 prob_sleep: float = 0.0,
                 start_after_s: float = 0.0,
                 seed: int = 0):
        self.mode = mode
        self.max_delay_s = max_delay_s
        self.prob_drop_rw = prob_drop_rw
        self.prob_drop_conn = prob_drop_conn
        self.prob_sleep = prob_sleep
        self.start_after_s = start_after_s
        self.rng = random.Random(seed)


class _Fuzzer:
    def __init__(self, cfg: FuzzConnConfig, writer):
        self.cfg = cfg
        self.writer = writer
        self._t0 = clock.monotonic()

    def _active(self) -> bool:
        return (clock.monotonic() - self._t0) >= self.cfg.start_after_s

    async def fuzz(self) -> bool:
        """Returns True if this IO should be swallowed (fuzz.go:110)."""
        if not self._active():
            return False
        cfg = self.cfg
        if failures.is_enabled():
            # chaos-schedule override: an armed p2p.fuzz.* site decides
            # (and logs) instead of the local probability draw
            if failures.fire("p2p.fuzz.kill") is not None:
                self.writer.close()
                return True
            if failures.fire("p2p.fuzz.drop") is not None:
                return True
            f = failures.fire("p2p.fuzz.delay")
            if f is not None:
                await clock.sleep(float(f.get(
                    "delay",
                    failures.site_rng("p2p.fuzz.delay").random()
                    * cfg.max_delay_s)))
                return False
        if cfg.mode == MODE_DELAY:
            await clock.sleep(cfg.rng.random() * cfg.max_delay_s)
            return False
        r = cfg.rng.random()
        if r <= cfg.prob_drop_rw:
            return True
        if r < cfg.prob_drop_rw + cfg.prob_drop_conn:
            self.writer.close()
            return True
        if r < cfg.prob_drop_rw + cfg.prob_drop_conn + cfg.prob_sleep:
            await clock.sleep(cfg.rng.random() * cfg.max_delay_s)
        return False


class FuzzedReader:
    """Duck-types the StreamReader surface SecretConnection uses."""

    def __init__(self, reader: asyncio.StreamReader, fuzzer: _Fuzzer):
        self._reader = reader
        self._fuzzer = fuzzer

    async def readexactly(self, n: int) -> bytes:
        # reads can only be delayed, not dropped: a swallowed read on a
        # reliable stream would silently shift the frame boundary
        f = self._fuzzer
        if f._active() and f.cfg.mode == MODE_DELAY:
            await clock.sleep(f.cfg.rng.random() * f.cfg.max_delay_s)
        return await self._reader.readexactly(n)

    def __getattr__(self, name):
        return getattr(self._reader, name)


class FuzzedWriter:
    """Duck-types the StreamWriter surface SecretConnection uses."""

    def __init__(self, writer: asyncio.StreamWriter, fuzzer: _Fuzzer):
        self._writer = writer
        self._fuzzer = fuzzer
        self._buffer = b""

    def write(self, data: bytes) -> None:
        # write() is sync in asyncio; the probabilistic decision is taken
        # at drain() (the flush point), dropping everything buffered since
        self._buffer += bytes(data)

    async def drain(self) -> None:
        data, self._buffer = self._buffer, b""
        if await self._fuzzer.fuzz():
            return                     # swallowed: peer never sees it
        if data:
            self._writer.write(data)
        await self._writer.drain()

    def close(self) -> None:
        self._writer.close()

    def __getattr__(self, name):
        return getattr(self._writer, name)


def fuzz_streams(reader, writer, cfg: FuzzConnConfig):
    """Wrap a stream pair (FuzzConnAfterFromConfig when
    cfg.start_after_s > 0, FuzzConnFromConfig otherwise)."""
    fz = _Fuzzer(cfg, writer)
    return FuzzedReader(reader, fz), FuzzedWriter(writer, fz)
