"""Bucketed address book with anti-poisoning placement (reference:
``p2p/pex/addrbook.go`` — old/new bucket design; this is a fresh
implementation of the same defensive idea, not a translation).

Threat model: a malicious peer floods PEX responses with addresses to
(1) evict known-good entries and (2) fill the book with nodes it
controls.  Defenses, mirroring the reference's design:

- **Two tiers.**  *New* buckets hold unvetted addresses learned from
  PEX/seeds; *old* buckets hold addresses we have successfully connected
  to.  Old entries are NEVER evicted by new-address pressure — only a
  confirmed-good address can displace one, and only by demotion rules.
- **Hashed placement.**  An address maps to one bucket via
  ``H(salt, source-group, addr-group)`` (new) or ``H(salt, addr-group)``
  (old), where a *group* is the /16-style prefix of the IP (or the whole
  host for names).  A flood from one source can only thrash the few
  buckets its groups hash to; the per-book random salt keeps placement
  unpredictable to attackers.
- **Bounded buckets.**  Each bucket holds at most ``BUCKET_SIZE``
  entries; overflow evicts the *worst new* entry in that bucket (most
  failed attempts, oldest) — never an old-tier entry.
- **Promotion / demotion.**  ``mark_good`` (successful handshake)
  promotes new -> old.  ``mark_attempt`` counts dial failures; entries
  past ``MAX_ATTEMPTS`` are dropped on the next overflow or pick.
  ``mark_bad`` issues a timed ban (TTL-expiring; the peer-quality
  scorer escalates repeat offenders).

The public surface (add/pick/sample/size/save/mark_*) is shared with the
PEX reactor and the seed crawler.
"""

from __future__ import annotations

import hashlib
import json
import os
import random

from ..libs import clock

N_NEW_BUCKETS = 256
N_OLD_BUCKETS = 64
BUCKET_SIZE = 64
BUCKETS_PER_SOURCE = 16     # distinct new-buckets one source can reach:
#   a flood from one subnet lands in at most 16 of the 256 buckets
#   (<= 1024 entries), so >93% of the new tier is untouchable by any
#   single source, and the old tier entirely so
MAX_ATTEMPTS = 5            # dial failures before an entry is droppable
OLD_BIAS = 0.6              # chance pick() prefers the vetted tier
DEFAULT_BAN_TTL_S = 3600.0  # mark_bad without an explicit TTL


def _group(addr: str) -> str:
    """Coarse network group of a dialable address: first two octets of
    an IPv4 (the /16), the whole host otherwise.  Bucket placement
    granularity — one subnet maps to few buckets."""
    host = addr.rsplit(":", 1)[0].strip("[]")
    parts = host.split(".")
    if len(parts) == 4 and all(p.isdigit() for p in parts):
        return f"{parts[0]}.{parts[1]}"
    return host


class _Entry:
    __slots__ = ("node_id", "addr", "src_group", "added", "attempts",
                 "last_success")

    def __init__(self, node_id: str, addr: str, src_group: str):
        self.node_id = node_id
        self.addr = addr
        self.src_group = src_group
        self.added = clock.walltime()
        self.attempts = 0
        self.last_success = 0.0

    def to_json(self):
        return {"id": self.node_id, "addr": self.addr,
                "src": self.src_group, "added": self.added,
                "attempts": self.attempts, "ok": self.last_success}

    @classmethod
    def from_json(cls, d):
        e = cls(d["id"], d["addr"], d.get("src", ""))
        e.added = d.get("added", 0.0)
        e.attempts = d.get("attempts", 0)
        e.last_success = d.get("ok", 0.0)
        return e


class AddrBook:
    """Bucketed book; drop-in for the previous flat implementation."""

    def __init__(self, path: str | None = None):
        self.path = path
        self._salt = os.urandom(8).hex()
        self._new: list[dict[str, _Entry]] = [
            {} for _ in range(N_NEW_BUCKETS)]
        self._old: list[dict[str, _Entry]] = [
            {} for _ in range(N_OLD_BUCKETS)]
        self._where: dict[str, tuple[str, int]] = {}   # id -> (tier, idx)
        # timed bans: id -> expiry (epoch seconds).  Bans used to be a
        # forever-set; now they expire so a transient bad actor (or a
        # node that restarted out of a corrupting state) is readmitted.
        self._banned: dict[str, float] = {}
        if path and os.path.exists(path):
            self._load()

    # ------------------------------------------------------------ placement

    def _hash(self, *parts: str) -> int:
        h = hashlib.sha256("|".join((self._salt,) + parts).encode())
        return int.from_bytes(h.digest()[:8], "big")

    def _new_bucket(self, e: _Entry) -> int:
        # double hash: the address group picks one of BUCKETS_PER_SOURCE
        # slots, the (source, slot) pair picks the bucket — so one source
        # group reaches at most BUCKETS_PER_SOURCE distinct buckets no
        # matter how many addresses it invents
        slot = self._hash("spread", e.src_group,
                          _group(e.addr)) % BUCKETS_PER_SOURCE
        return self._hash("new", e.src_group, str(slot)) % N_NEW_BUCKETS

    def _old_bucket(self, e: _Entry) -> int:
        return self._hash("old", _group(e.addr)) % N_OLD_BUCKETS

    # ------------------------------------------------------------- file io

    def _load(self) -> None:
        try:
            with open(self.path) as f:
                d = json.load(f)
        except (OSError, json.JSONDecodeError):
            return
        self._salt = d.get("salt", self._salt)
        banned = d.get("banned", {})
        if isinstance(banned, dict):
            # current schema: {node_id: expiry}; expired entries drop,
            # and an uncoercible expiry (hand-edited file) counts as
            # expired rather than refusing to boot the node
            now = clock.walltime()
            self._banned = {}
            for nid, exp in banned.items():
                try:
                    exp = float(exp)
                except (TypeError, ValueError):
                    continue
                if exp > now:
                    self._banned[nid] = exp
        else:
            # legacy bare list (the forever-ban era): those bans carried
            # no expiry, so treat them as already expired on load — a
            # peer banned by an old build is readmitted, not doomed
            self._banned = {}
        for tier, key in (("new", "new"), ("old", "old")):
            for ed in d.get(key, []):
                e = _Entry.from_json(ed)
                self._place(e, tier)
        # legacy flat format ({"addrs": {id: addr}}): import as new tier
        for nid, addr in d.get("addrs", {}).items():
            if nid not in self._where and not self.is_banned(nid):
                self._place(_Entry(nid, addr, _group(addr)), "new")

    SAVE_INTERVAL_S = 10.0      # debounce for hot-path mutations: the
    #   reference dumps the book on a ticker, not per handshake

    def save(self) -> None:
        """Unconditional full dump (shutdown / explicit persistence)."""
        if not self.path:
            return
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            now = clock.walltime()
            json.dump({
                "salt": self._salt,
                "new": [e.to_json() for b in self._new for e in b.values()],
                "old": [e.to_json() for b in self._old for e in b.values()],
                "banned": {nid: exp for nid, exp in self._banned.items()
                           if exp > now},
            }, f, indent=1)
        os.replace(tmp, self.path)
        self._last_save = clock.walltime()

    def save_debounced(self) -> None:
        """Hot-path persistence (every handshake/PEX response mutates
        the book): a multi-MB JSON dump per event would block the p2p
        loop, so writes are throttled to one per SAVE_INTERVAL_S; the
        book is a cache — losing the last few seconds on crash is fine
        (PexReactor.stop() flushes via save())."""
        if clock.walltime() - getattr(self, "_last_save", 0.0) >= \
                self.SAVE_INTERVAL_S:
            self.save()

    # ------------------------------------------------------------- mutation

    def _place(self, e: _Entry, tier: str) -> bool:
        """Insert into the tier's hashed bucket, respecting capacity.
        New-tier overflow evicts the worst *new* entry of that bucket;
        old-tier overflow refuses (old entries are precious)."""
        if tier == "old":
            idx = self._old_bucket(e)
            bucket = self._old[idx]
            if e.node_id not in bucket and len(bucket) >= BUCKET_SIZE:
                return False
        else:
            idx = self._new_bucket(e)
            bucket = self._new[idx]
            if e.node_id not in bucket and len(bucket) >= BUCKET_SIZE:
                worst = max(bucket.values(),
                            key=lambda x: (x.attempts, -x.added))
                del bucket[worst.node_id]
                self._where.pop(worst.node_id, None)
        bucket[e.node_id] = e
        self._where[e.node_id] = (tier, idx)
        return True

    def _get(self, node_id: str) -> _Entry | None:
        loc = self._where.get(node_id)
        if loc is None:
            return None
        tier, idx = loc
        return (self._old if tier == "old" else self._new)[idx].get(node_id)

    def add(self, node_id: str, addr: str, persist: bool = True,
            source: str = "", proven: bool = False) -> bool:
        """Learn an address.  ``source`` is the advertising peer's own
        address (its group scopes which new-bucket the entry can land
        in).  Hearsay never displaces an old-tier entry; a PROVEN
        address (we dialed it successfully — pex outbound path) replaces
        any entry and lands directly in the vetted tier, so a peer that
        moved updates cleanly."""
        if not addr or self.is_banned(node_id):
            return False

        cur = self._get(node_id)
        if cur is not None:
            if cur.addr == addr:
                return False
            tier = self._where[node_id][0]
            if tier == "old" and not proven:
                return False           # vetted address wins over hearsay
            self._drop(node_id)
        e = _Entry(node_id, addr, _group(source or addr))
        if proven:
            e.last_success = clock.walltime()
            ok = self._place(e, "old") or self._place(e, "new")
        else:
            ok = self._place(e, "new")
        if ok and persist:
            self.save_debounced()
        return ok

    def _drop(self, node_id: str) -> None:
        loc = self._where.pop(node_id, None)
        if loc is not None:
            tier, idx = loc
            (self._old if tier == "old" else self._new)[idx].pop(
                node_id, None)

    def mark_good(self, node_id: str) -> None:
        """Successful connection/handshake: promote to the old tier
        (addrbook.go MarkGood)."""
        e = self._get(node_id)
        if e is None:
            return
        e.attempts = 0
        e.last_success = clock.walltime()
        if self._where[node_id][0] != "old":
            self._drop(node_id)
            if not self._place(e, "old"):
                self._place(e, "new")      # old bucket full: stay new
        self.save_debounced()

    def mark_attempt(self, node_id: str) -> None:
        e = self._get(node_id)
        if e is None:
            return
        e.attempts += 1
        if e.attempts > MAX_ATTEMPTS:
            if self._where[node_id][0] == "old":
                # repeated failures demote a vetted entry back to the
                # unvetted tier (attempts kept) — so a peer that moved
                # can finally have its stale address replaced by
                # hearsay, and further failures drop it entirely
                self._drop(node_id)
                e.attempts = MAX_ATTEMPTS      # one more failure drops
                self._place(e, "new")
            else:
                self._drop(node_id)

    def mark_bad(self, node_id: str,
                 ttl: float = DEFAULT_BAN_TTL_S) -> None:
        """Timed ban and forget (addrbook MarkBad, but with a TTL — the
        caller escalates repeat offenders; forever-bans are gone)."""
        self._banned[node_id] = clock.walltime() + ttl
        self._drop(node_id)
        self.save_debounced()

    def is_banned(self, node_id: str) -> bool:
        """Active-ban check; an expired ban is dropped on read so the
        peer is readmitted without any sweeper."""
        exp = self._banned.get(node_id)
        if exp is None:
            return False
        if exp <= clock.walltime():
            self._banned.pop(node_id, None)
            return False
        return True

    def banned(self) -> dict[str, float]:
        """Active bans as {node_id: expiry-epoch-seconds}."""
        now = clock.walltime()
        for nid in [n for n, exp in self._banned.items() if exp <= now]:
            self._banned.pop(nid, None)
        return dict(self._banned)

    # ------------------------------------------------------------ selection

    def _tier_items(self, tier) -> list[_Entry]:
        return [e for b in tier for e in b.values()]

    def pick(self, exclude: set[str], n: int = 1) -> list[tuple[str, str]]:
        """Dial candidates, biased toward the vetted old tier."""
        old = [e for e in self._tier_items(self._old)
               if e.node_id not in exclude]
        new = [e for e in self._tier_items(self._new)
               if e.node_id not in exclude and e.attempts <= MAX_ATTEMPTS]
        random.shuffle(old)
        random.shuffle(new)
        out = []
        while len(out) < n and (old or new):
            use_old = old and (not new or random.random() < OLD_BIAS)
            e = (old if use_old else new).pop()
            out.append((e.node_id, e.addr))
        return out

    def sample(self, n: int = 32) -> list[tuple[str, str]]:
        """Random address sample for a PEX response (both tiers)."""
        all_e = self._tier_items(self._old) + self._tier_items(self._new)
        random.shuffle(all_e)
        return [(e.node_id, e.addr) for e in all_e[:n]]

    def is_good(self, node_id: str) -> bool:
        loc = self._where.get(node_id)
        return loc is not None and loc[0] == "old"

    def size(self) -> int:
        return len(self._where)

    def num_old(self) -> int:
        return sum(len(b) for b in self._old)

    def num_new(self) -> int:
        return sum(len(b) for b in self._new)
