"""Switch: reactor registry + peer lifecycle (reference:
``p2p/switch.go:72,110,163,269``).

Owns the Transport, accepts/dials peers, builds each peer's MConnection
from the union of reactor channel descriptors, dispatches received messages
to the owning reactor, fans out broadcasts, and reconnects persistent peers
with exponential backoff after errors (switch.go reconnectToPeer)."""

from __future__ import annotations

import asyncio
import random

from ..libs import aio, clock

from ..libs import log as tmlog
from .conn import (ConnectionLostError, MConnection, MConnectionError,
                   PongTimeoutError)
from .metrics import p2p_metrics, peer_label
from .node_info import NodeInfo
from .peer import Peer
from .quality import PeerMisbehaviorError, PeerScorer
from .reactor import ChannelDescriptor, Reactor
from .transport import Transport

RECONNECT_BASE_DELAY = 0.5
RECONNECT_MAX_DELAY = 30.0
RECONNECT_MAX_ATTEMPTS = 20
# per-peer telemetry flush cadence (Prometheus series are written here,
# never from the packet path); the Switch constructor can override
TELEMETRY_FLUSH_INTERVAL = 2.0


class SwitchError(Exception):
    pass


class Switch:
    def __init__(self, transport: Transport,
                 ping_interval: float = 10.0, pong_timeout: float = 5.0,
                 emulated_latency: float = 0.0,
                 telemetry_interval: float = TELEMETRY_FLUSH_INTERVAL,
                 scorer: PeerScorer | None = None,
                 chaos_scope: str = "",
                 reconnect_base_delay: float = RECONNECT_BASE_DELAY,
                 reconnect_max_delay: float = RECONNECT_MAX_DELAY):
        self.transport = transport
        self.emulated_latency = emulated_latency
        # node-wide peer reputation: every layer's misbehavior reports
        # funnel through report_peer into this one scorer, which orders
        # disconnects and timed bans (p2p/quality.py)
        self.scorer = scorer if scorer is not None else PeerScorer()
        # selector scope stamped on every MConnection so [chaos] specs
        # with node=<name> arm one node's links in an in-proc ensemble
        self.chaos_scope = chaos_scope
        self.reactors: dict[str, Reactor] = {}
        self._chan_to_reactor: dict[int, Reactor] = {}
        self._descriptors: list[ChannelDescriptor] = []
        self.peers: dict[str, Peer] = {}
        # node ids we have EVER dialed persistently: the ban exemption
        # must hold while the peer is between connections (late async
        # misbehavior reports land after removal) and for its inbound
        # reconnects (which never carry persistent=True themselves)
        self._persistent_ids: set[str] = set()
        self.ping_interval = ping_interval
        self.pong_timeout = pong_timeout
        self.telemetry_interval = telemetry_interval
        # reconnect pacing: production keeps the module defaults; the
        # scenario lab shrinks them so a healed partition re-knits in
        # virtual seconds instead of riding a 30 s backoff ceiling
        self.reconnect_base_delay = reconnect_base_delay
        self.reconnect_max_delay = reconnect_max_delay
        self._running = False
        self._reconnect_tasks: dict[str, asyncio.Task] = {}
        self._telemetry_task: asyncio.Task | None = None
        # last flushed (bytes..., drops) per (peer_label, chan_name) so
        # the sampler incs counters by delta, keeping them monotonic
        self._flushed: dict[tuple[str, str], tuple] = {}
        transport.on_accept = self._on_accepted

        # labeled per node id: multi-node in-process ensembles share the
        # process-wide registry
        self._m_node = transport.node_key.id[:8]
        self.log = tmlog.logger("p2p", node=chaos_scope or self._m_node)
        self._m = p2p_metrics()
        self._m_peers_out = self._m.peers.bind(node=self._m_node,
                                               direction="outbound")
        self._m_peers_in = self._m.peers.bind(node=self._m_node,
                                              direction="inbound")
        self._m_rtt = self._m.ping_rtt_seconds.bind(node=self._m_node)
        # per-channel dispatch counters, pre-bound at add_reactor time so
        # the receive hot path pays one dict lookup + one bound inc
        self._m_reactor_msgs: dict[int, object] = {}

    # ----------------------------------------------------------- reactors

    def add_reactor(self, name: str, reactor: Reactor) -> None:
        for desc in reactor.get_channels():
            if desc.channel_id in self._chan_to_reactor:
                raise SwitchError(
                    f"channel {desc.channel_id:#x} already claimed")
            self._chan_to_reactor[desc.channel_id] = reactor
            self._descriptors.append(desc)
            self._m_reactor_msgs[desc.channel_id] = \
                self._m.reactor_msgs.bind(reactor=name, node=self._m_node)
        self.reactors[name] = reactor
        reactor.set_switch(self)

    @property
    def channel_ids(self) -> bytes:
        return bytes(sorted(d.channel_id for d in self._descriptors))

    # ---------------------------------------------------------- lifecycle

    async def start(self) -> None:
        self._running = True
        for reactor in self.reactors.values():
            await reactor.start()
        if self.telemetry_interval > 0:
            self._telemetry_task = asyncio.create_task(
                self._telemetry_routine())

    async def stop(self) -> None:
        self._running = False
        # cancel everything BEFORE the first await: a yield here would
        # let an in-flight reconnect dial land a peer after the removal
        # snapshot below, leaking its MConnection tasks
        tele_task, self._telemetry_task = self._telemetry_task, None
        if tele_task is not None:
            tele_task.cancel()
        for task in self._reconnect_tasks.values():
            task.cancel()
        self._reconnect_tasks.clear()
        for peer in list(self.peers.values()):
            await self._remove_peer(peer, "switch stopping")
        for reactor in self.reactors.values():
            await reactor.stop()
        await self.transport.close()
        if tele_task is not None:
            try:
                await tele_task
            except (asyncio.CancelledError, Exception):
                pass

    # -------------------------------------------------------------- peers

    async def _on_accepted(self, conn, node_info: NodeInfo) -> None:
        try:
            await self._add_peer(conn, node_info, outbound=False)
        except SwitchError as e:
            # refusing an inbound (banned / duplicate / stopping) is a
            # normal outcome, not an unretrieved task exception
            self.log.debug("inbound peer refused", err=str(e))

    async def dial_peer(self, addr: str, persistent: bool = False) -> Peer:
        try:
            conn, node_info = await self.transport.dial(addr)
        except asyncio.CancelledError:
            raise
        except Exception:
            self._m.dial_failures.inc(node=self._m_node)
            raise
        return await self._add_peer(conn, node_info, outbound=True,
                                    persistent=persistent, dial_addr=addr)

    async def _add_peer(self, conn, node_info: NodeInfo, outbound: bool,
                        persistent: bool = False,
                        dial_addr: str | None = None) -> Peer:
        if not self._running:
            # an accept (or concurrent dial) whose handshake finishes
            # while stop() runs must not land a peer after the removal
            # snapshot — its MConnection tasks would never be cancelled
            # and the peer gauges would report a phantom forever
            conn.close()
            raise SwitchError("switch is not running")
        own_id = self.transport.node_key.id
        if node_info.node_id == own_id:
            conn.close()
            raise SwitchError("refusing to connect to self")
        if node_info.node_id in self.peers:
            conn.close()
            raise SwitchError(f"duplicate peer {node_info.node_id[:12]}")
        if persistent:
            self._persistent_ids.add(node_info.node_id)
        if not persistent and \
                node_info.node_id not in self._persistent_ids and \
                self.scorer.is_banned(node_info.node_id):
            # admission control: a timed ban refuses the connection at
            # the door (inbound and plain outbound alike).  Persistent
            # peers are operator-pinned and exempt from bans — including
            # their INBOUND reconnects, which don't carry the flag.
            conn.close()
            raise SwitchError(f"peer {node_info.node_id[:12]} is banned")

        peer_box: list[Peer] = []
        reactor_msgs = self._m_reactor_msgs

        def on_receive(chan_id: int, msg: bytes) -> None:
            reactor = self._chan_to_reactor.get(chan_id)
            if reactor is not None and peer_box:
                bound = reactor_msgs.get(chan_id)
                if bound is not None:
                    bound.inc()
                reactor.receive(chan_id, peer_box[0], msg)

        def on_error(exc: Exception) -> None:
            if peer_box:
                aio.spawn(self.stop_peer_for_error(peer_box[0], exc))

        mconn = MConnection(conn, self._descriptors, on_receive, on_error,
                            ping_interval=self.ping_interval,
                            pong_timeout=self.pong_timeout,
                            emulated_latency=self.emulated_latency)
        mconn.on_rtt = self._m_rtt.observe
        mconn.chaos_scope = self.chaos_scope
        peer = Peer(node_info, mconn, outbound, persistent, dial_addr)
        peer_box.append(peer)
        self.peers[peer.id] = peer
        self._set_peer_gauges()
        mconn.start()
        for reactor in self.reactors.values():
            reactor.add_peer(peer)
        return peer

    def _set_peer_gauges(self) -> None:
        n_out = sum(1 for p in self.peers.values() if p.outbound)
        self._m_peers_out.set(n_out)
        self._m_peers_in.set(len(self.peers) - n_out)

    # ------------------------------------------------------- peer quality

    @staticmethod
    def _classify_error(err) -> str | None:
        """Map a connection-teardown cause to a misbehavior event, or
        None when it isn't the peer's fault (plain network failures) or
        was already scored (PeerMisbehaviorError)."""
        if not isinstance(err, Exception):
            return None                      # string reason / None
        if isinstance(err, (PeerMisbehaviorError, ConnectionLostError,
                            asyncio.CancelledError)):
            return None
        if isinstance(err, PongTimeoutError):
            return "pong_timeout"
        if isinstance(err, MConnectionError):
            return "malformed_frame"         # post-AEAD decode/framing
        if isinstance(err, (ConnectionError, OSError)):
            return None
        return "protocol_error"              # reactor raised on input

    def _score(self, peer_id: str, event: str, *, persistent: bool,
               detail: str = "", weight: float | None = None) -> str | None:
        """Record one event with the scorer + metrics; returns the
        ordered action without executing it."""
        action = self.scorer.report(peer_id, event, weight=weight,
                                    persistent=persistent, detail=detail)
        self._m.misbehavior.inc(node=self._m_node, event=event)
        if action == "ban":
            self._m.peer_bans.inc(node=self._m_node, reason=event)
            self.log.warn("peer banned", peer=peer_id[:12], reason=event,
                          detail=detail[:80])
        return action

    def report_peer(self, peer_id: str, event: str, detail: str = "",
                    weight: float | None = None,
                    disconnect: bool = False) -> str | None:
        """Reactor-facing misbehavior report.  Scores the event; when
        the scorer orders a disconnect/ban — or the caller already
        decided the peer must go (``disconnect=True``, e.g. blocksync
        dropping a bad block server) — the peer is stopped.  Persistent
        peers are re-dialed by stop_peer_for_error as usual."""
        peer = self.peers.get(peer_id)
        # a late report for a disconnected peer must still honor the
        # persistent-peer ban exemption
        persistent = (peer.persistent if peer is not None else False) \
            or peer_id in self._persistent_ids
        action = self._score(peer_id, event, persistent=persistent,
                             detail=detail, weight=weight)
        if peer is not None and (action is not None or disconnect):
            aio.spawn(self.stop_peer_for_error(
                peer, PeerMisbehaviorError(event, detail)))
        return action

    async def stop_peer_for_error(self, peer: Peer, err) -> None:
        """switch.go StopPeerForError + persistent reconnect."""
        if peer.id not in self.peers:
            return
        if isinstance(err, PongTimeoutError):
            self._m.pong_timeouts.inc(node=self._m_node)
        event = self._classify_error(err)
        if event is not None:
            # connection-level misbehavior (garbage frames, reactor
            # blow-ups, silent death) feeds the same ledger as the
            # in-band reports, so a reconnect-and-misbehave loop
            # escalates to a timed ban
            self._score(peer.id, event,
                        persistent=(peer.persistent
                                    or peer.id in self._persistent_ids),
                        detail=repr(err)[:160])
        await self._remove_peer(peer, err)
        if self._running and peer.persistent and peer.dial_addr:
            self._schedule_reconnect(peer.dial_addr)

    async def stop_peer_gracefully(self, peer: Peer) -> None:
        await self._remove_peer(peer, None)

    async def _remove_peer(self, peer: Peer, reason) -> None:
        self.peers.pop(peer.id, None)
        self._set_peer_gauges()
        self._drop_peer_series(peer)
        for reactor in self.reactors.values():
            try:
                reactor.remove_peer(peer, reason)
            except Exception:
                pass
        await peer.stop()

    def _schedule_reconnect(self, addr: str) -> None:
        if addr in self._reconnect_tasks:
            return

        async def _reconnect():
            delay = self.reconnect_base_delay
            attempts = 0
            while True:
                await clock.sleep(delay * (1 + 0.2 * random.random()))
                if not self._running:
                    return
                if any(p.dial_addr == addr for p in self.peers.values()):
                    return      # already re-dialed (a racing loop won)
                try:
                    await self.dial_peer(addr, persistent=True)
                    return
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    if isinstance(e, SwitchError) and \
                            "duplicate peer" in str(e):
                        # the peer reconnected INBOUND while we backed
                        # off: mission accomplished — without this the
                        # now-unbounded loop would re-handshake against
                        # a connected peer every max-delay forever
                        return
                    attempts += 1
                    if attempts == RECONNECT_MAX_ATTEMPTS:
                        # the reference gives up here — silently losing
                        # an operator-pinned peer forever.  Log + count
                        # the backoff exhaustion, then keep retrying at
                        # the max delay (with jitter) indefinitely: a
                        # persistent peer is persistent.
                        self._m.reconnect_giveups.inc(node=self._m_node)
                        self.log.warn(
                            "persistent-peer reconnect exhausted backoff; "
                            "continuing at max delay", addr=addr,
                            attempts=attempts, err=repr(e)[:80])
                    delay = min(delay * 2, self.reconnect_max_delay)

        task = asyncio.create_task(_reconnect())
        task.add_done_callback(
            lambda _t: self._reconnect_tasks.pop(addr, None))
        self._reconnect_tasks[addr] = task

    # ---------------------------------------------------------- telemetry

    async def _telemetry_routine(self) -> None:
        """Periodic flush of per-peer plain-int counters into the
        peer-labeled Prometheus series (delta-inc keeps counters
        monotonic; gauges are set).  Runs off the packet path at
        ``telemetry_interval`` — the hot path only ever touches ints."""
        try:
            while True:
                await clock.sleep(self.telemetry_interval)
                try:
                    self.flush_peer_telemetry()
                except Exception:
                    pass          # never let a metrics bug kill p2p
        except asyncio.CancelledError:
            raise

    def flush_peer_telemetry(self) -> None:
        for peer in list(self.peers.values()):
            self._flush_one_peer(peer)

    def _flush_one_peer(self, peer: Peer) -> None:
        mets, node = self._m, self._m_node
        pl = peer_label(peer.id)
        mconn = peer.mconn
        for ch in mconn.channels.values():
            cname = ch.display_name
            key = (pl, cname)
            cur = (ch.sent_bytes, ch.recv_bytes, ch.sent_msgs,
                   ch.recv_msgs, ch.queue_full_drops)
            last = self._flushed.get(key, (0, 0, 0, 0, 0))
            if cur[0] > last[0]:
                mets.peer_send_bytes.inc(cur[0] - last[0], node=node,
                                         peer=pl, channel=cname)
            if cur[1] > last[1]:
                mets.peer_recv_bytes.inc(cur[1] - last[1], node=node,
                                         peer=pl, channel=cname)
            if cur[2] > last[2]:
                mets.peer_send_msgs.inc(cur[2] - last[2], node=node,
                                        peer=pl, channel=cname)
            if cur[3] > last[3]:
                mets.peer_recv_msgs.inc(cur[3] - last[3], node=node,
                                        peer=pl, channel=cname)
            if cur[4] > last[4]:
                mets.peer_queue_drops.inc(cur[4] - last[4], node=node,
                                          peer=pl, channel=cname)
                mets.queue_full_drops.inc(cur[4] - last[4], node=node,
                                          channel=cname)
            self._flushed[key] = cur
            mets.peer_queue_depth.set(ch.queue.qsize(), node=node,
                                      peer=pl, channel=cname)
        mets.peer_send_rate.set(mconn.send_monitor.rate, node=node,
                                peer=pl)
        mets.peer_recv_rate.set(mconn.recv_monitor.rate, node=node,
                                peer=pl)
        mets.peer_score.set(self.scorer.score(peer.id), node=node,
                            peer=pl)
        if mconn.last_rtt_s is not None:
            mets.peer_rtt.set(mconn.last_rtt_s, node=node, peer=pl)

    def _drop_peer_series(self, peer: Peer) -> None:
        """Final counter flush (up to one sampler interval of deltas is
        still unreported — queue-full drops especially cluster right
        before a disconnect), then drop the gauges so a departed peer
        never reports stale depth/rate/RTT forever.  Counters stay
        (Prometheus counters are append-only; the cardinality guard
        reclaims them under churn)."""
        try:
            self._flush_one_peer(peer)
        except Exception:
            pass                  # metrics must never block removal
        pl = peer_label(peer.id)
        mets, node = self._m, self._m_node
        for key in [k for k in self._flushed if k[0] == pl]:
            self._flushed.pop(key, None)
            mets.peer_queue_depth.remove(node=node, peer=pl,
                                         channel=key[1])
        mets.peer_send_rate.remove(node=node, peer=pl)
        mets.peer_recv_rate.remove(node=node, peer=pl)
        mets.peer_rtt.remove(node=node, peer=pl)
        mets.peer_score.remove(node=node, peer=pl)

    def peer_snapshot(self) -> list[dict]:
        """Per-peer telemetry dicts for `/net_info` and the liveness
        watchdog's incident bundles, each carrying the scorer's quality
        block (score / event counts / ban history)."""
        out = []
        for p in self.peers.values():
            d = p.telemetry()
            d["quality"] = self.scorer.peer_info(p.id)
            out.append(d)
        return out

    def quietest_peer_recv_age_s(self) -> float | None:
        """Seconds since the MOST RECENTLY heard-from peer last produced
        a complete packet — the watchdog's "all peers went quiet" input
        (None with no peers: an isolated node is a different condition)."""
        if not self.peers:
            return None
        now = clock.monotonic()
        return min(now - p.mconn.last_recv_mono
                   for p in self.peers.values())

    # ---------------------------------------------------------- broadcast

    def broadcast(self, channel_id: int, msg: bytes,
                  except_peer: Peer | None = None) -> None:
        """Fan a message to every connected peer (switch.go:269)."""
        for peer in self.peers.values():
            if except_peer is not None and peer.id == except_peer.id:
                continue
            peer.send(channel_id, msg)

    def n_peers(self) -> int:
        return len(self.peers)
