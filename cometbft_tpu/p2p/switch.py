"""Switch: reactor registry + peer lifecycle (reference:
``p2p/switch.go:72,110,163,269``).

Owns the Transport, accepts/dials peers, builds each peer's MConnection
from the union of reactor channel descriptors, dispatches received messages
to the owning reactor, fans out broadcasts, and reconnects persistent peers
with exponential backoff after errors (switch.go reconnectToPeer)."""

from __future__ import annotations

import asyncio
import time

from ..libs import aio
import random

from .conn import MConnection, PongTimeoutError
from .metrics import p2p_metrics, peer_label
from .node_info import NodeInfo
from .peer import Peer
from .reactor import ChannelDescriptor, Reactor
from .transport import Transport

RECONNECT_BASE_DELAY = 0.5
RECONNECT_MAX_DELAY = 30.0
RECONNECT_MAX_ATTEMPTS = 20
# per-peer telemetry flush cadence (Prometheus series are written here,
# never from the packet path); the Switch constructor can override
TELEMETRY_FLUSH_INTERVAL = 2.0


class SwitchError(Exception):
    pass


class Switch:
    def __init__(self, transport: Transport,
                 ping_interval: float = 10.0, pong_timeout: float = 5.0,
                 emulated_latency: float = 0.0,
                 telemetry_interval: float = TELEMETRY_FLUSH_INTERVAL):
        self.transport = transport
        self.emulated_latency = emulated_latency
        self.reactors: dict[str, Reactor] = {}
        self._chan_to_reactor: dict[int, Reactor] = {}
        self._descriptors: list[ChannelDescriptor] = []
        self.peers: dict[str, Peer] = {}
        self.ping_interval = ping_interval
        self.pong_timeout = pong_timeout
        self.telemetry_interval = telemetry_interval
        self._running = False
        self._reconnect_tasks: dict[str, asyncio.Task] = {}
        self._telemetry_task: asyncio.Task | None = None
        # last flushed (bytes..., drops) per (peer_label, chan_name) so
        # the sampler incs counters by delta, keeping them monotonic
        self._flushed: dict[tuple[str, str], tuple] = {}
        transport.on_accept = self._on_accepted

        # labeled per node id: multi-node in-process ensembles share the
        # process-wide registry
        self._m_node = transport.node_key.id[:8]
        self._m = p2p_metrics()
        self._m_peers_out = self._m.peers.bind(node=self._m_node,
                                               direction="outbound")
        self._m_peers_in = self._m.peers.bind(node=self._m_node,
                                              direction="inbound")
        self._m_rtt = self._m.ping_rtt_seconds.bind(node=self._m_node)
        # per-channel dispatch counters, pre-bound at add_reactor time so
        # the receive hot path pays one dict lookup + one bound inc
        self._m_reactor_msgs: dict[int, object] = {}

    # ----------------------------------------------------------- reactors

    def add_reactor(self, name: str, reactor: Reactor) -> None:
        for desc in reactor.get_channels():
            if desc.channel_id in self._chan_to_reactor:
                raise SwitchError(
                    f"channel {desc.channel_id:#x} already claimed")
            self._chan_to_reactor[desc.channel_id] = reactor
            self._descriptors.append(desc)
            self._m_reactor_msgs[desc.channel_id] = \
                self._m.reactor_msgs.bind(reactor=name, node=self._m_node)
        self.reactors[name] = reactor
        reactor.set_switch(self)

    @property
    def channel_ids(self) -> bytes:
        return bytes(sorted(d.channel_id for d in self._descriptors))

    # ---------------------------------------------------------- lifecycle

    async def start(self) -> None:
        self._running = True
        for reactor in self.reactors.values():
            await reactor.start()
        if self.telemetry_interval > 0:
            self._telemetry_task = asyncio.create_task(
                self._telemetry_routine())

    async def stop(self) -> None:
        self._running = False
        # cancel everything BEFORE the first await: a yield here would
        # let an in-flight reconnect dial land a peer after the removal
        # snapshot below, leaking its MConnection tasks
        tele_task, self._telemetry_task = self._telemetry_task, None
        if tele_task is not None:
            tele_task.cancel()
        for task in self._reconnect_tasks.values():
            task.cancel()
        self._reconnect_tasks.clear()
        for peer in list(self.peers.values()):
            await self._remove_peer(peer, "switch stopping")
        for reactor in self.reactors.values():
            await reactor.stop()
        await self.transport.close()
        if tele_task is not None:
            try:
                await tele_task
            except (asyncio.CancelledError, Exception):
                pass

    # -------------------------------------------------------------- peers

    async def _on_accepted(self, conn, node_info: NodeInfo) -> None:
        await self._add_peer(conn, node_info, outbound=False)

    async def dial_peer(self, addr: str, persistent: bool = False) -> Peer:
        try:
            conn, node_info = await self.transport.dial(addr)
        except asyncio.CancelledError:
            raise
        except Exception:
            self._m.dial_failures.inc(node=self._m_node)
            raise
        return await self._add_peer(conn, node_info, outbound=True,
                                    persistent=persistent, dial_addr=addr)

    async def _add_peer(self, conn, node_info: NodeInfo, outbound: bool,
                        persistent: bool = False,
                        dial_addr: str | None = None) -> Peer:
        if not self._running:
            # an accept (or concurrent dial) whose handshake finishes
            # while stop() runs must not land a peer after the removal
            # snapshot — its MConnection tasks would never be cancelled
            # and the peer gauges would report a phantom forever
            conn.close()
            raise SwitchError("switch is not running")
        own_id = self.transport.node_key.id
        if node_info.node_id == own_id:
            conn.close()
            raise SwitchError("refusing to connect to self")
        if node_info.node_id in self.peers:
            conn.close()
            raise SwitchError(f"duplicate peer {node_info.node_id[:12]}")

        peer_box: list[Peer] = []
        reactor_msgs = self._m_reactor_msgs

        def on_receive(chan_id: int, msg: bytes) -> None:
            reactor = self._chan_to_reactor.get(chan_id)
            if reactor is not None and peer_box:
                bound = reactor_msgs.get(chan_id)
                if bound is not None:
                    bound.inc()
                reactor.receive(chan_id, peer_box[0], msg)

        def on_error(exc: Exception) -> None:
            if peer_box:
                aio.spawn(self.stop_peer_for_error(peer_box[0], exc))

        mconn = MConnection(conn, self._descriptors, on_receive, on_error,
                            ping_interval=self.ping_interval,
                            pong_timeout=self.pong_timeout,
                            emulated_latency=self.emulated_latency)
        mconn.on_rtt = self._m_rtt.observe
        peer = Peer(node_info, mconn, outbound, persistent, dial_addr)
        peer_box.append(peer)
        self.peers[peer.id] = peer
        self._set_peer_gauges()
        mconn.start()
        for reactor in self.reactors.values():
            reactor.add_peer(peer)
        return peer

    def _set_peer_gauges(self) -> None:
        n_out = sum(1 for p in self.peers.values() if p.outbound)
        self._m_peers_out.set(n_out)
        self._m_peers_in.set(len(self.peers) - n_out)

    async def stop_peer_for_error(self, peer: Peer, err) -> None:
        """switch.go StopPeerForError + persistent reconnect."""
        if peer.id not in self.peers:
            return
        if isinstance(err, PongTimeoutError):
            self._m.pong_timeouts.inc(node=self._m_node)
        await self._remove_peer(peer, err)
        if self._running and peer.persistent and peer.dial_addr:
            self._schedule_reconnect(peer.dial_addr)

    async def stop_peer_gracefully(self, peer: Peer) -> None:
        await self._remove_peer(peer, None)

    async def _remove_peer(self, peer: Peer, reason) -> None:
        self.peers.pop(peer.id, None)
        self._set_peer_gauges()
        self._drop_peer_series(peer)
        for reactor in self.reactors.values():
            try:
                reactor.remove_peer(peer, reason)
            except Exception:
                pass
        await peer.stop()

    def _schedule_reconnect(self, addr: str) -> None:
        if addr in self._reconnect_tasks:
            return

        async def _reconnect():
            delay = RECONNECT_BASE_DELAY
            for _ in range(RECONNECT_MAX_ATTEMPTS):
                await asyncio.sleep(delay * (1 + 0.2 * random.random()))
                if not self._running:
                    return
                try:
                    await self.dial_peer(addr, persistent=True)
                    return
                except Exception:
                    delay = min(delay * 2, RECONNECT_MAX_DELAY)
            # give up silently (reference logs and gives up too)

        task = asyncio.create_task(_reconnect())
        task.add_done_callback(
            lambda _t: self._reconnect_tasks.pop(addr, None))
        self._reconnect_tasks[addr] = task

    # ---------------------------------------------------------- telemetry

    async def _telemetry_routine(self) -> None:
        """Periodic flush of per-peer plain-int counters into the
        peer-labeled Prometheus series (delta-inc keeps counters
        monotonic; gauges are set).  Runs off the packet path at
        ``telemetry_interval`` — the hot path only ever touches ints."""
        try:
            while True:
                await asyncio.sleep(self.telemetry_interval)
                try:
                    self.flush_peer_telemetry()
                except Exception:
                    pass          # never let a metrics bug kill p2p
        except asyncio.CancelledError:
            raise

    def flush_peer_telemetry(self) -> None:
        for peer in list(self.peers.values()):
            self._flush_one_peer(peer)

    def _flush_one_peer(self, peer: Peer) -> None:
        mets, node = self._m, self._m_node
        pl = peer_label(peer.id)
        mconn = peer.mconn
        for ch in mconn.channels.values():
            cname = ch.display_name
            key = (pl, cname)
            cur = (ch.sent_bytes, ch.recv_bytes, ch.sent_msgs,
                   ch.recv_msgs, ch.queue_full_drops)
            last = self._flushed.get(key, (0, 0, 0, 0, 0))
            if cur[0] > last[0]:
                mets.peer_send_bytes.inc(cur[0] - last[0], node=node,
                                         peer=pl, channel=cname)
            if cur[1] > last[1]:
                mets.peer_recv_bytes.inc(cur[1] - last[1], node=node,
                                         peer=pl, channel=cname)
            if cur[2] > last[2]:
                mets.peer_send_msgs.inc(cur[2] - last[2], node=node,
                                        peer=pl, channel=cname)
            if cur[3] > last[3]:
                mets.peer_recv_msgs.inc(cur[3] - last[3], node=node,
                                        peer=pl, channel=cname)
            if cur[4] > last[4]:
                mets.peer_queue_drops.inc(cur[4] - last[4], node=node,
                                          peer=pl, channel=cname)
                mets.queue_full_drops.inc(cur[4] - last[4], node=node,
                                          channel=cname)
            self._flushed[key] = cur
            mets.peer_queue_depth.set(ch.queue.qsize(), node=node,
                                      peer=pl, channel=cname)
        mets.peer_send_rate.set(mconn.send_monitor.rate, node=node,
                                peer=pl)
        mets.peer_recv_rate.set(mconn.recv_monitor.rate, node=node,
                                peer=pl)
        if mconn.last_rtt_s is not None:
            mets.peer_rtt.set(mconn.last_rtt_s, node=node, peer=pl)

    def _drop_peer_series(self, peer: Peer) -> None:
        """Final counter flush (up to one sampler interval of deltas is
        still unreported — queue-full drops especially cluster right
        before a disconnect), then drop the gauges so a departed peer
        never reports stale depth/rate/RTT forever.  Counters stay
        (Prometheus counters are append-only; the cardinality guard
        reclaims them under churn)."""
        try:
            self._flush_one_peer(peer)
        except Exception:
            pass                  # metrics must never block removal
        pl = peer_label(peer.id)
        mets, node = self._m, self._m_node
        for key in [k for k in self._flushed if k[0] == pl]:
            self._flushed.pop(key, None)
            mets.peer_queue_depth.remove(node=node, peer=pl,
                                         channel=key[1])
        mets.peer_send_rate.remove(node=node, peer=pl)
        mets.peer_recv_rate.remove(node=node, peer=pl)
        mets.peer_rtt.remove(node=node, peer=pl)

    def peer_snapshot(self) -> list[dict]:
        """Per-peer telemetry dicts for `/net_info` and the liveness
        watchdog's incident bundles."""
        return [p.telemetry() for p in self.peers.values()]

    def quietest_peer_recv_age_s(self) -> float | None:
        """Seconds since the MOST RECENTLY heard-from peer last produced
        a complete packet — the watchdog's "all peers went quiet" input
        (None with no peers: an isolated node is a different condition)."""
        if not self.peers:
            return None
        now = time.monotonic()
        return min(now - p.mconn.last_recv_mono
                   for p in self.peers.values())

    # ---------------------------------------------------------- broadcast

    def broadcast(self, channel_id: int, msg: bytes,
                  except_peer: Peer | None = None) -> None:
        """Fan a message to every connected peer (switch.go:269)."""
        for peer in self.peers.values():
            if except_peer is not None and peer.id == except_peer.id:
                continue
            peer.send(channel_id, msg)

    def n_peers(self) -> int:
        return len(self.peers)
