"""Switch: reactor registry + peer lifecycle (reference:
``p2p/switch.go:72,110,163,269``).

Owns the Transport, accepts/dials peers, builds each peer's MConnection
from the union of reactor channel descriptors, dispatches received messages
to the owning reactor, fans out broadcasts, and reconnects persistent peers
with exponential backoff after errors (switch.go reconnectToPeer)."""

from __future__ import annotations

import asyncio

from ..libs import aio
import random

from .conn import MConnection
from .node_info import NodeInfo
from .peer import Peer
from .reactor import ChannelDescriptor, Reactor
from .transport import Transport

RECONNECT_BASE_DELAY = 0.5
RECONNECT_MAX_DELAY = 30.0
RECONNECT_MAX_ATTEMPTS = 20


class SwitchError(Exception):
    pass


class Switch:
    def __init__(self, transport: Transport,
                 ping_interval: float = 10.0, pong_timeout: float = 5.0,
                 emulated_latency: float = 0.0):
        self.transport = transport
        self.emulated_latency = emulated_latency
        self.reactors: dict[str, Reactor] = {}
        self._chan_to_reactor: dict[int, Reactor] = {}
        self._descriptors: list[ChannelDescriptor] = []
        self.peers: dict[str, Peer] = {}
        self.ping_interval = ping_interval
        self.pong_timeout = pong_timeout
        self._running = False
        self._reconnect_tasks: dict[str, asyncio.Task] = {}
        transport.on_accept = self._on_accepted
        from ..libs import metrics as _m

        # labeled per node id: multi-node in-process ensembles share the
        # process-wide registry
        self._m_node = transport.node_key.id[:8]
        self._m_peers = _m.gauge("p2p_peers", "connected peers")

    # ----------------------------------------------------------- reactors

    def add_reactor(self, name: str, reactor: Reactor) -> None:
        for desc in reactor.get_channels():
            if desc.channel_id in self._chan_to_reactor:
                raise SwitchError(
                    f"channel {desc.channel_id:#x} already claimed")
            self._chan_to_reactor[desc.channel_id] = reactor
            self._descriptors.append(desc)
        self.reactors[name] = reactor
        reactor.set_switch(self)

    @property
    def channel_ids(self) -> bytes:
        return bytes(sorted(d.channel_id for d in self._descriptors))

    # ---------------------------------------------------------- lifecycle

    async def start(self) -> None:
        self._running = True
        for reactor in self.reactors.values():
            await reactor.start()

    async def stop(self) -> None:
        self._running = False
        for task in self._reconnect_tasks.values():
            task.cancel()
        self._reconnect_tasks.clear()
        for peer in list(self.peers.values()):
            await self._remove_peer(peer, "switch stopping")
        for reactor in self.reactors.values():
            await reactor.stop()
        await self.transport.close()

    # -------------------------------------------------------------- peers

    async def _on_accepted(self, conn, node_info: NodeInfo) -> None:
        await self._add_peer(conn, node_info, outbound=False)

    async def dial_peer(self, addr: str, persistent: bool = False) -> Peer:
        conn, node_info = await self.transport.dial(addr)
        return await self._add_peer(conn, node_info, outbound=True,
                                    persistent=persistent, dial_addr=addr)

    async def _add_peer(self, conn, node_info: NodeInfo, outbound: bool,
                        persistent: bool = False,
                        dial_addr: str | None = None) -> Peer:
        own_id = self.transport.node_key.id
        if node_info.node_id == own_id:
            conn.close()
            raise SwitchError("refusing to connect to self")
        if node_info.node_id in self.peers:
            conn.close()
            raise SwitchError(f"duplicate peer {node_info.node_id[:12]}")

        peer_box: list[Peer] = []

        def on_receive(chan_id: int, msg: bytes) -> None:
            reactor = self._chan_to_reactor.get(chan_id)
            if reactor is not None and peer_box:
                reactor.receive(chan_id, peer_box[0], msg)

        def on_error(exc: Exception) -> None:
            if peer_box:
                aio.spawn(self.stop_peer_for_error(peer_box[0], exc))

        mconn = MConnection(conn, self._descriptors, on_receive, on_error,
                            ping_interval=self.ping_interval,
                            pong_timeout=self.pong_timeout,
                            emulated_latency=self.emulated_latency)
        peer = Peer(node_info, mconn, outbound, persistent, dial_addr)
        peer_box.append(peer)
        self.peers[peer.id] = peer
        self._m_peers.set(len(self.peers), node=self._m_node)
        mconn.start()
        for reactor in self.reactors.values():
            reactor.add_peer(peer)
        return peer

    async def stop_peer_for_error(self, peer: Peer, err) -> None:
        """switch.go StopPeerForError + persistent reconnect."""
        if peer.id not in self.peers:
            return
        await self._remove_peer(peer, err)
        if self._running and peer.persistent and peer.dial_addr:
            self._schedule_reconnect(peer.dial_addr)

    async def stop_peer_gracefully(self, peer: Peer) -> None:
        await self._remove_peer(peer, None)

    async def _remove_peer(self, peer: Peer, reason) -> None:
        self.peers.pop(peer.id, None)
        self._m_peers.set(len(self.peers), node=self._m_node)
        for reactor in self.reactors.values():
            try:
                reactor.remove_peer(peer, reason)
            except Exception:
                pass
        await peer.stop()

    def _schedule_reconnect(self, addr: str) -> None:
        if addr in self._reconnect_tasks:
            return

        async def _reconnect():
            delay = RECONNECT_BASE_DELAY
            for _ in range(RECONNECT_MAX_ATTEMPTS):
                await asyncio.sleep(delay * (1 + 0.2 * random.random()))
                if not self._running:
                    return
                try:
                    await self.dial_peer(addr, persistent=True)
                    return
                except Exception:
                    delay = min(delay * 2, RECONNECT_MAX_DELAY)
            # give up silently (reference logs and gives up too)

        task = asyncio.create_task(_reconnect())
        task.add_done_callback(
            lambda _t: self._reconnect_tasks.pop(addr, None))
        self._reconnect_tasks[addr] = task

    # ---------------------------------------------------------- broadcast

    def broadcast(self, channel_id: int, msg: bytes,
                  except_peer: Peer | None = None) -> None:
        """Fan a message to every connected peer (switch.go:269)."""
        for peer in self.peers.values():
            if except_peer is not None and peer.id == except_peer.id:
                continue
            peer.send(channel_id, msg)

    def n_peers(self) -> int:
        return len(self.peers)
