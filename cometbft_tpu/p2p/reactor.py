"""Reactor interface + channel descriptors (reference:
``p2p/base_reactor.go:15-31`` and the channel-id registry of SURVEY §2.7)."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class ChannelDescriptor:
    channel_id: int
    priority: int = 1
    send_queue_capacity: int = 64
    max_msg_size: int = 1 << 20
    name: str = ""


class Reactor:
    """Subclass and register on a Switch.  All callbacks run on the event
    loop — same single-writer discipline as everything else."""

    def __init__(self):
        self.switch = None

    def get_channels(self) -> list[ChannelDescriptor]:
        return []

    def set_switch(self, switch) -> None:
        self.switch = switch

    async def start(self) -> None:
        pass

    async def stop(self) -> None:
        pass

    def add_peer(self, peer) -> None:
        """Peer successfully connected and exchanged NodeInfo."""

    def remove_peer(self, peer, reason: object = None) -> None:
        """Peer disconnected or errored."""

    def receive(self, channel_id: int, peer, msg: bytes) -> None:
        """A complete message arrived for one of our channels."""
