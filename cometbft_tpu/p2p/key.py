"""Persistent node identity (reference: ``p2p/key.go``).

A node's ID is the hex of its ed25519 pubkey address (first 20 bytes of
SHA-256) — self-authenticating: the SecretConnection handshake proves
possession of the key behind the ID.
"""

from __future__ import annotations

import json
import os

from ..crypto.keys import Ed25519PrivKey, Ed25519PubKey


def node_id(pub_key: Ed25519PubKey) -> str:
    return pub_key.address().hex()


class NodeKey:
    def __init__(self, priv_key: Ed25519PrivKey):
        self.priv_key = priv_key

    @property
    def pub_key(self) -> Ed25519PubKey:
        return self.priv_key.pub_key()

    @property
    def id(self) -> str:
        return node_id(self.pub_key)

    @classmethod
    def generate(cls) -> "NodeKey":
        return cls(Ed25519PrivKey.generate())

    @classmethod
    def from_secret(cls, secret: bytes) -> "NodeKey":
        return cls(Ed25519PrivKey.from_secret(secret))

    # -------------------------------------------------------- persistence

    @classmethod
    def load_or_gen(cls, path: str) -> "NodeKey":
        if os.path.exists(path):
            return cls.load(path)
        nk = cls.generate()
        nk.save(path)
        return nk

    @classmethod
    def load(cls, path: str) -> "NodeKey":
        with open(path) as f:
            doc = json.load(f)
        return cls(Ed25519PrivKey(bytes.fromhex(doc["priv_key"])))

    def save(self, path: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"id": self.id,
                       "priv_key": self.priv_key.bytes().hex()}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
