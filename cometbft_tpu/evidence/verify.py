"""Evidence verification (reference: ``internal/evidence/verify.go:19,110,164``).

DuplicateVoteEvidence: both votes must be validly signed by the same
validator, who must have been in the validator set at the evidence height;
the recorded powers must match that historical set.  Age is checked against
the consensus evidence params (expired evidence is invalid).

LightClientAttackEvidence verification needs the conflicting block's commit
checked against the common-height validator set with trusting semantics
(``VerifyCommitLightTrustingAllSignatures``, the evidence-path hot-path
call site) — done when the conflicting block payload is present."""

from __future__ import annotations

from fractions import Fraction

from ..types.evidence import (DuplicateVoteEvidence, Evidence, EvidenceError,
                              EvidenceNotApplicableError,
                              LightClientAttackEvidence)
from ..types.validation import VerifyCommitLightTrustingAllSignatures


def verify_evidence(ev: Evidence, state, state_store,
                    backend: str | None = None, block_store=None) -> None:
    """internal/evidence/verify.go:19 — dispatch + age check.
    Raises EvidenceError on any failure.

    When ``block_store`` is given, the evidence's claimed timestamp is
    pinned to the committed block time at its height (verify.go:36-44) —
    otherwise an attacker could stamp ancient evidence with a fresh time
    and slide it past the duration half of the expiry check."""
    err = ev.validate_basic()
    if err:
        raise EvidenceError(f"invalid evidence: {err}")

    ev_time = ev.time_ns()
    if block_store is not None:
        blk = block_store.load_block(ev.height())
        if blk is None:
            # not necessarily malicious: a statesync'd node has no
            # blocks below its snapshot base
            raise EvidenceNotApplicableError(
                f"no committed block at evidence height {ev.height()}")
        if ev_time != blk.header.time_ns:
            raise EvidenceError(
                f"evidence time {ev_time} != block time "
                f"{blk.header.time_ns} at height {ev.height()}")

    height = state.last_block_height
    ev_params = state.consensus_params.evidence
    age_blocks = height - ev.height()
    age_ns = state.last_block_time_ns - ev_time
    if age_blocks > ev_params.max_age_num_blocks and \
            age_ns > ev_params.max_age_duration_ns:
        # expiry race with the sender's pruning, not malice
        raise EvidenceNotApplicableError(
            f"evidence from height {ev.height()} is too old "
            f"({age_blocks} blocks, {age_ns} ns)")

    if isinstance(ev, DuplicateVoteEvidence):
        _verify_duplicate_vote(ev, state.chain_id, state_store)
    elif isinstance(ev, LightClientAttackEvidence):
        _verify_light_client_attack(ev, state.chain_id, state_store, backend)
    else:
        raise EvidenceError(f"unknown evidence type {type(ev).__name__}")


def _verify_duplicate_vote(ev: DuplicateVoteEvidence, chain_id: str,
                           state_store) -> None:
    """verify.go:164 VerifyDuplicateVote."""
    vals = state_store.load_validators(ev.height())
    if vals is None:
        # pruned / statesync'd history: we cannot judge, so don't blame
        raise EvidenceNotApplicableError(
            f"no validator set at height {ev.height()}")
    idx, val = vals.get_by_address(ev.vote_a.validator_address)
    if idx < 0:
        raise EvidenceError("validator not in set at evidence height")
    if ev.validator_power != val.voting_power:
        raise EvidenceError(
            f"validator power mismatch {ev.validator_power} != "
            f"{val.voting_power}")
    if ev.total_voting_power != vals.total_voting_power():
        raise EvidenceError(
            f"total power mismatch {ev.total_voting_power} != "
            f"{vals.total_voting_power()}")
    for v in (ev.vote_a, ev.vote_b):
        # BLS validators sign the zero-timestamp aggregation domain
        # (types/vote.py sign_bytes_for); Ed25519 the reference encoding
        if not val.pub_key.verify_signature(
                v.sign_bytes_for(chain_id, val.pub_key.type()),
                v.signature):
            raise EvidenceError("invalid vote signature in evidence")


def _verify_light_client_attack(ev: LightClientAttackEvidence,
                                chain_id: str, state_store,
                                backend: str | None) -> None:
    """verify.go:110 VerifyLightClientAttack (conflicting-block commit
    check against the common-height set with 1/3 trust)."""
    common_vals = state_store.load_validators(ev.common_height)
    if common_vals is None:
        raise EvidenceNotApplicableError(
            f"no validator set at common height {ev.common_height}")
    blk = ev.conflicting_block
    if blk is None:
        raise EvidenceError("missing conflicting block payload")
    commit = getattr(blk, "commit", None)
    if commit is None:
        raise EvidenceError("conflicting block has no commit")
    VerifyCommitLightTrustingAllSignatures(
        chain_id, common_vals, commit, trust_level=Fraction(1, 3),
        backend=backend)
