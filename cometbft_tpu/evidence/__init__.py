from .pool import EvidencePool
from .reactor import EVIDENCE_CHANNEL, EvidenceReactor
from .verify import verify_evidence

__all__ = ["EvidencePool", "EvidenceReactor", "EVIDENCE_CHANNEL",
           "verify_evidence"]
