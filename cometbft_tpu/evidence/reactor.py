"""Evidence reactor: gossip pending evidence to peers on channel 0x38
(reference: ``internal/evidence/reactor.go``; channel id at
``internal/evidence/reactor.go:17``).

The reference walks the pool's clist per peer, sending one evidence at a
time; with the pool's on_evidence_added hook and small evidence volumes,
broadcasting on add + a full sync on peer connect covers the same
delivery guarantees."""

from __future__ import annotations

import msgpack

from ..libs import aio

from ..types import codec
from ..types.evidence import EvidenceError, EvidenceNotApplicableError
from ..p2p.reactor import ChannelDescriptor, Reactor
from .pool import EvidencePool

EVIDENCE_CHANNEL = 0x38
PENDING_SYNC_MAX_BYTES = 1 << 20


class EvidenceReactor(Reactor):
    def __init__(self, pool: EvidencePool):
        super().__init__()
        self.pool = pool
        pool.on_evidence_added = self._broadcast_evidence

    def get_channels(self):
        return [ChannelDescriptor(EVIDENCE_CHANNEL, priority=6,
                                  send_queue_capacity=100, name="evidence")]

    def add_peer(self, peer) -> None:
        for ev in self.pool.pending_evidence(PENDING_SYNC_MAX_BYTES):
            peer.send(EVIDENCE_CHANNEL, self._msg(ev))

    def receive(self, channel_id: int, peer, msg: bytes) -> None:
        d = msgpack.unpackb(msg, raw=False)
        if d.get("@") != "ev":
            return
        try:
            self.pool.add_evidence(codec.unpack(d["e"]))
        except EvidenceNotApplicableError:
            # evidence we can't currently judge (expired, below our
            # block base, no state yet): drop it WITHOUT punishing — a
            # freshly statesync'd node must not ban honest peers
            # re-gossiping legitimate pending evidence
            return
        except EvidenceError as e:
            # invalid gossiped evidence: drop the peer (reactor.go Receive
            # punishes the sender) and score it heavily — fabricated
            # evidence is a deliberate act, repetition earns a timed ban
            if self.switch is None:
                return
            if hasattr(self.switch, "report_peer"):
                self.switch.report_peer(peer.id, "bad_evidence",
                                        detail=repr(e)[:120],
                                        disconnect=True)
            else:
                aio.spawn(self.switch.stop_peer_for_error(
                    peer, "invalid evidence"))

    def _msg(self, ev) -> bytes:
        return msgpack.packb({"@": "ev", "e": codec.pack(ev)},
                             use_bin_type=True)

    def _broadcast_evidence(self, ev) -> None:
        if self.switch is not None:
            self.switch.broadcast(EVIDENCE_CHANNEL, self._msg(ev))
