"""Evidence pool: collect, verify, persist and serve Byzantine-behavior
evidence until it is committed in a block (reference:
``internal/evidence/pool.go:24,190,248``).

Consensus reports conflicting votes as raw vote pairs
(``report_conflicting_votes``, the pool's consensusBuffer); they become
``DuplicateVoteEvidence`` stamped with the committed block's time/valset on
the next ``update`` — the reference does exactly this two-phase dance
because evidence needs the block time, which isn't known when the conflict
surfaces."""

from __future__ import annotations

from typing import Callable

from ..abci.types import Misbehavior
from ..storage.db import KVStore, MemDB
from ..types import codec
from ..types.evidence import (DuplicateVoteEvidence, Evidence, EvidenceError,
                              EvidenceNotApplicableError,
                              LightClientAttackEvidence)
from ..types.vote import Vote
from .verify import verify_evidence

K_PENDING = b"evp/"
K_COMMITTED = b"evc/"


def _key(prefix: bytes, ev: Evidence) -> bytes:
    return prefix + ev.height().to_bytes(8, "big") + ev.hash()


class EvidencePool:
    def __init__(self, db: KVStore | None = None, state_store=None,
                 block_store=None, backend: str | None = None):
        self.db = db or MemDB()
        self.state_store = state_store
        self.block_store = block_store
        self.backend = backend
        self.state = None                   # latest sm.State, set by update
        self._conflicting_votes: list[tuple[Vote, Vote]] = []
        self.on_evidence_added: Callable[[Evidence], None] = lambda ev: None

    # ------------------------------------------------------------ ingest

    def report_conflicting_votes(self, vote_a: Vote, vote_b: Vote) -> None:
        """pool.go ReportConflictingVotes — buffered until the next block
        commit supplies time + validator set."""
        self._conflicting_votes.append((vote_a, vote_b))

    def add_evidence(self, ev: Evidence) -> bool:
        """pool.go:190 AddEvidence (gossip/RPC path). Returns False if
        already known; raises EvidenceError if invalid."""
        if self.is_pending(ev) or self.is_committed(ev):
            return False
        if self.state is None or self.state_store is None:
            raise EvidenceNotApplicableError(
                "evidence pool has no state yet")
        verify_evidence(ev, self.state, self.state_store,
                        backend=self.backend, block_store=self.block_store)
        self.db.set(_key(K_PENDING, ev), codec.pack(ev))
        self.on_evidence_added(ev)
        return True

    # ----------------------------------------------------------- queries

    def is_pending(self, ev: Evidence) -> bool:
        return self.db.get(_key(K_PENDING, ev)) is not None

    def is_committed(self, ev: Evidence) -> bool:
        return self.db.get(_key(K_COMMITTED, ev)) is not None

    def _iter_pending(self):
        return self.db.iterate(K_PENDING, K_PENDING + b"\xff" * 48)

    def pending_evidence(self, max_bytes: int) -> list[Evidence]:
        """pool.go PendingEvidence, size-capped for proposals."""
        out, total = [], 0
        for _, raw in sorted(self._iter_pending()):
            ev = codec.unpack(raw)
            total += len(raw)
            if max_bytes > 0 and total > max_bytes:
                break
            out.append(ev)
        return out

    # ------------------------------------------------- block-exec interface

    def check_evidence(self, evidence: list[Evidence]) -> None:
        """Validate evidence carried by a proposed block
        (pool.go CheckEvidence): every item must verify, no duplicates,
        total size within the consensus params cap (a block a validator
        accepts must not exceed what an honest proposer may build)."""
        seen = set()
        total = 0
        max_bytes = (self.state.consensus_params.evidence.max_bytes
                     if self.state is not None else 0)
        for ev in evidence:
            h = ev.hash()
            if h in seen:
                raise EvidenceError("duplicate evidence in block")
            seen.add(h)
            total += len(codec.pack(ev))
            if max_bytes > 0 and total > max_bytes:
                raise EvidenceError(
                    f"evidence in block exceeds max bytes "
                    f"({total} > {max_bytes})")
            if self.is_committed(ev):
                raise EvidenceError("evidence was already committed")
            if not self.is_pending(ev):
                if self.state is None or self.state_store is None:
                    raise EvidenceError("evidence pool has no state yet")
                verify_evidence(ev, self.state, self.state_store,
                                backend=self.backend,
                                block_store=self.block_store)

    def update(self, state, committed: list[Evidence]) -> None:
        """pool.go Update: mark committed, prune expired, convert buffered
        conflicting votes into DuplicateVoteEvidence."""
        self.state = state
        for ev in committed:
            self.db.set(_key(K_COMMITTED, ev), b"\x01")
            self.db.delete(_key(K_PENDING, ev))
        self._prune_expired(state)
        self._process_conflicting_votes(state)

    def _process_conflicting_votes(self, state) -> None:
        pairs, still_waiting = self._conflicting_votes, []
        self._conflicting_votes = []
        for a, b in pairs:
            try:
                vals = self.state_store.load_validators(a.height) \
                    if self.state_store else None
                # evidence time is pinned to the block time at the vote's
                # height (pool.go processConsensusBuffer)
                blk = self.block_store.load_block(a.height) \
                    if self.block_store else None
                if vals is None or blk is None:
                    if a.height >= state.last_block_height:
                        still_waiting.append((a, b))   # block not yet committed
                    continue
                ev = DuplicateVoteEvidence.from_votes(
                    a, b, blk.header.time_ns, vals)
                if self.is_pending(ev) or self.is_committed(ev):
                    continue
                verify_evidence(ev, state, self.state_store,
                                backend=self.backend,
                                block_store=self.block_store)
                self.db.set(_key(K_PENDING, ev), codec.pack(ev))
                self.on_evidence_added(ev)
            except EvidenceError:
                continue
        self._conflicting_votes.extend(still_waiting)

    def _prune_expired(self, state) -> None:
        params = state.consensus_params.evidence
        height = state.last_block_height
        now = state.last_block_time_ns
        for key, raw in list(self._iter_pending()):
            ev = codec.unpack(raw)
            if height - ev.height() > params.max_age_num_blocks and \
                    now - ev.time_ns() > params.max_age_duration_ns:
                self.db.delete(key)

    def abci_evidence(self, evidence: list[Evidence],
                      state) -> list[Misbehavior]:
        """types/evidence.go ABCI() — Misbehavior records for FinalizeBlock/
        PrepareProposal so the app can punish (e.g. slash) offenders."""
        out = []
        for ev in evidence:
            if isinstance(ev, DuplicateVoteEvidence):
                out.append(Misbehavior(
                    type=ev.abci_kind(),
                    validator_address=ev.vote_a.validator_address,
                    validator_power=ev.validator_power,
                    height=ev.height(), time_ns=ev.time_ns(),
                    total_voting_power=ev.total_voting_power))
            elif isinstance(ev, LightClientAttackEvidence):
                for val in ev.byzantine_validators:
                    out.append(Misbehavior(
                        type=ev.abci_kind(),
                        validator_address=val.address,
                        validator_power=val.voting_power,
                        height=ev.height(), time_ns=ev.time_ns(),
                        total_voting_power=ev.total_voting_power))
        return out
