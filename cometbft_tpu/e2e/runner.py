"""e2e testnet runner (reference: ``test/e2e/runner``): turn a Manifest
into a live multi-OS-process testnet on localhost — generate wired homes,
spawn node processes through the CLI, start late joiners when the chain
reaches their height, apply the perturbation schedule, drive load, and
check the end-state invariants (progress, agreement, light-client
verification).

The reference orchestrates docker containers; one machine with OS
processes exercises the same protocol surface (real TCP, real processes,
real kill/pause signals)."""

from __future__ import annotations

import asyncio
import os
import signal
import subprocess
import sys
import time

from .manifest import Manifest

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


class RunnerError(Exception):
    pass


class Runner:
    def __init__(self, manifest: Manifest, base_dir: str,
                 base_port: int = 26656, fast_timeouts: bool = True,
                 log=print):
        self.m = manifest
        self.base_dir = base_dir
        self.base_port = base_port
        self.fast_timeouts = fast_timeouts
        self.log = log
        self.procs: dict[str, subprocess.Popen] = {}
        self.app_procs: dict[str, subprocess.Popen] = {}
        self.paused: set[str] = set()
        # stable order: validators first so port 0 is a validator RPC
        self.names = sorted(
            self.m.nodes,
            key=lambda n: (self.m.nodes[n].mode != "validator", n))
        self.ports = {name: base_port + 3 * i
                      for i, name in enumerate(self.names)}

    def app_port(self, name: str) -> int:
        """Port of the external ABCI app process (socket/grpc nodes)."""
        return self.ports[name] + 2

    # ---------------------------------------------------------- setup

    def home(self, name: str) -> str:
        return os.path.join(self.base_dir, name)

    def rpc_port(self, name: str) -> int:
        return self.ports[name] + 1

    def setup(self) -> None:
        """testnet generation per manifest roles (runner/setup.go).

        The working dir is WIPED first (runner/cleanup.go runs before
        every setup): a previous run's chain data under the same --dir
        otherwise bleeds into this run — a different manifest's genesis
        against stale blockstores produced stuck-at-0 nodes and replay
        crashes before this existed."""
        import shutil as _shutil

        if os.path.isdir(self.base_dir):
            _shutil.rmtree(self.base_dir, ignore_errors=True)
        from .gen import HomeSpec, generate_homes

        powers = self.m.validator_powers()
        backing = [n for n in self.names
                   if self.m.nodes[n].mode != "light"]
        seeds = [n for n in backing if self.m.nodes[n].mode == "seed"]
        specs = [HomeSpec(name=n, p2p_port=self.ports[n],
                          rpc_port=self.rpc_port(n),
                          power=powers.get(n),
                          key_type=self.m.nodes[n].key_type)
                 for n in backing]

        def peers(spec) -> str:
            # with seeds in the topology, non-seed nodes discover the
            # network through them via PEX (manifest.go seed semantics);
            # otherwise everyone wires to everyone
            if seeds and spec.name not in seeds:
                return ""
            return ",".join(f"tcp://127.0.0.1:{self.ports[o]}"
                            for o in backing if o != spec.name)

        def tweak(spec, cfg) -> None:
            cfg.base.signature_backend = "cpu"
            cfg.p2p.emulated_latency_ms = self.m.emulated_latency_ms
            node = self.m.nodes[spec.name]
            cfg.storage.db_backend = node.database
            cfg.p2p.seed_mode = spec.name in seeds
            if node.abci_protocol != "builtin":
                cfg.base.abci = node.abci_protocol
                cfg.base.proxy_app = f"127.0.0.1:{self.app_port(spec.name)}"
            if seeds and spec.name not in seeds:
                cfg.p2p.seeds = ",".join(
                    f"tcp://127.0.0.1:{self.ports[s]}" for s in seeds)
            if self.m.fuzz:
                cfg.p2p.test_fuzz = True
                cfg.p2p.fuzz_start_after_s = 5.0
            if self.fast_timeouts:
                cfg.consensus.timeout_propose = 300_000_000
                cfg.consensus.timeout_prevote = 150_000_000
                cfg.consensus.timeout_precommit = 150_000_000
                cfg.consensus.timeout_commit = 100_000_000

        generate_homes(self.base_dir, specs, self.m.chain_id,
                       initial_height=self.m.initial_height,
                       persistent_peers=peers, tweak=tweak)

    # ---------------------------------------------------------- process

    def _spawn(self, name: str) -> None:
        node = self.m.nodes[name]
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=_REPO)
        if node.abci_protocol != "builtin" and name not in self.app_procs:
            # out-of-process app: one kvstore server per node, living
            # across node kill/restart perturbations (the external-app
            # topology the reference's generator sweeps)
            app_cmd = [sys.executable, "-m", "cometbft_tpu", "abci",
                       "kvstore", "--port", str(self.app_port(name))]
            if node.abci_protocol == "grpc":
                app_cmd.append("--grpc")
            app_log = open(os.path.join(self.base_dir,
                                        f"{name}.app.log"), "ab")
            self.log(f"[e2e] starting {name} app ({node.abci_protocol})")
            self.app_procs[name] = subprocess.Popen(
                app_cmd, stdout=app_log, stderr=subprocess.STDOUT,
                env=env, cwd=_REPO)
            app_log.close()
            self._wait_for_port(self.app_port(name), 20.0)
        if node.mode == "light":
            cmd = self._light_cmd(name)
        else:
            cmd = [sys.executable, "-m", "cometbft_tpu",
                   "--home", self.home(name), "start"]
        self.log(f"[e2e] starting {name} ({node.mode})")
        log_path = os.path.join(self.base_dir, f"{name}.log")
        log_f = open(log_path, "ab")
        self.procs[name] = subprocess.Popen(
            cmd, stdout=log_f, stderr=subprocess.STDOUT,
            env=env, cwd=_REPO)
        log_f.close()          # the child keeps its own fd

    def _wait_for_port(self, port: int, timeout_s: float) -> None:
        """Block until the app server accepts connections: the node
        process has no connect-retry, so losing the interpreter-startup
        race would crash it at boot with ConnectionRefused."""
        import socket

        end = time.monotonic() + timeout_s
        while time.monotonic() < end:
            try:
                with socket.create_connection(("127.0.0.1", port),
                                              timeout=1.0):
                    return
            except OSError:
                time.sleep(0.1)
        raise RunnerError(f"app server on port {port} never came up")

    def _light_cmd(self, name: str) -> list[str]:
        primary = self._primary_name()
        anchor = self._trust_anchor
        return [sys.executable, "-m", "cometbft_tpu", "light",
                "--primary", f"127.0.0.1:{self.rpc_port(primary)}",
                "--chain-id", self.m.chain_id,
                "--trust-height", str(anchor[0]),
                "--trust-hash", anchor[1],
                "--port", str(self.rpc_port(name))]

    def _primary_name(self) -> str:
        for n in self.names:
            if self.m.nodes[n].mode in ("validator", "full"):
                return n
        raise RunnerError("no primary for light node")

    # ------------------------------------------------------------- run

    async def run(self, deadline_s: float = 240.0) -> dict:
        from ..rpc import HTTPClient, RPCError

        async def call(port, method, timeout=30.0, **kw):
            cli = HTTPClient("127.0.0.1", port)
            end = time.monotonic() + timeout
            while True:
                try:
                    # per-attempt bound: a SIGSTOPped node accepts the TCP
                    # connection but never answers, and the retry-loop
                    # timeout only runs between attempts
                    return await asyncio.wait_for(cli.call(method, **kw),
                                                  10.0)
                except (OSError, RPCError, asyncio.TimeoutError):
                    if time.monotonic() > end:
                        raise
                    await asyncio.sleep(0.3)

        pending_start = {n for n in self.names
                         if self.m.nodes[n].start_at > 0
                         or self.m.nodes[n].mode == "light"}
        for name in self.names:
            if name not in pending_start:
                self._spawn(name)

        schedule = []          # (height, action, node) not yet applied
        for name in self.names:
            for h, action in self.m.nodes[name].schedule():
                schedule.append((h, action, name))
        schedule.sort()
        valset_updates = sorted(self.m.validator_updates.items())

        watch_port = self.rpc_port(self._primary_name())
        await call(watch_port, "status", timeout=60.0)
        load_task = asyncio.create_task(self._drive_load(watch_port))
        self._trust_anchor = None
        last_perturb_t = time.monotonic()
        deadline = time.monotonic() + deadline_s
        try:
            while True:
                st = await call(watch_port, "status")
                h = st["sync_info"]["latest_block_height"]

                anchor_h = self.m.initial_height + 1
                if (self._trust_anchor is None and h >= anchor_h
                        and any(self.m.nodes[n].mode == "light"
                                for n in self.names)):
                    blk = await call(watch_port, "block", height=anchor_h)
                    self._trust_anchor = (anchor_h,
                                          blk["block_id"]["hash"]["~b"])

                for name in sorted(pending_start):
                    node = self.m.nodes[name]
                    needs_anchor = node.mode == "light"
                    if h >= node.start_at and (
                            not needs_anchor or self._trust_anchor):
                        pending_start.discard(name)
                        self._spawn(name)

                # apply due perturbations anywhere in the schedule (not
                # just the head): recovery actions (restart/resume) also
                # fire after a stall grace, because a kill/pause may have
                # cost the chain its quorum and made their trigger height
                # unreachable — per-node order is still preserved
                fired = True
                while fired:
                    fired = False
                    for i, (sched_h, action, name) in enumerate(schedule):
                        earlier_same_node = any(
                            n2 == name for _, _, n2 in schedule[:i])
                        due = sched_h <= h or (
                            action in ("restart", "resume")
                            and not earlier_same_node
                            and time.monotonic() - last_perturb_t > 10.0)
                        if due:
                            schedule.pop(i)
                            self._perturb(name, action)
                            last_perturb_t = time.monotonic()
                            fired = True
                            break

                while valset_updates and valset_updates[0][0] <= h:
                    _, updates = valset_updates.pop(0)
                    for vname, power in updates.items():
                        await self._submit_valset_tx(call, watch_port,
                                                     vname, power)

                if (h >= self.m.final_height and not pending_start
                        and not schedule and not valset_updates):
                    break
                if time.monotonic() > deadline:
                    raise RunnerError(
                        f"deadline: h={h}, pending={pending_start}, "
                        f"schedule={schedule}")
                await asyncio.sleep(0.5)
        finally:
            load_task.cancel()

        return await self._check_invariants(call)

    def _perturb(self, name: str, action: str) -> None:
        self.log(f"[e2e] perturb {action} {name}")
        proc = self.procs.get(name)
        if action == "kill" and proc is not None:
            if name in self.paused:          # SIGKILL works on stopped too
                self.paused.discard(name)
            proc.kill()
            proc.wait()
        elif action == "restart":
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait()
            self.paused.discard(name)        # a fresh process is running
            self._spawn(name)
        elif action == "pause" and proc is not None:
            proc.send_signal(signal.SIGSTOP)
            self.paused.add(name)
        elif action == "resume" and proc is not None:
            proc.send_signal(signal.SIGCONT)
            self.paused.discard(name)

    async def _drive_load(self, port: int) -> None:
        from ..rpc import HTTPClient, RPCError

        cli = HTTPClient("127.0.0.1", port)
        ld = self.m.load
        if ld.rate <= 0 or ld.duration <= 0:
            return                     # load disabled
        end = time.monotonic() + ld.duration
        i = 0
        while time.monotonic() < end:
            tx = (b"e2e%06d=" % i) + os.urandom(max(1, ld.size // 2)).hex(
                ).encode()[:ld.size]
            try:
                await cli.call("broadcast_tx_async", tx=tx.hex())
            except Exception:
                pass
            i += 1
            await asyncio.sleep(1.0 / ld.rate)

    # ------------------------------------------------------ invariants

    async def _check_invariants(self, call) -> dict:
        """runner/test.go: every live full/validator node reaches the
        final height and agrees on block hashes; light proxies serve
        verified headers matching the chain."""
        target = self.m.final_height
        heights = {}
        hashes = {}
        for name in self.names:
            node = self.m.nodes[name]
            if node.mode == "light" or name in self.paused:
                continue
            if self.procs.get(name) is None or \
                    self.procs[name].poll() is not None:
                continue               # killed and never restarted
            port = self.rpc_port(name)
            end = time.monotonic() + 150
            while True:
                try:
                    st = await call(port, "status", timeout=120.0)
                except (OSError, asyncio.TimeoutError) as e:
                    raise RunnerError(
                        f"{name} rpc unreachable: {e}; last log lines:\n"
                        f"{self._log_tail(name)}") from e
                heights[name] = st["sync_info"]["latest_block_height"]
                if heights[name] >= target:
                    break
                if time.monotonic() > end:
                    raise RunnerError(f"{name} stuck at {heights[name]} "
                                      f"< {target}")
                await asyncio.sleep(0.3)
            blk = await call(port, "block", height=target)
            hashes[name] = blk["block_id"]["hash"]["~b"]

        if len(set(hashes.values())) > 1:
            raise RunnerError(f"fork at {target}: {hashes}")

        light_ok = {}
        for name in self.names:
            if self.m.nodes[name].mode != "light":
                continue
            port = self.rpc_port(name)
            blk = await call(port, "block", height=target, timeout=60.0)
            got = blk["block_id"]["hash"]["~b"]
            if hashes and got not in set(hashes.values()):
                raise RunnerError(f"light {name} diverges at {target}")
            light_ok[name] = True

        # manifest validator_updates took effect: fold them over genesis
        # and compare with the live validator set
        validators = {}
        if self.m.validator_updates:
            expect = dict(self.m.validator_powers())
            for _, updates in sorted(self.m.validator_updates.items()):
                for name, power in updates.items():
                    if power == 0:
                        expect.pop(name, None)
                    else:
                        expect[name] = power
            port = self.rpc_port(self._primary_name())
            want = {self.node_pub_key_hex(n): p
                    for n, p in expect.items()}
            end = time.monotonic() + 30    # updates apply at height+2
            while True:
                vres = await call(port, "validators", timeout=60.0)
                got = {v["pub_key"]: v["voting_power"]
                       for v in vres["validators"]}
                if got == want:
                    break
                if time.monotonic() > end:
                    raise RunnerError(f"validator set mismatch: "
                                      f"want {want}, got {got}")
                await asyncio.sleep(0.5)
            validators = expect

        return {"final_height": target, "heights": heights,
                "agreement_hash": next(iter(hashes.values()), None),
                "light_verified": light_ok,
                "validators": validators}

    def node_pub_key_hex(self, name: str) -> str:
        """The node's validator pubkey (from its generated FilePV file)."""
        import json as _json

        with open(os.path.join(self.home(name), "config",
                               "priv_validator_key.json")) as f:
            return _json.load(f)["pub_key"]

    async def _submit_valset_tx(self, call, port: int, name: str,
                                power: int) -> None:
        """Manifest validator_update -> kvstore valset tx
        (val:<b64 pubkey>!<power>, abci/kvstore.py).  The power is
        zero-padded by a per-run sequence number so re-applying an
        earlier (name, power) pair still produces a unique tx — the
        mempool cache silently drops byte-identical resubmissions."""
        import base64

        self._valset_seq = getattr(self, "_valset_seq", 0) + 1
        pk = bytes.fromhex(self.node_pub_key_hex(name))
        padded = b"%0*d" % (len(str(power)) + self._valset_seq, power)
        tx = b"val:" + base64.b64encode(pk) + b"!" + padded
        self.log(f"[e2e] validator_update {name} -> power {power}")
        # ``call`` retries RPCError (incl. the RETRYABLE overload shed,
        # -32099) for its whole timeout window, so a loaded run resends
        # this control-plane tx instead of aborting
        res = await call(port, "broadcast_tx_sync", tx=tx.hex())
        if res.get("code", 0) != 0:
            raise RunnerError(f"valset tx for {name} rejected: {res}")

    def _log_tail(self, name: str, n: int = 15) -> str:
        try:
            with open(os.path.join(self.base_dir, f"{name}.log"),
                      errors="replace") as f:
                return "".join(f.readlines()[-n:])
        except OSError:
            return "(no log)"

    # --------------------------------------------------------- teardown

    def stop(self) -> None:
        for name, proc in self.procs.items():
            if proc.poll() is None:
                if name in self.paused:
                    proc.send_signal(signal.SIGCONT)
                proc.send_signal(signal.SIGTERM)
        for proc in list(self.procs.values()) + list(
                self.app_procs.values()):
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
