"""e2e testnet manifest (reference: ``test/e2e/pkg/manifest.go``): a TOML
description of a network — node roles, validator powers, late starts,
perturbation schedule, load — that the runner turns into a live
multi-process testnet on localhost.

Example::

    initial_height = 1
    [validators]
    validator01 = 10
    validator02 = 10
    validator03 = 10

    [node.validator01]
    [node.validator02]
    perturb = ["kill:5", "restart:8"]
    [node.validator03]
    [node.full01]
    mode = "full"
    start_at = 4
    [node.light01]
    mode = "light"
    start_at = 6

    [load]
    rate = 20.0
    duration = 15.0
"""

from __future__ import annotations

from dataclasses import dataclass, field

MODES = ("validator", "full", "seed", "light")
PERTURBATIONS = ("kill", "restart", "pause", "resume")
DATABASES = ("logdb", "native", "memdb")
ABCI_PROTOCOLS = ("builtin", "socket", "grpc")


class ManifestError(Exception):
    pass


@dataclass
class NodeManifest:
    name: str = ""
    mode: str = "validator"            # manifest.go:158 ModeStr
    start_at: int = 0                  # join when the chain reaches this
    key_type: str = "ed25519"
    database: str = "logdb"            # storage.db_backend sweep axis
    abci_protocol: str = "builtin"     # builtin | socket | grpc (the
    #   runner spawns an external kvstore app process for the latter two)
    # "action:height" entries, applied when the chain passes height
    perturb: list[str] = field(default_factory=list)

    def schedule(self) -> list[tuple[int, str]]:
        out = []
        for p in self.perturb:
            action, _, h = p.partition(":")
            if action not in PERTURBATIONS or not h.isdigit():
                raise ManifestError(
                    f"bad perturbation {p!r} on {self.name} "
                    f"(want action:height, action in {PERTURBATIONS})")
            out.append((int(h), action))
        return sorted(out)


@dataclass
class LoadManifest:
    rate: float = 10.0                 # tx/s
    duration: float = 10.0
    size: int = 64


@dataclass
class Manifest:
    initial_height: int = 1
    chain_id: str = "e2e-net"
    validators: dict = field(default_factory=dict)   # name -> power
    nodes: dict = field(default_factory=dict)        # name -> NodeManifest
    # height -> {node name -> power}: valset txs the runner submits when
    # the chain passes that height (manifest.go:34 ValidatorUpdatesMap;
    # power 0 removes the validator)
    validator_updates: dict = field(default_factory=dict)
    load: LoadManifest = field(default_factory=LoadManifest)
    # network-wide knobs
    emulated_latency_ms: float = 0.0
    fuzz: bool = False
    final_height: int = 10             # success bar: all nodes reach this

    def validate(self) -> None:
        if not self.nodes:
            raise ManifestError("manifest has no nodes")
        vals = [n for n in self.nodes.values() if n.mode == "validator"]
        if not vals:
            raise ManifestError("manifest has no validator nodes")
        for name in self.validators:
            if name not in self.nodes:
                raise ManifestError(f"validators entry {name!r} is not a "
                                    f"node")
        for n in self.nodes.values():
            if n.mode not in MODES:
                raise ManifestError(f"bad mode {n.mode!r} for {n.name}")
            if n.database not in DATABASES:
                raise ManifestError(f"bad database {n.database!r} for "
                                    f"{n.name} (want one of {DATABASES})")
            if n.abci_protocol not in ABCI_PROTOCOLS:
                raise ManifestError(
                    f"bad abci_protocol {n.abci_protocol!r} for {n.name} "
                    f"(want one of {ABCI_PROTOCOLS})")
            if n.database == "memdb" and any(
                    p.startswith(("kill", "restart")) for p in n.perturb):
                raise ManifestError(
                    f"{n.name}: memdb does not survive kill/restart "
                    f"perturbations")
            n.schedule()
        for h, updates in self.validator_updates.items():
            if h <= 0:
                raise ManifestError(f"validator_update height {h} "
                                    f"must be positive")
            for name, power in updates.items():
                node = self.nodes.get(name)
                if node is None or node.mode == "light":
                    raise ManifestError(f"validator_update target "
                                        f"{name!r} is not a backing node")
                if power < 0:
                    raise ManifestError(f"validator_update power for "
                                        f"{name!r} must be >= 0")
                if node.key_type != "ed25519":
                    # the kvstore valset tx carries ed25519 keys only
                    # (abci/kvstore.py:122)
                    raise ManifestError(
                        f"validator_update target {name!r} has key type "
                        f"{node.key_type!r}; only ed25519 is supported")

    def validator_powers(self) -> dict:
        """Explicit [validators] map, else all validator-mode nodes at
        power 100 (manifest.go:28 default)."""
        if self.validators:
            return dict(self.validators)
        return {name: 100 for name, n in self.nodes.items()
                if n.mode == "validator"}


def manifest_to_toml(m: Manifest) -> str:
    """Serialize a manifest back to the TOML the runner/CLI consume —
    the generator's output format."""
    def q(s: str) -> str:
        return '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'

    lines = [f"chain_id = {q(m.chain_id)}",
             f"initial_height = {m.initial_height}",
             f"final_height = {m.final_height}"]
    if m.emulated_latency_ms:
        lines.append(f"emulated_latency_ms = {m.emulated_latency_ms}")
    if m.fuzz:
        lines.append("fuzz = true")
    if m.validators:
        lines.append("\n[validators]")
        for name, power in m.validators.items():
            lines.append(f"{name} = {power}")
    for name, n in m.nodes.items():
        lines.append(f"\n[node.{name}]")
        if n.mode != "validator":
            lines.append(f"mode = {q(n.mode)}")
        if n.start_at:
            lines.append(f"start_at = {n.start_at}")
        if n.key_type != "ed25519":
            lines.append(f"key_type = {q(n.key_type)}")
        if n.database != "logdb":
            lines.append(f"database = {q(n.database)}")
        if n.abci_protocol != "builtin":
            lines.append(f"abci_protocol = {q(n.abci_protocol)}")
        if n.perturb:
            lines.append("perturb = ["
                         + ", ".join(q(p) for p in n.perturb) + "]")
    for h, updates in sorted(m.validator_updates.items()):
        lines.append(f"\n[validator_update.{h}]")
        for name, power in updates.items():
            lines.append(f"{name} = {power}")
    lines.append("\n[load]")
    lines.append(f"rate = {m.load.rate}")
    lines.append(f"duration = {m.load.duration}")
    lines.append(f"size = {m.load.size}")
    return "\n".join(lines) + "\n"


def loads_toml(text: str) -> dict:
    """Manifest TOML text -> dict, through stdlib ``tomllib`` when it
    exists (Python >= 3.11) and the repo's flat-TOML parser otherwise —
    ``manifest_to_toml`` only emits the flat grammar that parser covers,
    so both paths agree on every generated manifest."""
    try:
        import tomllib
    except ImportError:
        from ..config import _parse_flat_toml

        return _parse_flat_toml(text)
    return tomllib.loads(text)


def load_manifest(path: str) -> Manifest:
    with open(path, "r", encoding="utf-8") as f:
        doc = loads_toml(f.read())
    return manifest_from_dict(doc)


def manifest_from_dict(doc: dict) -> Manifest:
    m = Manifest()
    m.initial_height = int(doc.get("initial_height", 1))
    m.chain_id = doc.get("chain_id", "e2e-net")
    m.final_height = int(doc.get("final_height", 10))
    m.emulated_latency_ms = float(doc.get("emulated_latency_ms", 0.0))
    m.fuzz = bool(doc.get("fuzz", False))
    m.validators = {k: int(v) for k, v in doc.get("validators", {}).items()}
    for name, nd in doc.get("node", {}).items():
        nm = NodeManifest(name=name)
        nm.mode = nd.get("mode", "validator")
        nm.start_at = int(nd.get("start_at", 0))
        nm.key_type = nd.get("key_type", "ed25519")
        nm.database = nd.get("database", "logdb")
        nm.abci_protocol = nd.get("abci_protocol", "builtin")
        nm.perturb = list(nd.get("perturb", []))
        m.nodes[name] = nm
    for h, updates in doc.get("validator_update", {}).items():
        m.validator_updates[int(h)] = {k: int(v)
                                       for k, v in updates.items()}
    if "load" in doc:
        ld = doc["load"]
        m.load = LoadManifest(rate=float(ld.get("rate", 10.0)),
                              duration=float(ld.get("duration", 10.0)),
                              size=int(ld.get("size", 64)))
    m.validate()
    return m
