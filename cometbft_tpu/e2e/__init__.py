"""Manifest-driven e2e testnets (reference: ``test/e2e``)."""

from .manifest import (Manifest, ManifestError, NodeManifest,
                       load_manifest, manifest_from_dict)
from .runner import Runner, RunnerError

__all__ = ["Manifest", "ManifestError", "NodeManifest", "Runner",
           "RunnerError", "load_manifest", "manifest_from_dict"]
