"""Randomized testnet manifest generator (reference:
``test/e2e/generator/generator.go``): a seed deterministically expands
into a manifest sweeping the configuration axes — database backend, ABCI
transport, key types, node roles, late starts, perturbations, validator
updates, latency emulation — so permutation coverage finds integration
bugs hand-written manifests never exercise.

Determinism contract: ``generate_manifest(seed)`` depends only on the
seed (its own ``random.Random``), so a CI failure reproduces from the
seed alone.
"""

from __future__ import annotations

import random

from .manifest import LoadManifest, Manifest, NodeManifest


def _weighted(rng: random.Random, choices: dict):
    """One key of ``choices`` picked by weight."""
    total = sum(choices.values())
    x = rng.uniform(0, total)
    for k, w in choices.items():
        x -= w
        if x <= 0:
            return k
    return k


def generate_manifest(seed: int, *, compact: bool = False) -> Manifest:
    """Deterministic manifest for ``seed``.

    ``compact`` bounds the topology for CI (<= 4 backing nodes, short
    chain); without it, up to 4 validators + 2 full nodes + seed +
    light client.
    """
    rng = random.Random(seed)
    m = Manifest()
    m.chain_id = f"gen-{seed}"
    m.final_height = 8 if compact else rng.choice([10, 12, 15])

    n_validators = rng.randint(2, 3 if compact else 4)
    n_full = rng.randint(0, 1 if compact else 2)
    with_seed_node = (not compact) and rng.random() < 0.3
    with_light = rng.random() < (0.3 if compact else 0.5)

    databases = {"logdb": 3, "native": 2, "memdb": 1}
    abcis = {"builtin": 3, "socket": 2, "grpc": 1}
    key_types = {"ed25519": 4, "secp256k1": 1}

    names: list[str] = []
    for i in range(n_validators):
        name = f"validator{i + 1:02d}"
        node = NodeManifest(name=name, mode="validator")
        node.database = _weighted(rng, databases)
        node.abci_protocol = _weighted(rng, abcis)
        node.key_type = _weighted(rng, key_types)
        m.nodes[name] = node
        m.validators[name] = rng.choice([10, 20, 50, 100])
        names.append(name)

    for i in range(n_full):
        name = f"full{i + 1:02d}"
        node = NodeManifest(name=name, mode="full")
        node.database = _weighted(rng, databases)
        node.abci_protocol = _weighted(rng, abcis)
        if rng.random() < 0.7:
            node.start_at = rng.randint(2, max(2, m.final_height // 2))
        m.nodes[name] = node
        names.append(name)

    if with_seed_node:
        m.nodes["seed01"] = NodeManifest(name="seed01", mode="seed")

    if with_light:
        m.nodes["light01"] = NodeManifest(
            name="light01", mode="light",
            start_at=rng.randint(2, max(2, m.final_height // 2)))

    # perturbations: only on validators the chain can spare (keep > 2/3
    # of voting power un-perturbed so liveness never depends on the
    # recovery action firing promptly), never on memdb nodes
    perturbable = [n for n in names
                   if m.nodes[n].mode == "validator"
                   and m.nodes[n].database != "memdb"]
    total_power = sum(m.validators.values())
    budget = total_power - (total_power * 2 // 3 + 1)
    rng.shuffle(perturbable)
    for name in perturbable:
        if m.validators[name] > budget or rng.random() > 0.5:
            continue
        budget -= m.validators[name]
        h = rng.randint(3, max(3, m.final_height - 4))
        kind = rng.choice(["kill", "pause"])
        recover = {"kill": "restart", "pause": "resume"}[kind]
        m.nodes[name].perturb = [f"{kind}:{h}", f"{recover}:{h + 2}"]

    # a validator-power update mid-chain (ed25519 targets only — the
    # kvstore valset tx carries ed25519 keys)
    ed_vals = [n for n in m.validators
               if m.nodes[n].key_type == "ed25519"
               and not m.nodes[n].perturb]
    if ed_vals and rng.random() < 0.5:
        target = rng.choice(ed_vals)
        h = rng.randint(3, max(3, m.final_height - 3))
        m.validator_updates[h] = {
            target: m.validators[target] + rng.choice([10, 25])}

    if rng.random() < 0.3:
        m.emulated_latency_ms = rng.choice([20.0, 50.0])
    if (not compact) and rng.random() < 0.2:
        m.fuzz = True

    m.load = LoadManifest(rate=rng.choice([5.0, 10.0, 20.0]),
                          duration=10.0 if compact else 20.0,
                          size=rng.choice([32, 64, 256]))
    m.validate()
    return m
