"""Shared testnet home generation (reference: ``cmd/cometbft/commands/
testnet.go`` + ``test/e2e/runner/setup.go``): one place that lays out
node homes — keys, shared genesis, wired configs — used by both the
`testnet` CLI command and the manifest e2e runner."""

from __future__ import annotations

import os
import time
from dataclasses import dataclass


@dataclass
class HomeSpec:
    name: str
    p2p_port: int
    rpc_port: int
    power: int | None = None          # None -> not a genesis validator
    key_type: str = "ed25519"


def generate_homes(base_dir: str, specs: list[HomeSpec], chain_id: str,
                   *, initial_height: int = 1,
                   persistent_peers=None, tweak=None) -> None:
    """Create a home per spec with a shared genesis.

    ``persistent_peers(spec) -> str`` supplies each node's peer list
    (default: all other nodes).  ``tweak(spec, cfg)`` mutates each
    config before save."""
    from ..config import Config
    from ..p2p import NodeKey
    from ..privval import FilePV
    from ..types.genesis import GenesisDoc, GenesisValidator

    pvs = {}
    for spec in specs:
        home = os.path.join(base_dir, spec.name)
        os.makedirs(os.path.join(home, "config"), exist_ok=True)
        os.makedirs(os.path.join(home, "data"), exist_ok=True)
        cfg = Config()
        NodeKey.load_or_gen(os.path.join(home, cfg.base.node_key_file))
        pvs[spec.name] = FilePV.load_or_generate(
            os.path.join(home, cfg.base.priv_validator_key_file),
            os.path.join(home, cfg.base.priv_validator_state_file),
            key_type=spec.key_type)

    doc = GenesisDoc(
        chain_id=chain_id,
        genesis_time_ns=time.time_ns(),
        initial_height=initial_height,
        validators=[GenesisValidator(pvs[s.name].get_pub_key(),
                                     s.power, s.name,
                                     pop=pvs[s.name].pop())
                    for s in specs if s.power is not None])

    for spec in specs:
        home = os.path.join(base_dir, spec.name)
        cfg = Config()
        cfg.base.moniker = spec.name
        cfg.p2p.laddr = f"tcp://127.0.0.1:{spec.p2p_port}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{spec.rpc_port}"
        if persistent_peers is not None:
            cfg.p2p.persistent_peers = persistent_peers(spec)
        else:
            cfg.p2p.persistent_peers = ",".join(
                f"tcp://127.0.0.1:{o.p2p_port}"
                for o in specs if o.name != spec.name)
        if tweak is not None:
            tweak(spec, cfg)
        cfg.save(os.path.join(home, "config", "config.toml"))
        doc.save(os.path.join(home, cfg.base.genesis_file))
