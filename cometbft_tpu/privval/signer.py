"""Remote signer: keep the validator key in a separate process (HSM
stand-in) and sign over a socket (reference: ``privval/signer_client.go:17``
SignerClient, ``privval/signer_server.go`` SignerServer, message schema in
``privval/msgs.go``).

SignerServer listens on TCP/UNIX and serves a wrapped PrivValidator
(normally a FilePV); SignerClient implements PrivValidator for the node
side.  Messages are length-prefixed msgpack: PubKeyRequest/Response,
SignVoteRequest/SignedVoteResponse, SignProposalRequest/
SignedProposalResponse, Ping/Pong; errors travel as {"err": ...} replies
(remoteSignerError)."""

from __future__ import annotations

import asyncio
import functools
import struct

import msgpack

from ..crypto.keys import (ED25519_KEY_TYPE, PubKey,
                           pub_key_from_type_bytes)
from ..libs import failures
from ..types import codec
from ..types.priv_validator import PrivValidator
from ..types.vote import Proposal, Vote

_LEN = struct.Struct("<I")
MAX_MSG = 1 << 20
# default bound on one signer round trip (config base.priv_validator_
# timeout_s overrides; 0 disables).  A wedged signer process used to
# block consensus FOREVER — with the deadline it costs one missed vote
# and a reconnect instead.
DEFAULT_ROUND_TRIP_TIMEOUT_S = 5.0


class RemoteSignerError(Exception):
    pass


class SignerTimeoutError(RemoteSignerError):
    """One round trip exceeded the deadline: the signer is wedged or the
    link is black-holing.  The listener treats this exactly like a
    dropped connection (close + re-accept the signer's redial)."""


@functools.cache
def _signer_metrics():
    from ..libs import metrics as m

    return m.counter("privval_signer_timeouts_total",
                     "remote-signer round trips abandoned on deadline")


async def _send(writer: asyncio.StreamWriter, obj: dict) -> None:
    raw = msgpack.packb(obj, use_bin_type=True)
    writer.write(_LEN.pack(len(raw)) + raw)
    await writer.drain()


async def _recv(reader: asyncio.StreamReader) -> dict:
    hdr = await reader.readexactly(_LEN.size)
    (ln,) = _LEN.unpack(hdr)
    if ln > MAX_MSG:
        raise RemoteSignerError(f"oversized signer message: {ln}")
    return msgpack.unpackb(await reader.readexactly(ln), raw=False)


class SignerServer:
    """Serves a PrivValidator's signing operations to one or more nodes."""

    def __init__(self, pv: PrivValidator):
        self.pv = pv
        self._server: asyncio.Server | None = None

    async def listen(self, host: str = "127.0.0.1",
                     port: int = 0) -> tuple[str, int]:
        self._server = await asyncio.start_server(self._serve, host, port)
        addr = self._server.sockets[0].getsockname()
        return addr[0], addr[1]

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                req = await _recv(reader)
                await _send(writer, await self._handle(req))
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    async def _handle(self, req: dict) -> dict:
        tag = req.get("@")
        try:
            if tag == "ping":
                return {"@": "pong"}
            if tag == "pubkey_req":
                pub = self.pv.get_pub_key()
                return {"@": "pubkey_res", "pub": pub.bytes(),
                        "type": pub.type()}
            if tag == "sign_vote_req":
                vote: Vote = codec.from_dict(req["vote"])
                await self.pv.sign_vote(req["chain_id"], vote,
                                        sign_extension=req["ext"])
                return {"@": "signed_vote_res", "vote": codec.to_dict(vote)}
            if tag == "sign_proposal_req":
                prop: Proposal = codec.from_dict(req["proposal"])
                await self.pv.sign_proposal(req["chain_id"], prop)
                return {"@": "signed_proposal_res",
                        "proposal": codec.to_dict(prop)}
            return {"@": "err", "msg": f"unknown request {tag!r}"}
        except Exception as e:  # bftlint: disable=EXC001 -- double-sign refusals and sign errors ride back over the wire as err frames; the client re-raises
            return {"@": "err", "msg": f"{type(e).__name__}: {e}"}


class SignerClient(PrivValidator):
    """Node-side PrivValidator backed by a remote SignerServer."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter, pub_key: PubKey,
                 timeout_s: float = DEFAULT_ROUND_TRIP_TIMEOUT_S):
        self._reader = reader
        self._writer = writer
        self._pub_key = pub_key
        self._lock = asyncio.Lock()      # one in-flight request at a time
        self.timeout_s = timeout_s

    @classmethod
    async def connect(cls, host: str, port: int,
                      timeout_s: float = DEFAULT_ROUND_TRIP_TIMEOUT_S
                      ) -> "SignerClient":
        reader, writer = await asyncio.open_connection(host, port)
        return await cls.from_streams(reader, writer, timeout_s=timeout_s)

    @classmethod
    async def from_streams(cls, reader, writer,
                           timeout_s: float = DEFAULT_ROUND_TRIP_TIMEOUT_S
                           ) -> "SignerClient":
        """Handshake over an already-open connection (either dial
        direction ends up here)."""
        await _send(writer, {"@": "pubkey_req"})
        res = await _recv(reader)
        if res.get("@") != "pubkey_res":
            raise RemoteSignerError(f"bad pubkey response: {res}")
        pub = pub_key_from_type_bytes(res.get("type", ED25519_KEY_TYPE),
                                      res["pub"])
        return cls(reader, writer, pub, timeout_s=timeout_s)

    async def close(self) -> None:
        self._writer.close()

    async def _round_trip(self, req: dict) -> dict:
        """One request/response, bounded by ``timeout_s`` (covering lock
        wait, send, and receive: a request wedged behind another wedged
        request must time out too, not queue forever)."""

        async def go() -> dict:
            async with self._lock:
                fired = failures.fire("signer.round_trip.hang")
                if fired is not None:
                    # chaos: the signer process is wedged — nothing comes
                    # back until (long after) the deadline
                    await asyncio.sleep(float(fired.get("delay", 3600.0)))
                await _send(self._writer, req)
                return await _recv(self._reader)

        if self.timeout_s and self.timeout_s > 0:
            try:
                res = await asyncio.wait_for(go(), self.timeout_s)
            except asyncio.TimeoutError:
                _signer_metrics().inc()
                raise SignerTimeoutError(
                    f"remote signer did not answer within "
                    f"{self.timeout_s}s") from None
        else:
            res = await go()
        if res.get("@") == "err":
            raise RemoteSignerError(res.get("msg", "remote signer error"))
        return res

    async def ping(self) -> None:
        await self._round_trip({"@": "ping"})

    def get_pub_key(self) -> PubKey:
        return self._pub_key

    async def sign_vote(self, chain_id: str, vote: Vote,
                        sign_extension: bool) -> None:
        res = await self._round_trip({
            "@": "sign_vote_req", "chain_id": chain_id,
            "vote": codec.to_dict(vote), "ext": sign_extension})
        signed: Vote = codec.from_dict(res["vote"])
        vote.signature = signed.signature
        vote.timestamp_ns = signed.timestamp_ns
        vote.extension_signature = signed.extension_signature

    async def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        res = await self._round_trip({
            "@": "sign_proposal_req", "chain_id": chain_id,
            "proposal": codec.to_dict(proposal)})
        signed: Proposal = codec.from_dict(res["proposal"])
        proposal.signature = signed.signature
        proposal.timestamp_ns = signed.timestamp_ns


class SignerListener(PrivValidator):
    """Node side of the reference topology: the node LISTENS on
    ``priv_validator_laddr`` and the remote signer dials in
    (``privval/signer_listener_endpoint.go``).

    Itself a PrivValidator: every operation runs against the currently
    connected signer, and a dropped connection triggers a re-accept of
    the signer's redial (the reference endpoint's WaitForConnection), so
    a signer restart does not halt the validator."""

    def __init__(self, accept_timeout: float = 30.0,
                 timeout_s: float = DEFAULT_ROUND_TRIP_TIMEOUT_S):
        self._server: asyncio.Server | None = None
        self._accepted: asyncio.Queue = asyncio.Queue()
        self._client: SignerClient | None = None
        self._accept_timeout = accept_timeout
        self._timeout_s = timeout_s
        self._lock = asyncio.Lock()

    async def listen(self, host: str = "127.0.0.1",
                     port: int = 0) -> tuple[str, int]:
        async def on_conn(reader, writer):
            await self._accepted.put((reader, writer))

        self._server = await asyncio.start_server(on_conn, host, port)
        addr = self._server.sockets[0].getsockname()
        return addr[0], addr[1]

    async def wait_for_signer(self, timeout: float | None = None
                              ) -> SignerClient:
        """Accept connections until one completes the pubkey handshake
        (a stray probe that connects without speaking is dropped)."""
        deadline = asyncio.get_event_loop().time() + (
            timeout if timeout is not None else self._accept_timeout)
        while True:
            remaining = deadline - asyncio.get_event_loop().time()
            if remaining <= 0:
                raise RemoteSignerError(
                    "timed out waiting for the remote signer to connect")
            try:
                reader, writer = await asyncio.wait_for(
                    self._accepted.get(), remaining)
            except asyncio.TimeoutError:
                raise RemoteSignerError(
                    "timed out waiting for the remote signer to connect")
            try:
                self._client = await asyncio.wait_for(
                    SignerClient.from_streams(reader, writer,
                                              timeout_s=self._timeout_s),
                    min(5.0, max(0.1, remaining)))
                return self._client
            except Exception:  # bftlint: disable=EXC001 -- a failed handshake closes the conn and loops to re-accept under the caller's deadline
                writer.close()

    async def _reconnect(self) -> SignerClient:
        old, self._client = self._client, None
        if old is not None:
            await old.close()
        return await self.wait_for_signer()

    async def _with_signer(self, op):
        """Run op against the live client; on a dropped connection OR a
        round-trip timeout (a wedged signer is indistinguishable from a
        dead link, and the abandoned request leaves the stream
        unframed), close + re-accept the signer's redial and retry
        once."""
        async with self._lock:
            if self._client is None:
                await self.wait_for_signer()
            try:
                return await op(self._client)
            except (asyncio.IncompleteReadError, ConnectionError,
                    SignerTimeoutError, OSError):  # bftlint: disable=EXC001 -- dropped-link/wedged-signer discipline (PR 10): close, re-accept the redial, retry once; the retry re-raises
                await self._reconnect()
                return await op(self._client)

    # PrivValidator surface (delegates with reconnect)

    def get_pub_key(self) -> PubKey:
        if self._client is None:
            raise RemoteSignerError("remote signer is not connected")
        return self._client.get_pub_key()

    async def sign_vote(self, chain_id: str, vote: Vote,
                        sign_extension: bool) -> None:
        await self._with_signer(
            lambda c: c.sign_vote(chain_id, vote, sign_extension))

    async def sign_proposal(self, chain_id: str, proposal) -> None:
        await self._with_signer(
            lambda c: c.sign_proposal(chain_id, proposal))

    async def ping(self) -> None:
        await self._with_signer(lambda c: c.ping())

    async def close(self) -> None:
        # close live + queued connections BEFORE wait_closed(): on 3.12
        # the server waits for every connection transport to finish, so
        # the reversed order deadlocks
        if self._client is not None:
            await self._client.close()
            self._client = None
        while not self._accepted.empty():
            _, writer = self._accepted.get_nowait()
            writer.close()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()


async def serve_dialer(pv: PrivValidator, host: str, port: int,
                       max_retries: int = 0,
                       retry_interval: float = 1.0) -> None:
    """Signer side of the reference topology: dial the node's
    ``priv_validator_laddr`` and serve signing requests over the dialed
    connection until it closes (``privval/signer_dialer_endpoint.go`` +
    ``signer_server.go``).  Reconnects up to ``max_retries`` times
    (0 = forever), covering node restarts."""
    server = SignerServer(pv)
    attempts = 0
    while True:
        try:
            reader, writer = await asyncio.open_connection(host, port)
        except OSError:
            attempts += 1
            if max_retries and attempts >= max_retries:
                raise
            await asyncio.sleep(retry_interval)
            continue
        attempts = 0
        try:
            await server._serve(reader, writer)
        except Exception:  # bftlint: disable=EXC001 -- a malformed frame must not kill the signer daemon; it closes and redials
            writer.close()
        await asyncio.sleep(retry_interval)
