"""Remote signer: keep the validator key in a separate process (HSM
stand-in) and sign over a socket (reference: ``privval/signer_client.go:17``
SignerClient, ``privval/signer_server.go`` SignerServer, message schema in
``privval/msgs.go``).

SignerServer listens on TCP/UNIX and serves a wrapped PrivValidator
(normally a FilePV); SignerClient implements PrivValidator for the node
side.  Messages are length-prefixed msgpack: PubKeyRequest/Response,
SignVoteRequest/SignedVoteResponse, SignProposalRequest/
SignedProposalResponse, Ping/Pong; errors travel as {"err": ...} replies
(remoteSignerError)."""

from __future__ import annotations

import asyncio
import struct

import msgpack

from ..crypto.keys import Ed25519PubKey, PubKey
from ..types import codec
from ..types.priv_validator import PrivValidator
from ..types.vote import Proposal, Vote

_LEN = struct.Struct("<I")
MAX_MSG = 1 << 20


class RemoteSignerError(Exception):
    pass


async def _send(writer: asyncio.StreamWriter, obj: dict) -> None:
    raw = msgpack.packb(obj, use_bin_type=True)
    writer.write(_LEN.pack(len(raw)) + raw)
    await writer.drain()


async def _recv(reader: asyncio.StreamReader) -> dict:
    hdr = await reader.readexactly(_LEN.size)
    (ln,) = _LEN.unpack(hdr)
    if ln > MAX_MSG:
        raise RemoteSignerError(f"oversized signer message: {ln}")
    return msgpack.unpackb(await reader.readexactly(ln), raw=False)


class SignerServer:
    """Serves a PrivValidator's signing operations to one or more nodes."""

    def __init__(self, pv: PrivValidator):
        self.pv = pv
        self._server: asyncio.Server | None = None

    async def listen(self, host: str = "127.0.0.1",
                     port: int = 0) -> tuple[str, int]:
        self._server = await asyncio.start_server(self._serve, host, port)
        addr = self._server.sockets[0].getsockname()
        return addr[0], addr[1]

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                req = await _recv(reader)
                await _send(writer, await self._handle(req))
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    async def _handle(self, req: dict) -> dict:
        tag = req.get("@")
        try:
            if tag == "ping":
                return {"@": "pong"}
            if tag == "pubkey_req":
                return {"@": "pubkey_res",
                        "pub": self.pv.get_pub_key().bytes()}
            if tag == "sign_vote_req":
                vote: Vote = codec.from_dict(req["vote"])
                await self.pv.sign_vote(req["chain_id"], vote,
                                        sign_extension=req["ext"])
                return {"@": "signed_vote_res", "vote": codec.to_dict(vote)}
            if tag == "sign_proposal_req":
                prop: Proposal = codec.from_dict(req["proposal"])
                await self.pv.sign_proposal(req["chain_id"], prop)
                return {"@": "signed_proposal_res",
                        "proposal": codec.to_dict(prop)}
            return {"@": "err", "msg": f"unknown request {tag!r}"}
        except Exception as e:           # double-sign refusals ride back
            return {"@": "err", "msg": f"{type(e).__name__}: {e}"}


class SignerClient(PrivValidator):
    """Node-side PrivValidator backed by a remote SignerServer."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter, pub_key: PubKey):
        self._reader = reader
        self._writer = writer
        self._pub_key = pub_key
        self._lock = asyncio.Lock()      # one in-flight request at a time

    @classmethod
    async def connect(cls, host: str, port: int) -> "SignerClient":
        reader, writer = await asyncio.open_connection(host, port)
        await _send(writer, {"@": "pubkey_req"})
        res = await _recv(reader)
        if res.get("@") != "pubkey_res":
            raise RemoteSignerError(f"bad pubkey response: {res}")
        return cls(reader, writer, Ed25519PubKey(res["pub"]))

    async def close(self) -> None:
        self._writer.close()

    async def _round_trip(self, req: dict) -> dict:
        async with self._lock:
            await _send(self._writer, req)
            res = await _recv(self._reader)
        if res.get("@") == "err":
            raise RemoteSignerError(res.get("msg", "remote signer error"))
        return res

    async def ping(self) -> None:
        await self._round_trip({"@": "ping"})

    def get_pub_key(self) -> PubKey:
        return self._pub_key

    async def sign_vote(self, chain_id: str, vote: Vote,
                        sign_extension: bool) -> None:
        res = await self._round_trip({
            "@": "sign_vote_req", "chain_id": chain_id,
            "vote": codec.to_dict(vote), "ext": sign_extension})
        signed: Vote = codec.from_dict(res["vote"])
        vote.signature = signed.signature
        vote.timestamp_ns = signed.timestamp_ns
        vote.extension_signature = signed.extension_signature

    async def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        res = await self._round_trip({
            "@": "sign_proposal_req", "chain_id": chain_id,
            "proposal": codec.to_dict(proposal)})
        signed: Proposal = codec.from_dict(res["proposal"])
        proposal.signature = signed.signature
        proposal.timestamp_ns = signed.timestamp_ns
